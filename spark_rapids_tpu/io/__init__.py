"""Columnar IO: Parquet/ORC/CSV via pyarrow CPU decode + device upload.

Reference: SURVEY.md §2.5 — the reference reads footers and assembles row
groups on CPU, then decodes on GPU (``Table.readParquet``,
GpuParquetScan.scala:1022). TPUs have no decode engines, so the decode
boundary shifts fully to the CPU (DESIGN.md §7): pyarrow decodes to Arrow;
upload to device is the HostColumnarToGpu step. The three reader strategies
(PERFILE / COALESCING / MULTITHREADED, GpuParquetScan.scala:1451,824,1145)
are preserved at the host level in scan.py.
"""

from __future__ import annotations

import glob as _glob
import os
from typing import Any, Dict, List, Optional

from ..columnar import dtypes as dt


def expand_paths(paths: List[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                # prune hidden/staging dirs (_temporary, .hive-staging) and
                # sort in place for deterministic traversal across hosts
                dirs[:] = sorted(d for d in dirs if not d.startswith((".", "_")))
                for f in sorted(files):
                    if not f.startswith((".", "_")) and not f.endswith(".crc"):
                        out.append(os.path.join(root, f))
        elif any(ch in p for ch in "*?["):
            out.extend(sorted(_glob.glob(p)))
        else:
            out.append(p)
    return out


def infer_schema(fmt: str, paths: List[str],
                 options: Dict[str, Any]) -> dt.Schema:
    files = expand_paths(paths)
    if not files:
        raise FileNotFoundError(f"no input files in {paths}")
    first = files[0]
    if fmt == "parquet":
        import pyarrow.parquet as pq
        arrow_schema = pq.read_schema(first)
    elif fmt == "orc":
        import pyarrow.orc as orc
        arrow_schema = orc.ORCFile(first).schema
    elif fmt == "csv":
        arrow_schema = _csv_schema(first, options)
    else:
        raise ValueError(f"unsupported format {fmt}")
    fields = []
    for name, typ in zip(arrow_schema.names, arrow_schema.types):
        fields.append(dt.Field(name, dt.from_arrow(typ)))
    return dt.Schema(fields)


def _csv_opts(options: Dict[str, Any]):
    import pyarrow.csv as pcsv
    header = str(options.get("header", "false")).lower() == "true"
    delim = options.get("sep", options.get("delimiter", ","))
    read_opts = pcsv.ReadOptions(autogenerate_column_names=not header)
    parse_opts = pcsv.ParseOptions(delimiter=delim)
    # Spark: only the configured nullValue (default empty string) reads as NULL
    conv = pcsv.ConvertOptions(
        null_values=[options.get("nullValue", "")], strings_can_be_null=True)
    return header, read_opts, parse_opts, conv


def _csv_schema(path: str, options: Dict[str, Any]):
    """Schema from the first block only (no full-file decode at plan time)."""
    import pyarrow.csv as pcsv
    header, read_opts, parse_opts, conv = _csv_opts(options)
    with pcsv.open_csv(path, read_options=read_opts, parse_options=parse_opts,
                       convert_options=conv) as reader:
        schema = reader.schema
    if not header:
        import pyarrow as pa
        schema = pa.schema([f.with_name(f"_c{i}")
                            for i, f in enumerate(schema)])
    return schema


def _read_csv(path: str, options: Dict[str, Any]):
    import pyarrow.csv as pcsv
    header, read_opts, parse_opts, conv = _csv_opts(options)
    table = pcsv.read_csv(path, read_options=read_opts,
                          parse_options=parse_opts, convert_options=conv)
    if not header:
        # Spark naming: _c0, _c1...
        table = table.rename_columns(
            [f"_c{i}" for i in range(table.num_columns)])
    return table


def read_file_to_arrow(fmt: str, path: str, options: Dict[str, Any],
                       columns: Optional[List[str]] = None, filters=None):
    if fmt == "parquet":
        import pyarrow.parquet as pq
        return pq.read_table(path, columns=columns, filters=filters)
    if fmt == "orc":
        import pyarrow.orc as orc
        return orc.ORCFile(path).read(columns=columns)
    if fmt == "csv":
        t = _read_csv(path, options)
        if columns:
            t = t.select(columns)
        return t
    raise ValueError(f"unsupported format {fmt}")


def read_to_arrow(fmt: str, paths: List[str], options: Dict[str, Any]):
    import pyarrow as pa
    files = expand_paths(paths)
    tables = [read_file_to_arrow(fmt, f, options) for f in files]
    if len(tables) == 1:
        return tables[0]
    return pa.concat_tables(tables, promote_options="permissive")
