"""File scan exec with the reference's three reader strategies.

Reference: ``GpuParquetScan.scala`` — PERFILE (``ParquetPartitionReader:1451``,
one file per batch), COALESCING (``MultiFileParquetPartitionReader:824``,
combine many small files into one buffer before decode; disabled when
``input_file_name()`` is used), MULTITHREADED
(``MultiFileCloudParquetPartitionReader:1145``, background CPU threads
prefetch+decode for high-latency storage; pool ``MultiFileThreadPoolFactory``).
Strategy conf: ``spark.rapids.tpu.sql.format.parquet.reader.type``
(RapidsConf.scala:510), thread count (RapidsConf.scala:548).

Predicate pushdown: pyarrow's parquet reader prunes row groups with min/max
stats from pushed filters — the same CPU-side ``filterBlocks`` role
(GpuParquetScan.scala:239-297).
"""

from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, Iterator, List, Optional

from .. import config as cfg
from ..analysis.contracts import exec_contract
from ..columnar import dtypes as dt
from ..columnar.batch import ColumnarBatch
from ..ops import expressions as ex
from ..plan import logical as lp
from ..plan.physical import Partition, TpuExec, exec_metrics
from . import expand_paths, read_file_to_arrow
from ..exec.tracing import trace_span


def _pushdown_filters(exprs: List[ex.Expression]):
    """Translate simple predicates to pyarrow filter tuples (row-group prune)."""
    from ..ops import predicates as pr
    out = []
    for e in exprs:
        if isinstance(e, (pr.EqualTo, pr.LessThan, pr.LessThanOrEqual,
                          pr.GreaterThan, pr.GreaterThanOrEqual)):
            l, r = e.children
            if isinstance(l, ex.ColumnRef) and isinstance(r, ex.Literal) \
                    and r.value is not None:
                op = {pr.EqualTo: "=", pr.LessThan: "<", pr.LessThanOrEqual: "<=",
                      pr.GreaterThan: ">", pr.GreaterThanOrEqual: ">="}[type(e)]
                out.append((l.col_name, op, r.value))
    return out or None


class TpuFileScanExec(TpuExec):
    """GpuFileSourceScanExec / GpuBatchScanExec analog."""

    CONTRACT = exec_contract(schema="defined", partitioning="source")
    METRICS = exec_metrics("bufferTime", "tpuDecodeTime")

    def __init__(self, plan: lp.FileScan, conf: Optional[cfg.TpuConf] = None):
        super().__init__()
        self.plan = plan
        self.conf = conf or cfg.TpuConf()
        self.files = expand_paths(plan.paths)
        from . import partition_schema
        want = set(plan.schema.names())
        self.pschema = dt.Schema([
            f for f in partition_schema(self.files, plan.paths)
            if f.name in want])
        # column pruning (planner's _prune_scan_columns): only decode/upload
        # referenced file columns; partition values are appended post-read
        proj = getattr(plan, "projection", None)
        pnames = {f.name for f in self.pschema}
        self.columns = ([c for c in proj if c not in pnames]
                        if proj else None)
        self.reader_type = str(
            self.conf.get_key("spark.rapids.tpu.sql.format.parquet.reader.type",
                              "COALESCING")).upper()
        self.num_threads = int(self.conf.get_key(
            "spark.rapids.tpu.sql.format.parquet.multiThreadedRead.numThreads", 4))
        self.filters = _pushdown_filters(plan.pushed_filters) \
            if plan.fmt == "parquet" else None

    @property
    def schema(self) -> dt.Schema:
        return self.plan.schema

    @property
    def output_partitions(self) -> int:
        if self.reader_type == "PERFILE":
            return max(1, len(self.files))
        return 1

    def execute(self) -> List[Partition]:
        if not self.files:
            def empty():
                return
                yield
            return [empty()]
        if self.reader_type == "MULTITHREADED":
            return [self._multithreaded()]
        if self.reader_type == "COALESCING" and self.plan.fmt != "csv":
            return [self._coalescing()]
        # PERFILE: one partition per file (Spark's FilePartition granularity,
        # the task-parallel unit) — multi-file scans drive distributed plans
        return [self._perfile(f) for f in self.files]

    # -- strategies ----------------------------------------------------------
    def _read(self, path: str):
        from ..ops.hashing import InputFileName
        InputFileName.set_current(path)
        t = read_file_to_arrow(self.plan.fmt, path, self.plan.options,
                               columns=self.columns, filters=self.filters,
                               roots=self.plan.paths, pschema=self.pschema)
        self.metrics.inc("bufferTime")
        return t

    def _perfile(self, f: str) -> Partition:
        table = self._read(f)
        if table.num_rows == 0:
            return
        with trace_span("scan_decode", self.metrics, "tpuDecodeTime"):
            batch = ColumnarBatch.from_arrow(table)
        self.metrics.inc("numOutputRows", batch.num_rows)
        self.metrics.inc("numOutputBatches")
        yield batch

    def _coalescing(self) -> Partition:
        """Combine files up to the batch byte target before one upload
        (MultiFileParquetPartitionReader's coalesce behavior)."""
        import pyarrow as pa
        target = self.conf.batch_size_bytes
        pending, pending_bytes = [], 0
        for f in self.files:
            t = self._read(f)
            if t.num_rows == 0:
                continue
            pending.append(t)
            pending_bytes += t.nbytes
            if pending_bytes >= target:
                yield self._upload(pending)
                pending, pending_bytes = [], 0
        if pending:
            yield self._upload(pending)

    def _multithreaded(self) -> Partition:
        """Background prefetch threads (MultiFileCloudParquetPartitionReader)."""
        with ThreadPoolExecutor(max_workers=self.num_threads) as pool:
            futures = [pool.submit(self._read, f) for f in self.files]
            for fut in futures:
                t = fut.result()
                if t.num_rows == 0:
                    continue
                yield self._upload([t])

    def _upload(self, tables) -> ColumnarBatch:
        import pyarrow as pa
        table = tables[0] if len(tables) == 1 else \
            pa.concat_tables(tables, promote_options="permissive")
        with trace_span("scan_decode", self.metrics, "tpuDecodeTime"):
            batch = ColumnarBatch.from_arrow(table)
        self.metrics.inc("numOutputRows", batch.num_rows)
        self.metrics.inc("numOutputBatches")
        return batch

    def _node_string(self):
        return (f"TpuFileScanExec[{self.plan.fmt}, {len(self.files)} files, "
                f"{self.reader_type}]")
