"""File scan exec with the reference's three reader strategies, streaming.

Reference: ``GpuParquetScan.scala`` — PERFILE (``ParquetPartitionReader:1451``,
one file per batch), COALESCING (``MultiFileParquetPartitionReader:824``,
combine many small files into one buffer before decode; disabled when
``input_file_name()`` is used), MULTITHREADED
(``MultiFileCloudParquetPartitionReader:1145``, background CPU threads
prefetch+decode for high-latency storage; pool ``MultiFileThreadPoolFactory``).
Strategy conf: ``spark.rapids.tpu.sql.format.parquet.reader.type``
(RapidsConf.scala:510), thread count (RapidsConf.scala:548).

Streaming (ISSUE 11): no strategy materializes a whole partition before
compute. Decode runs on named ``tpu-scan-prefetch-N`` threads
(``spark.rapids.tpu.sql.scan.prefetchThreads``; bounded join on shutdown —
the transport-thread discipline), batches are packed into the pinned
bounce-buffer staging arena on the prefetch thread, and the task thread
only performs the device upload — BEHIND semaphore admission and memory
reservation (GpuSemaphore.scala:74: acquire after host IO, before device
work) — while the pool decodes the next batches. Each yielded batch is
sliced to the autotuned target rows (plan/stage_compiler.tuned_batch_rows)
so downstream fused stages run at the largest safe capacity.

Predicate pushdown: pyarrow's parquet reader prunes row groups with min/max
stats from pushed filters — the same CPU-side ``filterBlocks`` role
(GpuParquetScan.scala:239-297).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Iterator, List, Optional

from .. import config as cfg
from ..analysis.contracts import exec_contract
from ..columnar import dtypes as dt
from ..columnar.batch import ColumnarBatch
from ..ops import expressions as ex
from ..plan import logical as lp
from ..plan.physical import (Partition, TpuExec, _reserve, _task_begin,
                             exec_metrics)
from . import expand_paths, read_file_to_arrow
from ..exec.tracing import trace_span

# pinned staging arena for scan uploads (exec/native_alloc bounce buffers):
# prefetch threads pack decoded batches here; oversize batches fall back to
# transient buffers (acquire returns None)
_STAGING_LOCK = threading.Lock()
_STAGING = None
_STAGING_ARENA_BYTES = 128 << 20


def _staging_acquire(nbytes: int):
    global _STAGING
    with _STAGING_LOCK:
        if _STAGING is None:
            from ..exec.native_alloc import BounceBufferManager
            _STAGING = BounceBufferManager(_STAGING_ARENA_BYTES)
        if nbytes > _STAGING_ARENA_BYTES // 2:
            return None
        return _STAGING.acquire(nbytes)


def _staging_release(window) -> None:
    if window is None:
        return
    with _STAGING_LOCK:
        if _STAGING is not None:
            _STAGING.release(window)


class _StagingTracker:
    """Owns every arena window one partition drain has acquired but not
    yet released. Staged preps can sit buffered ahead of the consumer (in
    the prefetch pipeline, or in a half-consumed prep list) — if the
    drain generator is abandoned mid-stream (limit early-exit, a failing
    sibling read), those windows would otherwise leak and permanently
    shrink the process-global arena. ``release_all`` runs in the drain's
    ``finally``. Windows are keyed by identity: memoryview equality
    compares CONTENT, and two zero-filled windows are equal."""

    def __init__(self):
        self._lock = threading.Lock()  # lint: raw-lock-ok per-partition-drain transient bookkeeping, dies with the generator
        self._open: Dict[int, Any] = {}
        self._closed = False

    def acquire(self, nbytes: int):
        with self._lock:
            if self._closed:
                return None
        w = _staging_acquire(nbytes)
        if w is None:
            return None
        with self._lock:
            if not self._closed:
                self._open[id(w)] = w
                return w
        # a straggler pack thread lost the race with release_all: hand the
        # window straight back (the prep falls back to a transient buffer)
        _staging_release(w)
        return None

    def release(self, w) -> None:
        if w is None:
            return
        with self._lock:
            if self._open.pop(id(w), None) is None:
                return                 # release_all already returned it
        _staging_release(w)

    def release_all(self) -> None:
        """Terminal: returns every outstanding window and refuses new
        acquisitions, so late prefetch-side packs cannot leak."""
        with self._lock:
            self._closed = True
            ws, self._open = list(self._open.values()), {}
        for w in ws:
            _staging_release(w)


def _pushdown_filters(exprs: List[ex.Expression]):
    """Translate simple predicates to pyarrow filter tuples (row-group prune)."""
    from ..ops import predicates as pr
    out = []
    for e in exprs:
        if isinstance(e, (pr.EqualTo, pr.LessThan, pr.LessThanOrEqual,
                          pr.GreaterThan, pr.GreaterThanOrEqual)):
            l, r = e.children
            if isinstance(l, ex.ColumnRef) and isinstance(r, ex.Literal) \
                    and r.value is not None:
                op = {pr.EqualTo: "=", pr.LessThan: "<", pr.LessThanOrEqual: "<=",
                      pr.GreaterThan: ">", pr.GreaterThanOrEqual: ">="}[type(e)]
                out.append((l.col_name, op, r.value))
    return out or None


class TpuFileScanExec(TpuExec):
    """GpuFileSourceScanExec / GpuBatchScanExec analog: a streaming batch
    ITERATOR — decode-ahead threads feed double-buffered staged uploads
    overlapping device compute; partitions never materialize."""

    CONTRACT = exec_contract(schema="defined", partitioning="source")
    METRICS = exec_metrics("bufferTime", "tpuDecodeTime")

    def __init__(self, plan: lp.FileScan, conf: Optional[cfg.TpuConf] = None):
        super().__init__()
        self.plan = plan
        self.conf = conf or cfg.TpuConf()
        self.files = expand_paths(plan.paths)
        from . import partition_schema
        want = set(plan.schema.names())
        self.pschema = dt.Schema([
            f for f in partition_schema(self.files, plan.paths)
            if f.name in want])
        # column pruning (planner's _prune_scan_columns): only decode/upload
        # referenced file columns; partition values are appended post-read
        proj = getattr(plan, "projection", None)
        pnames = {f.name for f in self.pschema}
        self.columns = ([c for c in proj if c not in pnames]
                        if proj else None)
        self.reader_type = str(
            self.conf.get_key("spark.rapids.tpu.sql.format.parquet.reader.type",
                              "COALESCING")).upper()
        # prefetch pool size: scan.prefetchThreads, unless the legacy
        # parquet multiThreadedRead.numThreads was set explicitly
        legacy_key = cfg.READER_THREADS.key
        if legacy_key in getattr(self.conf, "_settings", {}):
            self.num_threads = int(self.conf.get(cfg.READER_THREADS))
        else:
            self.num_threads = int(self.conf.get(cfg.SCAN_PREFETCH_THREADS))
        self.filters = _pushdown_filters(plan.pushed_filters) \
            if plan.fmt == "parquet" else None
        # autotuned rows per yielded batch (docs/fusion.md §4)
        from ..plan.stage_compiler import tuned_batch_rows
        self.target_rows = tuned_batch_rows(self.conf, self.plan.schema)

    @property
    def schema(self) -> dt.Schema:
        return self.plan.schema

    @property
    def output_partitions(self) -> int:
        if self.reader_type == "PERFILE":
            return max(1, len(self.files))
        return 1

    def execute(self) -> List[Partition]:
        if not self.files:
            def empty():
                return
                yield
            return [empty()]
        if self.reader_type == "MULTITHREADED":
            return [self._multithreaded()]
        if self.reader_type == "COALESCING" and self.plan.fmt != "csv":
            return [self._coalescing()]
        # PERFILE: one partition per file (Spark's FilePartition granularity,
        # the task-parallel unit) — multi-file scans drive distributed plans
        return [self._perfile(f) for f in self.files]

    # -- strategies ----------------------------------------------------------
    def _read(self, path: str):
        from ..ops.hashing import InputFileName
        InputFileName.set_current(path)
        t = read_file_to_arrow(self.plan.fmt, path, self.plan.options,
                               columns=self.columns, filters=self.filters,
                               roots=self.plan.paths, pschema=self.pschema)
        self.metrics.inc("bufferTime")
        return t

    def _preps_of(self, table, tracker: _StagingTracker) -> List[Any]:
        """Host half for one decoded table: slice to the autotuned batch
        rows, convert to padded numpy, and pack each slice into the pinned
        staging arena — all CPU work, safe on a prefetch thread before the
        task holds the semaphore."""
        out = []
        n = table.num_rows
        if n == 0:
            return out
        step = max(1, int(self.target_rows))
        # metered even off the task thread: the bag is thread-safe, and a
        # decode-bound scan must still show its cost in tpuDecodeTime
        with trace_span("scan_decode", self.metrics, "tpuDecodeTime"):
            for pos in range(0, n, step):
                piece = table.slice(pos, min(step, n - pos))
                prep = ColumnarBatch.prep_from_arrow(piece)
                out.append(ColumnarBatch.stage_prepped(prep,
                                                       tracker.acquire))
        return out

    def _upload(self, prep, tracker: _StagingTracker) -> ColumnarBatch:
        """Device half: admission-checked single-transfer upload of one
        staged batch (the task-thread side of the double buffer)."""
        _reserve(ColumnarBatch.prepped_size_bytes(prep))
        window = ColumnarBatch.staged_window(prep)
        try:
            with trace_span("scan_upload", self.metrics, "tpuDecodeTime"):
                batch = ColumnarBatch.upload_prepped(prep)
        finally:
            tracker.release(window)
        self.metrics.inc("numOutputRows", batch.num_rows_raw)
        self.metrics.inc("numOutputBatches")
        return batch

    def _drain(self, prep_lists, tracker: _StagingTracker) -> Partition:
        """Yield uploaded batches from an iterator of prep lists; the
        semaphore is taken once host-side input exists (the reference's
        acquire-after-host-IO ordering). Abandonment at any point —
        early-exit consumers, upstream decode errors — returns every
        still-staged arena window."""
        first = True
        try:
            for preps in prep_lists:
                for prep in preps:
                    if first:
                        _task_begin()
                        first = False
                    yield self._upload(prep, tracker)
        finally:
            # stop the upstream pipeline first (ordered_prefetch joins its
            # workers bounded), then return every still-staged window
            close = getattr(prep_lists, "close", None)
            if close is not None:
                try:
                    close()
                except Exception:
                    pass
            tracker.release_all()

    def _perfile(self, f: str) -> Partition:
        tracker = _StagingTracker()

        def lists():
            yield self._preps_of(self._read(f), tracker)
        return self._drain(lists(), tracker)

    def _coalescing(self) -> Partition:
        """Combine small files up to the batch byte target before one
        staged upload (MultiFileParquetPartitionReader's coalesce
        behavior): decode runs ahead on the prefetch pool, and the
        concat/pad/pack of each coalesced group runs on a dedicated pack
        thread, so the task thread pays only reserve+upload; large file
        groups stream out in autotuned-row slices."""
        import pyarrow as pa
        from ..exec.tasks import ordered_prefetch, prefetch_map
        target = self.conf.batch_size_bytes
        tracker = _StagingTracker()

        def groups():
            pending, pending_bytes = [], 0
            for t in ordered_prefetch(self.files, self._read,
                                      threads=self.num_threads,
                                      depth=max(2, self.num_threads),
                                      name="tpu-scan-prefetch"):
                if t.num_rows == 0:
                    continue
                pending.append(t)
                pending_bytes += t.nbytes
                if pending_bytes >= target:
                    yield pending
                    pending, pending_bytes = [], 0
            if pending:
                yield pending

        def pack(tables):
            table = tables[0] if len(tables) == 1 else \
                pa.concat_tables(tables, promote_options="permissive")
            return self._preps_of(table, tracker)

        return self._drain(
            prefetch_map(groups(), pack, depth=2,
                         name="tpu-scan-prefetch-pack"),
            tracker)

    def _multithreaded(self) -> Partition:
        """Background prefetch threads (MultiFileCloudParquetPartitionReader):
        each ``tpu-scan-prefetch-N`` worker reads, decodes AND stages one
        file's batches; the task thread drains uploads batch-by-batch with
        at most ~2 files of staged batches buffered ahead (double
        buffering) — a partition is never materialized."""
        from ..exec.tasks import ordered_prefetch
        tracker = _StagingTracker()
        return self._drain(ordered_prefetch(
            self.files, lambda f: self._preps_of(self._read(f), tracker),
            threads=self.num_threads, depth=max(2, self.num_threads),
            name="tpu-scan-prefetch"), tracker)

    def _node_string(self):
        return (f"TpuFileScanExec[{self.plan.fmt}, {len(self.files)} files, "
                f"{self.reader_type}]")
