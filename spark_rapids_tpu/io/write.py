"""File writers: Parquet/ORC/CSV output (GpuDataWritingCommandExec analog).

Reference: ``GpuParquetFileFormat.scala`` / ``GpuOrcFileFormat`` write through
cuDF TableWriter on device; ``GpuFileFormatWriter.scala`` handles partitioned
writes (sort by partition cols, split, one writer per partition dir). Here the
device batch downloads to Arrow and pyarrow writes — the encode boundary moves
to CPU exactly like the decode side (DESIGN.md §7).
"""

from __future__ import annotations

import os
import shutil
import uuid
from typing import Dict, List

from ..analysis.contracts import exec_contract
from ..columnar import dtypes as dt
from ..columnar.batch import ColumnarBatch
from ..plan import logical as lp
from ..plan.physical import Partition, TpuExec, exec_metrics


class TpuWriteFileExec(TpuExec):
    CONTRACT = exec_contract(schema="defined", partitioning="preserve",
                             extras=("empty_schema",))
    METRICS = exec_metrics()

    def __init__(self, child: TpuExec, plan: lp.WriteFile):
        super().__init__(child)
        self.plan = plan

    @property
    def schema(self) -> dt.Schema:
        return dt.Schema([])

    def execute(self) -> List[Partition]:
        path = self.plan.path
        mode = self.plan.mode
        if os.path.exists(path):
            if mode == "overwrite":
                shutil.rmtree(path) if os.path.isdir(path) else os.unlink(path)
            elif mode in ("error", "errorifexists"):
                raise FileExistsError(f"path {path} already exists")
            elif mode == "ignore":
                def noop():
                    return
                    yield
                return [noop()]
        os.makedirs(path, exist_ok=True)

        def write_part(idx: int, part: Partition) -> Partition:
            batches = list(part)
            if batches:
                self._write_batches(idx, batches)
            return
            yield

        parts = self.children[0].execute()
        out = [write_part(i, p) for i, p in enumerate(parts)]
        # force execution eagerly (write is an action)
        for o in out:
            for _ in o:
                pass
        self._write_success()
        def done():
            return
            yield
        return [done()]

    def _write_success(self):
        with open(os.path.join(self.plan.path, "_SUCCESS"), "w"):
            pass

    def _write_batches(self, idx: int, batches: List[ColumnarBatch]) -> None:
        import pyarrow as pa
        tables = [b.to_arrow() for b in batches]
        table = tables[0] if len(tables) == 1 else pa.concat_tables(tables)
        if self.plan.partition_by:
            self._write_partitioned(idx, table)
            return
        self._write_table(table, self.plan.path, idx)

    def _write_partitioned(self, idx: int, table) -> None:
        """Partitioned write: split by partition column values into
        key=value/ dirs (GpuFileFormatWriter partitioned path)."""
        import pyarrow as pa
        import pyarrow.compute as pc
        pcols = self.plan.partition_by
        rest = [n for n in table.schema.names if n not in pcols]
        # group rows by partition tuple
        keys = list(zip(*[table.column(c).to_pylist() for c in pcols]))
        groups: Dict[tuple, List[int]] = {}
        for i, k in enumerate(keys):
            groups.setdefault(k, []).append(i)
        for k, rows in groups.items():
            sub = table.take(rows).select(rest)
            dirname = "/".join(
                f"{c}={'__HIVE_DEFAULT_PARTITION__' if v is None else v}"
                for c, v in zip(pcols, k))
            outdir = os.path.join(self.plan.path, dirname)
            os.makedirs(outdir, exist_ok=True)
            self._write_table(sub, outdir, idx)

    def _write_table(self, table, outdir: str, idx: int) -> None:
        fmt = self.plan.fmt
        name = f"part-{idx:05d}-{uuid.uuid4().hex[:12]}"
        if fmt == "parquet":
            import pyarrow.parquet as pq
            pq.write_table(table, os.path.join(outdir, name + ".parquet"))
        elif fmt == "orc":
            import pyarrow.orc as orc
            orc.write_table(table, os.path.join(outdir, name + ".orc"))
        elif fmt == "csv":
            import pyarrow.csv as pcsv
            header = str(self.plan.options.get("header", "false")).lower() == "true"
            opts = pcsv.WriteOptions(include_header=header)
            pcsv.write_csv(table, os.path.join(outdir, name + ".csv"), opts)
        else:
            raise ValueError(f"unsupported write format {fmt}")
