"""ML integration: columnar export to jax / numpy / torch.

Reference: docs/ml-integration.md + ColumnarRdd (SURVEY.md §2.4 #34)."""

from .export import (collect_device, to_device_arrays, to_feature_matrix,
                     to_numpy, to_torch)

__all__ = ["collect_device", "to_device_arrays", "to_feature_matrix",
           "to_numpy", "to_torch"]
