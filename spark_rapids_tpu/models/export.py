"""Columnar ML export: DataFrame -> device arrays / framework tensors.

Reference: ``InternalColumnarRddConverter.scala:42-475`` + ``ColumnarRdd
.scala:41-46`` — the zero-copy DataFrame -> RDD[cudf.Table] handoff that
feeds XGBoost's DMatrix builder, detected via the transition-tagged
``GpuColumnarToRowExec`` (GpuTransitionOverrides.scala:369-374).

TPU-standalone: the engine's batches already hold jax device arrays, so the
export IS zero-copy — ``collect_device`` returns the columns' arrays still
resident on device; ``to_feature_matrix`` stacks numeric columns into the
``[n_rows, n_features]`` f32 design matrix an XGBoost/linear trainer wants
(one XLA transpose-free stack, no host round-trip); ``to_torch`` /
``to_numpy`` cross to host frameworks explicitly.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..columnar import dtypes as dt
from ..columnar.batch import ColumnarBatch


def collect_device(df) -> ColumnarBatch:
    """Materialize a DataFrame fully on device (the ColumnarRdd.convert
    analog: batches stay as device arrays, no row conversion)."""
    return df.collect_batch()


def to_device_arrays(df) -> Dict[str, Tuple]:
    """{column name: (data, validity)} jax arrays, sliced to num_rows."""
    batch = collect_device(df)
    out = {}
    for f, c in zip(batch.schema, batch.columns):
        out[f.name] = (c.data[:batch.num_rows], c.validity[:batch.num_rows])
    return out


def to_feature_matrix(df, feature_cols: Optional[List[str]] = None,
                      label_col: Optional[str] = None,
                      nan_for_null: bool = True):
    """(features f32[n, k], labels f32[n] | None): the DMatrix handoff.

    NULLs become NaN (XGBoost's missing-value convention) when
    ``nan_for_null``; non-numeric columns are rejected."""
    import jax.numpy as jnp
    batch = collect_device(df)
    names = feature_cols or [
        f.name for f in batch.schema
        if f.name != label_col and (f.dtype.is_numeric or f.dtype == dt.BOOL)]
    cols = []
    for n in names:
        c = batch.column(n)
        f = batch.schema[batch.schema.index_of(n)]
        if not (f.dtype.is_numeric or f.dtype == dt.BOOL):
            raise TypeError(f"feature column {n!r} is {f.dtype}, not numeric")
        d = c.data[:batch.num_rows].astype(jnp.float32)
        if nan_for_null:
            d = jnp.where(c.validity[:batch.num_rows], d, jnp.nan)
        cols.append(d)
    feats = jnp.stack(cols, axis=1) if cols else jnp.zeros((0, 0), jnp.float32)
    labels = None
    if label_col is not None:
        lc = batch.column(label_col)
        labels = lc.data[:batch.num_rows].astype(jnp.float32)
    return feats, labels


def to_numpy(df) -> Dict[str, "np.ndarray"]:
    """Host numpy arrays (masked: NULL -> NaN for floats, None-able object
    arrays are avoided — validity returned alongside)."""
    import numpy as np
    out = {}
    for name, (data, valid) in to_device_arrays(df).items():
        out[name] = (np.asarray(data), np.asarray(valid))
    return out


def to_torch(df, feature_cols: Optional[List[str]] = None,
             label_col: Optional[str] = None):
    """(features, labels) torch CPU tensors for torch-side training."""
    import numpy as np
    import torch
    feats, labels = to_feature_matrix(df, feature_cols, label_col)
    t_feats = torch.from_numpy(np.array(feats))
    t_labels = torch.from_numpy(np.array(labels)) \
        if labels is not None else None
    return t_feats, t_labels
