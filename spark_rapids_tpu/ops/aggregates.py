"""Group-by and reduction aggregate kernels: the cuDF ``groupBy.aggregate`` analog.

Reference: ``org/apache/spark/sql/rapids/AggregateFunctions.scala`` (531 LoC) —
each Spark aggregate decomposes into ``CudfAggregate`` update/merge pairs
(average = sum + count; the hash-agg exec drives update-aggregation per batch and
merge-aggregation across batches, aggregate.scala:305-560).

TPU-first design (DESIGN.md §3): no device hash tables. Group-by is sort-based:
  lexsort rows by the group keys -> segment-start flags -> segment ids ->
  ``jax.ops.segment_*`` reductions with num_segments = capacity (static shape).
Group count travels as a device scalar; group keys are the key values at segment
starts, compacted to the front. SQL null semantics: aggregates skip NULL inputs;
an all-NULL (or empty) group yields NULL for sum/min/max/avg and 0 for count.
"""

from __future__ import annotations

from typing import List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..columnar import dtypes as dt
from ..columnar.column import Column
from . import kernels as K


class AggSpec(NamedTuple):
    """One aggregation over one input column (None input = COUNT(*))."""
    op: str                      # count/count_star/sum/min/max/avg/first/last
    column: Optional[Column]
    ignore_nulls: bool = True    # for first/last


def _sum_dtype(in_dtype: dt.DType) -> dt.DType:
    """Spark widens SUM: integral -> bigint, floating -> double."""
    if in_dtype.is_integral or in_dtype == dt.BOOL:
        return dt.INT64
    return dt.FLOAT64


def result_dtype(op: str, in_dtype: Optional[dt.DType]) -> dt.DType:
    if op in ("count", "count_star"):
        return dt.INT64
    if op == "sum":
        return _sum_dtype(in_dtype)
    if op == "avg":
        return dt.FLOAT64
    return in_dtype  # min/max/first/last preserve type


# ---------------------------------------------------------------------------
# Segment reductions (update phase)
# ---------------------------------------------------------------------------

def _seg_sum(data, seg_ids, num_segments):
    return jax.ops.segment_sum(data, seg_ids, num_segments=num_segments)


def _seg_min(data, seg_ids, num_segments):
    return jax.ops.segment_min(data, seg_ids, num_segments=num_segments)


def _seg_max(data, seg_ids, num_segments):
    return jax.ops.segment_max(data, seg_ids, num_segments=num_segments)


def _masked(data, mask, fill):
    return jnp.where(mask, data, jnp.asarray(fill, data.dtype))


def _string_ordinal_minmax(col: Column, contrib, seg_ids, num_segments: int,
                           want_min: bool):
    """Min/max for strings: reduce over the *row index* ordered by the encoded
    string key, then gather the winning row's bytes."""
    cap = col.capacity
    words = K.pack_string_words(col.data, col.lengths)
    # build a sortable composite: argsort rows by string order, then the rank of
    # each row is a uint32 we can min/max within segments
    order = jnp.lexsort(tuple(reversed(
        [w for w in words.T] + [col.lengths.astype(jnp.uint32)])))
    rank = jnp.zeros(cap, dtype=jnp.int32).at[order].set(
        jnp.arange(cap, dtype=jnp.int32))
    sentinel = jnp.int32(cap) if want_min else jnp.int32(-1)
    r = jnp.where(contrib, rank, sentinel)
    red = _seg_min(r, seg_ids, num_segments) if want_min else \
        _seg_max(r, seg_ids, num_segments)
    has = red != sentinel
    win_rank = jnp.where(has, red, 0)
    # rank -> row index
    win_row = order[jnp.clip(win_rank, 0, cap - 1)]
    return win_row, has


def segment_aggregate(spec: AggSpec, seg_ids: jnp.ndarray, live: jnp.ndarray,
                      capacity: int,
                      num_segments: Optional[int] = None) -> Column:
    """Update-phase aggregation: reduce each segment of input rows to one output
    row per group id. Output column has ``num_segments`` slots (group g at
    slot g; defaults to ``capacity`` for the sort-based path where segment ids
    live in row space); slots beyond the group count are zeroed+invalid by
    construction because no row contributes to them.
    """
    ns = capacity if num_segments is None else num_segments
    op = spec.op
    if op == "count_star":
        data = _seg_sum(live.astype(jnp.int64), seg_ids, ns)
        valid = _seg_sum(live.astype(jnp.int32), seg_ids, ns) > 0
        return Column(dt.INT64, data, valid)

    col = spec.column
    contrib = live & col.validity
    if op == "count":
        data = _seg_sum(contrib.astype(jnp.int64), seg_ids, ns)
        valid = _seg_sum(live.astype(jnp.int32), seg_ids, ns) > 0
        return Column(dt.INT64, data, valid)

    group_has = _seg_sum(contrib.astype(jnp.int32), seg_ids, ns) > 0

    if op == "sum":
        out_t = _sum_dtype(col.dtype)
        d = _masked(col.data.astype(out_t.numpy_dtype), contrib, 0)
        data = _seg_sum(d, seg_ids, ns)
        return Column(out_t, _masked(data, group_has, 0), group_has)

    if op == "avg":
        d = _masked(col.data.astype(jnp.float64), contrib, 0.0)
        s = _seg_sum(d, seg_ids, ns)
        c = _seg_sum(contrib.astype(jnp.float64), seg_ids, ns)
        data = jnp.where(group_has, s / jnp.maximum(c, 1.0), 0.0)
        return Column(dt.FLOAT64, data, group_has)

    if op in ("min", "max"):
        if col.dtype == dt.STRING:
            win_row, has = _string_ordinal_minmax(col, contrib, seg_ids, ns,
                                                  want_min=(op == "min"))
            out = K.gather_column(col, win_row, out_valid=has)
            return out
        if col.dtype.is_floating:
            # Spark total order: NaN largest. Use +/-inf fill, restore NaN via flags.
            is_nan = jnp.isnan(col.data) & contrib
            seg_nan = _seg_sum(is_nan.astype(jnp.int32), seg_ids, ns) > 0
            seg_non_nan = _seg_sum((contrib & ~is_nan).astype(jnp.int32),
                                   seg_ids, ns) > 0
            fill = jnp.inf if op == "min" else -jnp.inf
            d = _masked(col.data, contrib & ~is_nan, fill)
            red = (_seg_min if op == "min" else _seg_max)(d, seg_ids, ns)
            if op == "min":
                data = jnp.where(seg_non_nan, red, jnp.nan)  # all-NaN group -> NaN
            else:
                data = jnp.where(seg_nan, jnp.nan, red)      # any NaN -> NaN max
            data = jnp.where(group_has, data, 0.0).astype(col.data.dtype)
            return Column(col.dtype, data, group_has)
        if col.dtype == dt.BOOL:
            d = _masked(col.data.astype(jnp.int32), contrib, 1 if op == "min" else 0)
            red = (_seg_min if op == "min" else _seg_max)(d, seg_ids, ns)
            data = (red > 0) & group_has
            return Column(dt.BOOL, data, group_has)
        info = jnp.iinfo(col.data.dtype)
        fill = info.max if op == "min" else info.min
        d = _masked(col.data, contrib, fill)
        red = (_seg_min if op == "min" else _seg_max)(d, seg_ids, ns)
        return Column(col.dtype, _masked(red, group_has, 0), group_has)

    if op in ("first", "last"):
        idx = jnp.arange(capacity, dtype=jnp.int32)
        pick_from = contrib if spec.ignore_nulls else live
        grp_has = _seg_sum(pick_from.astype(jnp.int32), seg_ids, ns) > 0
        if op == "first":
            r = jnp.where(pick_from, idx, capacity)
            win = _seg_min(r, seg_ids, ns)
        else:
            r = jnp.where(pick_from, idx, -1)
            win = _seg_max(r, seg_ids, ns)
        win = jnp.clip(win, 0, capacity - 1)
        return K.gather_column(col, win, out_valid=grp_has)

    raise ValueError(f"unknown aggregate op {op!r}")


# ---------------------------------------------------------------------------
# Whole group-by driver
# ---------------------------------------------------------------------------

def groupby_aggregate(key_cols: Sequence[Column], specs: Sequence[AggSpec],
                      num_rows, capacity: int,
                      live_mask: Optional[jnp.ndarray] = None
                      ) -> Tuple[List[Column], List[Column], jnp.ndarray]:
    """Sort-based group-by: returns (group key columns, agg result columns,
    device group count). All outputs have ``capacity`` slots with groups
    compacted to the front. ``live_mask`` (folded-filter rows) sorts dead
    rows last instead of requiring a compacted input.

    cuDF analog: ``Table.groupBy(...).aggregate(...)`` as driven by
    GpuHashAggregateExec (aggregate.scala:427-485).
    """
    if live_mask is not None:
        num_rows = jnp.sum(live_mask).astype(jnp.int32)
    sort_keys = [K.SortKey(c) for c in key_cols]
    order = K.sort_indices(sort_keys, num_rows, capacity,
                           live_mask=live_mask)
    sorted_keys = [K.gather_column(c, order) for c in key_cols]
    live = jnp.arange(capacity) < num_rows
    starts = K.segment_starts_from_sorted_keys(sorted_keys, num_rows, capacity)
    seg_ids = K.segment_ids(starts)
    n_groups = jnp.sum(starts).astype(jnp.int32)

    # group keys: gather the first row of each segment to the front
    start_perm, _ = K.compaction_indices(starts)
    group_live = jnp.arange(capacity) < n_groups
    out_keys = [K.gather_column(c, start_perm, out_valid=group_live)
                for c in sorted_keys]

    out_aggs: List[Column] = []
    for spec in specs:
        s = spec
        if spec.column is not None:
            s = spec._replace(column=K.gather_column(spec.column, order))
        agg = segment_aggregate(s, seg_ids, live, capacity)
        # mask agg slots beyond the group count (paranoia: segment ids of padding
        # rows alias the last group, which is a real group, so data is fine; but
        # enforce the padding invariant explicitly)
        out_aggs.append(_mask_to(agg, group_live))
    return out_keys, out_aggs, n_groups


def reduce_aggregate(specs: Sequence[AggSpec], num_rows, capacity: int,
                     live_mask: Optional[jnp.ndarray] = None
                     ) -> List[Column]:
    """Grouping-free reduction (SELECT SUM(x) FROM t): one output row at
    slot 0 of a min-bucket (128-slot) column.

    Empty input: count = 0, everything else NULL (aggregate.scala:487-505
    empty-input reduction semantics). ``live_mask`` replaces the prefix
    row mask for folded-filter inputs (no compaction needed at all here).
    Internally this is ``segment_aggregate`` with ONE segment — a 1-slot
    segment reduction lowers to a plain masked reduce, not the
    full-capacity segment machinery the sort path needs (which cost
    ~100 ms per 1M-row batch here, ~100x the actual reduction).
    """
    seg_ids = jnp.zeros(capacity, dtype=jnp.int32)
    live = live_mask if live_mask is not None \
        else jnp.arange(capacity) < num_rows
    out_cap = 128                       # MIN_CAPACITY bucket
    out: List[Column] = []
    one = jnp.arange(out_cap) < 1
    for spec in specs:
        agg = segment_aggregate(spec, seg_ids, live, capacity,
                                num_segments=1)
        pad = out_cap - 1
        if agg.dtype.var_width:
            agg = Column(agg.dtype, jnp.pad(agg.data, ((0, pad), (0, 0))),
                         jnp.pad(agg.validity, (0, pad)),
                         jnp.pad(agg.lengths, (0, pad)))
        else:
            agg = Column(agg.dtype, jnp.pad(agg.data, (0, pad)),
                         jnp.pad(agg.validity, (0, pad)))
        if spec.op in ("count", "count_star"):
            # count of empty input is 0 (valid), not NULL
            data = jnp.where(one, agg.data, 0)
            out.append(Column(dt.INT64, data, one))
        else:
            out.append(_mask_to(agg, one))
    return out


# ---------------------------------------------------------------------------
# MXU fast path: one-hot matmul segment reductions (TPU-native)
# ---------------------------------------------------------------------------
#
# Scatter-based segment_sum is the slowest primitive on TPU (random HBM
# writes); the systolic array is the fastest. For bounded group counts the
# reduction is a matmul: sum_g = one_hot(seg_ids, K)^T @ values, generated
# on the fly and fed to the MXU. float64 values ride a hi/lo float32 split
# with chunked float64 accumulation (~1e-5 rel — inside the reference's own
# benchmark epsilon, BenchUtils.compareResults epsilon=1e-4, and the spirit
# of its variableFloatAgg conf). Counts are exact (integer sums < 2^24 per
# chunk are exact in f32, chunk totals accumulate in f64).

MATMUL_MAX_GROUPS = 4096
_MM_CHUNK = 1 << 17


def _mm_chunks(n: int) -> int:
    return max(1, n // _MM_CHUNK)


def _matmul_segment_sum_f64(data: jnp.ndarray, contrib: jnp.ndarray,
                            seg_ids: jnp.ndarray, K: int) -> jnp.ndarray:
    cap = data.shape[0]
    ch = _mm_chunks(cap)
    d = jnp.where(contrib, data, 0.0)
    ids = jnp.where(contrib, seg_ids, K)        # masked rows -> dropped slot
    hi = d.astype(jnp.float32)
    lo = (d - hi.astype(jnp.float64)).astype(jnp.float32)
    oh = jax.nn.one_hot(ids.reshape(ch, -1), K, dtype=jnp.float32)
    shi = jnp.einsum("cnk,cn->ck", oh, hi.reshape(ch, -1),
                     precision=jax.lax.Precision.HIGHEST)
    slo = jnp.einsum("cnk,cn->ck", oh, lo.reshape(ch, -1),
                     precision=jax.lax.Precision.HIGHEST)
    return (shi.astype(jnp.float64) + slo.astype(jnp.float64)).sum(0)


def _matmul_segment_count(contrib: jnp.ndarray, seg_ids: jnp.ndarray,
                          K: int) -> jnp.ndarray:
    cap = contrib.shape[0]
    ch = _mm_chunks(cap)
    ids = jnp.where(contrib, seg_ids, K)
    oh = jax.nn.one_hot(ids.reshape(ch, -1), K, dtype=jnp.float32)
    c = jnp.einsum("cnk->ck", oh,
                   precision=jax.lax.Precision.HIGHEST)
    return c.astype(jnp.int64).sum(0)


def _matmul_supported(spec: AggSpec) -> bool:
    if spec.op in ("count", "count_star"):
        return True
    if spec.op in ("sum", "avg") and spec.column is not None and \
            spec.column.dtype.is_floating:
        return True
    return False


def segment_aggregate_matmul(spec: AggSpec, seg_ids: jnp.ndarray,
                             live: jnp.ndarray, K: int) -> Column:
    """MXU reduction to K group slots (first K slots of capacity outputs)."""
    op = spec.op
    if op == "count_star":
        data = _matmul_segment_count(live, seg_ids, K)
        return Column(dt.INT64, data, jnp.ones(K, jnp.bool_))
    col = spec.column
    contrib = live & col.validity
    cnt = _matmul_segment_count(contrib, seg_ids, K)
    if op == "count":
        return Column(dt.INT64, cnt, jnp.ones(K, jnp.bool_))
    has = cnt > 0
    s = _matmul_segment_sum_f64(col.data.astype(jnp.float64), contrib,
                                seg_ids, K)
    if op == "sum":
        return Column(dt.FLOAT64, jnp.where(has, s, 0.0), has)
    if op == "avg":
        data = jnp.where(has, s / jnp.maximum(cnt.astype(jnp.float64), 1.0),
                         0.0)
        return Column(dt.FLOAT64, data, has)
    raise ValueError(f"matmul path does not support {op}")


# ---------------------------------------------------------------------------
# Dense-range MXU group-by: the perfect-hash fast path (sort-free)
# ---------------------------------------------------------------------------
#
# When a single fixed-width integral key spans a small range (DuckDB's
# "perfect hash aggregate" condition; scans know key ranges from parquet
# row-group statistics), the group slot is simply ``key - rmin``: no sort, no
# compaction, no large gathers. Every aggregate becomes ONE chunked one-hot
# matmul on the MXU plus a K-sized cleanup. This is the fastest group-by
# shape on TPU by ~50x over the sort-based path (the whole pipeline is
# elementwise passes + systolic-array matmuls at full HBM bandwidth).
#
# Exactness: counts ride f32 per-chunk (chunk = 2^17 < 2^24 exact),
# accumulated in i64. Float sums ride a hi/lo f32 split with f64 chunk
# accumulation (~1e-6 abs; values must be within F32_SAFE_ABSMAX — the
# dispatch checks and falls back). Integer sums are bit-exact: 16 nibble
# planes per i64, each plane's per-chunk f32 sum <= 15 * 2^17 < 2^24,
# recombined with shifts in i64 (wraparound = Spark bigint overflow).
# min/max/first/last use K-sized segment scatters (cheap at dense K).

DENSE_MAX_SLOTS = 4096
_DENSE_CHUNK = 1 << 17


def dense_supported_key(col: Column) -> bool:
    return col.dtype in (dt.INT8, dt.INT16, dt.INT32, dt.INT64, dt.BOOL,
                         dt.DATE, dt.TIMESTAMP)


# chunk partial sums of the hi/lo f32 planes must stay finite in f32:
# |v| * chunk_rows must be < f32 max (3.4e38); 1e33 * 2^17 ~ 1.3e38.
F32_SAFE_ABSMAX = 1e33


def dense_key_stats(key_col: Column, num_rows,
                    extra_mask: Optional[jnp.ndarray] = None,
                    float_cols: Sequence[Column] = ()):
    """Dense-dispatch statistics in ONE device computation.

    Returns ``(rmin, decision)``: ``rmin`` stays a device i64 scalar (exact,
    fed straight into ``groupby_dense``); ``decision`` is one f64 vector
    ``[span, n_usable, *absmax_per_float_col]`` — a single host sync decides
    the static slot count and whether every float agg column is within the
    f32-safe range (values beyond it would overflow the hi/lo split).
    """
    cap = key_col.capacity
    live = jnp.arange(cap) < num_rows
    if extra_mask is not None:
        live = live & extra_mask
    usable = live & key_col.validity
    k = key_col.data.astype(jnp.int64)
    imax = jnp.iinfo(jnp.int64).max
    imin = jnp.iinfo(jnp.int64).min
    rmin = jnp.min(jnp.where(usable, k, imax))
    rmax = jnp.max(jnp.where(usable, k, imin))
    nu = jnp.sum(usable.astype(jnp.int32))
    # span in f64 (approximate is fine: it only gates the <= DENSE_MAX_SLOTS
    # test, where exact small spans are exactly representable)
    span = jnp.where(nu > 0,
                     rmax.astype(jnp.float64) - rmin.astype(jnp.float64), 0.0)
    rmin = jnp.where(nu > 0, rmin, 0)
    parts = [span, nu.astype(jnp.float64)]
    for c in float_cols:
        contrib = live & c.validity
        a = jnp.abs(c.data)
        a = jnp.where(contrib & ~jnp.isnan(c.data), a, 0.0)  # NaN sums are
        parts.append(jnp.max(a).astype(jnp.float64))         # NaN either way
    return rmin, jnp.stack(parts)


def _onehot_feature_sums(seg: jnp.ndarray, feats: Sequence[jnp.ndarray],
                         K_slots: int) -> jnp.ndarray:
    """sum of each feature per slot via ONE chunked one-hot matmul; f64[K, F].

    ``feats`` is a list of f32[cap] arrays; they are stacked per chunk inside
    the scan body so the full [cap, F] matrix never materializes in HBM.

    Non-bucketed capacities are zero-padded up to a multiple of _DENSE_CHUNK
    so (a) the chunk reshape is always legal for any public caller and (b)
    per-chunk rows never exceed _DENSE_CHUNK — the bound the f32-exactness
    analysis (top of this section) assumes.
    """
    cap = seg.shape[0]
    if cap <= _DENSE_CHUNK:
        ch = 1
    else:
        ch = -(-cap // _DENSE_CHUNK)
        padded = ch * _DENSE_CHUNK
        if padded != cap:
            pad = padded - cap
            # padded rows contribute 0 to every feature plane regardless of
            # their (zero) segment id
            seg = jnp.concatenate([seg, jnp.zeros(pad, seg.dtype)])
            feats = [jnp.concatenate([f, jnp.zeros(pad, f.dtype)])
                     for f in feats]
            cap = padded

    def body(acc, xs):
        s, fs = xs
        f = jnp.stack(fs, axis=-1)
        oh = jax.nn.one_hot(s, K_slots, dtype=jnp.float32)
        p = jnp.einsum("nk,nf->kf", oh, f,
                       precision=jax.lax.Precision.HIGHEST)
        return acc + p.astype(jnp.float64), None

    acc, _ = jax.lax.scan(
        body, jnp.zeros((K_slots, len(feats)), jnp.float64),
        (seg.reshape(ch, -1), tuple(f.reshape(ch, -1) for f in feats)))
    return acc


def _int_nibble_planes(data: jnp.ndarray, contrib: jnp.ndarray
                       ) -> List[jnp.ndarray]:
    """16 f32 nibble planes of an int64; per-chunk f32 sums stay exact."""
    u = data.astype(jnp.int64).astype(jnp.uint64)
    return [jnp.where(contrib,
                      ((u >> jnp.uint64(4 * p)) & jnp.uint64(0xF)
                       ).astype(jnp.float32), 0.0)
            for p in range(16)]


def _recombine_nibble_sums(acc: jnp.ndarray) -> jnp.ndarray:
    """i64 totals from 16 nibble-plane f64 sums (wraps like Spark bigint)."""
    total = jnp.zeros(acc.shape[0], dtype=jnp.uint64)
    for p in range(16):
        total = total + (acc[:, p].astype(jnp.uint64) << jnp.uint64(4 * p))
    return total.astype(jnp.int64)


def groupby_dense(key_col: Column, specs: Sequence[AggSpec], num_rows,
                  K_slots: int, rmin,
                  extra_mask: Optional[jnp.ndarray] = None
                  ) -> Tuple[List[Column], List[Column], jnp.ndarray]:
    """Dense-range group-by. Fully traceable (jit-safe): only ``K_slots`` is
    static; ``rmin``/``num_rows`` may be device scalars.

    Caller contract: every live non-NULL key satisfies
    ``0 <= key - rmin <= K_slots - 2`` (slot ``K_slots - 1`` is reserved for
    the NULL-key group, which Spark keeps as a real group). Outputs are
    compacted to the front, key-ordered with the NULL group last; returns
    (key columns, agg columns, device group count) at K_slots capacity.
    """
    cap = key_col.capacity
    live = jnp.arange(cap) < num_rows
    if extra_mask is not None:
        live = live & extra_mask
    key_ok = live & key_col.validity
    k_i = key_col.data.astype(jnp.int64)
    null_slot = jnp.int32(K_slots - 1)
    seg = jnp.where(key_ok, (k_i - rmin).astype(jnp.int32), null_slot)
    seg = jnp.clip(jnp.where(live, seg, null_slot), 0, K_slots - 1)

    # Plan every matmul-reducible feature into ONE chunked one-hot scan
    # (occupancy + per-column contrib counts + hi/lo value planes + int
    # nibble planes), then assemble per-spec outputs from the [K, F] sums.
    feats: List[jnp.ndarray] = [live.astype(jnp.float32)]   # 0: occupancy
    feat_idx = {}

    def add_feats(key, build_list) -> int:
        """Register feature array(s) once per (role, column); return index."""
        if key not in feat_idx:
            feat_idx[key] = len(feats)
            built = build_list()
            feats.extend(built if isinstance(built, list) else [built])
        return feat_idx[key]

    plans = []
    for spec in specs:
        op = spec.op
        if op == "count_star":
            plans.append(("count_star",))
            continue
        col = spec.column
        contrib = live & col.validity
        cid = id(col.data)
        if op in ("min", "max", "first", "last"):
            # scatter segment reductions are cheap at dense K; reuse the
            # canonical Spark semantics (NaN total order, sentinels, nulls)
            plans.append(("done", segment_aggregate(spec, seg, live, cap,
                                                    num_segments=K_slots)))
            continue
        ci = add_feats(("contrib", cid),
                       lambda c=contrib: c.astype(jnp.float32))
        if op == "count":
            plans.append(("count", ci))
        elif op == "sum" and (col.dtype.is_integral or col.dtype == dt.BOOL):
            ni = add_feats(("nibbles", cid),
                           lambda c=col, m=contrib: _int_nibble_planes(
                               c.data, m))
            plans.append(("int_sum", ni, ci))
        elif op in ("sum", "avg"):
            # NaN contributions are excluded from the matmul features (0*NaN
            # would poison every slot in the chunk) and re-introduced per
            # slot via a NaN-count feature: any NaN in a group -> NaN result
            def hilo(c=col, m=contrib):
                d = c.data.astype(jnp.float64)
                nan = jnp.isnan(d)
                hi = jnp.where(nan, 0.0, d).astype(jnp.float32)
                lo = (jnp.where(nan, 0.0, d)
                      - hi.astype(jnp.float64)).astype(jnp.float32)
                z = jnp.float32(0)
                mnn = m & ~nan
                return [jnp.where(mnn, hi, z), jnp.where(mnn, lo, z),
                        (m & nan).astype(jnp.float32)]
            hl = add_feats(("hilo", cid), hilo)
            plans.append((op, hl, ci))
        else:
            raise ValueError(f"dense path does not support {op!r}")

    acc = _onehot_feature_sums(seg, feats, K_slots)
    occupancy = acc[:, 0]
    present = occupancy > 0

    slot_aggs: List[Column] = []
    for plan in plans:
        kind = plan[0]
        if kind == "done":
            slot_aggs.append(plan[1])
        elif kind == "count_star":
            slot_aggs.append(Column(dt.INT64, occupancy.astype(jnp.int64),
                                    present))
        elif kind == "count":
            c = acc[:, plan[1]]
            slot_aggs.append(Column(dt.INT64, c.astype(jnp.int64), present))
        elif kind == "int_sum":
            ni, ci = plan[1], plan[2]
            s = _recombine_nibble_sums(acc[:, ni:ni + 16])
            has = acc[:, ci] > 0
            slot_aggs.append(Column(dt.INT64, _masked(s, has, 0), has))
        else:                                     # sum / avg on floats
            hl, ci = plan[1], plan[2]
            s = acc[:, hl] + acc[:, hl + 1]
            s = jnp.where(acc[:, hl + 2] > 0, jnp.nan, s)   # NaN contribs
            cnt = acc[:, ci]
            has = cnt > 0
            if kind == "sum":
                slot_aggs.append(
                    Column(dt.FLOAT64, jnp.where(has, s, 0.0), has))
            else:
                data = jnp.where(has, s / jnp.maximum(cnt, 1.0), 0.0)
                slot_aggs.append(Column(dt.FLOAT64, data, has))

    # key column per slot: rmin + slot index; NULL group at the last slot
    slot_ids = jnp.arange(K_slots, dtype=jnp.int64)
    key_data_i = jnp.asarray(rmin, jnp.int64) + slot_ids
    is_null_slot = slot_ids == (K_slots - 1)
    key_valid = present & ~is_null_slot
    if key_col.dtype == dt.BOOL:
        key_data = (key_data_i != 0) & key_valid
    else:
        key_data = jnp.where(key_valid, key_data_i,
                             0).astype(key_col.data.dtype)

    # compact occupied slots to the front (stable: keeps key order,
    # NULL group last)
    perm, n_groups = K.compaction_indices(present)
    group_live = jnp.arange(K_slots) < n_groups
    out_key = K.gather_column(
        Column(key_col.dtype, key_data, key_valid), perm,
        out_valid=group_live)
    out_aggs = [K.gather_column(c, perm, out_valid=group_live)
                for c in slot_aggs]
    return [out_key], out_aggs, n_groups


def dense_feature_count(specs: Sequence[AggSpec]) -> int:
    """Number of matmul feature planes groupby_dense builds for ``specs``
    (mirrors the planning loop above; used to report accurate FLOPs)."""
    n = 1                                   # occupancy
    seen = set()
    for spec in specs:
        if spec.op in ("count_star", "min", "max", "first", "last"):
            continue
        cid = id(spec.column.data)
        if ("contrib", cid) not in seen:
            seen.add(("contrib", cid))
            n += 1
        if spec.op == "sum" and (spec.column.dtype.is_integral or
                                 spec.column.dtype == dt.BOOL):
            if ("nibbles", cid) not in seen:
                seen.add(("nibbles", cid))
                n += 16
        elif spec.op in ("sum", "avg"):
            if ("hilo", cid) not in seen:
                seen.add(("hilo", cid))
                n += 3
    return n


# ---------------------------------------------------------------------------
# Single-word-key MXU group-by: the fully TPU-native fast path
# ---------------------------------------------------------------------------
#
# For a single fixed-width key column the whole group-by avoids large gathers
# and scatters entirely:
#   1. sort the VALUES of the order-encoded key (no argsort, no row gather)
#   2. distinct count -> host sync -> static K bucket
#   3. distinct keys via Kb-sized gathers (binary search on the sorted array)
#   4. per-row group id = rank of the key among distinct keys, computed as a
#      chunked compare-reduce (sum_g [uniq_g < key_i]) on the VPU — no gather
#   5. every aggregate rides ONE chunked one-hot matmul on the MXU
# Cost on 8M rows ~ one sort + one cumsum + one compare-reduce + one matmul.

def _encode_single_word(col: Column) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(uint64 order-encoded key, usable mask). Single-word dtypes only."""
    words = K.encode_orderable_words(col.data, col.dtype)
    if len(words) == 1:
        return words[0].astype(jnp.uint64), col.validity
    # floats encode as (nan_rank, value): fold into one word via bitcast
    nan_rank, value = words
    bits = jax.lax.bitcast_convert_type(value.astype(jnp.float64), jnp.uint64) \
        if value.dtype == jnp.float64 else \
        jax.lax.bitcast_convert_type(value.astype(jnp.float32),
                                     jnp.uint32).astype(jnp.uint64)
    sign = bits >> (63 if value.dtype == jnp.float64 else 31)
    flip = jnp.where(sign == 1, ~bits,
                     bits | jnp.uint64(0x8000_0000_0000_0000))
    return (nan_rank.astype(jnp.uint64) << 63) | (flip >> 1), col.validity


def _decode_single_word(enc: jnp.ndarray, dtype: dt.DType) -> jnp.ndarray:
    if dtype == dt.BOOL:
        return enc.astype(jnp.uint8) != 0
    w = dtype.byte_width
    u = enc.astype(_UNSIGNED_BY_W[w]) ^ jnp.asarray(
        K._SIGNBIT[w], dtype=_UNSIGNED_BY_W[w])
    return u.astype(dtype.numpy_dtype)


_UNSIGNED_BY_W = {1: jnp.uint8, 2: jnp.uint16, 4: jnp.uint32, 8: jnp.uint64}

_KEY_SENTINEL = jnp.uint64(0xFFFF_FFFF_FFFF_FFFF)


def _singleword_supported(col: Column) -> bool:
    return col.dtype != dt.STRING and not col.dtype.is_floating


def groupby_singleword(key_col: Column, specs: Sequence[AggSpec],
                       num_rows, capacity: int,
                       extra_mask: Optional[jnp.ndarray] = None
                       ) -> Optional[Tuple[List[Column], List[Column], int]]:
    """MXU group-by for one fixed-width integral key. Returns None when the
    distinct-count bucket exceeds MATMUL_MAX_GROUPS (caller falls back).
    NULL keys group together under the sentinel slot (Spark groupby keeps
    null groups)."""
    live = jnp.arange(capacity) < num_rows
    if extra_mask is not None:
        live = live & extra_mask
    enc, usable = _encode_single_word(key_col)
    # null keys get sentinel-1 (still a group); padding gets the sentinel
    enc = jnp.where(live & usable, enc,
                    jnp.where(live, _KEY_SENTINEL - 1, _KEY_SENTINEL))
    sorted_enc = jnp.sort(enc)
    prev = jnp.concatenate([sorted_enc[:1] ^ jnp.uint64(1), sorted_enc[:-1]])
    starts = (sorted_enc != prev) & (sorted_enc != _KEY_SENTINEL)
    n_groups = int(jnp.sum(starts))  # lint: host-sync-ok single-word group-count sync sizes the dense bucket (documented dynamic-size read)
    if n_groups == 0:
        return [], [], 0

    from ..columnar.column import bucket as _bucket
    Kb = _bucket(n_groups, 128)
    if Kb > MATMUL_MAX_GROUPS:
        return None

    seg_sorted = jnp.cumsum(starts.astype(jnp.int32)) - 1
    pos = jnp.searchsorted(seg_sorted, jnp.arange(Kb, dtype=jnp.int32),
                           side="left")
    uniq = sorted_enc[jnp.clip(pos, 0, capacity - 1)]
    uniq = jnp.where(jnp.arange(Kb) < n_groups, uniq, _KEY_SENTINEL)

    # per-row rank among distinct keys: chunked compare-reduce (VPU)
    ch = _mm_chunks(capacity)
    encc = enc.reshape(ch, -1)

    def per_chunk(kk):
        return jnp.sum((kk[:, None] > uniq[None, :]).astype(jnp.int32),
                       axis=1)

    seg_ids = jax.lax.map(per_chunk, encc).reshape(-1)
    seg_ids = jnp.clip(seg_ids, 0, Kb - 1)

    group_live = jnp.arange(Kb) < n_groups
    key_data = _decode_single_word(uniq, key_col.dtype)
    null_slot = uniq == _KEY_SENTINEL - 1
    key_valid = group_live & ~null_slot
    key_data = jnp.where(key_valid, key_data,
                         jnp.zeros((), key_data.dtype))
    out_keys = [Column(key_col.dtype, key_data, key_valid)]

    out_aggs: List[Column] = []
    for spec in specs:
        agg = segment_aggregate_matmul(spec, seg_ids, live, Kb)
        out_aggs.append(_mask_to(agg, group_live))
    return out_keys, out_aggs, n_groups


def _dense_spec_supported(spec: AggSpec) -> bool:
    if spec.op in ("count", "count_star"):
        return True
    c = spec.column
    if c is None:
        return False
    if spec.op in ("sum", "avg"):
        return c.dtype.is_integral or c.dtype == dt.BOOL or c.dtype.is_floating
    if spec.op in ("min", "max"):
        return c.dtype != dt.STRING
    return spec.op in ("first", "last")


def groupby_aggregate_fast(key_cols: Sequence[Column], specs: Sequence[AggSpec],
                           num_rows: int, capacity: int,
                           allow_matmul: bool = True,
                           dense_state: Optional[dict] = None
                           ) -> Tuple[List[Column], List[Column], int]:
    """Eager (host-driven) group-by: dispatches the dense-range MXU path when
    a single integral key spans a small range (one cheap stats sync), else
    sorts, syncs the group count, and uses MXU matmul reductions when the
    group-count bucket is small enough; otherwise the traced sort path.

    ``dense_state`` is an optional caller-held memo dict: once a batch's key
    span disqualifies the dense path, ``dense_state["enabled"]`` flips False
    so later batches of the same operator skip the stats pass entirely
    (key domains are stable across a stream; the flag never flips back).

    Returns host-int group count (callers outside jit). The host sync here is
    the same one TpuHashAggregateExec already performs on n_groups.
    """
    import numpy as _np
    from ..columnar.column import bucket as _bucket
    float_cols = [s.column for s in specs
                  if s.op in ("sum", "avg") and s.column is not None
                  and s.column.dtype.is_floating]
    f32_safe = None        # unknown until a stats sync measures the values
    if (allow_matmul and len(key_cols) == 1
            and (dense_state is None or dense_state.get("enabled", True))
            and dense_supported_key(key_cols[0])
            and all(_dense_spec_supported(s) for s in specs)):
        rmin_d, decision = dense_key_stats(key_cols[0], num_rows,
                                           float_cols=float_cols)
        stats = _np.asarray(decision)  # lint: host-sync-ok the ONE dense-path stats sync (span/absmax decide the kernel)
        span, absmaxes = stats[0], stats[2:]
        f32_safe = bool(all(a <= F32_SAFE_ABSMAX for a in absmaxes))
        if span + 2 <= DENSE_MAX_SLOTS and f32_safe:
            Kb = _bucket(int(span) + 2, 128)
            out_keys, out_aggs, ngd = groupby_dense(
                key_cols[0], specs, num_rows, Kb, rmin_d)
            return out_keys, out_aggs, int(ngd)
        if span + 2 > DENSE_MAX_SLOTS and dense_state is not None:
            dense_state["enabled"] = False

    sort_keys = [K.SortKey(c) for c in key_cols]
    order = K.sort_indices(sort_keys, num_rows, capacity)
    sorted_keys = [K.gather_column(c, order) for c in key_cols]
    live = jnp.arange(capacity) < num_rows
    starts = K.segment_starts_from_sorted_keys(sorted_keys, num_rows, capacity)
    seg_ids = K.segment_ids(starts)
    if f32_safe is None and allow_matmul and float_cols:
        # fold the value-range check into the n_groups sync: the hi/lo f32
        # matmul path is only safe for values within F32_SAFE_ABSMAX
        parts = [jnp.sum(starts).astype(jnp.float64)]
        for c in float_cols:
            contrib = live & c.validity
            a = jnp.where(contrib & ~jnp.isnan(c.data), jnp.abs(c.data), 0.0)
            parts.append(jnp.max(a).astype(jnp.float64))
        arr = _np.asarray(jnp.stack(parts))  # lint: host-sync-ok n_groups + f32-range folded into one stats sync
        n_groups = int(arr[0])
        f32_safe = bool(all(a <= F32_SAFE_ABSMAX for a in arr[1:]))
    else:
        n_groups = int(jnp.sum(starts))  # lint: host-sync-ok eager-path group-count sync sizes the output bucket

    Kb = _bucket(max(n_groups, 1))
    use_mm = (allow_matmul and Kb <= MATMUL_MAX_GROUPS and
              f32_safe is not False and
              all(_matmul_supported(s) for s in specs))

    start_perm, _ = K.compaction_indices(starts)
    group_live = jnp.arange(capacity) < n_groups
    out_keys = [K.gather_column(c, start_perm, out_valid=group_live)
                for c in sorted_keys]

    out_aggs: List[Column] = []
    if use_mm:
        kidx = start_perm[:Kb]
        out_keys = [K.gather_column(c, kidx,
                                    out_valid=jnp.arange(Kb) < n_groups)
                    for c in sorted_keys]
        for spec in specs:
            s = spec
            if spec.column is not None:
                s = spec._replace(column=K.gather_column(spec.column, order))
            agg = segment_aggregate_matmul(s, seg_ids, live, Kb)
            out_aggs.append(_mask_to(agg, jnp.arange(Kb) < n_groups))
        return out_keys, out_aggs, n_groups

    for spec in specs:
        s = spec
        if spec.column is not None:
            s = spec._replace(column=K.gather_column(spec.column, order))
        agg = segment_aggregate(s, seg_ids, live, capacity)
        out_aggs.append(_mask_to(agg, group_live))
    return out_keys, out_aggs, n_groups


def _mask_to(col: Column, mask: jnp.ndarray) -> Column:
    validity = col.validity & mask
    if col.dtype == dt.STRING:
        data = jnp.where(mask[:, None], col.data, jnp.uint8(0))
        lengths = jnp.where(mask, col.lengths, jnp.int32(0))
        return Column(col.dtype, data, validity, lengths)
    data = jnp.where(validity, col.data, jnp.zeros((), col.data.dtype))
    return Column(col.dtype, data, validity)
