"""Group-by and reduction aggregate kernels: the cuDF ``groupBy.aggregate`` analog.

Reference: ``org/apache/spark/sql/rapids/AggregateFunctions.scala`` (531 LoC) —
each Spark aggregate decomposes into ``CudfAggregate`` update/merge pairs
(average = sum + count; the hash-agg exec drives update-aggregation per batch and
merge-aggregation across batches, aggregate.scala:305-560).

TPU-first design (DESIGN.md §3): no device hash tables. Group-by is sort-based:
  lexsort rows by the group keys -> segment-start flags -> segment ids ->
  ``jax.ops.segment_*`` reductions with num_segments = capacity (static shape).
Group count travels as a device scalar; group keys are the key values at segment
starts, compacted to the front. SQL null semantics: aggregates skip NULL inputs;
an all-NULL (or empty) group yields NULL for sum/min/max/avg and 0 for count.
"""

from __future__ import annotations

from typing import List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..columnar import dtypes as dt
from ..columnar.column import Column
from . import kernels as K


class AggSpec(NamedTuple):
    """One aggregation over one input column (None input = COUNT(*))."""
    op: str                      # count/count_star/sum/min/max/avg/first/last
    column: Optional[Column]
    ignore_nulls: bool = True    # for first/last


def _sum_dtype(in_dtype: dt.DType) -> dt.DType:
    """Spark widens SUM: integral -> bigint, floating -> double."""
    if in_dtype.is_integral or in_dtype == dt.BOOL:
        return dt.INT64
    return dt.FLOAT64


def result_dtype(op: str, in_dtype: Optional[dt.DType]) -> dt.DType:
    if op in ("count", "count_star"):
        return dt.INT64
    if op == "sum":
        return _sum_dtype(in_dtype)
    if op == "avg":
        return dt.FLOAT64
    return in_dtype  # min/max/first/last preserve type


# ---------------------------------------------------------------------------
# Segment reductions (update phase)
# ---------------------------------------------------------------------------

def _seg_sum(data, seg_ids, num_segments):
    return jax.ops.segment_sum(data, seg_ids, num_segments=num_segments)


def _seg_min(data, seg_ids, num_segments):
    return jax.ops.segment_min(data, seg_ids, num_segments=num_segments)


def _seg_max(data, seg_ids, num_segments):
    return jax.ops.segment_max(data, seg_ids, num_segments=num_segments)


def _masked(data, mask, fill):
    return jnp.where(mask, data, jnp.asarray(fill, data.dtype))


def _string_ordinal_minmax(col: Column, contrib, seg_ids, cap: int, want_min: bool):
    """Min/max for strings: reduce over the *row index* ordered by the encoded
    string key, then gather the winning row's bytes."""
    words = K.pack_string_words(col.data, col.lengths)
    # build a sortable composite: argsort rows by string order, then the rank of
    # each row is a uint32 we can min/max within segments
    order = jnp.lexsort(tuple(reversed(
        [w for w in words.T] + [col.lengths.astype(jnp.uint32)])))
    rank = jnp.zeros(cap, dtype=jnp.int32).at[order].set(
        jnp.arange(cap, dtype=jnp.int32))
    sentinel = jnp.int32(cap) if want_min else jnp.int32(-1)
    r = jnp.where(contrib, rank, sentinel)
    red = _seg_min(r, seg_ids, cap) if want_min else _seg_max(r, seg_ids, cap)
    has = red != sentinel
    win_rank = jnp.where(has, red, 0)
    # rank -> row index
    win_row = order[jnp.clip(win_rank, 0, cap - 1)]
    return win_row, has


def segment_aggregate(spec: AggSpec, seg_ids: jnp.ndarray, live: jnp.ndarray,
                      capacity: int) -> Column:
    """Update-phase aggregation: reduce each segment of input rows to one output
    row per group id. Output column has ``capacity`` slots (group g at slot g);
    slots beyond the group count are zeroed+invalid by construction because no
    row contributes to them.
    """
    op = spec.op
    if op == "count_star":
        data = _seg_sum(live.astype(jnp.int64), seg_ids, capacity)
        valid = _seg_sum(live.astype(jnp.int32), seg_ids, capacity) > 0
        return Column(dt.INT64, data, valid)

    col = spec.column
    contrib = live & col.validity
    if op == "count":
        data = _seg_sum(contrib.astype(jnp.int64), seg_ids, capacity)
        valid = _seg_sum(live.astype(jnp.int32), seg_ids, capacity) > 0
        return Column(dt.INT64, data, valid)

    group_has = _seg_sum(contrib.astype(jnp.int32), seg_ids, capacity) > 0

    if op == "sum":
        out_t = _sum_dtype(col.dtype)
        d = _masked(col.data.astype(out_t.numpy_dtype), contrib, 0)
        data = _seg_sum(d, seg_ids, capacity)
        return Column(out_t, _masked(data, group_has, 0), group_has)

    if op == "avg":
        d = _masked(col.data.astype(jnp.float64), contrib, 0.0)
        s = _seg_sum(d, seg_ids, capacity)
        c = _seg_sum(contrib.astype(jnp.float64), seg_ids, capacity)
        data = jnp.where(group_has, s / jnp.maximum(c, 1.0), 0.0)
        return Column(dt.FLOAT64, data, group_has)

    if op in ("min", "max"):
        if col.dtype == dt.STRING:
            win_row, has = _string_ordinal_minmax(col, contrib, seg_ids, capacity,
                                                  want_min=(op == "min"))
            out = K.gather_column(col, win_row, out_valid=has)
            return out
        if col.dtype.is_floating:
            # Spark total order: NaN largest. Use +/-inf fill, restore NaN via flags.
            is_nan = jnp.isnan(col.data) & contrib
            seg_nan = _seg_sum(is_nan.astype(jnp.int32), seg_ids, capacity) > 0
            seg_non_nan = _seg_sum((contrib & ~is_nan).astype(jnp.int32),
                                   seg_ids, capacity) > 0
            fill = jnp.inf if op == "min" else -jnp.inf
            d = _masked(col.data, contrib & ~is_nan, fill)
            red = (_seg_min if op == "min" else _seg_max)(d, seg_ids, capacity)
            if op == "min":
                data = jnp.where(seg_non_nan, red, jnp.nan)  # all-NaN group -> NaN
            else:
                data = jnp.where(seg_nan, jnp.nan, red)      # any NaN -> NaN max
            data = jnp.where(group_has, data, 0.0).astype(col.data.dtype)
            return Column(col.dtype, data, group_has)
        if col.dtype == dt.BOOL:
            d = _masked(col.data.astype(jnp.int32), contrib, 1 if op == "min" else 0)
            red = (_seg_min if op == "min" else _seg_max)(d, seg_ids, capacity)
            data = (red > 0) & group_has
            return Column(dt.BOOL, data, group_has)
        info = jnp.iinfo(col.data.dtype)
        fill = info.max if op == "min" else info.min
        d = _masked(col.data, contrib, fill)
        red = (_seg_min if op == "min" else _seg_max)(d, seg_ids, capacity)
        return Column(col.dtype, _masked(red, group_has, 0), group_has)

    if op in ("first", "last"):
        idx = jnp.arange(capacity, dtype=jnp.int32)
        pick_from = contrib if spec.ignore_nulls else live
        grp_has = _seg_sum(pick_from.astype(jnp.int32), seg_ids, capacity) > 0
        if op == "first":
            r = jnp.where(pick_from, idx, capacity)
            win = _seg_min(r, seg_ids, capacity)
        else:
            r = jnp.where(pick_from, idx, -1)
            win = _seg_max(r, seg_ids, capacity)
        win = jnp.clip(win, 0, capacity - 1)
        return K.gather_column(col, win, out_valid=grp_has)

    raise ValueError(f"unknown aggregate op {op!r}")


# ---------------------------------------------------------------------------
# Whole group-by driver
# ---------------------------------------------------------------------------

def groupby_aggregate(key_cols: Sequence[Column], specs: Sequence[AggSpec],
                      num_rows, capacity: int
                      ) -> Tuple[List[Column], List[Column], jnp.ndarray]:
    """Sort-based group-by: returns (group key columns, agg result columns,
    device group count). All outputs have ``capacity`` slots with groups
    compacted to the front.

    cuDF analog: ``Table.groupBy(...).aggregate(...)`` as driven by
    GpuHashAggregateExec (aggregate.scala:427-485).
    """
    sort_keys = [K.SortKey(c) for c in key_cols]
    order = K.sort_indices(sort_keys, num_rows, capacity)
    sorted_keys = [K.gather_column(c, order) for c in key_cols]
    live = jnp.arange(capacity) < num_rows
    starts = K.segment_starts_from_sorted_keys(sorted_keys, num_rows, capacity)
    seg_ids = K.segment_ids(starts)
    n_groups = jnp.sum(starts).astype(jnp.int32)

    # group keys: gather the first row of each segment to the front
    start_perm, _ = K.compaction_indices(starts)
    group_live = jnp.arange(capacity) < n_groups
    out_keys = [K.gather_column(c, start_perm, out_valid=group_live)
                for c in sorted_keys]

    out_aggs: List[Column] = []
    for spec in specs:
        s = spec
        if spec.column is not None:
            s = spec._replace(column=K.gather_column(spec.column, order))
        agg = segment_aggregate(s, seg_ids, live, capacity)
        # mask agg slots beyond the group count (paranoia: segment ids of padding
        # rows alias the last group, which is a real group, so data is fine; but
        # enforce the padding invariant explicitly)
        out_aggs.append(_mask_to(agg, group_live))
    return out_keys, out_aggs, n_groups


def reduce_aggregate(specs: Sequence[AggSpec], num_rows, capacity: int
                     ) -> List[Column]:
    """Grouping-free reduction (SELECT SUM(x) FROM t): one output row at slot 0.

    Empty input: count = 0, everything else NULL (aggregate.scala:487-505
    empty-input reduction semantics).
    """
    seg_ids = jnp.zeros(capacity, dtype=jnp.int32)
    live = jnp.arange(capacity) < num_rows
    out: List[Column] = []
    one = jnp.arange(capacity) < 1
    for spec in specs:
        agg = segment_aggregate(spec, seg_ids, live, capacity)
        if spec.op in ("count", "count_star"):
            # count of empty input is 0 (valid), not NULL
            data = jnp.where(one, agg.data, 0)
            out.append(Column(dt.INT64, data, one))
        else:
            out.append(_mask_to(agg, one))
    return out


def _mask_to(col: Column, mask: jnp.ndarray) -> Column:
    validity = col.validity & mask
    if col.dtype == dt.STRING:
        data = jnp.where(mask[:, None], col.data, jnp.uint8(0))
        lengths = jnp.where(mask, col.lengths, jnp.int32(0))
        return Column(col.dtype, data, validity, lengths)
    data = jnp.where(validity, col.data, jnp.zeros((), col.data.dtype))
    return Column(col.dtype, data, validity)
