"""Arithmetic expressions (GpuAdd/Subtract/Multiply/Divide/Remainder/Pmod/Abs/...).

Reference: ``org/apache/spark/sql/rapids/arithmetic.scala`` (417 LoC) — each op maps
to a cuDF BinaryOp through ``CudfBinaryExpression``. Here each op is a jnp expression
with Spark null semantics: result is NULL if any input is NULL; division by zero
yields NULL (non-ANSI Spark); integral ops wrap on overflow (Java semantics, which
jnp integer arithmetic matches).

Type coercion is done during analysis (api layer inserts Casts); binary ops here
assume both sides share the result dtype.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..columnar import dtypes as dt
from ..columnar.batch import ColumnarBatch
from ..columnar.column import Column, Scalar
from .expressions import (Expression, combine_validity, data_validity,
                          is_traced, result_column)


def _ns(*vals):
    """numpy for host (scalar-fold) operands, jnp for device/tracer ones:
    the safe-compute helpers below run on both paths without a literal
    constant ever round-tripping the device."""
    return np if all(isinstance(v, (np.ndarray, np.generic))
                     for v in vals) else jnp


class BinaryArithmetic(Expression):
    symbol = "?"

    @property
    def left(self) -> Expression:
        return self.children[0]

    @property
    def right(self) -> Expression:
        return self.children[1]

    @property
    def dtype(self) -> dt.DType:
        return self.left.dtype

    def _compute(self, l, r):
        raise NotImplementedError

    def _extra_validity(self, l, r):
        """Override to add null-producing conditions (e.g. div by zero)."""
        return None

    def eval(self, batch: ColumnarBatch):
        lv = self.left.eval(batch)
        rv = self.right.eval(batch)
        if isinstance(lv, Scalar) and isinstance(rv, Scalar):
            return self._fold_scalars(lv, rv)
        ld, lval = data_validity(lv, self.dtype)
        rd, rval = data_validity(rv, self.dtype)
        extra = self._extra_validity(ld, rd)
        data = self._compute_safe(ld, rd)
        validity = combine_validity(lval, rval)
        if extra is not None:
            validity = extra if validity is True else (validity & extra)
        if validity is not True:
            data = jnp.where(jnp.broadcast_to(validity, (batch.capacity,)), data,
                             jnp.zeros((), data.dtype))
        return result_column(self.dtype, data, validity, batch.capacity)

    def _compute_safe(self, l, r):
        return self._compute(l, r)

    def _fold_scalars(self, lv: Scalar, rv: Scalar) -> Scalar:
        # pure-numpy fold: literal operands stay host-side end to end (the
        # compute helpers pick their namespace via _ns), so a constant
        # expression costs zero device round trips per batch
        if lv.is_null or rv.is_null:
            return Scalar(None, self.dtype)
        if is_traced(lv.value) or is_traced(rv.value):
            # a rebindable Parameter under an active fused trace (e.g.
            # ``:d - 0.01`` around a placeholder): the fold must stay
            # in-graph. Null-producing ops (div by zero) can't — their
            # nullness depends on the traced VALUE, which a Scalar can't
            # carry — so they raise here and the consumer falls back to
            # the (correct) eager path for this stage.
            lt = jnp.asarray(lv.value, self.dtype.numpy_dtype)
            rt = jnp.asarray(rv.value, self.dtype.numpy_dtype)
            if self._extra_validity(lt, rt) is not None:
                raise TypeError(
                    f"scalar {self.symbol} over a traced parameter has "
                    "value-dependent nullability; host fold required")
            return Scalar(self._compute_safe(lt, rt), self.dtype)
        l = np.asarray(lv.value, self.dtype.numpy_dtype)   # lint: host-sync-ok numpy view of a python literal, no device value
        r = np.asarray(rv.value, self.dtype.numpy_dtype)   # lint: host-sync-ok numpy view of a python literal, no device value
        extra = self._extra_validity(l, r)
        if extra is not None and not bool(extra):
            return Scalar(None, self.dtype)
        with np.errstate(invalid="ignore", divide="ignore", over="ignore"):
            out = self._compute_safe(l, r)
        return Scalar(np.asarray(out).item(), self.dtype)  # lint: host-sync-ok numpy result of the host fold above

    def __repr__(self):
        return f"({self.children[0]!r} {self.symbol} {self.children[1]!r})"


class Add(BinaryArithmetic):
    symbol = "+"
    def _compute(self, l, r): return l + r


class Subtract(BinaryArithmetic):
    symbol = "-"
    def _compute(self, l, r): return l - r


class Multiply(BinaryArithmetic):
    symbol = "*"
    def _compute(self, l, r): return l * r


class Divide(BinaryArithmetic):
    """Spark `/`: always floating; x/0 -> NULL (GpuDivide, arithmetic.scala)."""
    symbol = "/"

    @property
    def nullable(self) -> bool:
        return True

    def _extra_validity(self, l, r):
        return r != 0

    def _compute_safe(self, l, r):
        xp = _ns(l, r)
        safe_r = xp.where(r != 0, r, xp.ones((), xp.result_type(r)))
        return l / safe_r


class IntegralDivide(BinaryArithmetic):
    """Spark `div`: long division; x div 0 -> NULL (GpuIntegralDivide)."""
    symbol = "div"

    @property
    def dtype(self) -> dt.DType:
        return dt.INT64

    @property
    def nullable(self) -> bool:
        return True

    def _extra_validity(self, l, r):
        return r != 0

    def _compute_safe(self, l, r):
        xp = _ns(l, r)
        safe_r = xp.where(r != 0, r, xp.ones((), xp.result_type(r)))
        # Java integer division truncates toward zero; // floors.
        q = xp.floor_divide(l, safe_r)
        rem = l - q * safe_r
        neg = ((l < 0) != (safe_r < 0)) & (rem != 0)
        return (q + xp.where(neg, xp.ones((), q.dtype), xp.zeros((), q.dtype))
                ).astype(xp.int64)


class Remainder(BinaryArithmetic):
    """Spark `%`: Java semantics (sign of dividend); x % 0 -> NULL even for floats
    (GpuRemainder)."""
    symbol = "%"

    @property
    def nullable(self) -> bool:
        return True

    def _extra_validity(self, l, r):
        return r != 0

    def _compute_safe(self, l, r):
        xp = _ns(l, r)
        one = xp.ones((), xp.result_type(r))
        safe_r = xp.where(r != 0, r, one)
        # Java %: truncated remainder (same sign as dividend) = fmod
        return xp.fmod(l, safe_r)


class Pmod(BinaryArithmetic):
    """Positive modulus (GpuPmod): ((x % y) + y) % y; y == 0 -> NULL."""
    symbol = "pmod"

    @property
    def nullable(self) -> bool:
        return True

    def _extra_validity(self, l, r):
        return r != 0

    def _compute_safe(self, l, r):
        xp = _ns(l, r)
        one = xp.ones((), xp.result_type(r))
        safe_r = xp.where(r != 0, r, one)
        m = xp.fmod(l, safe_r)
        return xp.where(m != 0, xp.fmod(m + safe_r, safe_r), m)


class UnaryMinus(Expression):
    """GpuUnaryMinus."""
    @property
    def dtype(self) -> dt.DType:
        return self.children[0].dtype

    def eval(self, batch: ColumnarBatch):
        v = self.children[0].eval(batch)
        if isinstance(v, Scalar):
            return Scalar(None if v.is_null else -v.value, self.dtype)
        return Column(self.dtype, -v.data, v.validity)

    def __repr__(self):
        return f"(- {self.children[0]!r})"


class UnaryPositive(Expression):
    @property
    def dtype(self) -> dt.DType:
        return self.children[0].dtype

    def eval(self, batch: ColumnarBatch):
        return self.children[0].eval(batch)


class Abs(Expression):
    """GpuAbs."""
    @property
    def dtype(self) -> dt.DType:
        return self.children[0].dtype

    def eval(self, batch: ColumnarBatch):
        v = self.children[0].eval(batch)
        if isinstance(v, Scalar):
            return Scalar(None if v.is_null else abs(v.value), self.dtype)
        return Column(self.dtype, jnp.abs(v.data), v.validity)
