"""ARRAY expressions + explode kernels.

Reference: ``complexTypeExtractors.scala`` (GetArrayItem), ``collection
OperationsExprs`` (size), ``GpuGenerateExec.scala`` (explode/posexplode via
per-row repeat + flatten), ``stringFunctions.scala`` StringSplit.

TPU-first layout: ARRAY<primitive> is a padded element matrix
``elem[cap, W]`` + ``lengths[cap]`` (same shape discipline as strings —
static shapes, vectorizable). NULL elements inside arrays are out of scope
(split/sequence-produced arrays never contain them).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp

from ..columnar import dtypes as dt
from ..columnar.batch import ColumnarBatch
from ..columnar.column import Column, Scalar, bucket
from . import kernels as K
from .expressions import Expression


class GetArrayItem(Expression):
    """arr[i] (complexTypeExtractors.scala GetArrayItem): out-of-bounds or
    NULL array -> NULL. ``one_based=True`` is element_at's indexing:
    1-based from the front, negative counts from the end, and 0 yields
    NULL (Spark raises; returning NULL keeps execution total)."""

    def __init__(self, child: Expression, index: Expression,
                 one_based: bool = False):
        super().__init__(child, index)
        self.one_based = one_based

    @property
    def dtype(self):
        return self.children[0].dtype.element

    @property
    def nullable(self):
        return True

    def eval(self, batch: ColumnarBatch):
        from .expressions import materialize
        arr = materialize(self.children[0].eval(batch), batch)
        idx = self.children[1].eval(batch)
        cap, w = arr.data.shape
        if isinstance(idx, Scalar):
            if idx.is_null:
                return Column.full_null(self.dtype, cap)
            i = jnp.full(cap, int(idx.value), jnp.int32)
            ivalid = jnp.ones(cap, jnp.bool_)
        else:
            i = idx.data.astype(jnp.int32)
            ivalid = idx.validity
        if self.one_based:
            eff = jnp.where(i > 0, i - 1, arr.lengths + i)
            ok = arr.validity & ivalid & (i != 0) & (eff >= 0) & \
                (eff < arr.lengths)
            i = eff
        else:
            ok = arr.validity & ivalid & (i >= 0) & (i < arr.lengths)
        ic = jnp.clip(i, 0, w - 1)
        if arr.elem_validity is not None:
            # a present-but-NULL element yields NULL
            ok = ok & jnp.take_along_axis(arr.elem_validity, ic[:, None],
                                          axis=1)[:, 0]
        data = jnp.take_along_axis(arr.data, ic[:, None], axis=1)[:, 0]
        data = jnp.where(ok, data, jnp.zeros((), data.dtype))
        return Column(self.dtype, data, ok)


class Size(Expression):
    """size(arr): Spark 3.0 legacy semantics — size(NULL) = -1
    (spark.sql.legacy.sizeOfNull defaults true in the reference era)."""

    @property
    def dtype(self):
        return dt.INT32

    @property
    def nullable(self):
        return False

    def eval(self, batch: ColumnarBatch):
        from .expressions import materialize
        arr = materialize(self.children[0].eval(batch), batch)
        data = jnp.where(arr.validity, arr.lengths, jnp.int32(-1))
        live = batch.row_mask()
        return Column(dt.INT32, jnp.where(live, data, 0), live)


class Explode(Expression):
    """Generator marker: planned by TpuGenerateExec, never evaluated inline
    (GpuGenerateExec.scala). ``pos=True`` = posexplode."""

    def __init__(self, child: Expression, pos: bool = False):
        super().__init__(child)
        self.pos = pos

    @property
    def dtype(self):
        t = self.children[0].dtype
        if isinstance(self.children[0], StringSplit):
            return dt.STRING
        return t.element if t.element is not None else t

    @property
    def nullable(self):
        return True

    def eval(self, batch):
        raise RuntimeError("Explode is planned by TpuGenerateExec")


class StringSplit(Expression):
    """split(str, delim) -> array<string>. Single-byte literal delimiters
    run on-device fused with explode (GpuGenerateExec path); other shapes
    tag off to the CPU engine (the reference likewise gates its regex
    delimiters, GpuOverrides.scala:343-351)."""

    def __init__(self, child: Expression, delimiter: str):
        super().__init__(child)
        self.delimiter = delimiter

    @property
    def dtype(self):
        return dt.ARRAY_STRING

    @property
    def nullable(self):
        return True

    def eval(self, batch):
        raise RuntimeError("StringSplit is planned (explode-fused) or "
                           "runs on the CPU engine")


# ---------------------------------------------------------------------------
# Explode kernels
# ---------------------------------------------------------------------------

def explode_indices(lengths: jnp.ndarray, valid: jnp.ndarray,
                    live: jnp.ndarray, out_cap: int
                    ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """(src_row, elem_pos, out_count) mapping output rows to (row, element).
    NULL/empty arrays produce no rows (Spark explode)."""
    n = jnp.where(live & valid, lengths, 0).astype(jnp.int64)
    cum = jnp.cumsum(n)
    total = cum[-1] if n.shape[0] else jnp.int64(0)
    out_i = jnp.arange(out_cap, dtype=jnp.int64)
    src = jnp.searchsorted(cum, out_i, side="right").astype(jnp.int32)
    src = jnp.clip(src, 0, n.shape[0] - 1)
    base = cum[src] - n[src]
    elem = (out_i - base).astype(jnp.int32)
    out_live = out_i < total
    return (jnp.where(out_live, src, 0),
            jnp.where(out_live, elem, 0),
            total.astype(jnp.int32))


def explode_array(arr: Column, other_cols: List[Column], live: jnp.ndarray,
                  out_cap: int
                  ) -> Tuple[List[Column], Column, Column, jnp.ndarray]:
    """(repeated other columns, element column, pos column, out_count)."""
    src, elem, count = explode_indices(arr.lengths, arr.validity, live,
                                       out_cap)
    out_live = jnp.arange(out_cap) < count
    others = [K.gather_column(c, src, out_valid=out_live)
              for c in other_cols]
    w = arr.data.shape[1]
    ec = jnp.clip(elem, 0, w - 1)
    data = arr.data[src, ec]
    data = jnp.where(out_live, data, jnp.zeros((), data.dtype))
    evalid = out_live
    if arr.elem_validity is not None:
        # exploded NULL elements become NULL rows (Spark explode keeps
        # them; only NULL/empty ARRAYS produce no rows)
        evalid = out_live & arr.elem_validity[src, ec]
        data = jnp.where(evalid, data, jnp.zeros((), data.dtype))
    elem_col = Column(arr.dtype.element, data, evalid)
    pos_col = Column(dt.INT32, jnp.where(out_live, elem, 0), out_live)
    return others, elem_col, pos_col, count


def split_part_counts(col: Column, delim: int
                      ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(is_delim[cap, w], n_parts[cap]) — shared by the output-sizing sync
    and the explode kernel so the widest intermediate computes once."""
    w = col.data.shape[1]
    is_delim = (col.data == jnp.uint8(delim)) & \
        (jnp.arange(w)[None, :] < col.lengths[:, None])
    n_parts = jnp.where(col.validity, 1 + jnp.sum(is_delim, axis=1), 0)
    return is_delim, n_parts


def split_explode(col: Column, delim: int, other_cols: List[Column],
                  live: jnp.ndarray, out_cap: int,
                  precomputed: Optional[Tuple] = None
                  ) -> Tuple[List[Column], Column, Column, jnp.ndarray]:
    """Fused split(str, d) + explode: one output STRING row per part,
    without materializing the intermediate array<string>.

    Spark split semantics: "a,b" -> ["a","b"]; "" -> [""]; NULL -> no rows.
    """
    cap, w = col.data.shape
    in_len = col.lengths
    is_delim, n_parts = (precomputed if precomputed is not None
                         else split_part_counts(col, delim))

    src, part, count = explode_indices(n_parts, col.validity, live, out_cap)
    out_live = jnp.arange(out_cap) < count

    # per-row part boundaries from delimiter ordinals: dpos[r, p] = byte
    # position of the (p+1)-th delimiter (w when absent); then
    #   start of part p = p == 0 ? 0 : dpos[p-1] + 1
    #   end   of part p = min(dpos[p], len)   (last part ends at len)
    W2 = w + 1
    rank = jnp.cumsum(is_delim, axis=1)             # 1-based delim ordinal
    pos_j = jnp.broadcast_to(jnp.arange(w)[None, :], (cap, w))
    dpos = jnp.full((cap, W2), w, jnp.int32)
    dpos = dpos.at[jnp.arange(cap)[:, None],
                   jnp.where(is_delim, rank - 1, W2 - 1)].min(
        jnp.where(is_delim, pos_j, w).astype(jnp.int32), mode="drop")

    pc = jnp.clip(part, 0, W2 - 1)
    prev = dpos[src, jnp.clip(pc - 1, 0, W2 - 1)]
    p_start = jnp.where(pc == 0, 0, prev + 1)
    p_end = jnp.minimum(dpos[src, pc], in_len[src].astype(jnp.int32))
    p_len = jnp.maximum(p_end - p_start, 0)

    # gather each part's bytes into a fresh padded matrix
    out_w = w
    gather_j = p_start[:, None] + jnp.arange(out_w)[None, :]
    gather_j = jnp.clip(gather_j, 0, w - 1)
    bytes_out = col.data[src[:, None], gather_j]
    mask = jnp.arange(out_w)[None, :] < p_len[:, None]
    bytes_out = jnp.where(mask & out_live[:, None], bytes_out, jnp.uint8(0))
    elem_col = Column(dt.STRING, bytes_out, out_live,
                      jnp.where(out_live, p_len, 0).astype(jnp.int32))
    others = [K.gather_column(c, src, out_valid=out_live)
              for c in other_cols]
    pos_col = Column(dt.INT32, jnp.where(out_live, part, 0), out_live)
    return others, elem_col, pos_col, count
