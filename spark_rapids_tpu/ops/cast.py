"""Cast expression: the GpuCast matrix (reference ``GpuCast.scala``, 861 LoC).

Device-side (fusable) casts: numeric<->numeric, numeric<->bool, date<->timestamp,
timestamp<->integral-seconds. Host-side (non-fusable, like the reference's
conf-gated string casts, GpuOverrides.scala:591-602): anything involving STRING.

Spark non-ANSI semantics implemented here:
* float->integral saturates at the target range, NaN -> 0 (Java double->long rules)
* integral->narrower-integral wraps (Java truncation)
* bool->numeric is 0/1; numeric->bool is x != 0
* string->numeric returns NULL on unparseable input
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np

from ..columnar import dtypes as dt
from ..columnar.batch import ColumnarBatch
from ..columnar.column import Column, Scalar
from .expressions import Expression, is_traced, result_column

_INT_RANGE = {
    dt.INT8: (-(1 << 7), (1 << 7) - 1),
    dt.INT16: (-(1 << 15), (1 << 15) - 1),
    dt.INT32: (-(1 << 31), (1 << 31) - 1),
    dt.INT64: (-(1 << 63), (1 << 63) - 1),
}

MICROS_PER_SECOND = 1_000_000
MICROS_PER_DAY = 86_400 * MICROS_PER_SECOND


def _is_device_castable(src: dt.DType, dst: dt.DType) -> bool:
    if src == dst:
        return True
    if dt.STRING in (src, dst):
        return False
    return True


class Cast(Expression):
    def __init__(self, child: Expression, to: dt.DType, ansi: bool = False):
        super().__init__(child)
        self.to = to
        self.ansi = ansi

    @property
    def fusable(self) -> bool:  # type: ignore[override]
        return _is_device_castable(self.children[0].dtype, self.to)

    @property
    def dtype(self) -> dt.DType:
        return self.to

    @property
    def nullable(self) -> bool:
        src = self.children[0].dtype
        if src == dt.STRING and self.to != dt.STRING:
            return True
        return self.children[0].nullable

    def eval(self, batch: ColumnarBatch):
        v = self.children[0].eval(batch)
        src = self.children[0].dtype
        if isinstance(v, Scalar):
            return _cast_scalar(v, src, self.to)
        if src == self.to:
            return v
        if _is_device_castable(src, self.to):
            data = device_cast(v.data, src, self.to)
            return Column(self.to, data, v.validity)
        return _host_cast_column(v, src, self.to, batch)

    def __repr__(self):
        return f"cast({self.children[0]!r} AS {self.to})"


def device_cast(data: jnp.ndarray, src: dt.DType, dst: dt.DType,
                xp=jnp) -> jnp.ndarray:
    """Cast kernel over jnp arrays; ``xp=np`` evaluates the identical
    semantics in pure numpy (scalar folding must not bind jax primitives —
    under an active trace even constant-input ops return tracers)."""
    if src == dst:
        return data
    npdst = dst.numpy_dtype
    if dst == dt.BOOL:
        return data != 0
    if src == dt.BOOL:
        return data.astype(npdst)
    if src == dt.DATE and dst == dt.TIMESTAMP:
        return data.astype(xp.int64) * MICROS_PER_DAY
    if src == dt.TIMESTAMP and dst == dt.DATE:
        return xp.floor_divide(data, MICROS_PER_DAY).astype(xp.int32)
    if src == dt.TIMESTAMP and dst.is_integral:
        secs = xp.floor_divide(data, MICROS_PER_SECOND)
        return secs.astype(npdst)
    if src.is_integral and dst == dt.TIMESTAMP:
        return data.astype(xp.int64) * MICROS_PER_SECOND
    if src == dt.TIMESTAMP and dst.is_floating:
        return data.astype(xp.float64) / MICROS_PER_SECOND
    if src.is_floating and dst == dt.TIMESTAMP:
        return (data * MICROS_PER_SECOND).astype(xp.int64)
    if src.is_floating and dst.is_integral:
        lo, hi = _INT_RANGE[dst]
        trunc = xp.trunc(xp.where(xp.isnan(data), 0.0, data))
        clipped = xp.clip(trunc, float(lo), float(hi))
        # first go through int64 (saturating), then wrap-narrow like Java
        as64 = xp.where(trunc <= float(lo), xp.int64(lo),
                        xp.where(trunc >= float(hi), xp.int64(hi),
                                 clipped.astype(xp.int64)))
        return as64.astype(npdst)
    # integral->integral (wrap), integral->float, float<->float, date<->int
    return data.astype(npdst)


def _cast_scalar(v: Scalar, src: dt.DType, dst: dt.DType) -> Scalar:
    if v.is_null:
        return Scalar(None, dst)
    if src == dst:
        return v
    if is_traced(v.value):
        # a rebindable Parameter under an active fused trace (the analyzer
        # coerces placeholder dtypes with Casts, e.g. :q LONG -> DOUBLE):
        # the cast must compile INTO the program — the numpy fold below
        # would concretize the tracer and abort the whole stage to eager
        if not _is_device_castable(src, dst):
            raise TypeError(
                f"cast {src}->{dst} of a traced parameter is host-only")
        return Scalar(device_cast(jnp.asarray(v.value, src.numpy_dtype),
                                  src, dst, xp=jnp), dst)
    if dst == dt.STRING:
        return Scalar(_format_value(v.value, src), dst)
    if src == dt.STRING:
        return Scalar(_parse_value(v.value, dst), dst)
    # pure numpy: scalar folding runs inside fused traces, where any jax
    # primitive bind would return a tracer and break host conversion
    out = np.asarray(  # lint: host-sync-ok pure-numpy fold (xp=np): no device value involved
        device_cast(np.asarray(v.value, src.numpy_dtype),  # lint: host-sync-ok numpy view of a python literal
                    src, dst, xp=np))
    return Scalar(out.item(), dst)  # lint: host-sync-ok numpy result of the host fold above


# ---------------------------------------------------------------------------
# Host-side string casts (non-fusable; analog of conf-gated GpuCast string paths)
# ---------------------------------------------------------------------------

def _format_value(value, src: dt.DType) -> str:
    import datetime
    if src == dt.BOOL:
        return "true" if value else "false"
    if src.is_integral:
        return str(int(value))
    if src.is_floating:
        f = float(value)
        if f != f:
            return "NaN"
        if f in (float("inf"), float("-inf")):
            return "Infinity" if f > 0 else "-Infinity"
        if f == int(f) and abs(f) < 1e16:
            return f"{f:.1f}"
        return repr(f)
    if src == dt.DATE:
        return (datetime.date(1970, 1, 1) +
                datetime.timedelta(days=int(value))).isoformat()
    if src == dt.TIMESTAMP:
        ts = datetime.datetime(1970, 1, 1) + datetime.timedelta(
            microseconds=int(value))
        base = ts.strftime("%Y-%m-%d %H:%M:%S")
        if ts.microsecond:
            return f"{base}.{ts.microsecond:06d}".rstrip("0")
        return base
    raise TypeError(f"cannot format {src} as string")


def _parse_value(s: str, dst: dt.DType):
    import datetime
    s = s.strip()
    try:
        if dst == dt.BOOL:
            ls = s.lower()
            if ls in ("true", "t", "yes", "y", "1"):
                return True
            if ls in ("false", "f", "no", "n", "0"):
                return False
            return None
        if dst.is_integral:
            val = int(s)
            lo, hi = _INT_RANGE[dst]
            return val if lo <= val <= hi else None
        if dst.is_floating:
            return float(s)
        if dst == dt.DATE:
            return (datetime.date.fromisoformat(s) -
                    datetime.date(1970, 1, 1)).days
        if dst == dt.TIMESTAMP:
            fmt = s.replace("T", " ")
            d = datetime.datetime.fromisoformat(fmt)
            if d.tzinfo is not None:
                # honor the UTC offset: convert to UTC before differencing
                d = d.astimezone(datetime.timezone.utc).replace(tzinfo=None)
            # integer timedelta division: float total_seconds() loses the
            # last microsecond on ~1% of values
            return (d - datetime.datetime(1970, 1, 1)) // \
                datetime.timedelta(microseconds=1)
    except (ValueError, OverflowError):
        return None
    raise TypeError(f"cannot parse string as {dst}")


def _host_cast_column(v: Column, src: dt.DType, dst: dt.DType,
                      batch: ColumnarBatch) -> Column:
    n = batch.num_rows
    cap = batch.capacity
    if src == dt.STRING:
        values = v.to_pylist(n)
        parsed = [None if x is None else _parse_value(x, dst) for x in values]
        return Column.from_pylist(parsed, dst, capacity=cap)
    # fixed-width -> string
    valid = np.asarray(v.validity[:n])  # lint: host-sync-ok host string-cast path: planner routed this column through host formatting
    data = np.asarray(v.data[:n])  # lint: host-sync-ok host string-cast path (same transition as above)
    out = [(_format_value(data[i], src) if valid[i] else None) for i in range(n)]
    return Column.from_pylist(out, dt.STRING, capacity=cap)
