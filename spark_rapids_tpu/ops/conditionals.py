"""Conditional expressions: If, CaseWhen, Coalesce, Least, Greatest, Nvl, NullIf.

Reference: ``conditionalExpressions.scala`` + ``nullExpressions.scala`` (~520 LoC).
All are lazy in Spark row-land but eager columnar here (both branches evaluated,
selected by mask) — same trade the reference makes on GPU.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import jax.numpy as jnp

from ..columnar import dtypes as dt
from ..columnar.batch import ColumnarBatch
from ..columnar.column import Column, Scalar
from .expressions import Expression, data_validity, materialize, result_column


def _select(mask, a: Column, b: Column, dtype: dt.DType, capacity: int) -> Column:
    """Row-wise select between two materialized columns of the same dtype."""
    mask = jnp.broadcast_to(mask, (capacity,))
    validity = jnp.where(mask, a.validity, b.validity)
    if dtype == dt.STRING:
        w = max(a.data.shape[1], b.data.shape[1])
        ad = jnp.pad(a.data, ((0, 0), (0, w - a.data.shape[1])))
        bd = jnp.pad(b.data, ((0, 0), (0, w - b.data.shape[1])))
        data = jnp.where(mask[:, None], ad, bd)
        lengths = jnp.where(mask, a.lengths, b.lengths)
        return Column(dtype, data, validity, lengths)
    data = jnp.where(mask, a.data, b.data)
    return Column(dtype, data, validity)


def _bool_mask(v, capacity: int) -> jnp.ndarray:
    """Predicate value -> taken-mask (NULL predicate counts as false, Spark semantics)."""
    if isinstance(v, Scalar):
        taken = bool(v.value) if not v.is_null else False
        return jnp.broadcast_to(jnp.asarray(taken), (capacity,))
    return v.data & v.validity


class If(Expression):
    """GpuIf."""

    @property
    def dtype(self) -> dt.DType:
        return self.children[1].dtype

    def eval(self, batch: ColumnarBatch):
        pred = self.children[0].eval(batch)
        tv = self.children[1].eval(batch)
        fv = self.children[2].eval(batch)
        if isinstance(pred, Scalar) and isinstance(tv, Scalar) and isinstance(fv, Scalar):
            taken = bool(pred.value) if not pred.is_null else False
            return tv if taken else fv
        mask = _bool_mask(pred, batch.capacity)
        return _select(mask, materialize(tv, batch), materialize(fv, batch),
                       self.dtype, batch.capacity)


class CaseWhen(Expression):
    """GpuCaseWhen: children = [cond1, val1, cond2, val2, ..., (else)]."""

    def __init__(self, branches: List[Tuple[Expression, Expression]],
                 else_value: Optional[Expression] = None):
        flat: List[Expression] = []
        for c, v in branches:
            flat.extend([c, v])
        if else_value is not None:
            flat.append(else_value)
        super().__init__(*flat)
        self.num_branches = len(branches)
        self.has_else = else_value is not None

    @property
    def dtype(self) -> dt.DType:
        return self.children[1].dtype

    def eval(self, batch: ColumnarBatch):
        cap = batch.capacity
        if self.has_else:
            result = materialize(self.children[-1].eval(batch), batch)
        else:
            result = Column.full_null(self.dtype, cap)
        # apply branches last-to-first so the first matching branch wins
        for i in reversed(range(self.num_branches)):
            cond = self.children[2 * i].eval(batch)
            val = materialize(self.children[2 * i + 1].eval(batch), batch)
            mask = _bool_mask(cond, cap)
            result = _select(mask, val, result, self.dtype, cap)
        return result


class Coalesce(Expression):
    """GpuCoalesce: first non-null argument."""

    @property
    def dtype(self) -> dt.DType:
        return self.children[0].dtype

    @property
    def nullable(self) -> bool:
        return all(c.nullable for c in self.children)

    def eval(self, batch: ColumnarBatch):
        cap = batch.capacity
        result = Column.full_null(self.dtype, cap)
        decided = jnp.zeros(cap, dtype=jnp.bool_)
        for child in self.children:
            v = materialize(child.eval(batch), batch)
            take = (~decided) & v.validity
            result = _select(take, v, result, self.dtype, cap)
            decided = decided | v.validity
        return result


class Nvl(Coalesce):
    """ifnull/nvl = 2-arg coalesce (nullExpressions.scala)."""


class NullIf(Expression):
    """nullif(a, b): NULL when a = b else a."""

    @property
    def dtype(self) -> dt.DType:
        return self.children[0].dtype

    def eval(self, batch: ColumnarBatch):
        from .predicates import float_eq
        from .strings_util import string_equal
        a = materialize(self.children[0].eval(batch), batch)
        bv = self.children[1].eval(batch)
        in_dtype = self.dtype
        if in_dtype == dt.STRING:
            eq = string_equal(a, bv, batch.capacity)
            bvalid = bv.validity if isinstance(bv, Column) else \
                jnp.broadcast_to(jnp.asarray(not bv.is_null), (batch.capacity,))
            eq = eq & bvalid
        else:
            bd, bval = data_validity(bv, in_dtype)
            eq = float_eq(a.data, bd) if in_dtype.is_floating else (a.data == bd)
            bvalid = bv.validity if isinstance(bv, Column) else \
                jnp.broadcast_to(jnp.asarray(bval), (batch.capacity,))
            eq = eq & bvalid
        eq_mask = jnp.broadcast_to(eq, (batch.capacity,)) & a.validity
        validity = a.validity & ~eq_mask
        if self.dtype == dt.STRING:
            return Column(self.dtype, a.data, validity, a.lengths)
        return Column(self.dtype, jnp.where(validity, a.data,
                                            jnp.zeros((), a.data.dtype)), validity)


class _MinMaxN(Expression):
    """Least/Greatest: skip NULLs; NULL only when all inputs NULL. NaN handling:
    greatest treats NaN as largest (Spark uses standard ordering)."""

    _take_greater: bool

    @property
    def dtype(self) -> dt.DType:
        return self.children[0].dtype

    @property
    def nullable(self) -> bool:
        return all(c.nullable for c in self.children)

    def eval(self, batch: ColumnarBatch):
        cap = batch.capacity
        result = Column.full_null(self.dtype, cap)
        for child in self.children:
            v = materialize(child.eval(batch), batch)
            if self.dtype == dt.STRING:
                from .strings_util import string_compare
                cmp = string_compare(v, result, cap)
                better = cmp > 0 if self._take_greater else cmp < 0
            elif self.dtype.is_floating:
                from .predicates import float_lt
                better = float_lt(result.data, v.data) if self._take_greater \
                    else float_lt(v.data, result.data)
            else:
                better = v.data > result.data if self._take_greater \
                    else v.data < result.data
            take = v.validity & (~result.validity | better)
            result = _select(take, v, result, self.dtype, cap)
        return result


class Greatest(_MinMaxN):
    _take_greater = True


class Least(_MinMaxN):
    _take_greater = False
