"""Date/time expressions: the datetimeExpressions analog.

Reference: ``org/apache/spark/sql/rapids/datetimeExpressions.scala`` (575 LoC) —
year/month/day/hour/minute/second, date add/sub/diff, unix_timestamp family,
from_unixtime. Storage (dtypes.py): DATE = int32 days since epoch, TIMESTAMP =
int64 microseconds since epoch (same physical choice as cuDF TIMESTAMP_DAYS /
TIMESTAMP_MICROSECONDS).

Civil-date decomposition uses the days->(y,m,d) integer algorithm (public-domain
"civil_from_days", Howard Hinnant's date algorithms) — branch-free and fully
vectorizable on the VPU, unlike a host strftime loop.
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp

from ..columnar import dtypes as dt
from ..columnar.batch import ColumnarBatch
from ..columnar.column import Column, Scalar
from .expressions import (Expression, combine_validity, data_validity,
                          result_column)

MICROS_PER_SECOND = 1_000_000
MICROS_PER_DAY = 86_400 * MICROS_PER_SECOND


def civil_from_days(days: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """(year, month, day) int32 triples from days-since-1970 (vectorized)."""
    z = days.astype(jnp.int64) + 719468
    era = jnp.floor_divide(z, 146097)
    doe = z - era * 146097                                   # [0, 146096]
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)          # [0, 365]
    mp = (5 * doy + 2) // 153                                # [0, 11]
    d = doy - (153 * mp + 2) // 5 + 1                        # [1, 31]
    m = jnp.where(mp < 10, mp + 3, mp - 9)                   # [1, 12]
    y = jnp.where(m <= 2, y + 1, y)
    return y.astype(jnp.int32), m.astype(jnp.int32), d.astype(jnp.int32)


def days_from_civil(y: jnp.ndarray, m: jnp.ndarray, d: jnp.ndarray) -> jnp.ndarray:
    """days-since-1970 from (year, month, day) (vectorized inverse)."""
    y = y.astype(jnp.int64)
    m = m.astype(jnp.int64)
    d = d.astype(jnp.int64)
    y = jnp.where(m <= 2, y - 1, y)
    era = jnp.floor_divide(y, 400)
    yoe = y - era * 400
    mp = jnp.where(m > 2, m - 3, m + 9)
    doy = (153 * mp + 2) // 5 + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return (era * 146097 + doe - 719468).astype(jnp.int32)


def _days_of(col_data: jnp.ndarray, in_dtype: dt.DType) -> jnp.ndarray:
    """Normalize DATE(int32 days) or TIMESTAMP(int64 micros) to days."""
    if in_dtype == dt.TIMESTAMP:
        return jnp.floor_divide(col_data, MICROS_PER_DAY).astype(jnp.int32)
    return col_data


class _DatePart(Expression):
    """Base: extract an int32 part from a DATE or TIMESTAMP child."""

    @property
    def dtype(self):
        return dt.INT32

    def _part(self, data, in_dtype):
        raise NotImplementedError

    def eval(self, batch: ColumnarBatch):
        v = self.children[0].eval(batch)
        in_dtype = self.children[0].dtype
        if isinstance(v, Scalar):
            if v.is_null:
                return Scalar(None, dt.INT32)
            data = jnp.asarray([v.value], dtype=in_dtype.numpy_dtype)
            return Scalar(int(self._part(data, in_dtype)[0]), dt.INT32)
        data = self._part(v.data, in_dtype)
        data = jnp.where(v.validity, data, 0)
        return result_column(dt.INT32, data, v.validity, batch.capacity)


class Year(_DatePart):
    def _part(self, data, in_dtype):
        y, _, _ = civil_from_days(_days_of(data, in_dtype))
        return y


class Month(_DatePart):
    def _part(self, data, in_dtype):
        _, m, _ = civil_from_days(_days_of(data, in_dtype))
        return m


class DayOfMonth(_DatePart):
    def _part(self, data, in_dtype):
        _, _, d = civil_from_days(_days_of(data, in_dtype))
        return d


class Quarter(_DatePart):
    def _part(self, data, in_dtype):
        _, m, _ = civil_from_days(_days_of(data, in_dtype))
        return (m - 1) // 3 + 1


class DayOfWeek(_DatePart):
    """Spark: Sunday=1 .. Saturday=7; epoch day 0 (1970-01-01) was a Thursday."""
    def _part(self, data, in_dtype):
        days = _days_of(data, in_dtype).astype(jnp.int64)
        return (jnp.mod(days + 4, 7) + 1).astype(jnp.int32)


class WeekDay(_DatePart):
    """Monday=0 .. Sunday=6 (Spark weekday())."""
    def _part(self, data, in_dtype):
        days = _days_of(data, in_dtype).astype(jnp.int64)
        return jnp.mod(days + 3, 7).astype(jnp.int32)


class DayOfYear(_DatePart):
    def _part(self, data, in_dtype):
        days = _days_of(data, in_dtype)
        y, _, _ = civil_from_days(days)
        jan1 = days_from_civil(y, jnp.ones_like(y), jnp.ones_like(y))
        return days - jan1 + 1


class LastDay(Expression):
    """last_day(date): last day of the month, returns DATE."""

    @property
    def dtype(self):
        return dt.DATE

    def eval(self, batch: ColumnarBatch):
        v = self.children[0].eval(batch)
        in_dtype = self.children[0].dtype
        if isinstance(v, Scalar):
            if v.is_null:
                return Scalar(None, dt.DATE)
            data = jnp.asarray([v.value], dtype=in_dtype.numpy_dtype)
            return Scalar(int(self._compute(data, in_dtype)[0]), dt.DATE)
        data = jnp.where(v.validity, self._compute(v.data, in_dtype), 0)
        return result_column(dt.DATE, data, v.validity, batch.capacity)

    def _compute(self, data, in_dtype):
        days = _days_of(data, in_dtype)
        y, m, _ = civil_from_days(days)
        ny = jnp.where(m == 12, y + 1, y)
        nm = jnp.where(m == 12, 1, m + 1)
        return days_from_civil(ny, nm, jnp.ones_like(nm)) - 1


class _TimePart(_DatePart):
    """Hour/minute/second from TIMESTAMP micros (floor semantics for pre-epoch)."""
    _div: int
    _mod: int

    def _part(self, data, in_dtype):
        assert in_dtype == dt.TIMESTAMP
        sec = jnp.floor_divide(data, MICROS_PER_SECOND)
        return jnp.mod(jnp.floor_divide(sec, self._div), self._mod).astype(jnp.int32)


class Hour(_TimePart):
    _div, _mod = 3600, 24


class Minute(_TimePart):
    _div, _mod = 60, 60


class Second(_TimePart):
    _div, _mod = 1, 60


class DateAdd(Expression):
    """date_add(date, n): DATE + int days (GpuDateAdd)."""
    _sign = 1

    @property
    def dtype(self):
        return dt.DATE

    def eval(self, batch: ColumnarBatch):
        lv = self.children[0].eval(batch)
        rv = self.children[1].eval(batch)
        ld, lval = data_validity(lv, dt.DATE)
        rd, rval = data_validity(rv, dt.INT32)
        data = ld + self._sign * rd.astype(jnp.int32)
        validity = combine_validity(lval, rval)
        if validity is not True:
            data = jnp.where(jnp.broadcast_to(validity, (batch.capacity,)), data, 0)
        if isinstance(lv, Scalar) and isinstance(rv, Scalar):
            if lv.is_null or rv.is_null:
                return Scalar(None, dt.DATE)
            return Scalar(int(data), dt.DATE)
        return result_column(dt.DATE, data, validity, batch.capacity)


class DateSub(DateAdd):
    _sign = -1


class DateDiff(Expression):
    """datediff(end, start): int32 day difference (GpuDateDiff)."""

    @property
    def dtype(self):
        return dt.INT32

    def eval(self, batch: ColumnarBatch):
        lv = self.children[0].eval(batch)
        rv = self.children[1].eval(batch)
        ld, lval = data_validity(lv, dt.DATE)
        rd, rval = data_validity(rv, dt.DATE)
        data = ld - rd
        validity = combine_validity(lval, rval)
        if isinstance(lv, Scalar) and isinstance(rv, Scalar):
            if lv.is_null or rv.is_null:
                return Scalar(None, dt.INT32)
            return Scalar(int(data), dt.INT32)
        if validity is not True:
            data = jnp.where(jnp.broadcast_to(validity, (batch.capacity,)), data, 0)
        return result_column(dt.INT32, data, validity, batch.capacity)


class AddMonths(Expression):
    """add_months(date, n): clamps day to the target month's last day."""

    @property
    def dtype(self):
        return dt.DATE

    def eval(self, batch: ColumnarBatch):
        lv = self.children[0].eval(batch)
        rv = self.children[1].eval(batch)
        ld, lval = data_validity(lv, dt.DATE)
        rd, rval = data_validity(rv, dt.INT32)
        y, m, d = civil_from_days(jnp.atleast_1d(ld))
        total = y.astype(jnp.int64) * 12 + (m - 1) + jnp.atleast_1d(rd).astype(jnp.int64)
        ny = jnp.floor_divide(total, 12).astype(jnp.int32)
        nm = (jnp.mod(total, 12) + 1).astype(jnp.int32)
        # clamp day to the target month's length (= first-of-next minus first)
        nny = jnp.where(nm == 12, ny + 1, ny)
        nnm = jnp.where(nm == 12, 1, nm + 1)
        month_len = (days_from_civil(nny, nnm, jnp.ones_like(nnm)) -
                     days_from_civil(ny, nm, jnp.ones_like(nm)))
        nd = jnp.minimum(d, month_len.astype(jnp.int32))
        data = days_from_civil(ny, nm, nd)
        validity = combine_validity(lval, rval)
        if isinstance(lv, Scalar) and isinstance(rv, Scalar):
            if lv.is_null or rv.is_null:
                return Scalar(None, dt.DATE)
            return Scalar(int(data[0]), dt.DATE)
        if validity is not True:
            data = jnp.where(jnp.broadcast_to(validity, (batch.capacity,)), data, 0)
        return result_column(dt.DATE, data, validity, batch.capacity)


class UnixTimestamp(Expression):
    """unix_timestamp(ts): TIMESTAMP -> bigint seconds (floor). The string-input
    form goes through Cast(string->timestamp) during analysis, mirroring the
    reference's conf-gated improvedTimeOps path (RapidsConf improvedTimeOps)."""

    @property
    def dtype(self):
        return dt.INT64

    def eval(self, batch: ColumnarBatch):
        v = self.children[0].eval(batch)
        in_dtype = self.children[0].dtype
        if isinstance(v, Scalar):
            if v.is_null:
                return Scalar(None, dt.INT64)
            micros = (v.value * MICROS_PER_DAY if in_dtype == dt.DATE else v.value)
            return Scalar(int(micros // MICROS_PER_SECOND), dt.INT64)
        data = v.data.astype(jnp.int64)
        if in_dtype == dt.DATE:
            data = data * (MICROS_PER_DAY // MICROS_PER_SECOND)
        else:
            data = jnp.floor_divide(data, MICROS_PER_SECOND)
        data = jnp.where(v.validity, data, 0)
        return result_column(dt.INT64, data, v.validity, batch.capacity)


class FromUnixTime(Expression):
    """from_unixtime(sec): bigint seconds -> TIMESTAMP (micros). Spark returns a
    formatted string; analysis composes Cast(timestamp->string) for the default
    format, matching the reference's from_unixtime handling."""

    @property
    def dtype(self):
        return dt.TIMESTAMP

    def eval(self, batch: ColumnarBatch):
        v = self.children[0].eval(batch)
        if isinstance(v, Scalar):
            if v.is_null:
                return Scalar(None, dt.TIMESTAMP)
            return Scalar(int(v.value) * MICROS_PER_SECOND, dt.TIMESTAMP)
        data = v.data.astype(jnp.int64) * MICROS_PER_SECOND
        data = jnp.where(v.validity, data, 0)
        return result_column(dt.TIMESTAMP, data, v.validity, batch.capacity)


class ToDate(Expression):
    """to_date / Cast-to-date from TIMESTAMP (floor to day)."""

    @property
    def dtype(self):
        return dt.DATE

    def eval(self, batch: ColumnarBatch):
        v = self.children[0].eval(batch)
        in_dtype = self.children[0].dtype
        if isinstance(v, Scalar):
            if v.is_null:
                return Scalar(None, dt.DATE)
            if in_dtype == dt.DATE:
                return v
            return Scalar(int(v.value // MICROS_PER_DAY), dt.DATE)
        if in_dtype == dt.DATE:
            return v
        data = jnp.floor_divide(v.data, MICROS_PER_DAY).astype(jnp.int32)
        data = jnp.where(v.validity, data, 0)
        return result_column(dt.DATE, data, v.validity, batch.capacity)
