"""Expression tree base classes: the ``GpuExpression`` analog.

Reference: ``GpuExpressions.scala:63-109`` (columnarEval contract: each expression
evaluates a ColumnarBatch to a GpuColumnVector or Scalar) plus ``literals.scala``,
``GpuBoundAttribute.scala``, ``namedExpressions.scala``.

TPU-first difference (DESIGN.md §2): ``eval`` is pure jax.numpy over the batch's
device arrays, so an entire expression tree traces into ONE XLA computation instead
of one cuDF kernel launch per node. Expressions that need host work (e.g. number->
string formatting) set ``fusable = False`` and run eagerly between fused stages.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Union

import jax.numpy as jnp
import numpy as np

from ..columnar import dtypes as dt
from ..columnar.batch import ColumnarBatch
from ..columnar.column import Column, Scalar

ColumnOrScalar = Union[Column, Scalar]


def is_traced(value: Any) -> bool:
    """True when ``value`` is a jax tracer — a rebindable :class:`Parameter`
    riding an active fused trace. Scalar folds must keep such values
    in-graph (jnp): any numpy/python conversion would concretize the tracer
    and abort the whole fused program back to eager."""
    import jax

    return isinstance(value, jax.core.Tracer)


class Expression:
    """Base expression. Subclasses set ``children`` and implement ``dtype``/``eval``."""

    fusable: bool = True          # False => needs host execution, breaks stage fusion
    side_effect_free: bool = True

    def __init__(self, *children: "Expression"):
        self.children: List[Expression] = list(children)

    @property
    def dtype(self) -> dt.DType:
        raise NotImplementedError

    @property
    def nullable(self) -> bool:
        return any(c.nullable for c in self.children) if self.children else True

    def eval(self, batch: ColumnarBatch) -> ColumnOrScalar:
        raise NotImplementedError

    # -- tree utilities ------------------------------------------------------
    def transform(self, fn) -> "Expression":
        """Bottom-up transform returning a new tree (Catalyst transformUp analog)."""
        new_children = [c.transform(fn) for c in self.children]
        node = self
        if new_children != self.children:
            node = self.with_children(new_children)
        replaced = fn(node)
        return node if replaced is None else replaced

    def transform_down(self, fn) -> "Expression":
        """Top-down transform (Catalyst transformDown analog): ``fn`` sees
        each ORIGINAL node before its children are rewritten, and a replaced
        node's subtree is not descended into. Required whenever ``fn`` matches
        nodes by identity — a bottom-up pass copies any node whose children
        changed, so identity checks would silently miss it."""
        replaced = fn(self)
        if replaced is not None:
            return replaced
        new_children = [c.transform_down(fn) for c in self.children]
        if new_children != self.children:
            return self.with_children(new_children)
        return self

    def with_children(self, children: List["Expression"]) -> "Expression":
        import copy
        node = copy.copy(node_src := self)
        node.children = children
        # subclasses keeping aliases of children must override
        node._rebind_child_aliases()
        return node

    def _rebind_child_aliases(self) -> None:
        pass

    def collect(self, pred) -> List["Expression"]:
        out = [self] if pred(self) else []
        for c in self.children:
            out.extend(c.collect(pred))
        return out

    def tree_fusable(self) -> bool:
        return self.fusable and all(c.tree_fusable() for c in self.children)

    @property
    def name(self) -> str:
        return type(self).__name__

    def sql_name(self) -> str:
        return type(self).__name__.lower()

    def __repr__(self):
        args = ", ".join(repr(c) for c in self.children)
        return f"{type(self).__name__}({args})"


# ---------------------------------------------------------------------------
# Leaves
# ---------------------------------------------------------------------------

class Literal(Expression):
    """GpuLiteral analog (literals.scala)."""

    def __init__(self, value: Any, dtype: Optional[dt.DType] = None):
        super().__init__()
        if dtype is None:
            if isinstance(value, bool):
                dtype = dt.BOOL
            elif isinstance(value, int):
                dtype = dt.INT64  # will narrow via implicit cast if needed
            elif isinstance(value, float):
                dtype = dt.FLOAT64
            elif isinstance(value, str):
                dtype = dt.STRING
            elif value is None:
                dtype = dt.NULLTYPE
            else:
                raise TypeError(f"cannot infer literal type for {value!r}")
        self._dtype = dtype
        self.value = value

    @property
    def dtype(self) -> dt.DType:
        return self._dtype

    @property
    def nullable(self) -> bool:
        return self.value is None

    def eval(self, batch: ColumnarBatch) -> Scalar:
        return Scalar(self.value, self._dtype)

    def __repr__(self):
        return f"Literal({self.value!r})"


class Parameter(Literal):
    """A runtime query parameter: a :class:`Literal` whose VALUE is a
    rebindable scalar argument instead of a plan constant (the serving
    front door, docs/plan_cache.md).

    The plan cache's parameterization pass replaces eligible constant
    subtrees with Parameters so q6 with a different date range produces
    the SAME plan fingerprint and the same compiled ``_fused_fn``
    signatures — the structural cache key is ``("param", slot, dtype)``,
    never the value. Fused programs receive the current values as extra
    traced jit arguments appended after the batch's flat arrays
    (``ColumnarBatch.params``); eager/CPU paths read ``self.value`` like
    any literal (Parameter IS-A Literal, so every isinstance fast path
    keeps working).

    ``slot``: plan-wide parameter index (deterministic traversal order —
    structural, so two plans of the same shape number identically).
    ``trace_pos``: position of this parameter inside its consuming fused
    program's appended argument tuple (stamped by the consumer before its
    first trace; baked into the compiled program).
    ``name``: optional prepared-statement placeholder name (``:name``).
    """

    def __init__(self, value: Any = None, dtype: Optional[dt.DType] = None,
                 slot: int = -1, name: Optional[str] = None):
        if dtype is None and value is None:
            # a named placeholder before its first bind: dtype resolves
            # from the first execute()'s value
            Expression.__init__(self)
            self._dtype = None
            self.value = None
        else:
            super().__init__(value, dtype)
        self.slot = slot
        self.param_name = name
        self.trace_pos: Optional[int] = None

    @property
    def dtype(self) -> dt.DType:
        if self._dtype is None:
            # pre-bind: parse builds throwaway analyzed copies (schema
            # probes like df.columns) that must not crash on a
            # placeholder nobody has bound yet — it types as NULLTYPE
            # there. Execution re-analyzes AFTER binding, and eval()
            # still refuses to run unbound.
            return dt.NULLTYPE
        return self._dtype

    @property
    def nullable(self) -> bool:
        return False          # parameters never bind NULL (bind() rejects)

    def bind(self, value: Any, dtype: Optional[dt.DType] = None,
             retype: bool = False) -> None:
        """Rebind the runtime value. The dtype is FIXED once set — the
        compiled programs were traced for it; only a prepared
        statement's PARSE-TREE placeholders may ``retype`` (a dtype
        change there produces a different fingerprint and a fresh
        plan, never a stale program)."""
        if value is None:
            raise ValueError(
                f"parameter :{self.param_name or self.slot} cannot bind "
                "NULL (plan a literal NULL instead)")
        if self._dtype is None or retype:
            self._dtype = dtype if dtype is not None else \
                Literal(value).dtype
        self.value = value

    def traceable(self) -> bool:
        """Whether this parameter's value can ride as a traced 0-d jit
        argument (fixed-width scalar dtypes). Non-traceable parameters
        (strings) stay baked: their VALUE joins the structural cache key
        so a rebind can never reuse a stale program."""
        return (self._dtype is not None and
                self._dtype.numpy_dtype is not None and
                not self._dtype.var_width)

    def eval(self, batch: ColumnarBatch) -> Scalar:
        if self._dtype is None or self.value is None:
            raise RuntimeError(
                f"unbound parameter :{self.param_name or self.slot} — "
                "prepared statements must bind every placeholder before "
                "execution")
        pv = getattr(batch, "params", ()) if batch is not None else ()
        if pv and self.trace_pos is not None and self.trace_pos < len(pv):
            # inside a fused trace: the value is a traced 0-d argument
            return Scalar(pv[self.trace_pos], self.dtype)
        return Scalar(self.value, self.dtype)

    def __repr__(self):
        tag = self.param_name or f"p{self.slot}"
        return f"Param(:{tag}={self.value!r})"


def ordered_params(exprs: Sequence[Expression]) -> List["Parameter"]:
    """Unique TRACEABLE Parameters across ``exprs`` in slot order, each
    stamped with its ``trace_pos`` — the canonical appended-argument
    ordering a fused program and its call sites must agree on.
    Non-traceable parameters (strings) stay baked; their values ride the
    structural cache key instead."""
    by_slot: dict = {}
    for e in exprs:
        for p in e.collect(lambda x: isinstance(x, Parameter)):
            if p.traceable():
                by_slot.setdefault(p.slot, p)
    out = [by_slot[s] for s in sorted(by_slot)]
    for i, p in enumerate(out):
        p.trace_pos = i
    return out


def param_arg_values(params: Sequence["Parameter"]) -> tuple:
    """The current binding of each parameter as a dtype-stable numpy
    scalar — the extra jit arguments appended after a batch's flat
    arrays. Host-side value boxing, no device sync."""
    return tuple(
        np.asarray(p.value, dtype=p.dtype.numpy_dtype)  # lint: host-sync-ok boxes a python scalar host-side; no device value involved
        for p in params)


class ColumnRef(Expression):
    """Name-based column reference (pre-binding; Catalyst AttributeReference analog)."""

    def __init__(self, col_name: str):
        super().__init__()
        self.col_name = col_name
        self._resolved: Optional[dt.Field] = None

    def resolve(self, schema: dt.Schema) -> "ColumnRef":
        self._resolved = schema[self.col_name]
        return self

    @property
    def dtype(self) -> dt.DType:
        if self._resolved is None:
            raise RuntimeError(f"unresolved column {self.col_name!r}")
        return self._resolved.dtype

    @property
    def nullable(self) -> bool:
        return self._resolved.nullable if self._resolved else True

    def eval(self, batch: ColumnarBatch) -> Column:
        return batch.column(self.col_name)

    def __repr__(self):
        return f"col({self.col_name!r})"


class BoundReference(Expression):
    """Ordinal-bound input reference (GpuBoundReference, GpuBoundAttribute.scala)."""

    def __init__(self, ordinal: int, dtype: dt.DType, nullable: bool = True,
                 col_name: str = ""):
        super().__init__()
        self.ordinal = ordinal
        self._dtype = dtype
        self._nullable = nullable
        self.col_name = col_name

    @property
    def dtype(self) -> dt.DType:
        return self._dtype

    @property
    def nullable(self) -> bool:
        return self._nullable

    def eval(self, batch: ColumnarBatch) -> Column:
        return batch.columns[self.ordinal]

    def __repr__(self):
        return f"input[{self.ordinal}, {self._dtype}]"


class Alias(Expression):
    """Named output wrapper (GpuAlias, namedExpressions.scala)."""

    def __init__(self, child: Expression, alias: str):
        super().__init__(child)
        self.alias = alias

    @property
    def child(self) -> Expression:
        return self.children[0]

    def _rebind_child_aliases(self) -> None:
        pass

    @property
    def dtype(self) -> dt.DType:
        return self.child.dtype

    @property
    def nullable(self) -> bool:
        return self.child.nullable

    def eval(self, batch: ColumnarBatch) -> ColumnOrScalar:
        return self.child.eval(batch)

    def __repr__(self):
        return f"{self.child!r} AS {self.alias}"


def output_name(expr: Expression, idx: int) -> str:
    if isinstance(expr, Alias):
        return expr.alias
    if isinstance(expr, ColumnRef):
        return expr.col_name
    if isinstance(expr, BoundReference) and expr.col_name:
        return expr.col_name
    return f"col{idx}"


# ---------------------------------------------------------------------------
# Eval helpers shared by concrete expression modules
# ---------------------------------------------------------------------------

def materialize(value: ColumnOrScalar, batch: ColumnarBatch) -> Column:
    """Scalar -> broadcast Column at the batch's capacity (rare; ops prefer inline)."""
    if isinstance(value, Scalar):
        return Column.from_scalar(value, batch.num_rows, batch.capacity)
    return value


def data_validity(value: ColumnOrScalar, dtype: dt.DType):
    """(data, validity) pair usable in jnp broadcasting.

    Scalars become 0-d jnp values + validity True/False python bools so XLA folds
    them as constants inside fused computations.
    """
    if isinstance(value, Scalar):
        if value.is_null:
            return jnp.zeros((), dtype=dtype.numpy_dtype), False
        return jnp.asarray(value.value, dtype=dtype.numpy_dtype), True
    return value.data, value.validity


def combine_validity(*vs):
    """AND of validities where python ``True`` means always-valid."""
    cols = [v for v in vs if not (v is True)]
    if not cols:
        return True
    out = cols[0]
    for v in cols[1:]:
        out = out & v
    return out


def result_column(dtype: dt.DType, data: jnp.ndarray, validity, capacity: int,
                  lengths=None) -> Column:
    if validity is True:
        validity = jnp.ones(capacity, dtype=jnp.bool_)
    elif validity is False:
        validity = jnp.zeros(capacity, dtype=jnp.bool_)
    if data.ndim == 0 or (dtype != dt.STRING and data.shape[0] != capacity):
        data = jnp.broadcast_to(data, (capacity,))
    return Column(dtype, data, validity, lengths)


def lit(value: Any, dtype: Optional[dt.DType] = None) -> Literal:
    return Literal(value, dtype)


def col(name: str) -> ColumnRef:
    return ColumnRef(name)
