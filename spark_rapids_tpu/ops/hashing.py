"""Hash and misc nondeterministic expressions.

Reference: ``HashFunctions.scala`` (Md5, Murmur3Hash), ``GpuRand``,
``GpuMonotonicallyIncreasingID``, ``GpuSparkPartitionID`` (SURVEY.md §2.3).

Murmur3 here is bit-compatible with Spark's ``Murmur3Hash`` (x86_32 variant,
seed 42, Spark's special handling: ints/dates hash as int32, longs/timestamps
as int64, floats widened like Spark's hashLong/hashInt normalization, strings
hash their UTF-8 bytes). Bit-compat matters because hash partitioning must
place rows identically to Spark for golden-compare shuffles.
"""

from __future__ import annotations

from typing import List, Sequence

import jax.numpy as jnp
import numpy as np

from ..columnar import dtypes as dt
from ..columnar.batch import ColumnarBatch
from ..columnar.column import Column, Scalar
from .expressions import Expression, result_column

_C1 = jnp.uint32(0xCC9E2D51)
_C2 = jnp.uint32(0x1B873593)


def _rotl(x, r):
    return (x << r) | (x >> (32 - r))


def _mix_k1(k1):
    k1 = (k1 * _C1).astype(jnp.uint32)
    k1 = _rotl(k1, 15)
    return (k1 * _C2).astype(jnp.uint32)


def _mix_h1(h1, k1):
    h1 = h1 ^ k1
    h1 = _rotl(h1, 13)
    return (h1 * jnp.uint32(5) + jnp.uint32(0xE6546B64)).astype(jnp.uint32)


def _fmix(h1, length):
    h1 = h1 ^ jnp.uint32(length) if isinstance(length, int) else h1 ^ length.astype(jnp.uint32)
    h1 = h1 ^ (h1 >> 16)
    h1 = (h1 * jnp.uint32(0x85EBCA6B)).astype(jnp.uint32)
    h1 = h1 ^ (h1 >> 13)
    h1 = (h1 * jnp.uint32(0xC2B2AE35)).astype(jnp.uint32)
    return h1 ^ (h1 >> 16)


def _hash_int32(data: jnp.ndarray, seed: jnp.ndarray) -> jnp.ndarray:
    """Spark Murmur3_x86_32.hashInt: one 4-byte block."""
    k1 = _mix_k1(data.astype(jnp.uint32))
    h1 = _mix_h1(seed, k1)
    return _fmix(h1, 4)


def _hash_int64(data: jnp.ndarray, seed: jnp.ndarray) -> jnp.ndarray:
    """Spark hashLong: low word block then high word block."""
    low = data.astype(jnp.uint64).astype(jnp.uint32)
    high = (data.astype(jnp.uint64) >> 32).astype(jnp.uint32)
    h1 = _mix_h1(seed, _mix_k1(low))
    h1 = _mix_h1(h1, _mix_k1(high))
    return _fmix(h1, 8)


def _hash_bytes(data: jnp.ndarray, lengths: jnp.ndarray,
                seed: jnp.ndarray) -> jnp.ndarray:
    """Spark hashUnsafeBytes over UTF-8 strings: 4-byte little-endian blocks,
    then Spark's *signed-byte* tail mixing (each trailing byte hashed as an int
    block — matches UnsafeHashedRelation's hashUnsafeBytes, which Spark uses
    for string columns in Murmur3Hash)."""
    n, w = data.shape
    nblocks = w // 4
    h1 = jnp.broadcast_to(seed, (n,)).astype(jnp.uint32)
    # full 4-byte blocks while block fits within length
    for b in range(nblocks):
        chunk = data[:, b * 4:(b + 1) * 4].astype(jnp.uint32)
        k1 = chunk[:, 0] | (chunk[:, 1] << 8) | (chunk[:, 2] << 16) | (chunk[:, 3] << 24)
        in_block = lengths >= (b + 1) * 4
        h1 = jnp.where(in_block, _mix_h1(h1, _mix_k1(k1)), h1)
    # tail: Spark hashes each remaining byte as a SIGNED int block
    for i in range(4):
        # byte index = (len//4)*4 + i for rows where that's < len
        base = (lengths // 4) * 4
        idx = base + i
        take = idx < lengths
        byte = jnp.take_along_axis(
            data, jnp.clip(idx, 0, w - 1)[:, None].astype(jnp.int32), axis=1)[:, 0]
        sbyte = byte.astype(jnp.int8).astype(jnp.int32).astype(jnp.uint32)
        h1 = jnp.where(take, _mix_h1(h1, _mix_k1(sbyte)), h1)
    return _fmix(h1, lengths)


def murmur3_column(col: Column, seed: jnp.ndarray) -> jnp.ndarray:
    """int32 hash per row; NULL rows leave the seed unchanged (Spark semantics:
    null columns don't contribute to the hash)."""
    if col.dtype == dt.STRING:
        h = _hash_bytes(col.data, col.lengths, seed)
    elif col.dtype in (dt.INT64, dt.TIMESTAMP):
        h = _hash_int64(col.data, seed)
    elif col.dtype == dt.FLOAT64:
        # Spark: normalize -0.0 to 0.0, hash as long bits
        norm = jnp.where(col.data == 0.0, 0.0, col.data)
        import jax
        bits = jax.lax.bitcast_convert_type(norm, jnp.int64)
        h = _hash_int64(bits, seed)
    elif col.dtype == dt.FLOAT32:
        norm = jnp.where(col.data == 0.0, jnp.float32(0.0), col.data)
        import jax
        bits = jax.lax.bitcast_convert_type(norm, jnp.int32)
        h = _hash_int32(bits, seed)
    elif col.dtype == dt.BOOL:
        h = _hash_int32(col.data.astype(jnp.int32), seed)
    else:  # int8/16/32, date — all hash as int blocks
        h = _hash_int32(col.data.astype(jnp.int32), seed)
    return jnp.where(col.validity, h, seed).astype(jnp.uint32)


def murmur3_batch(cols: Sequence[Column], capacity: int,
                  seed: int = 42) -> jnp.ndarray:
    """Row hash across columns, chained like Spark's Murmur3Hash(children, 42):
    the previous column's hash is the next column's seed. Returns int32[cap]."""
    h = jnp.full(capacity, seed, dtype=jnp.uint32)
    for c in cols:
        h = murmur3_column(c, h)
    return h.astype(jnp.int32)


class Murmur3Hash(Expression):
    """hash(...) expression (Spark Murmur3Hash, seed 42)."""

    def __init__(self, *children: Expression, seed: int = 42):
        super().__init__(*children)
        self.seed = seed

    @property
    def dtype(self):
        return dt.INT32

    @property
    def nullable(self):
        return False

    def eval(self, batch: ColumnarBatch):
        from .expressions import materialize
        cols = [materialize(c.eval(batch), batch) for c in self.children]
        data = murmur3_batch(cols, batch.capacity, self.seed)
        live = batch.row_mask()
        return result_column(dt.INT32, jnp.where(live, data, 0), live,
                             batch.capacity)


class Md5(Expression):
    """md5(string) — host computed (no TPU digest units; the reference runs this
    on GPU via cuDF but the op is cold-path)."""
    fusable = False

    @property
    def dtype(self):
        return dt.STRING

    def eval(self, batch: ColumnarBatch):
        import hashlib
        v = self.children[0].eval(batch)
        if isinstance(v, Scalar):
            if v.is_null:
                return Scalar(None, dt.STRING)
            return Scalar(hashlib.md5(str(v.value).encode()).hexdigest(), dt.STRING)
        vals = v.to_pylist(batch.num_rows)
        out = [None if x is None else hashlib.md5(x.encode()).hexdigest()
               for x in vals]
        return Column.from_pylist(out, dt.STRING, capacity=batch.capacity)


class Rand(Expression):
    """rand(seed): per-row uniform [0,1) via threefry — deterministic given
    (seed, partition, batch ordinal) like GpuRand's per-partition XORShift
    stream. The batch ordinal is folded into the PRNG key so successive
    batches in a partition draw fresh values instead of replaying the
    sequence; the exec advances it via ``advance()`` after each batch."""
    side_effect_free = False

    def __init__(self, seed: int = 0):
        super().__init__()
        self.seed = seed
        self.partition_index = 0
        self._batch_ordinal = 0

    @property
    def dtype(self):
        return dt.FLOAT64

    @property
    def nullable(self):
        return False

    def advance(self, n_rows: int) -> None:
        self._batch_ordinal += 1

    def eval(self, batch: ColumnarBatch):
        import jax
        key = jax.random.fold_in(
            jax.random.key(self.seed + self.partition_index),
            self._batch_ordinal)
        data = jax.random.uniform(key, (batch.capacity,), dtype=jnp.float64)
        live = batch.row_mask()
        return result_column(dt.FLOAT64, jnp.where(live, data, 0.0), live,
                             batch.capacity)


class MonotonicallyIncreasingID(Expression):
    """(partition_id << 33) + row index (GpuMonotonicallyIncreasingID)."""
    side_effect_free = False

    def __init__(self):
        super().__init__()
        self.partition_index = 0
        self.row_offset = 0

    @property
    def dtype(self):
        return dt.INT64

    @property
    def nullable(self):
        return False

    def advance(self, n_rows: int) -> None:
        self.row_offset += n_rows

    def eval(self, batch: ColumnarBatch):
        base = (self.partition_index << 33) + self.row_offset
        data = jnp.arange(batch.capacity, dtype=jnp.int64) + base
        live = batch.row_mask()
        return result_column(dt.INT64, jnp.where(live, data, 0), live,
                             batch.capacity)


class SparkPartitionID(Expression):
    """spark_partition_id() (GpuSparkPartitionID)."""
    side_effect_free = False

    def __init__(self):
        super().__init__()
        self.partition_index = 0

    @property
    def dtype(self):
        return dt.INT32

    @property
    def nullable(self):
        return False

    def eval(self, batch: ColumnarBatch):
        live = batch.row_mask()
        data = jnp.where(live, jnp.int32(self.partition_index), 0)
        return result_column(dt.INT32, data, live, batch.capacity)


class InputFileName(Expression):
    """input_file_name() — populated by the scan exec via thread-local context
    (GpuInputFileBlock analog). Thread-local: partitions drain on concurrent
    task threads, each reading a different file."""
    side_effect_free = False

    _tls = __import__("threading").local()

    @property
    def dtype(self):
        return dt.STRING

    @property
    def nullable(self):
        return False

    @classmethod
    def set_current(cls, path: str) -> None:
        cls._tls.current_file = path

    def eval(self, batch: ColumnarBatch):
        return Scalar(getattr(self._tls, "current_file", ""), dt.STRING)
