"""Sort-merge equality join kernels: the cuDF join analog, TPU-first.

Reference: per-shim ``GpuHashJoin.scala:29-296`` drives cuDF hash joins
(``Table.onColumns(...).leftJoin/innerJoin``); the plugin replaces Spark's
sort-merge join with hash join. Here we invert (DESIGN.md §3): TPU has no device
hash tables but sorts fast, so all equality joins are sort-merge:

  1. lexsort the BUILD side by its keys (order-preserving unsigned encodings)
  2. vectorized multi-word binary search gives, per STREAM row, the contiguous
     range [lo, hi) of matching build rows
  3. a prefix-sum over match counts + gather expands the pairs into output rows

Two-phase dynamic-size protocol (DESIGN.md): ``join_match`` returns the device
total pair count; the host reads it, buckets an output capacity, and calls
``join_gather`` — the same cadence as cuDF's size-returning join calls. The
exec layer PIPELINES the two phases (exec/pipeline.PipelineWindow): match
dispatches for batches k+1..k+depth before batch k's size scalar resolves,
and sizes land in batched readbacks, so the per-batch device->host round
trip overlaps compute instead of serializing the stream. To keep the
dispatch half sync-free, every ``n_build``/``n_stream`` argument here
accepts a python int OR a device int scalar (all consumers are jnp ops).

SQL semantics: NULL keys never match (null-aware anti join is handled at the
exec level); Spark float semantics make NaN == NaN for joins, which the
encoded-words equality gives us for free (all NaN encode identically).
"""

from __future__ import annotations

from typing import List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..columnar import dtypes as dt
from ..columnar.column import Column
from . import kernels as K


def _widen_string(col: Column, width: int) -> Column:
    """Zero-pad a string column's byte matrix to ``width`` (order-preserving)."""
    cur = col.data.shape[1]
    if cur >= width:
        return col
    data = jnp.pad(col.data, ((0, 0), (0, width - cur)))
    return Column(col.dtype, data, col.validity, col.lengths)


def _normalize_words(cols: Sequence[Column]) -> Tuple[List[jnp.ndarray], jnp.ndarray]:
    """Stack all key columns' sort-key words into one most-significant-first
    list, plus the row-is-usable (all keys non-NULL) mask.

    Uses EXACTLY the encoding ``sort_indices`` sorts by (``_key_arrays``:
    null-rank word + value words), so the binary search's lexicographic order
    matches the build side's sorted order — including NULL rows, which sort
    first and carry zeroed data words. Word equality == SQL join-key equality
    for usable rows: NaNs unified by the NaN-rank word, -0.0 == 0.0 by native
    float compare, f64 compared at full precision.
    """
    all_words: List[jnp.ndarray] = []
    usable = None
    for c in cols:
        all_words.extend(K._key_arrays(K.SortKey(c)))
        usable = c.validity if usable is None else (usable & c.validity)
    return all_words, usable


def _lex_cmp(a_words: List[jnp.ndarray], b_words: List[jnp.ndarray]):
    """(a < b, a == b) elementwise lexicographic over word lists."""
    lt = jnp.zeros(a_words[0].shape, dtype=jnp.bool_)
    eq = jnp.ones(a_words[0].shape, dtype=jnp.bool_)
    for a, b in zip(a_words, b_words):
        lt = lt | (eq & (a < b))
        eq = eq & (a == b)
    return lt, eq


def _search_bounds(build_words: List[jnp.ndarray], n_build,
                   probe_words: List[jnp.ndarray], side: str) -> jnp.ndarray:
    """Vectorized binary search of each probe key into the sorted build keys.

    side='left' -> first index with build >= probe; 'right' -> first with
    build > probe. Build rows beyond n_build are treated as +infinity.
    """
    cap = build_words[0].shape[0]
    steps = max(1, (cap - 1).bit_length())
    lo = jnp.zeros(probe_words[0].shape, dtype=jnp.int32)
    hi = jnp.full(probe_words[0].shape, n_build, dtype=jnp.int32)

    def body(_, lohi):
        lo, hi = lohi
        active = lo < hi                        # converged lanes must freeze
        mid = (lo + hi) // 2
        midc = jnp.clip(mid, 0, cap - 1)
        bw = [w[midc] for w in build_words]
        blt, beq = _lex_cmp(bw, probe_words)   # build[mid] < probe, == probe
        if side == "left":
            go_right = blt                      # build < probe -> search right
        else:
            go_right = blt | beq                # build <= probe -> search right
        # rows at/after n_build are +infinity, never less-or-equal
        go_right = go_right & (mid < jnp.asarray(n_build, mid.dtype))
        lo = jnp.where(active & go_right, mid + 1, lo)
        hi = jnp.where(active & ~go_right, mid, hi)
        return lo, hi

    lo, hi = jax.lax.fori_loop(0, steps, body, (lo, hi))
    return lo


class JoinMatch(NamedTuple):
    lo: jnp.ndarray            # int32[stream_cap] first matching build row
    count: jnp.ndarray         # int32[stream_cap] matches per stream row
    build_order: jnp.ndarray   # int32[build_cap] sort permutation of build side
    total_pairs: jnp.ndarray   # int32 scalar: sum of counts
    build_matched: jnp.ndarray  # bool[build_cap] (in sorted order) build row matched


def join_match(build_keys: Sequence[Column], n_build,
               stream_keys: Sequence[Column], n_stream,
               stream_capacity: int) -> JoinMatch:
    """Phase 1: sort build side, find per-stream-row match ranges + counts."""
    build_cap = build_keys[0].capacity
    # string key pairs must encode to the same number of words: widen both
    # sides' byte matrices to the pair's max padded width (order-preserving)
    build_keys = list(build_keys)
    stream_keys = list(stream_keys)
    for i, (b, s) in enumerate(zip(build_keys, stream_keys)):
        if b.dtype == dt.STRING and s.dtype == dt.STRING:
            width = max(b.data.shape[1], s.data.shape[1])
            build_keys[i] = _widen_string(b, width)
            stream_keys[i] = _widen_string(s, width)
    order = K.sort_indices([K.SortKey(c) for c in build_keys], n_build, build_cap)
    sorted_build = [K.gather_column(c, order) for c in build_keys]
    b_words, b_usable = _normalize_words(sorted_build)
    s_words, s_usable = _normalize_words(stream_keys)

    lo = _search_bounds(b_words, n_build, s_words, "left")
    hi = _search_bounds(b_words, n_build, s_words, "right")

    s_live = jnp.arange(stream_capacity) < n_stream
    ok = s_usable & s_live
    count = jnp.where(ok, hi - lo, 0).astype(jnp.int32)
    # null build rows sort first (nulls_first) and can only match null probes,
    # which `ok` already excludes; but guard against usable-build mismatch
    b_live = jnp.arange(build_cap) < n_build
    # mark matched build rows: +1 at lo, -1 at hi, prefix sum > 0
    delta = jnp.zeros(build_cap + 1, dtype=jnp.int32)
    add = jnp.where(ok, 1, 0)
    delta = delta.at[jnp.clip(lo, 0, build_cap)].add(add)
    delta = delta.at[jnp.clip(hi, 0, build_cap)].add(-add)
    covered = jnp.cumsum(delta[:-1]) > 0
    build_matched = covered & b_live & b_usable
    total = jnp.sum(count).astype(jnp.int32)
    return JoinMatch(lo, count, order, total, build_matched)


def _expand_indices(m: JoinMatch, out_capacity: int
                    ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """(stream_idx, build_sorted_idx, live) for each of out_capacity output slots."""
    cum = jnp.cumsum(m.count)                    # inclusive
    starts = cum - m.count                       # exclusive prefix
    out_i = jnp.arange(out_capacity, dtype=jnp.int32)
    live = out_i < m.total_pairs
    # which stream row does output slot i belong to: first j with cum[j] > i
    stream_idx = jnp.searchsorted(cum, out_i, side="right").astype(jnp.int32)
    stream_idx = jnp.clip(stream_idx, 0, m.count.shape[0] - 1)
    offset = out_i - starts[stream_idx]
    build_sorted_idx = m.lo[stream_idx] + offset
    return stream_idx, build_sorted_idx, live


def join_gather(m: JoinMatch, stream_cols: Sequence[Column],
                build_cols: Sequence[Column], out_capacity: int,
                join_type: str = "inner", n_stream=None,
                ) -> Tuple[List[Column], List[Column], jnp.ndarray]:
    """Phase 2: expand matches into output columns at a host-chosen capacity.

    join_type:
      inner       — matched pairs only
      left        — + unmatched stream rows with NULL build columns
      left_semi   — stream rows with >=1 match (stream columns only)
      left_anti   — stream rows with 0 matches (stream columns only)
    Right joins are planned as left joins with sides swapped (the reference does
    the same remap, GpuHashJoin.scala:112-132). full outer = left + the
    unmatched build rows appended (exec layer composes it via
    ``unmatched_build_gather``).
    Returns (stream output cols, build output cols, device row count).
    """
    stream_cap = m.count.shape[0]
    if join_type in ("left_semi", "left_anti"):
        s_live = jnp.arange(stream_cap) < n_stream
        keep = (m.count > 0) if join_type == "left_semi" else \
            ((m.count == 0) & s_live)
        keep = keep & s_live
        perm, cnt = K.compaction_indices(keep)
        live = jnp.arange(stream_cap) < cnt
        out = [K.gather_column(c, perm, out_valid=live) for c in stream_cols]
        return out, [], cnt

    if join_type == "left":
        # every stream row emits max(count, 1) rows; the padded row carries
        # NULL build columns
        count = jnp.where(jnp.arange(stream_cap) < n_stream,
                          jnp.maximum(m.count, 1), 0).astype(jnp.int32)
        matched = m.count > 0
        m2 = m._replace(count=count, total_pairs=jnp.sum(count).astype(jnp.int32))
        stream_idx, build_sorted_idx, live = _expand_indices(m2, out_capacity)
        row_matched = matched[stream_idx]
        s_out = [K.gather_column(c, stream_idx, out_valid=live)
                 for c in stream_cols]
        bidx = m.build_order[jnp.clip(build_sorted_idx, 0,
                                      m.build_order.shape[0] - 1)]
        b_valid = live & row_matched
        b_out = [K.gather_column(c, bidx, out_valid=b_valid) for c in build_cols]
        return s_out, b_out, m2.total_pairs

    # inner
    stream_idx, build_sorted_idx, live = _expand_indices(m, out_capacity)
    s_out = [K.gather_column(c, stream_idx, out_valid=live) for c in stream_cols]
    bidx = m.build_order[jnp.clip(build_sorted_idx, 0, m.build_order.shape[0] - 1)]
    b_out = [K.gather_column(c, bidx, out_valid=live) for c in build_cols]
    return s_out, b_out, m.total_pairs


def unmatched_build_gather(m: JoinMatch, build_cols: Sequence[Column], n_build
                           ) -> Tuple[List[Column], jnp.ndarray]:
    """Build rows with no stream match, compacted (for FULL OUTER composition).
    Note: NULL-key build rows count as unmatched (full outer emits them)."""
    build_cap = m.build_order.shape[0]
    b_live = jnp.arange(build_cap) < n_build
    keep_sorted = b_live & ~m.build_matched
    # back to original row order indices
    perm, cnt = K.compaction_indices(keep_sorted)
    orig_idx = m.build_order[perm]
    live = jnp.arange(build_cap) < cnt
    out = [K.gather_column(c, orig_idx, out_valid=live) for c in build_cols]
    return out, cnt


def cross_join_gather(left_cols: Sequence[Column], n_left,
                      right_cols: Sequence[Column], n_right,
                      out_capacity: int
                      ) -> Tuple[List[Column], List[Column], jnp.ndarray]:
    """Cartesian product (GpuCartesianProductExec / BroadcastNestedLoop analog):
    output slot i -> (left i // n_right, right i % n_right)."""
    out_i = jnp.arange(out_capacity, dtype=jnp.int64)
    total = (jnp.asarray(n_left, jnp.int64) * jnp.asarray(n_right, jnp.int64)
             ).astype(jnp.int32)
    live = out_i < total
    nr = jnp.maximum(jnp.asarray(n_right, jnp.int64), 1)
    li = jnp.clip((out_i // nr).astype(jnp.int32), 0,
                  left_cols[0].capacity - 1 if left_cols else 0)
    ri = jnp.clip((out_i % nr).astype(jnp.int32), 0,
                  right_cols[0].capacity - 1 if right_cols else 0)
    l_out = [K.gather_column(c, li, out_valid=live) for c in left_cols]
    r_out = [K.gather_column(c, ri, out_valid=live) for c in right_cols]
    return l_out, r_out, total
