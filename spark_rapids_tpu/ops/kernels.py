"""Core columnar device kernels: gather, compaction, sort-key encoding, lexsort.

This is the in-tree replacement for the cuDF kernel surface the reference calls
through JNI (``SURVEY.md`` §2.11: join/groupby/sort/filter/contiguous-split all come
from ``ai.rapids.cudf``). Everything here is pure-functional jax.numpy so it can run
eagerly, under ``jax.jit``, or inside a fused whole-stage computation (DESIGN.md §2).

Key techniques (TPU-first, no data-dependent shapes):
* filter = stable compaction by ``argsort`` of the keep-mask — output capacity equals
  input capacity, the true row count travels as a device scalar
* sort = ``jnp.lexsort`` over *order-preserving unsigned key encodings* (sign-flip for
  ints, IEEE total-order trick for floats, big-endian packed words for strings) with
  explicit null-rank and padding-rank keys
"""

from __future__ import annotations

from typing import List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..columnar import dtypes as dt
from ..columnar.column import Column

_UNSIGNED = {1: jnp.uint8, 2: jnp.uint16, 4: jnp.uint32, 8: jnp.uint64}
_SIGNBIT = {1: 0x80, 2: 0x8000, 4: 0x8000_0000, 8: 0x8000_0000_0000_0000}


# ---------------------------------------------------------------------------
# Order-preserving unsigned encodings (for radix-style lexsort keys)
# ---------------------------------------------------------------------------

def encode_orderable_words(data: jnp.ndarray, dtype: dt.DType,
                           descending: bool = False) -> List[jnp.ndarray]:
    """Sort-key arrays (most-significant first) whose lexicographic order equals
    SQL ascending (or descending) order for this dtype.

    Ints/bool/date/timestamp: unsigned sign-flip encoding (bitwise NOT for desc).
    Floats: kept AS FLOATS — a NaN-rank key plus a NaN-free value key (negated for
    desc). No f64 bitcasts: TPU's X64 rewrite cannot bitcast emulated f64, and XLA
    sorts floats natively anyway. Spark semantics preserved: all NaN sort largest
    and equal (so desc puts NaN first).
    """
    if dtype == dt.BOOL:
        u = data.astype(jnp.uint8)
        return [~u if descending else u]
    if dtype.is_integral or dtype in (dt.DATE, dt.TIMESTAMP):
        w = dtype.byte_width
        u = data.astype(_UNSIGNED[w]) ^ jnp.asarray(_SIGNBIT[w], dtype=_UNSIGNED[w])
        return [~u if descending else u]
    if dtype.is_floating:
        is_nan = jnp.isnan(data)
        nan_rank = jnp.where(is_nan, jnp.uint8(0 if descending else 1),
                             jnp.uint8(1 if descending else 0))
        value = jnp.where(is_nan, jnp.zeros((), data.dtype), data)
        return [nan_rank, -value if descending else value]
    raise TypeError(f"not an orderable fixed-width type: {dtype}")


def pack_string_words(data: jnp.ndarray, lengths: jnp.ndarray) -> jnp.ndarray:
    """Pack a padded uint8[N, W] byte matrix into big-endian uint32[N, W/4] words.

    Unsigned word-wise lexicographic order == byte-wise lexicographic order because
    padding bytes are zero and any byte beats end-of-string (0 pad). Cuts lexsort
    passes by 4x vs per-byte keys.
    """
    n, w = data.shape
    pad_w = (-w) % 4
    if pad_w:
        data = jnp.pad(data, ((0, 0), (0, pad_w)))
        w += pad_w
    b = data.reshape(n, w // 4, 4).astype(jnp.uint32)
    return (b[..., 0] << 24) | (b[..., 1] << 16) | (b[..., 2] << 8) | b[..., 3]


class SortKey(NamedTuple):
    column: Column
    ascending: bool = True
    nulls_first: bool = True   # Spark default: NULLS FIRST for asc, NULLS LAST for desc


def _key_arrays_bits(key: SortKey) -> List[Tuple[jnp.ndarray, Optional[int]]]:
    """Most-significant-first (array, value_bit_width) pairs encoding one
    sort key. bit_width None marks float value keys (unpackable — they stay
    raw operands); small widths (1-bit null ranks, short string payloads)
    let pack_key_bits collapse whole key sets into one 32-bit sort lane,
    which is the difference between a seconds and a minutes sort compile."""
    col, asc = key.column, key.ascending
    encoded: List[Tuple[jnp.ndarray, Optional[int]]] = []
    if col.dtype == dt.STRING:
        W = int(col.data.shape[1])
        len_bits = max(1, (W + 1).bit_length())
        if W <= 3 and 8 * W + len_bits <= 32:
            # short strings: chars || length in ONE sub-32-bit value
            # (length low bits give the prefix tie-break directly)
            word = jnp.zeros(col.data.shape[0], jnp.uint32)
            for j in range(W):
                word = (word << jnp.uint32(8)) | col.data[:, j].astype(
                    jnp.uint32)
            word = (word << jnp.uint32(len_bits)) | col.lengths.astype(
                jnp.uint32)
            encoded.append((word, 8 * W + len_bits))
        else:
            words = pack_string_words(col.data, col.lengths)
            encoded += [(words[:, i], 32) for i in range(words.shape[1])]
            # length as final tie-break: zero padding is indistinguishable
            # from an embedded NUL in the word keys
            encoded.append((col.lengths.astype(jnp.uint32), len_bits))
        if not asc:
            encoded = [((a ^ jnp.uint32((1 << b) - 1)), b)
                       for a, b in encoded]
    else:
        for a in encode_orderable_words(col.data, col.dtype,
                                        descending=not asc):
            bw = _bit_width(a)
            encoded.append((a, bw))     # None for float value keys
    # null rank precedes value: 0 sorts before 1 (1-bit value)
    null_first = key.nulls_first
    null_rank = jnp.where(col.validity, jnp.uint8(1 if null_first else 0),
                          jnp.uint8(0 if null_first else 1))
    return [(null_rank, 1)] + encoded


def _key_arrays(key: SortKey) -> List[jnp.ndarray]:
    """Most-significant-first list of unsigned arrays encoding one sort key
    (unpacked form; mesh bound-comparison uses these directly)."""
    return [a for a, _b in _key_arrays_bits(key)]


def _bit_width(a: jnp.ndarray) -> Optional[int]:
    return {jnp.uint8: 8, jnp.uint16: 16, jnp.uint32: 32,
            jnp.uint64: 64}.get(a.dtype.type)


def pack_key_bits(items: List[Tuple[jnp.ndarray, Optional[int]]]
                  ) -> List[jnp.ndarray]:
    """Pack consecutive (array, bit_width) most-significant-first keys into
    uint32 lanes (earlier keys in higher bits), preserving lexicographic
    order while collapsing the sort operand count.

    Why: XLA's variadic-sort comparator compile time grows steeply with
    operand count (~15-30s PER 32-bit operand on both the CPU and TPU
    backends measured here), so a 7-operand lexsort costs minutes to
    compile. A groupby on two short string keys plus null/pad ranks fits in
    ONE packed lane. 32-bit lanes (not 64) because 64-bit integers are
    emulated on TPU under the x64 rewrite — a u64 comparator costs two u32
    comparators anyway. Values wider than 32 bits (and float value keys,
    width None) pass through as raw operands."""
    out: List[jnp.ndarray] = []
    cur: Optional[jnp.ndarray] = None
    used = 0
    for a, bits in items:
        if bits is None or bits > 32:
            if cur is not None:
                out.append(cur)
                cur, used = None, 0
            out.append(a)
            continue
        aa = a.astype(jnp.uint32)
        if cur is None:
            cur, used = aa, bits
        elif used + bits <= 32:
            cur = (cur << jnp.uint32(bits)) | aa
            used += bits
        else:
            out.append(cur)
            cur, used = aa, bits
    if cur is not None:
        out.append(cur)
    return out


def sort_indices(keys: Sequence[SortKey], num_rows, capacity: int,
                 live_mask: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Stable permutation ordering live rows by the keys; padding rows go last.

    cuDF analog: ``Table.orderBy`` (used by GpuSortExec, GpuSortExec.scala:33-105).
    ``num_rows`` may be a python int or a traced device scalar.
    ``live_mask`` marks live rows explicitly (already padding-masked):
    folded-filter consumers rank filtered-out rows last INSTEAD of
    physically compacting first — compaction's scatter is the slowest
    primitive on TPU, the sort is nearly free.
    """
    if live_mask is not None:
        pad_rank = (~live_mask).astype(jnp.uint8)
    else:
        pad_rank = (jnp.arange(capacity) >= num_rows).astype(jnp.uint8)
    msf: List[Tuple[jnp.ndarray, Optional[int]]] = [(pad_rank, 1)]
    for key in keys:
        msf.extend(_key_arrays_bits(key))
    packed = pack_key_bits(msf)
    # jnp.lexsort wants least-significant first
    return jnp.lexsort(tuple(reversed(packed)))


# ---------------------------------------------------------------------------
# Gather / compaction / slicing
# ---------------------------------------------------------------------------

def gather_column(col: Column, indices: jnp.ndarray,
                  out_valid: Optional[jnp.ndarray] = None) -> Column:
    """Row gather; ``out_valid`` additionally masks output rows (False => null+zero).

    cuDF analog: ``Table.gather``. Out-of-range indices must not occur (clip upstream).
    """
    from ..columnar.column import StructColumn
    validity = col.validity[indices]
    if out_valid is not None:
        validity = validity & out_valid
    if isinstance(col, StructColumn):
        kids = [gather_column(c, indices, out_valid=out_valid)
                for c in col.children]
        return StructColumn(col.dtype, kids, validity)
    if col.dtype.var_width:
        keep = out_valid if out_valid is not None else None
        data = col.data[indices]
        lengths = col.lengths[indices]
        evalid = (col.elem_validity[indices]
                  if col.elem_validity is not None else None)
        if keep is not None:
            data = jnp.where(keep[:, None], data,
                             jnp.zeros((), data.dtype))
            lengths = jnp.where(keep, lengths, jnp.int32(0))
            if evalid is not None:
                evalid = evalid & keep[:, None]
        return Column(col.dtype, data, validity, lengths, evalid)
    data = col.data[indices]
    if out_valid is not None:
        data = jnp.where(out_valid, data, jnp.zeros((), data.dtype))
    return Column(col.dtype, data, validity)


def compaction_indices(keep: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(perm, count): stable order with kept rows first. keep must be False
    on padding.

    Sort-free: cumsum ranks each row within its class (kept/dropped), one
    scatter inverts the position map. An XLA sort here would cost both a
    pathological comparator compile (tens of seconds per sort instance on
    some backends) and O(n log n) runtime for what is an O(n) operation.
    """
    n = keep.shape[0]
    n_keep = jnp.sum(keep).astype(jnp.int32)
    pos_keep = jnp.cumsum(keep).astype(jnp.int32) - 1
    pos_drop = n_keep + jnp.cumsum(~keep).astype(jnp.int32) - 1
    pos = jnp.where(keep, pos_keep, pos_drop)
    perm = jnp.zeros(n, jnp.int32).at[pos].set(
        jnp.arange(n, dtype=jnp.int32))
    return perm, n_keep


def compact_columns(cols: Sequence[Column], keep: jnp.ndarray
                    ) -> Tuple[List[Column], jnp.ndarray]:
    """Filter: keep rows where ``keep`` is True, compacted to the front.

    cuDF analog: ``Table.filter`` (GpuFilter helper, basicPhysicalOperators.scala:98-130).
    Returns same-capacity columns + device row count; caller syncs/rebuckets at a
    host boundary (DESIGN.md "dynamic-size protocol").
    """
    perm, count = compaction_indices(keep)
    live = jnp.arange(keep.shape[0]) < count
    return [gather_column(c, perm, out_valid=live) for c in cols], count


def slice_column(col: Column, start: int, out_capacity: int, length) -> Column:
    """Contiguous slice [start, start+length) into a fresh capacity (host-known start)."""
    idx = jnp.clip(jnp.arange(out_capacity) + start, 0, col.capacity - 1)
    live = jnp.arange(out_capacity) < length
    return gather_column(col, idx, out_valid=live)


def concat_columns(cols: Sequence[Column], counts: Sequence[int],
                   out_capacity: int) -> Column:
    """Concatenate same-dtype columns into one of out_capacity rows.

    cuDF analog: ``Table.concatenate`` (GpuCoalesceBatches.scala:132-702). Host-known
    counts (this runs at batch-coalesce boundaries, not inside fused stages).
    """
    from ..columnar.column import StructColumn
    dtype = cols[0].dtype
    if isinstance(cols[0], StructColumn):
        total = sum(counts)
        pad = out_capacity - total
        valids = [c.validity[:n] for c, n in zip(cols, counts)]
        if pad:
            valids.append(jnp.zeros(pad, jnp.bool_))
        kids = [concat_columns([c.children[k] for c in cols], counts,
                               out_capacity)
                for k in range(len(cols[0].children))]
        return StructColumn(dtype, kids, jnp.concatenate(valids))
    if dtype.var_width:
        width = max(int(c.data.shape[1]) for c in cols)
        has_ev = cols[0].elem_validity is not None
        datas, valids, lens, evs = [], [], [], []
        for c, n in zip(cols, counts):
            d = c.data[:n]
            if d.shape[1] < width:
                d = jnp.pad(d, ((0, 0), (0, width - d.shape[1])))
            datas.append(d)
            valids.append(c.validity[:n])
            lens.append(c.lengths[:n])
            if has_ev:
                e = c.elem_validity[:n]
                if e.shape[1] < width:
                    e = jnp.pad(e, ((0, 0), (0, width - e.shape[1])))
                evs.append(e)
        total = sum(counts)
        pad = out_capacity - total
        data = jnp.concatenate(datas + ([jnp.zeros((pad, width), datas[0].dtype)] if pad else []))
        valid = jnp.concatenate(valids + ([jnp.zeros(pad, jnp.bool_)] if pad else []))
        lengths = jnp.concatenate(lens + ([jnp.zeros(pad, jnp.int32)] if pad else []))
        evalid = None
        if has_ev:
            evalid = jnp.concatenate(
                evs + ([jnp.zeros((pad, width), jnp.bool_)] if pad else []))
        return Column(dtype, data, valid, lengths, evalid)
    datas = [c.data[:n] for c, n in zip(cols, counts)]
    valids = [c.validity[:n] for c, n in zip(cols, counts)]
    total = sum(counts)
    pad = out_capacity - total
    if pad:
        datas.append(jnp.zeros(pad, datas[0].dtype))
        valids.append(jnp.zeros(pad, jnp.bool_))
    return Column(dtype, jnp.concatenate(datas), jnp.concatenate(valids))


def rebucket_column(col: Column, num_rows: int, new_capacity: int) -> Column:
    """Grow/shrink capacity around the first num_rows rows (host-known count)."""
    return slice_column(col, 0, new_capacity, num_rows)


# ---------------------------------------------------------------------------
# Segment utilities (groupby/window building blocks)
# ---------------------------------------------------------------------------

def segment_starts_from_sorted_keys(key_cols: Sequence[Column], num_rows,
                                    capacity: int) -> jnp.ndarray:
    """Bool[cap]: True where row i starts a new group in key-sorted data.

    NULL keys compare equal to each other (Spark groupby semantics). Padding rows
    are never starts.
    """
    live = jnp.arange(capacity) < num_rows
    is_start = live & (jnp.arange(capacity) == 0)
    changed = jnp.zeros(capacity, dtype=jnp.bool_)
    for col in key_cols:
        prev_valid = jnp.concatenate([col.validity[:1], col.validity[:-1]])
        vdiff = col.validity != prev_valid
        if col.dtype == dt.STRING:
            prev_d = jnp.concatenate([col.data[:1], col.data[:-1]])
            ddiff = jnp.any(col.data != prev_d, axis=1)
            prev_l = jnp.concatenate([col.lengths[:1], col.lengths[:-1]])
            ddiff = ddiff | (col.lengths != prev_l)
        else:
            prev_d = jnp.concatenate([col.data[:1], col.data[:-1]])
            if col.dtype.is_floating:
                # NaN == NaN for grouping (Spark normalizes)
                both_nan = jnp.isnan(col.data) & jnp.isnan(prev_d)
                ddiff = (col.data != prev_d) & ~both_nan
            else:
                ddiff = col.data != prev_d
        # data diff only matters when both rows valid
        changed = changed | vdiff | (ddiff & col.validity & prev_valid)
    idx = jnp.arange(capacity)
    return is_start | (live & (idx > 0) & changed)


def segment_ids(starts: jnp.ndarray) -> jnp.ndarray:
    """Int32[cap] group id per row from group-start flags (0-based; padding gets last id+)."""
    return (jnp.cumsum(starts.astype(jnp.int32)) - 1).astype(jnp.int32)
