"""MAP expressions: CreateMap, GetMapValue, map_keys/map_values.

Reference: ``complexTypeExtractors.scala`` (GetMapValue),
``complexTypeCreator.scala`` (CreateMap), ``collectionOperations.scala``
(MapKeys/MapValues). TPU-first layout (columnar/dtypes.py MAP): one
``int64[cap, 3W]`` bitpattern matrix — keys in columns ``[0, W)``, values
in ``[W, 2W)``, per-entry value-validity flags in ``[2W, 3W)`` (Spark maps
may hold NULL values) — plus per-row entry counts, so every
transport/spill/concat path treats a map column like any other var-width
column. Lookups are a
vectorized compare + argmax over the W key lanes (no hashing — W is small
and static, the VPU eats the whole compare in one pass).

Only fixed-width primitive keys/values have this device layout; string
keys/values tag off to the CPU engine (plan/overrides.py gating).
"""

from __future__ import annotations

import jax.numpy as jnp

from ..columnar import dtypes as dt
from ..columnar.batch import ColumnarBatch
from ..columnar.column import Column, Scalar, bucket
from .expressions import Expression, materialize


def _halves(col: Column):
    """(keys[cap, W] in K-dtype, values[cap, W] in V-dtype,
    value_valid[cap, W] bool, W) — strided views of the interleaved
    [k, v, ok] entry lanes (see dtypes.MAP)."""
    w = col.data.shape[1] // 3
    kt, vt = col.dtype.key, col.dtype.element
    return (_from_bits(col.data[:, 0:3 * w:3], kt),
            _from_bits(col.data[:, 1:3 * w:3], vt),
            col.data[:, 2:3 * w:3] != 0, w)


def _from_bits(bits: jnp.ndarray, dtype: dt.DType) -> jnp.ndarray:
    if dtype.is_floating:
        import jax
        f = jax.lax.bitcast_convert_type(bits, jnp.float64)
        return f.astype(dtype.numpy_dtype) if dtype != dt.FLOAT64 else f
    return bits.astype(dtype.numpy_dtype)


def _to_bits(arr: jnp.ndarray, dtype: dt.DType) -> jnp.ndarray:
    if dtype.is_floating:
        import jax
        return jax.lax.bitcast_convert_type(
            arr.astype(jnp.float64), jnp.int64)
    return arr.astype(jnp.int64)


class CreateMap(Expression):
    """map(k1, v1, k2, v2, ...) — duplicate keys keep the LAST entry
    (spark.sql.mapKeyDedupPolicy=LAST_WIN; the CPU oracle matches).
    NULL keys are invalid in Spark; rows with a NULL key become NULL maps."""

    fusable = False               # see module docstring: eager-only bitcast

    def __init__(self, *kv: Expression):
        assert kv and len(kv) % 2 == 0, "map() needs key/value pairs"
        super().__init__(*kv)

    @property
    def dtype(self):
        return dt.MAP(self.children[0].dtype, self.children[1].dtype)

    @property
    def nullable(self):
        return True

    def eval(self, batch: ColumnarBatch):
        n_pairs = len(self.children) // 2
        keys = [materialize(self.children[2 * i].eval(batch), batch)
                for i in range(n_pairs)]
        vals = [materialize(self.children[2 * i + 1].eval(batch), batch)
                for i in range(n_pairs)]
        out_t = self.dtype
        w = bucket(n_pairs, 4)
        cap = batch.capacity
        live = batch.row_mask()

        kmat = jnp.stack([k.data for k in keys], axis=1)      # [cap, P]
        vmat = jnp.stack([v.data for v in vals], axis=1)
        vvalid = jnp.stack([v.validity for v in vals], axis=1)
        vmat = jnp.where(vvalid, vmat, jnp.zeros((), vmat.dtype))
        # LAST_WIN dedup with Spark's entry ORDER (ArrayBasedMapBuilder —
        # python dicts agree): a key keeps its FIRST occurrence position
        # but takes its LAST occurrence's value
        same = kmat[:, :, None] == kmat[:, None, :]           # [cap, P, P]
        earlier = jnp.tril(jnp.ones((n_pairs, n_pairs), bool), k=-1)[None]
        keep = ~jnp.any(same & earlier, axis=2)               # first occur.
        last_j = jnp.max(jnp.where(same, jnp.arange(n_pairs)[None, None, :],
                                   -1), axis=2)               # [cap, P]
        vmat = jnp.take_along_axis(vmat, last_j, axis=1)
        vvalid = jnp.take_along_axis(vvalid, last_j, axis=1)
        # compact kept entries to the front of the W lanes
        order = jnp.argsort(~keep, axis=1, stable=True)       # kept first
        kc = jnp.take_along_axis(kmat, order, axis=1)
        vc = jnp.take_along_axis(vmat, order, axis=1)
        vvc = jnp.take_along_axis(vvalid, order, axis=1)
        n_kept = jnp.sum(keep, axis=1).astype(jnp.int32)
        lane = jnp.arange(n_pairs)[None, :]
        kept_lane = lane < n_kept[:, None]
        pad_k = jnp.where(kept_lane, kc, jnp.zeros((), kc.dtype))
        pad_v = jnp.where(kept_lane, vc, jnp.zeros((), vc.dtype))
        pad_vv = (vvc & kept_lane).astype(jnp.int64)

        mat = jnp.zeros((cap, 3 * w), jnp.int64)
        mat = mat.at[:, 0:3 * n_pairs:3].set(_to_bits(pad_k, out_t.key))
        mat = mat.at[:, 1:3 * n_pairs + 1:3].set(
            _to_bits(pad_v, out_t.element))
        mat = mat.at[:, 2:3 * n_pairs + 2:3].set(pad_vv)
        valid = live & jnp.all(
            jnp.stack([k.validity for k in keys], axis=1), axis=1)
        mat = jnp.where(valid[:, None], mat, 0)
        lens = jnp.where(valid, n_kept, 0)
        return Column(out_t, mat, valid, lens)


class GetMapValue(Expression):
    """map[key] / element_at(map, key): NULL when the key is absent
    (complexTypeExtractors.scala GetMapValue)."""

    fusable = False               # see module docstring: eager-only bitcast

    def __init__(self, child: Expression, key: Expression):
        super().__init__(child, key)

    @property
    def dtype(self):
        return self.children[0].dtype.element

    @property
    def nullable(self):
        return True

    def eval(self, batch: ColumnarBatch):
        kt = self.children[0].dtype.key
        key_expr_t = self.children[1].dtype
        if (key_expr_t.numpy_dtype is None) != (kt.numpy_dtype is None) or \
                key_expr_t.var_width or kt.var_width:
            raise TypeError(
                f"map key lookup type {key_expr_t} incompatible with "
                f"map<{kt},...> (planner should have tagged this off)")
        m = materialize(self.children[0].eval(batch), batch)
        key = self.children[1].eval(batch)
        keys, vals, vvalid, w = _halves(m)
        cap = m.capacity
        # compare in float64 when either side is floating (casting the
        # lookup key INTO an integral key dtype would truncate 1.5 -> 1 and
        # match the wrong entry); integral/integral compares in int64 so a
        # bigint lookup against map<int,_> cannot wrap modulo 2^32
        cmp_f = kt.is_floating or key_expr_t.is_floating
        cmp_t = jnp.float64 if cmp_f else jnp.int64
        ck = keys.astype(cmp_t)
        if isinstance(key, Scalar):
            if key.is_null:
                return Column.full_null(self.dtype, cap)
            k = jnp.full((cap, 1), key.value, cmp_t)
            kvalid = jnp.ones(cap, jnp.bool_)
        else:
            k = key.data.astype(cmp_t)[:, None]
            kvalid = key.validity
        lane_ok = jnp.arange(w)[None, :] < m.lengths[:, None]
        match = (ck == k) & lane_ok
        found = jnp.any(match, axis=1)
        idx = jnp.argmax(match, axis=1)
        data = jnp.take_along_axis(vals, idx[:, None], axis=1)[:, 0]
        val_ok = jnp.take_along_axis(vvalid, idx[:, None], axis=1)[:, 0]
        ok = m.validity & kvalid & found & val_ok
        return Column(self.dtype, jnp.where(ok, data,
                                            jnp.zeros((), data.dtype)), ok)


class GetItem(Expression):
    """col[x] / element_at(col, x) dispatcher: whether ``col`` is a MAP or
    an ARRAY is unknown until column references resolve, so the choice
    happens at eval time. ``one_based=True`` is element_at's array
    indexing (1-based, negatives count from the end); maps ignore it."""

    def __init__(self, child: Expression, key: Expression,
                 one_based: bool = False):
        super().__init__(child, key)
        self.one_based = one_based

    @property
    def fusable(self):
        # only the MAP path carries the eager-only bitcast; plain array
        # indexing keeps fusing into staged programs
        try:
            return not dt.is_map(self.children[0].dtype)
        except Exception:
            return False

    @property
    def dtype(self):
        return self.children[0].dtype.element

    @property
    def nullable(self):
        return True

    def eval(self, batch: ColumnarBatch):
        from .arrays import GetArrayItem
        child, key = self.children
        if dt.is_map(child.dtype):
            return GetMapValue(child, key).eval(batch)
        return GetArrayItem(child, key,
                            one_based=self.one_based).eval(batch)


class MapKeys(Expression):
    """map_keys(m) -> array<K> (collectionOperations.scala MapKeys)."""

    fusable = False               # see module docstring: eager-only bitcast

    @property
    def dtype(self):
        return dt.ARRAY(self.children[0].dtype.key)

    @property
    def nullable(self):
        return True

    def eval(self, batch: ColumnarBatch):
        m = materialize(self.children[0].eval(batch), batch)
        keys, _vals, _vv, w = _halves(m)
        lane_ok = jnp.arange(w)[None, :] < m.lengths[:, None]
        ok = lane_ok & m.validity[:, None]
        data = jnp.where(ok, keys, jnp.zeros((), keys.dtype))
        return Column(self.dtype, data, m.validity,
                      jnp.where(m.validity, m.lengths, 0), ok)


class MapValues(Expression):
    """map_values(m) -> array<V> (collectionOperations.scala MapValues).
    NULL map values surface as NULL array elements (the array layout
    carries per-element validity)."""

    fusable = False               # see module docstring: eager-only bitcast

    @property
    def dtype(self):
        return dt.ARRAY(self.children[0].dtype.element)

    @property
    def nullable(self):
        return True

    def eval(self, batch: ColumnarBatch):
        m = materialize(self.children[0].eval(batch), batch)
        _keys, vals, vv, w = _halves(m)
        lane_ok = jnp.arange(w)[None, :] < m.lengths[:, None]
        ok = lane_ok & m.validity[:, None] & vv
        data = jnp.where(ok, vals, jnp.zeros((), vals.dtype))
        return Column(self.dtype, data, m.validity,
                      jnp.where(m.validity, m.lengths, 0), ok)
