"""Math expressions (trig/log/exp/pow/sqrt/...): GpuSin, GpuLog, GpuPow, ...

Reference: ``org/apache/spark/sql/rapids/mathExpressions.scala`` (361 LoC). Spark
semantics notes: log of non-positive returns NULL; sqrt of negative returns NaN;
all unary math ops operate on DOUBLE (analysis inserts casts).
"""

from __future__ import annotations

import math
from typing import Callable, Optional

import jax.numpy as jnp

from ..columnar import dtypes as dt
from ..columnar.batch import ColumnarBatch
from ..columnar.column import Column, Scalar
from .expressions import Expression, combine_validity, data_validity, result_column


class UnaryMath(Expression):
    """Double -> Double elementwise op."""
    fn: Callable = None       # jnp elementwise fn (column path)
    pyfn: Callable = None     # numpy twin (host scalar fold; no device trip)

    @property
    def dtype(self) -> dt.DType:
        return dt.FLOAT64

    def _domain_validity(self, d):
        """Return extra validity mask (None = total function)."""
        return None

    def _safe_input(self, d):
        return d

    def eval(self, batch: ColumnarBatch):
        v = self.children[0].eval(batch)
        if isinstance(v, Scalar):
            if v.is_null:
                return Scalar(None, dt.FLOAT64)
            # pure-host fold: the domain lambdas are plain comparisons and
            # pyfn is the numpy twin of fn, so a scalar input never touches
            # the device (this path runs per batch under eager eval)
            x = float(v.value)
            extra = self._domain_validity(x)
            if extra is not None and not bool(extra):
                return Scalar(None, dt.FLOAT64)
            import numpy as np
            fn = type(self).pyfn or type(self).fn
            with np.errstate(invalid="ignore", divide="ignore",
                             over="ignore"):
                return Scalar(float(fn(x)), dt.FLOAT64)
        d = v.data.astype(jnp.float64)
        extra = self._domain_validity(d)
        data = type(self).fn(self._safe_input(d))
        validity = v.validity if extra is None else (v.validity & extra)
        # keep the zeroed-invalid-rows invariant (column.py): exp(0)=1 etc. would
        # otherwise leave garbage on null/padding rows
        data = jnp.where(validity, data, jnp.zeros((), data.dtype))
        return result_column(dt.FLOAT64, data, validity, batch.capacity)

    def sql_name(self) -> str:
        return type(self).__name__.lower()


def _unary(name: str, fn, domain: Optional[Callable] = None,
           safe: Optional[Callable] = None) -> type:
    import numpy as np
    attrs = {"fn": staticmethod(fn)}
    # jnp elementwise fns share their numpy twin's name (jnp.sin -> np.sin):
    # the scalar fold uses the twin so literals never round-trip the device
    pyfn = getattr(np, getattr(fn, "__name__", ""), None)
    if pyfn is not None:
        attrs["pyfn"] = staticmethod(pyfn)
    if domain is not None:
        attrs["_domain_validity"] = lambda self, d, _dom=domain: _dom(d)
    if safe is not None:
        attrs["_safe_input"] = lambda self, d, _s=safe: _s(d)
    return type(name, (UnaryMath,), attrs)


Sin = _unary("Sin", jnp.sin)
Cos = _unary("Cos", jnp.cos)
Tan = _unary("Tan", jnp.tan)
Asin = _unary("Asin", jnp.arcsin)
Acos = _unary("Acos", jnp.arccos)
Atan = _unary("Atan", jnp.arctan)
Sinh = _unary("Sinh", jnp.sinh)
Cosh = _unary("Cosh", jnp.cosh)
Tanh = _unary("Tanh", jnp.tanh)
Exp = _unary("Exp", jnp.exp)
Expm1 = _unary("Expm1", jnp.expm1)
Sqrt = _unary("Sqrt", jnp.sqrt)       # sqrt(<0) = NaN, matches Spark
Cbrt = _unary("Cbrt", jnp.cbrt)
Rint = _unary("Rint", jnp.rint)
Signum = _unary("Signum", jnp.sign)
ToDegrees = _unary("ToDegrees", jnp.degrees)
ToRadians = _unary("ToRadians", jnp.radians)
# Spark: log/log10/log2/log1p of x <= 0 (or <= -1 for log1p) returns NULL
Log = _unary("Log", jnp.log, domain=lambda d: d > 0,
             safe=lambda d: jnp.where(d > 0, d, 1.0))
Log10 = _unary("Log10", jnp.log10, domain=lambda d: d > 0,
               safe=lambda d: jnp.where(d > 0, d, 1.0))
Log2 = _unary("Log2", jnp.log2, domain=lambda d: d > 0,
              safe=lambda d: jnp.where(d > 0, d, 1.0))
Log1p = _unary("Log1p", jnp.log1p, domain=lambda d: d > -1,
               safe=lambda d: jnp.where(d > -1, d, 0.0))


class Floor(Expression):
    """GpuFloor: returns LONG for double input (Spark semantics)."""
    @property
    def dtype(self) -> dt.DType:
        return dt.INT64 if self.children[0].dtype.is_floating else self.children[0].dtype

    def eval(self, batch: ColumnarBatch):
        v = self.children[0].eval(batch)
        if isinstance(v, Scalar):
            return Scalar(None if v.is_null else math.floor(v.value), self.dtype)
        if not self.children[0].dtype.is_floating:
            return v
        return Column(self.dtype, jnp.floor(v.data).astype(jnp.int64), v.validity)


class Ceil(Expression):
    """GpuCeil."""
    @property
    def dtype(self) -> dt.DType:
        return dt.INT64 if self.children[0].dtype.is_floating else self.children[0].dtype

    def eval(self, batch: ColumnarBatch):
        v = self.children[0].eval(batch)
        if isinstance(v, Scalar):
            return Scalar(None if v.is_null else math.ceil(v.value), self.dtype)
        if not self.children[0].dtype.is_floating:
            return v
        return Column(self.dtype, jnp.ceil(v.data).astype(jnp.int64), v.validity)


class Pow(Expression):
    """GpuPow (binary)."""
    @property
    def dtype(self) -> dt.DType:
        return dt.FLOAT64

    def eval(self, batch: ColumnarBatch):
        lv = self.children[0].eval(batch)
        rv = self.children[1].eval(batch)
        if isinstance(lv, Scalar) and isinstance(rv, Scalar):
            if lv.is_null or rv.is_null:
                return Scalar(None, dt.FLOAT64)
            return Scalar(float(lv.value) ** float(rv.value), dt.FLOAT64)
        ld, lval = data_validity(lv, dt.FLOAT64)
        rd, rval = data_validity(rv, dt.FLOAT64)
        data = jnp.power(ld.astype(jnp.float64), rd.astype(jnp.float64))
        validity = combine_validity(lval, rval)
        if validity is not True:
            data = jnp.where(validity, data, 0.0)  # pow(0,0)=1 on invalid rows
        return result_column(dt.FLOAT64, data, validity, batch.capacity)


class Atan2(Expression):
    @property
    def dtype(self) -> dt.DType:
        return dt.FLOAT64

    def eval(self, batch: ColumnarBatch):
        lv = self.children[0].eval(batch)
        rv = self.children[1].eval(batch)
        ld, lval = data_validity(lv, dt.FLOAT64)
        rd, rval = data_validity(rv, dt.FLOAT64)
        data = jnp.arctan2(ld.astype(jnp.float64), rd.astype(jnp.float64))
        validity = combine_validity(lval, rval)
        if validity is not True:
            data = jnp.where(validity, data, 0.0)
        return result_column(dt.FLOAT64, data, validity, batch.capacity)


class Round(Expression):
    """GpuRound: HALF_UP rounding (Spark), scale as literal int."""

    def __init__(self, child: Expression, scale: int = 0):
        super().__init__(child)
        self.scale = scale

    @property
    def dtype(self) -> dt.DType:
        return self.children[0].dtype

    def eval(self, batch: ColumnarBatch):
        v = self.children[0].eval(batch)
        child_t = self.children[0].dtype
        factor = 10.0 ** self.scale
        if isinstance(v, Scalar):
            if v.is_null:
                return Scalar(None, self.dtype)
            x = float(v.value)
            r = math.floor(abs(x) * factor + 0.5) / factor * (1 if x >= 0 else -1)
            return Scalar(r if child_t.is_floating else int(r), self.dtype)
        if child_t.is_floating:
            # HALF_UP: round(|x|*f + 0.5)/f with sign restored (jnp.round is HALF_EVEN)
            scaled = jnp.abs(v.data) * factor
            rounded = jnp.floor(scaled + 0.5) / factor
            data = jnp.where(v.data < 0, -rounded, rounded)
            return Column(self.dtype, data.astype(v.data.dtype), v.validity)
        if self.scale >= 0:
            return v
        f = int(10 ** (-self.scale))
        half = f // 2
        sign = jnp.where(v.data < 0, -1, 1).astype(v.data.dtype)
        mag = jnp.abs(v.data.astype(jnp.int64))
        data = ((mag + half) // f * f).astype(v.data.dtype) * sign
        return Column(self.dtype, data, v.validity)
