"""Predicate expressions: comparisons, AND/OR/NOT, IsNull/IsNaN, In/InSet.

Reference: ``org/apache/spark/sql/rapids/predicates.scala`` (629 LoC). Spark null
semantics: comparisons are NULL if either side is NULL (except ``<=>``); AND/OR are
Kleene three-valued. Spark's NaN semantics (unlike IEEE): NaN = NaN is TRUE and NaN
is greater than every other double — implemented via ``float_eq``/``float_lt``,
consistent with the total order kernels.py uses for sort/group.
"""

from __future__ import annotations

from typing import List

import jax.numpy as jnp

from ..columnar import dtypes as dt
from ..columnar.batch import ColumnarBatch
from ..columnar.column import Column, Scalar
from .expressions import (Expression, combine_validity, data_validity,
                          result_column)
from .strings_util import string_equal, string_compare


def float_eq(l, r):
    """Spark float equality: NaN = NaN is TRUE (unlike IEEE)."""
    return (l == r) | (jnp.isnan(l) & jnp.isnan(r))


def float_lt(l, r):
    """Spark float ordering: NaN is greater than every other value."""
    return (l < r) | (jnp.isnan(r) & ~jnp.isnan(l))


class BinaryComparison(Expression):
    symbol = "?"

    @property
    def dtype(self) -> dt.DType:
        return dt.BOOL

    @property
    def left(self):
        return self.children[0]

    @property
    def right(self):
        return self.children[1]

    def _cmp(self, l, r):
        raise NotImplementedError

    def _cmp_float(self, l, r):
        """Spark NaN semantics (NaN = NaN true, NaN greatest); see float_eq/float_lt."""
        raise NotImplementedError

    def _string_cmp(self, lv, rv, batch):
        cmp = string_compare(lv, rv, batch.capacity)
        return self._cmp(cmp, jnp.zeros((), jnp.int32))

    def eval(self, batch: ColumnarBatch):
        in_dtype = self.left.dtype
        lv = self.left.eval(batch)
        rv = self.right.eval(batch)
        if isinstance(lv, Scalar) and isinstance(rv, Scalar):
            if lv.is_null or rv.is_null:
                return Scalar(None, dt.BOOL)
            return Scalar(bool(self._py_cmp(lv, rv)), dt.BOOL)
        if in_dtype == dt.STRING:
            data = self._string_cmp(lv, rv, batch)
            lval = lv.validity if isinstance(lv, Column) else (not lv.is_null)
            rval = rv.validity if isinstance(rv, Column) else (not rv.is_null)
            validity = combine_validity(lval, rval)
        else:
            ld, lval = data_validity(lv, in_dtype)
            rd, rval = data_validity(rv, in_dtype)
            data = self._cmp_float(ld, rd) if in_dtype.is_floating \
                else self._cmp(ld, rd)
            validity = combine_validity(lval, rval)
        if validity is not True:
            data = data & jnp.broadcast_to(validity, (batch.capacity,))
        return result_column(dt.BOOL, data, validity, batch.capacity)

    def _py_cmp(self, lv: Scalar, rv: Scalar):
        """Pure-host scalar compare — Spark's NaN semantics (NaN = NaN is
        TRUE, NaN greater than everything) inlined so a literal-literal
        fold never touches the device (this path runs per batch)."""
        l, r = lv.value, rv.value
        if self.left.dtype == dt.STRING:
            mapping = {"=": l == r, "<": l < r, "<=": l <= r, ">": l > r,
                       ">=": l >= r}
            return mapping[self.symbol] if self.symbol in mapping else (
                l != r)
        if self.left.dtype.is_floating:
            import math
            import numpy as np
            # round to the COLUMN dtype first (float32 literals must
            # compare at float32, like the column path): f32->f64 widening
            # is exact, so the python compare then matches a _cmp at npdt
            npdt = np.dtype(self.left.dtype.numpy_dtype).type
            l, r = float(npdt(l)), float(npdt(r))
            ln, rn = math.isnan(l), math.isnan(r)
            if ln or rn:
                eq = ln and rn
                lt = rn and not ln
            else:
                eq, lt = (l == r), (l < r)
        else:
            eq, lt = (l == r), (l < r)
        return {"=": eq, "!=": not eq, "<": lt, "<=": lt or eq,
                ">": not (lt or eq), ">=": not lt}[self.symbol]

    def __repr__(self):
        return f"({self.children[0]!r} {self.symbol} {self.children[1]!r})"


class EqualTo(BinaryComparison):
    symbol = "="
    def _cmp(self, l, r): return l == r
    def _cmp_float(self, l, r): return float_eq(l, r)
    def _string_cmp(self, lv, rv, batch):
        return string_equal(lv, rv, batch.capacity)


class LessThan(BinaryComparison):
    symbol = "<"
    def _cmp(self, l, r): return l < r
    def _cmp_float(self, l, r): return float_lt(l, r)


class LessThanOrEqual(BinaryComparison):
    symbol = "<="
    def _cmp(self, l, r): return l <= r
    def _cmp_float(self, l, r): return float_lt(l, r) | float_eq(l, r)


class GreaterThan(BinaryComparison):
    symbol = ">"
    def _cmp(self, l, r): return l > r
    def _cmp_float(self, l, r): return float_lt(r, l)


class GreaterThanOrEqual(BinaryComparison):
    symbol = ">="
    def _cmp(self, l, r): return l >= r
    def _cmp_float(self, l, r): return float_lt(r, l) | float_eq(l, r)


class NotEqual(BinaryComparison):
    """Spark has Not(EqualTo) but a direct != is convenient for the CPU engine too."""
    symbol = "!="
    def _cmp(self, l, r): return l != r
    def _cmp_float(self, l, r): return ~float_eq(l, r)
    def _string_cmp(self, lv, rv, batch):
        return ~string_equal(lv, rv, batch.capacity)


class EqualNullSafe(Expression):
    """`<=>`: never NULL; NULL <=> NULL is true (GpuEqualNullSafe)."""
    symbol = "<=>"

    @property
    def dtype(self):
        return dt.BOOL

    @property
    def nullable(self):
        return False

    def eval(self, batch: ColumnarBatch):
        lv = self.children[0].eval(batch)
        rv = self.children[1].eval(batch)
        in_dtype = self.children[0].dtype
        if in_dtype == dt.STRING:
            eq = string_equal(lv, rv, batch.capacity)
        else:
            ld, lval = data_validity(lv, in_dtype)
            rd, rval = data_validity(rv, in_dtype)
            eq = float_eq(ld, rd) if in_dtype.is_floating else (ld == rd)
        lval = lv.validity if isinstance(lv, Column) else (not lv.is_null)
        rval = rv.validity if isinstance(rv, Column) else (not rv.is_null)
        lval = jnp.broadcast_to(jnp.asarray(lval), (batch.capacity,))
        rval = jnp.broadcast_to(jnp.asarray(rval), (batch.capacity,))
        data = jnp.where(lval & rval, jnp.broadcast_to(eq, (batch.capacity,)),
                         lval == rval)
        # padding rows are invalid==invalid -> would read True; mask to live rows.
        # validity is the live-row mask (never NULL on live rows) so the padding
        # invariant (invalid + zeroed) holds for downstream consumers like Not.
        live = batch.row_mask()
        data = data & live
        return result_column(dt.BOOL, data, live, batch.capacity)


class And(Expression):
    """Kleene AND (GpuAnd): F & NULL = F; T & NULL = NULL."""
    symbol = "AND"

    @property
    def dtype(self):
        return dt.BOOL

    def eval(self, batch: ColumnarBatch):
        lv = self.children[0].eval(batch)
        rv = self.children[1].eval(batch)
        ld, lval = data_validity(lv, dt.BOOL)
        rd, rval = data_validity(rv, dt.BOOL)
        lval = jnp.broadcast_to(jnp.asarray(lval), (batch.capacity,))
        rval = jnp.broadcast_to(jnp.asarray(rval), (batch.capacity,))
        l_false = lval & ~ld
        r_false = rval & ~rd
        validity = l_false | r_false | (lval & rval)
        data = jnp.broadcast_to(ld & rd, (batch.capacity,)) & validity
        return result_column(dt.BOOL, data, validity, batch.capacity)

    def __repr__(self):
        return f"({self.children[0]!r} AND {self.children[1]!r})"


class Or(Expression):
    """Kleene OR (GpuOr): T | NULL = T; F | NULL = NULL."""
    symbol = "OR"

    @property
    def dtype(self):
        return dt.BOOL

    def eval(self, batch: ColumnarBatch):
        lv = self.children[0].eval(batch)
        rv = self.children[1].eval(batch)
        ld, lval = data_validity(lv, dt.BOOL)
        rd, rval = data_validity(rv, dt.BOOL)
        lval = jnp.broadcast_to(jnp.asarray(lval), (batch.capacity,))
        rval = jnp.broadcast_to(jnp.asarray(rval), (batch.capacity,))
        l_true = lval & ld
        r_true = rval & rd
        validity = l_true | r_true | (lval & rval)
        data = jnp.broadcast_to(l_true | r_true, (batch.capacity,))
        return result_column(dt.BOOL, data, validity, batch.capacity)

    def __repr__(self):
        return f"({self.children[0]!r} OR {self.children[1]!r})"


class Not(Expression):
    """GpuNot."""
    @property
    def dtype(self):
        return dt.BOOL

    def eval(self, batch: ColumnarBatch):
        v = self.children[0].eval(batch)
        if isinstance(v, Scalar):
            return Scalar(None if v.is_null else (not v.value), dt.BOOL)
        return Column(dt.BOOL, (~v.data) & v.validity, v.validity)

    def __repr__(self):
        return f"(NOT {self.children[0]!r})"


class IsNull(Expression):
    """GpuIsNull — never NULL itself."""
    @property
    def dtype(self):
        return dt.BOOL

    @property
    def nullable(self):
        return False

    def eval(self, batch: ColumnarBatch):
        v = self.children[0].eval(batch)
        if isinstance(v, Scalar):
            return Scalar(v.is_null, dt.BOOL)
        # padding rows are invalid; mask to live rows so they don't read as "null
        # rows", and keep validity=live so padding stays invalid + zeroed
        live = batch.row_mask()
        data = (~v.validity) & live
        return result_column(dt.BOOL, data, live, batch.capacity)


class IsNotNull(Expression):
    """GpuIsNotNull."""
    @property
    def dtype(self):
        return dt.BOOL

    @property
    def nullable(self):
        return False

    def eval(self, batch: ColumnarBatch):
        v = self.children[0].eval(batch)
        if isinstance(v, Scalar):
            return Scalar(not v.is_null, dt.BOOL)
        live = batch.row_mask()
        return result_column(dt.BOOL, v.validity & live, live, batch.capacity)


class IsNaN(Expression):
    """GpuIsNan."""
    @property
    def dtype(self):
        return dt.BOOL

    @property
    def nullable(self):
        return False

    def eval(self, batch: ColumnarBatch):
        v = self.children[0].eval(batch)
        if isinstance(v, Scalar):
            import math
            return Scalar(bool(v.value is not None and math.isnan(v.value)), dt.BOOL)
        live = batch.row_mask()
        return result_column(dt.BOOL, jnp.isnan(v.data) & v.validity, live,
                             batch.capacity)


class In(Expression):
    """GpuInSet/GpuIn with literal list: NULL semantics — if no match and the list
    contains NULL, result is NULL; NULL input gives NULL."""

    def __init__(self, child: Expression, values: List):
        super().__init__(child)
        self.values = values

    @property
    def dtype(self):
        return dt.BOOL

    def eval(self, batch: ColumnarBatch):
        child = self.children[0]
        v = child.eval(batch)
        has_null = any(x is None for x in self.values)
        concrete = [x for x in self.values if x is not None]
        if child.dtype == dt.STRING:
            match = jnp.zeros(batch.capacity, dtype=jnp.bool_)
            for s in concrete:
                match = match | string_equal(v, Scalar(s, dt.STRING), batch.capacity)
        else:
            vd, vval = data_validity(v, child.dtype)
            match = jnp.zeros(batch.capacity, dtype=jnp.bool_)
            for x in concrete:
                match = match | jnp.broadcast_to(
                    vd == jnp.asarray(x, child.dtype.numpy_dtype), (batch.capacity,))
        vval = v.validity if isinstance(v, Column) else jnp.broadcast_to(
            jnp.asarray(not v.is_null), (batch.capacity,))
        validity = vval & (match | (not has_null))
        data = match & validity
        return result_column(dt.BOOL, data, validity, batch.capacity)

    def __repr__(self):
        return f"({self.children[0]!r} IN {self.values!r})"
