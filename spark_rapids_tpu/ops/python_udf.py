"""Python / pandas UDF execution.

Reference: ``GpuArrowEvalPythonExec.scala:58-500`` — device batches stream to
a python worker as Arrow IPC, results stream back and re-join their input
batches (``BatchQueue``), with ``RebatchingRoundoffIterator`` aligning batch
sizes; plus ``GpuMapInPandasExec`` and friends (SURVEY.md §2.9).

TPU-standalone: the engine IS python, so the "worker" boundary collapses —
but the data contract is identical: device batch -> Arrow -> pandas ->
user function -> Arrow -> device batch. The udf-compiler (ops/udf_compiler)
tries to translate scalar python UDFs into native expressions first
(Plugin.scala:28-94's resolution rule); only untranslatable UDFs pay the
host round trip.
"""

from __future__ import annotations

from typing import Callable, List, Optional

from ..columnar import dtypes as dt
from ..columnar.batch import ColumnarBatch
from ..columnar.column import Column, bucket
from .expressions import Expression, materialize


class PandasUDF(Expression):
    """Scalar pandas UDF expression: fn(pandas.Series...) -> Series.
    Host-side (non-fusable): evaluation crosses device -> Arrow -> pandas
    and back, the GpuArrowEvalPythonExec data path minus the IPC socket."""

    fusable = False

    def __init__(self, fn: Callable, return_type: dt.DType,
                 *children: Expression, name: Optional[str] = None):
        super().__init__(*children)
        self.fn = fn
        self.return_type = return_type
        self.udf_name = name or getattr(fn, "__name__", "udf")

    @property
    def dtype(self) -> dt.DType:
        return self.return_type

    @property
    def nullable(self) -> bool:
        return True

    def eval(self, batch: ColumnarBatch):
        import pandas as pd
        n = batch.num_rows
        series = []
        for c in self.children:
            col = materialize(c.eval(batch), batch)
            series.append(pd.Series(col.to_arrow(n).to_pandas()))
        out = self.fn(*series)
        if not isinstance(out, pd.Series):
            out = pd.Series(out)
        if len(out) != n:
            raise ValueError(
                f"pandas UDF {self.udf_name!r} returned {len(out)} rows "
                f"for {n} input rows")
        vals = [None if pd.isna(v) else v for v in out]
        return Column.from_pylist(vals, self.return_type,
                                  capacity=batch.capacity)

    def __repr__(self):
        args = ", ".join(repr(c) for c in self.children)
        return f"{self.udf_name}({args})"


class PandasAggUDF(Expression):
    """Grouped-aggregate pandas UDF marker: fn(pandas.Series...) -> scalar
    per group (pyspark GROUPED_AGG; GpuAggregateInPandasExec's udf). Never
    evaluated row-wise — the planner routes the Aggregate through
    TpuAggregateInPandasExec, which slices per-group frames and calls
    ``fn`` once per group."""

    fusable = False

    def __init__(self, fn: Callable, return_type: dt.DType,
                 *children: Expression, name: Optional[str] = None):
        super().__init__(*children)
        self.fn = fn
        self.return_type = return_type
        self.udf_name = name or getattr(fn, "__name__", "agg_udf")

    @property
    def dtype(self) -> dt.DType:
        return self.return_type

    @property
    def nullable(self) -> bool:
        return True

    def eval(self, batch: ColumnarBatch):
        raise RuntimeError(
            f"grouped-agg pandas UDF {self.udf_name!r} is planned by "
            "AggregateInPandas, not evaluated directly")

    def __repr__(self):
        args = ", ".join(repr(c) for c in self.children)
        return f"{self.udf_name}({args})"


def _split_head_rest(merged: ColumnarBatch, take: int,
                     owned: bool = False):
    """Head ``[0, take)`` + rest ``[take, n)`` in ONE cached fused
    program per (schema, capacity, take-bucket, rest-bucket) shape class
    — the ``_RR_IDX_CACHE`` discipline applied to the UDF rebatch slicer,
    which previously re-dispatched a chain of eager gather programs per
    column per batch for BOTH halves. Routed through ``_fused_fn`` so the
    recompile audit and persistent compile cache see it. ``owned=True``
    (the merged batch was built inside the rebatch loop — never a
    caller's batch) additionally donates its buffers: the split is then
    provably its only consumer. Returns ``(head, rest)``; ``rest`` is
    None when nothing remains."""
    import jax
    from ..ops import kernels as K
    from ..plan.physical import (_donate_argnums, _dev_count, _fused_fn,
                                 _schema_sig)
    schema = merged.schema
    n = merged.num_rows
    rest = n - take
    head_cap = bucket(take)
    rest_cap = bucket(max(rest, 1))
    donate = _donate_argnums(merged, 1) if owned else ()
    sig = ("udf_rebatch", _schema_sig(schema), merged.capacity, take,
           head_cap, rest_cap, ("donate", bool(donate)))

    def build():
        def fn(num_rows, *arrays):
            b = ColumnarBatch.from_flat_arrays(schema, arrays, num_rows)
            head = [K.slice_column(c, 0, head_cap, take)
                    for c in b.columns]
            tail = [K.slice_column(c, take, rest_cap, num_rows - take)
                    for c in b.columns]
            return tuple(a for c in head + tail for a in c.arrays())
        return jax.jit(fn, donate_argnums=donate)

    outs = _fused_fn(sig, build)(_dev_count(merged),
                                 *merged.flat_arrays())
    from ..plan.physical import _note_donated
    _note_donated(merged, donate)
    nh = len(outs) // 2
    head = ColumnarBatch.from_flat_arrays(schema, list(outs[:nh]), take)
    if rest <= 0:
        return head, None
    return head, ColumnarBatch.from_flat_arrays(schema, list(outs[nh:]),
                                                rest)


def rebatch_iterator(batches, target_rows: int):
    """Align batch sizes to ~target_rows (RebatchingRoundoffIterator,
    GpuArrowEvalPythonExec.scala): concat small batches, slice large ones,
    so the python worker sees a steady batch cadence."""
    from ..plan.physical import concat_batches
    from ..ops import kernels as K
    pending: List[ColumnarBatch] = []
    pending_rows = 0
    # True while every batch in ``pending`` was built HERE (a rest
    # slice): only then may the split donate the merged buffers — a
    # caller's batch must never be freed under it
    pending_owned = False
    schema = None
    for b in batches:
        if b.num_rows == 0:
            continue
        schema = b.schema
        pending.append(b)
        pending_owned = False
        pending_rows += b.num_rows
        while pending_rows >= target_rows:
            merged = concat_batches(schema, pending)
            take = target_rows
            owned = pending_owned or all(merged is not p for p in pending)
            try:
                head, rest_b = _split_head_rest(merged, take, owned)
            except Exception:
                from ..plan.physical import _donation_consumed
                if owned and _donation_consumed(merged):
                    raise      # executed-and-donated: no eager re-read
                # host-payload columns (ObjectColumn) and other
                # untraceables keep the per-column eager slice path
                head_cols = [K.slice_column(c, 0, bucket(take), take)
                             for c in merged.columns]
                head = ColumnarBatch(schema, head_cols, take)
                rest = merged.num_rows - take
                rest_b = None
                if rest > 0:
                    rest_cols = [K.slice_column(c, take, bucket(rest),
                                                rest)
                                 for c in merged.columns]
                    rest_b = ColumnarBatch(schema, rest_cols, rest)
            yield head
            pending = [rest_b] if rest_b is not None else []
            pending_owned = rest_b is not None
            pending_rows = rest_b.num_rows if rest_b is not None else 0
    if pending:
        yield concat_batches(schema, pending)
