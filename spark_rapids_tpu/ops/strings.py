"""String expressions over padded byte matrices: the stringFunctions analog.

Reference: ``org/apache/spark/sql/rapids/stringFunctions.scala`` (898 LoC) —
substring/locate/replace/trim/pad/concat/contains/starts/ends/like/length/
upper/lower(incompat)/initcap, with regex-heavy patterns gated to CPU fallback
(GpuOverrides.scala:343-351). Same stance here: LIKE fast paths run on device,
general regex ops are host-side (``fusable = False``).

Representation (DESIGN.md §4): ``uint8[N, W]`` zero-padded bytes + ``int32[N]``
lengths. Character semantics (Spark's length/substring count characters, not
bytes) are implemented by classifying UTF-8 continuation bytes on the VPU.
Upper/Lower are ASCII-only — exactly the reference's "incompat" stance for
cuDF's non-locale-aware case mapping.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ..columnar import dtypes as dt
from ..columnar.batch import ColumnarBatch
from ..columnar.column import Column, Scalar, string_width_bucket
from .expressions import Expression, combine_validity, result_column
from .strings_util import operand_arrays, scalar_bytes

# ---------------------------------------------------------------------------
# Byte-matrix primitives
# ---------------------------------------------------------------------------


def _is_char_start(data: jnp.ndarray) -> jnp.ndarray:
    """True for bytes that start a UTF-8 character (not 0b10xxxxxx)."""
    return (data & 0xC0) != 0x80


def _char_count(data: jnp.ndarray, lengths: jnp.ndarray) -> jnp.ndarray:
    w = data.shape[1]
    in_str = jnp.arange(w)[None, :] < lengths[:, None]
    return jnp.sum((_is_char_start(data) & in_str).astype(jnp.int32), axis=1)


def _compact_rows(data: jnp.ndarray, keep: jnp.ndarray
                  ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Per-row stable compaction of kept bytes to the left; returns (data, lengths)."""
    order = jnp.argsort(~keep, axis=1, stable=True)
    out = jnp.take_along_axis(data, order, axis=1)
    new_len = jnp.sum(keep.astype(jnp.int32), axis=1)
    pos = jnp.arange(data.shape[1])[None, :]
    out = jnp.where(pos < new_len[:, None], out, jnp.uint8(0))
    return out, new_len


def _materialize_str(v, capacity: int, width: Optional[int] = None
                     ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """(data[cap, W], lengths[cap], validity[cap]) for a Column or Scalar operand."""
    if isinstance(v, Scalar):
        raw, n = scalar_bytes(v)
        w = width or string_width_bucket(max(n, 1))
        row = np.zeros((1, w), dtype=np.uint8)
        row[0, :n] = raw
        data = jnp.broadcast_to(jnp.asarray(row), (capacity, w))
        lengths = jnp.full(capacity, n, dtype=jnp.int32)
        validity = jnp.broadcast_to(jnp.asarray(not v.is_null), (capacity,))
        return data, lengths, validity
    data = v.data
    if width is not None and data.shape[1] < width:
        data = jnp.pad(data, ((0, 0), (0, width - data.shape[1])))
    return data, v.lengths, v.validity


def _find_pattern(data: jnp.ndarray, lengths: jnp.ndarray,
                  pat: np.ndarray) -> jnp.ndarray:
    """int32[N]: byte index of first occurrence of ``pat`` in each row, -1 if none.
    Empty pattern matches at 0."""
    n, w = data.shape
    p = len(pat)
    if p == 0:
        return jnp.zeros(n, dtype=jnp.int32)
    if p > w:
        return jnp.full(n, -1, dtype=jnp.int32)
    # match_at[i, j] = bytes j..j+p-1 equal pat and fit within length
    match = jnp.ones((n, w), dtype=jnp.bool_)
    for k, byte in enumerate(pat):
        shifted = jnp.roll(data, -k, axis=1) if k else data
        # roll wraps; positions beyond w-k are invalidated by the fit check below
        match = match & (shifted == np.uint8(byte))
    pos = jnp.arange(w)[None, :]
    fits = pos + p <= lengths[:, None]
    match = match & fits
    any_m = jnp.any(match, axis=1)
    first = jnp.argmax(match, axis=1).astype(jnp.int32)
    return jnp.where(any_m, first, -1)


# ---------------------------------------------------------------------------
# Expressions
# ---------------------------------------------------------------------------

class StringUnary(Expression):
    """Base for one-string-child device expressions."""

    @property
    def child(self):
        return self.children[0]


class Length(StringUnary):
    """GpuLength: character count (stringFunctions.scala)."""

    @property
    def dtype(self):
        return dt.INT32

    def eval(self, batch: ColumnarBatch):
        v = self.child.eval(batch)
        if isinstance(v, Scalar):
            return Scalar(None if v.is_null else len(str(v.value)), dt.INT32)
        data = _char_count(v.data, v.lengths)
        data = jnp.where(v.validity, data, 0)
        return result_column(dt.INT32, data, v.validity, batch.capacity)


class _AsciiCase(StringUnary):
    """ASCII-only case mapping — 'incompat' exactly like the reference's
    Upper/Lower (GpuOverrides registers them incompat; cuDF is not locale-aware)."""
    incompat = True
    _lo: int
    _hi: int
    _delta: int

    @property
    def dtype(self):
        return dt.STRING

    def eval(self, batch: ColumnarBatch):
        v = self.child.eval(batch)
        if isinstance(v, Scalar):
            if v.is_null:
                return Scalar(None, dt.STRING)
            f = str.upper if self._delta < 0 else str.lower
            return Scalar(f(v.value), dt.STRING)
        in_range = (v.data >= self._lo) & (v.data <= self._hi)
        data = jnp.where(in_range, v.data + self._delta, v.data).astype(jnp.uint8)
        return Column(dt.STRING, data, v.validity, v.lengths)


class Upper(_AsciiCase):
    _lo, _hi, _delta = ord("a"), ord("z"), -32


class Lower(_AsciiCase):
    _lo, _hi, _delta = ord("A"), ord("Z"), 32


class InitCap(StringUnary):
    """GpuInitCap (incompat in reference for the same ASCII reasons)."""
    incompat = True

    @property
    def dtype(self):
        return dt.STRING

    def eval(self, batch: ColumnarBatch):
        v = self.child.eval(batch)
        if isinstance(v, Scalar):
            if v.is_null:
                return Scalar(None, dt.STRING)
            return Scalar(" ".join(w.capitalize() for w in v.value.split(" ")), dt.STRING)
        is_sp = v.data == ord(" ")
        prev_sp = jnp.concatenate(
            [jnp.ones((v.data.shape[0], 1), jnp.bool_), is_sp[:, :-1]], axis=1)
        lower = (v.data >= ord("a")) & (v.data <= ord("z"))
        upper = (v.data >= ord("A")) & (v.data <= ord("Z"))
        data = jnp.where(prev_sp & lower, v.data - 32,
                         jnp.where(~prev_sp & upper, v.data + 32, v.data))
        return Column(dt.STRING, data.astype(jnp.uint8), v.validity, v.lengths)


class Substring(Expression):
    """GpuSubstring: 1-based character position, negative counts from the end."""

    def __init__(self, child: Expression, pos: Expression, length: Expression):
        super().__init__(child, pos, length)

    @property
    def dtype(self):
        return dt.STRING

    def eval(self, batch: ColumnarBatch):
        v = self.children[0].eval(batch)
        pos_v = self.children[1].eval(batch)
        len_v = self.children[2].eval(batch)
        cap = batch.capacity
        if isinstance(v, Scalar):
            from .expressions import materialize
            v = materialize(v, batch)
        nchars = _char_count(v.data, v.lengths)

        def _ints(x):
            if isinstance(x, Scalar):
                return jnp.full(cap, -1 if x.is_null else int(x.value), jnp.int32), \
                    jnp.asarray(not x.is_null)
            return x.data.astype(jnp.int32), x.validity

        pos, pval = _ints(pos_v)
        ln, lval = _ints(len_v)
        # Spark: pos 0 behaves like 1; negative pos counts from end
        start = jnp.where(pos > 0, pos - 1,
                          jnp.where(pos < 0, jnp.maximum(nchars + pos, 0), 0))
        ln = jnp.maximum(ln, 0)
        end = start + ln
        # classify each byte by its character index
        starts_m = _is_char_start(v.data)
        char_idx = jnp.cumsum(starts_m.astype(jnp.int32), axis=1) - 1
        w = v.data.shape[1]
        in_str = jnp.arange(w)[None, :] < v.lengths[:, None]
        keep = in_str & (char_idx >= start[:, None]) & (char_idx < end[:, None])
        data, lengths = _compact_rows(v.data, keep)
        validity = combine_validity(v.validity, pval, lval)
        validity = jnp.broadcast_to(validity, (cap,)) if validity is not True \
            else jnp.ones(cap, jnp.bool_)
        lengths = jnp.where(validity, lengths, 0)
        return Column(dt.STRING, data, validity, lengths)


class ConcatStr(Expression):
    """GpuConcat (string concat, NULL if any input NULL)."""

    @property
    def dtype(self):
        return dt.STRING

    def eval(self, batch: ColumnarBatch):
        cap = batch.capacity
        vals = [c.eval(batch) for c in self.children]
        if all(isinstance(v, Scalar) for v in vals):
            if any(v.is_null for v in vals):
                return Scalar(None, dt.STRING)
            return Scalar("".join(str(v.value) for v in vals), dt.STRING)
        mats = [_materialize_str(v, cap) for v in vals]
        total_w = string_width_bucket(sum(m[0].shape[1] for m in mats))
        out = jnp.zeros((cap, total_w), dtype=jnp.uint8)
        offset = jnp.zeros(cap, dtype=jnp.int32)
        pos = jnp.arange(total_w)[None, :]
        validity = None
        for data, lengths, valid in mats:
            w = data.shape[1]
            # scatter source bytes at [offset, offset+len)
            rel = pos - offset[:, None]
            in_src = (rel >= 0) & (rel < lengths[:, None])
            src = jnp.take_along_axis(
                data, jnp.clip(rel, 0, w - 1).astype(jnp.int32), axis=1)
            out = jnp.where(in_src, src, out)
            offset = offset + lengths
            validity = valid if validity is None else (validity & valid)
        lengths = jnp.where(validity, offset, 0)
        out = jnp.where(validity[:, None], out, jnp.uint8(0))
        out = jnp.where(pos < lengths[:, None], out, jnp.uint8(0))
        return Column(dt.STRING, out, validity, lengths)


class _PatternPredicate(Expression):
    """Base for Contains/StartsWith/EndsWith with a literal pattern."""

    @property
    def dtype(self):
        return dt.BOOL

    def _pattern(self) -> Optional[np.ndarray]:
        from .expressions import Literal
        rhs = self.children[1]
        if isinstance(rhs, Literal) and rhs.value is not None:
            return np.frombuffer(str(rhs.value).encode("utf-8"), dtype=np.uint8)
        return None

    def eval(self, batch: ColumnarBatch):
        v = self.children[0].eval(batch)
        pat = self._pattern()
        if pat is None:
            rv = self.children[1].eval(batch)
            if isinstance(rv, Scalar):
                if rv.is_null:
                    if isinstance(v, Scalar):
                        return Scalar(None, dt.BOOL)
                    return result_column(dt.BOOL, jnp.zeros(batch.capacity, jnp.bool_),
                                         jnp.zeros(batch.capacity, jnp.bool_),
                                         batch.capacity)
                pat = np.frombuffer(str(rv.value).encode(), dtype=np.uint8)
            else:
                raise NotImplementedError("column pattern runs on host fallback")
        if isinstance(v, Scalar):
            if v.is_null:
                return Scalar(None, dt.BOOL)
            return Scalar(self._py(str(v.value), bytes(pat).decode()), dt.BOOL)
        data = self._match(v.data, v.lengths, pat)
        live = batch.row_mask()
        return result_column(dt.BOOL, data & v.validity & live,
                             v.validity & live, batch.capacity)


class Contains(_PatternPredicate):
    def _py(self, s, p):
        return p in s

    def _match(self, data, lengths, pat):
        return _find_pattern(data, lengths, pat) >= 0


class StartsWith(_PatternPredicate):
    def _py(self, s, p):
        return s.startswith(p)

    def _match(self, data, lengths, pat):
        p = len(pat)
        if p == 0:
            return jnp.ones(data.shape[0], jnp.bool_)
        if p > data.shape[1]:
            return jnp.zeros(data.shape[0], jnp.bool_)
        head = data[:, :p]
        return jnp.all(head == jnp.asarray(pat), axis=1) & (lengths >= p)


class EndsWith(_PatternPredicate):
    def _py(self, s, p):
        return s.endswith(p)

    def _match(self, data, lengths, pat):
        p = len(pat)
        if p == 0:
            return jnp.ones(data.shape[0], jnp.bool_)
        w = data.shape[1]
        if p > w:
            return jnp.zeros(data.shape[0], jnp.bool_)
        # gather the last p bytes of each row
        idx = lengths[:, None] - p + jnp.arange(p)[None, :]
        tail = jnp.take_along_axis(data, jnp.clip(idx, 0, w - 1), axis=1)
        return jnp.all(tail == jnp.asarray(pat), axis=1) & (lengths >= p)


class Like(Expression):
    """GpuLike: SQL LIKE. Device fast paths for %x%, x%, %x, plain equality and
    '_'-free patterns; anything else runs through the host matcher (the
    reference likewise gates complex regexp to CPU, GpuOverrides.scala:343-351).
    """

    def __init__(self, child: Expression, pattern: str, escape: str = "\\"):
        super().__init__(child)
        self.pattern = pattern
        self.escape = escape

    @property
    def dtype(self):
        return dt.BOOL

    @property
    def fusable(self):
        return self._fast_path() is not None

    def _fast_path(self):
        p = self.pattern
        if self.escape in p or "_" in p:
            return None
        if "%" not in p:
            return ("eq", p)
        core = p.strip("%")
        if "%" in core:
            return None
        if p.startswith("%") and p.endswith("%") and len(p) >= 2:
            return ("contains", core)
        if p.endswith("%"):
            return ("prefix", core)
        if p.startswith("%"):
            return ("suffix", core)
        return None

    def eval(self, batch: ColumnarBatch):
        v = self.children[0].eval(batch)
        fp = self._fast_path()
        if isinstance(v, Scalar):
            if v.is_null:
                return Scalar(None, dt.BOOL)
            return Scalar(_like_py(str(v.value), self.pattern, self.escape), dt.BOOL)
        if fp is None:
            vals = v.to_pylist(batch.num_rows)
            out = [None if x is None else _like_py(x, self.pattern, self.escape)
                   for x in vals]
            return Column.from_pylist(out, dt.BOOL, capacity=batch.capacity)
        kind, core = fp
        pat = np.frombuffer(core.encode("utf-8"), dtype=np.uint8)
        if kind == "eq":
            data = (v.lengths == len(pat))
            if len(pat) <= v.data.shape[1]:
                w = v.data.shape[1]
                padded = np.zeros(w, dtype=np.uint8)
                padded[:len(pat)] = pat
                data = data & jnp.all(v.data == jnp.asarray(padded), axis=1)
            else:
                data = jnp.zeros(batch.capacity, jnp.bool_)
        elif kind == "contains":
            data = _find_pattern(v.data, v.lengths, pat) >= 0
        elif kind == "prefix":
            data = StartsWith._match(None, v.data, v.lengths, pat)
        else:
            data = EndsWith._match(None, v.data, v.lengths, pat)
        live = batch.row_mask()
        return result_column(dt.BOOL, data & v.validity & live, v.validity & live,
                             batch.capacity)


def _like_py(s: str, pattern: str, escape: str) -> bool:
    """Host LIKE matcher (reference semantics: % any seq, _ any one char)."""
    import re
    out = []
    i = 0
    while i < len(pattern):
        c = pattern[i]
        if c == escape and i + 1 < len(pattern):
            out.append(re.escape(pattern[i + 1]))
            i += 2
            continue
        if c == "%":
            out.append(".*")
        elif c == "_":
            out.append(".")
        else:
            out.append(re.escape(c))
        i += 1
    return re.fullmatch("".join(out), s, flags=re.DOTALL) is not None


class StringLocate(Expression):
    """GpuStringLocate: locate(substr, str[, pos]) — 1-based, 0 if not found."""

    def __init__(self, substr: Expression, child: Expression,
                 start: Optional[Expression] = None):
        from .expressions import Literal
        super().__init__(substr, child, start or Literal(1))

    @property
    def dtype(self):
        return dt.INT32

    def eval(self, batch: ColumnarBatch):
        from .expressions import Literal
        sub = self.children[0]
        assert isinstance(sub, Literal), "locate substr must be literal (ref parity)"
        v = self.children[1].eval(batch)
        start_v = self.children[2].eval(batch)
        if sub.value is None:
            if isinstance(v, Scalar):
                return Scalar(None, dt.INT32)
            return Column.full_null(dt.INT32, batch.capacity)
        pat = np.frombuffer(str(sub.value).encode(), dtype=np.uint8)
        if isinstance(v, Scalar):
            if v.is_null:
                return Scalar(None, dt.INT32)
            s = int(start_v.value or 1) if isinstance(start_v, Scalar) else 1
            return Scalar(str(v.value).find(str(sub.value), max(s - 1, 0)) + 1,
                          dt.INT32)
        # NOTE byte-position semantics beyond start=1 for multibyte strings:
        # matches reference for ASCII; multibyte+start>1 is an incompat corner
        found = _find_pattern(v.data, v.lengths, pat)
        # char position of the found byte index
        starts_m = _is_char_start(v.data)
        char_idx = jnp.cumsum(starts_m.astype(jnp.int32), axis=1) - 1
        w = v.data.shape[1]
        cpos = jnp.take_along_axis(char_idx,
                                   jnp.clip(found, 0, w - 1)[:, None], axis=1)[:, 0]
        data = jnp.where(found >= 0, cpos + 1, 0)
        if isinstance(start_v, Scalar) and (start_v.value or 1) != 1:
            # start offsets beyond 1: host fallback for exactness
            vals = v.to_pylist(batch.num_rows)
            s = int(start_v.value or 1)
            out = [None if x is None else
                   (x.find(str(sub.value), max(s - 1, 0)) + 1 if s >= 1 else 0)
                   for x in vals]
            return Column.from_pylist(out, dt.INT32, capacity=batch.capacity)
        data = jnp.where(v.validity, data, 0)
        return result_column(dt.INT32, data, v.validity, batch.capacity)


class StringReplace(Expression):
    """GpuStringReplace: replace(str, search, replace) with literal search/replace."""

    def __init__(self, child: Expression, search: str, replacement: str):
        super().__init__(child)
        self.search = search
        self.replacement = replacement

    @property
    def dtype(self):
        return dt.STRING

    fusable = False  # general replace changes widths; run on host between stages

    def eval(self, batch: ColumnarBatch):
        v = self.children[0].eval(batch)
        if isinstance(v, Scalar):
            if v.is_null:
                return Scalar(None, dt.STRING)
            return Scalar(str(v.value).replace(self.search, self.replacement),
                          dt.STRING)
        if self.search == "":
            return v
        vals = v.to_pylist(batch.num_rows)
        out = [None if x is None else x.replace(self.search, self.replacement)
               for x in vals]
        return Column.from_pylist(out, dt.STRING, capacity=batch.capacity)


class _Trim(Expression):
    """GpuStringTrim family (space-only trim, the common case)."""
    _left: bool
    _right: bool

    @property
    def dtype(self):
        return dt.STRING

    def eval(self, batch: ColumnarBatch):
        v = self.children[0].eval(batch)
        if isinstance(v, Scalar):
            if v.is_null:
                return Scalar(None, dt.STRING)
            s = str(v.value)
            if self._left and self._right:
                return Scalar(s.strip(" "), dt.STRING)
            return Scalar(s.lstrip(" ") if self._left else s.rstrip(" "), dt.STRING)
        w = v.data.shape[1]
        pos = jnp.arange(w)[None, :]
        in_str = pos < v.lengths[:, None]
        is_sp = (v.data == ord(" ")) & in_str
        keep = in_str
        if self._left:
            # leading spaces: cumulative all-spaces prefix
            lead = jnp.cumprod(is_sp.astype(jnp.int32), axis=1).astype(jnp.bool_)
            keep = keep & ~lead
        if self._right:
            rev = is_sp[:, ::-1] | ~in_str[:, ::-1]
            trail = jnp.cumprod(rev.astype(jnp.int32), axis=1)[:, ::-1].astype(jnp.bool_)
            keep = keep & ~trail
        data, lengths = _compact_rows(v.data, keep)
        return Column(dt.STRING, data, v.validity, jnp.where(v.validity, lengths, 0))


class StringTrim(_Trim):
    _left = _right = True


class StringTrimLeft(_Trim):
    _left, _right = True, False


class StringTrimRight(_Trim):
    _left, _right = False, True


class _Pad(Expression):
    """GpuStringLPad/RPad with literal width and pad string."""
    _left: bool

    def __init__(self, child: Expression, width: int, pad: str = " "):
        super().__init__(child)
        self.width = int(width)
        self.pad = pad or " "

    @property
    def dtype(self):
        return dt.STRING

    def eval(self, batch: ColumnarBatch):
        v = self.children[0].eval(batch)
        if isinstance(v, Scalar):
            if v.is_null:
                return Scalar(None, dt.STRING)
            s = str(v.value)
            f = s.rjust if self._left else s.ljust
            # python pads with a single char; emulate multi-char pad
            return Scalar(_pad_py(s, self.width, self.pad, self._left), dt.STRING)
        target = self.width
        out_w = string_width_bucket(max(target, v.data.shape[1]))
        data, lengths, validity = _materialize_str(v, batch.capacity, out_w)
        pat = np.frombuffer(self.pad.encode(), dtype=np.uint8)
        pos = jnp.arange(out_w)[None, :]
        # NOTE: character==byte here (ASCII pad assumption); multibyte pad is an
        # incompat corner the reference also sidesteps via cuDF byte pads
        pad_n = jnp.maximum(target - lengths, 0)
        if self._left:
            src_idx = pos - pad_n[:, None]
            from_src = (src_idx >= 0) & (src_idx < lengths[:, None])
            src = jnp.take_along_axis(
                data, jnp.clip(src_idx, 0, out_w - 1).astype(jnp.int32), axis=1)
            pad_b = jnp.asarray(pat)[jnp.mod(pos, len(pat))]
            out = jnp.where(from_src, src, jnp.broadcast_to(pad_b, (batch.capacity, out_w)))
        else:
            from_src = pos < lengths[:, None]
            pad_b = jnp.asarray(pat)[jnp.mod(pos - lengths[:, None], len(pat))]
            out = jnp.where(from_src, data, pad_b)
        new_len = jnp.minimum(jnp.maximum(lengths, target), target)
        out = jnp.where(pos < new_len[:, None], out, jnp.uint8(0))
        # truncation when source longer than width: keep first `target` bytes
        return Column(dt.STRING, out.astype(jnp.uint8), validity,
                      jnp.where(validity, new_len, 0))


def _pad_py(s: str, width: int, pad: str, left: bool) -> str:
    if len(s) >= width:
        return s[:width]
    fill = (pad * width)[: width - len(s)]
    return fill + s if left else s + fill


class StringLPad(_Pad):
    _left = True


class StringRPad(_Pad):
    _left = False


class RegExpExtractHost(Expression):
    """Host-side regexp_extract (non-fusable; reference falls back to CPU for
    regex — we keep the op available but off the fused path)."""
    fusable = False

    def __init__(self, child: Expression, pattern: str, group: int = 1):
        super().__init__(child)
        self.pattern = pattern
        self.group = group

    @property
    def dtype(self):
        return dt.STRING

    def eval(self, batch: ColumnarBatch):
        import re
        rx = re.compile(self.pattern)
        v = self.children[0].eval(batch)
        if isinstance(v, Scalar):
            if v.is_null:
                return Scalar(None, dt.STRING)
            m = rx.search(str(v.value))
            return Scalar(m.group(self.group) if m else "", dt.STRING)
        vals = v.to_pylist(batch.num_rows)
        out = []
        for x in vals:
            if x is None:
                out.append(None)
            else:
                m = rx.search(x)
                out.append(m.group(self.group) if m else "")
        return Column.from_pylist(out, dt.STRING, capacity=batch.capacity)


class RegExpReplaceHost(Expression):
    """Host-side regexp_replace (non-fusable; same gating stance as
    RegExpExtractHost — the reference CPU-falls-back for general regex,
    GpuOverrides.scala:343-351)."""
    fusable = False

    def __init__(self, child: Expression, pattern: str, replacement: str):
        super().__init__(child)
        self.pattern = pattern
        self.replacement = replacement

    @property
    def dtype(self):
        return dt.STRING

    def _compiled(self):
        import re
        rx = re.compile(self.pattern)
        # java-style group refs $1 -> python \1
        repl = re.sub(r"\$(\d+)", r"\\\1", self.replacement)
        return rx, repl

    def apply_list(self, vals):
        """Replacement over python values — ONE source of truth shared by
        the device op and the CPU engine oracle."""
        rx, repl = self._compiled()
        return [None if x is None else rx.sub(repl, x) for x in vals]

    def eval(self, batch: ColumnarBatch):
        v = self.children[0].eval(batch)
        if isinstance(v, Scalar):
            if v.is_null:
                return Scalar(None, dt.STRING)
            rx, repl = self._compiled()
            return Scalar(rx.sub(repl, str(v.value)), dt.STRING)
        out = self.apply_list(v.to_pylist(batch.num_rows))
        return Column.from_pylist(out, dt.STRING, capacity=batch.capacity)
