"""Vectorized helpers over padded-byte-matrix string columns (DESIGN.md §4).

Strings are ``uint8[cap, W]`` zero-padded + ``int32[cap]`` lengths. Lexicographic
comparison on the padded bytes is exact because the zero pad sorts before any real
byte (caveat, documented: strings containing NUL bytes compare as if truncated —
matches the reference's "corner cases fall back" stance for exotic data).
"""

from __future__ import annotations

from typing import Tuple, Union

import jax.numpy as jnp
import numpy as np

from ..columnar import dtypes as dt
from ..columnar.column import Column, Scalar

StrOperand = Union[Column, Scalar]


def scalar_bytes(s: Scalar) -> Tuple[np.ndarray, int]:
    b = s.value.encode("utf-8") if isinstance(s.value, str) else (s.value or b"")
    return np.frombuffer(b, dtype=np.uint8), len(b)


def operand_arrays(v: StrOperand, capacity: int, width: int):
    """(data[cap|1, W], lengths[cap|1]) as jnp arrays padded to ``width``."""
    if isinstance(v, Scalar):
        raw, n = scalar_bytes(v)
        assert n <= width, f"scalar of {n} bytes vs width {width}; use _widths()"
        row = np.zeros((1, width), dtype=np.uint8)
        row[0, :n] = raw
        return jnp.asarray(row), jnp.asarray(np.array([n], dtype=np.int32))
    data = v.data
    if data.shape[1] < width:
        data = jnp.pad(data, ((0, 0), (0, width - data.shape[1])))
    return data, v.lengths


def _widths(lv: StrOperand, rv: StrOperand) -> int:
    w = 1
    for v in (lv, rv):
        if isinstance(v, Scalar):
            w = max(w, len(scalar_bytes(v)[0]))
        else:
            w = max(w, int(v.data.shape[1]))
    return w


def string_compare(lv: StrOperand, rv: StrOperand, capacity: int) -> jnp.ndarray:
    """Three-way lexicographic compare -> int32[cap] in {-1, 0, 1}."""
    w = _widths(lv, rv)
    ld, _ = operand_arrays(lv, capacity, w)
    rd, _ = operand_arrays(rv, capacity, w)
    d = ld.astype(jnp.int16) - rd.astype(jnp.int16)
    nz = d != 0
    first = jnp.argmax(nz, axis=1)
    any_diff = jnp.any(nz, axis=1)
    byte_cmp = jnp.take_along_axis(d, first[:, None], axis=1)[:, 0]
    out = jnp.where(any_diff, jnp.sign(byte_cmp).astype(jnp.int32), jnp.int32(0))
    return jnp.broadcast_to(out, (capacity,))


def string_equal(lv: StrOperand, rv: StrOperand, capacity: int) -> jnp.ndarray:
    w = _widths(lv, rv)
    ld, ll = operand_arrays(lv, capacity, w)
    rd, rl = operand_arrays(rv, capacity, w)
    eq = jnp.all(ld == rd, axis=1) & (ll == rl)
    return jnp.broadcast_to(eq, (capacity,))
