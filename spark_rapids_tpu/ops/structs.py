"""STRUCT field access.

Reference: ``complexTypeExtractors.scala`` (GetStructField). TPU-first
design: the planner SHREDS every referenced field of a SCAN's struct
column into a flat child column (overrides._shred_struct_columns — the
fast path); a GetField that survives to execution reads the device
StructColumn's child directly (struct-of-columns layout,
columnar.column.StructColumn), or falls back to the host ObjectColumn
rendering for CPU-engine-only field types."""

from __future__ import annotations

import jax.numpy as jnp

from ..columnar import dtypes as dt
from ..columnar.batch import ColumnarBatch
from ..columnar.column import Column, ObjectColumn, StructColumn
from .expressions import Expression, materialize


class GetField(Expression):
    """struct.field (GetStructField analog)."""

    fusable = False          # eager: struct child extraction + mask

    def __init__(self, child: Expression, field: str):
        super().__init__(child)
        self.field = field

    @property
    def dtype(self) -> dt.DType:
        child_t = self.children[0].dtype
        if not dt.is_struct(child_t):
            raise TypeError(f"getField on non-struct {child_t}")
        for n, t in child_t.fields:
            if n == self.field:
                return t
        raise TypeError(f"no field {self.field!r} in {child_t}")

    @property
    def nullable(self) -> bool:
        return True

    def eval(self, batch: ColumnarBatch):
        col = materialize(self.children[0].eval(batch), batch)
        if isinstance(col, StructColumn):
            # device path: the child column masked by the struct validity
            # (a NULL struct yields NULL fields)
            idx = [n for n, _ in col.dtype.fields].index(self.field)
            child = col.children[idx]
            return child.with_arrays(
                child.data, child.validity & col.validity) \
                if not isinstance(child, StructColumn) else StructColumn(
                    child.dtype, child.children,
                    child.validity & col.validity)
        if not isinstance(col, ObjectColumn):
            raise RuntimeError(
                "GetField reached a non-struct column — planner bug")
        vals = [None if v is None else v.get(self.field)
                for v in col.values]
        return Column.from_pylist(vals, self.dtype,
                                  capacity=col.capacity)

    def __repr__(self):
        return f"{self.children[0]!r}.{self.field}"
