"""STRUCT field access.

Reference: ``complexTypeExtractors.scala`` (GetStructField). TPU-first
design: struct columns have NO device layout — the planner SHREDS every
referenced field into a flat child column at the scan
(overrides._shred_struct_columns), so a GetField that survives to
execution only ever sees the host-side ObjectColumn rendering (CPU
fallback plans and whole-struct materializations)."""

from __future__ import annotations

from ..columnar import dtypes as dt
from ..columnar.batch import ColumnarBatch
from ..columnar.column import Column, ObjectColumn
from .expressions import Expression, materialize


class GetField(Expression):
    """struct.field (GetStructField analog)."""

    fusable = False          # only evaluated on host object columns

    def __init__(self, child: Expression, field: str):
        super().__init__(child)
        self.field = field

    @property
    def dtype(self) -> dt.DType:
        child_t = self.children[0].dtype
        if not dt.is_struct(child_t):
            raise TypeError(f"getField on non-struct {child_t}")
        for n, t in child_t.fields:
            if n == self.field:
                return t
        raise TypeError(f"no field {self.field!r} in {child_t}")

    @property
    def nullable(self) -> bool:
        return True

    def eval(self, batch: ColumnarBatch):
        col = materialize(self.children[0].eval(batch), batch)
        if not isinstance(col, ObjectColumn):
            raise RuntimeError(
                "GetField reached a device struct column — the planner "
                "should have shredded it (overrides._shred_struct_columns)")
        vals = [None if v is None else v.get(self.field)
                for v in col.values]
        return Column.from_pylist(vals, self.dtype,
                                  capacity=col.capacity)

    def __repr__(self):
        return f"{self.children[0]!r}.{self.field}"
