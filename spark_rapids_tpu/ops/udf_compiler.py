"""udf-compiler: python-bytecode scalar UDFs -> native expression trees.

Reference: the ``udf-compiler`` module translates Scala UDF *bytecode* into
Catalyst expressions via javassist reflection + CFG symbolic execution
(``udf-compiler/.../LambdaReflection.scala``, ``CFG.scala:329``,
``Instruction.scala:830``, ``CatalystExpressionBuilder.scala:45-126``),
falling back to the original UDF when translation fails.

TPU-standalone analog: ``dis`` disassembles the python function; a symbolic
stack machine maps the instruction stream onto this framework's expression
algebra. Scope: scalar lambdas/functions with arithmetic, comparisons,
boolean logic, ``abs``/``min``/``max``, constants, closure cells, and
BRANCHING control flow — if/else, ternaries, early returns, and/or
short-circuits translate by exploring both arms of every conditional jump
with an accumulated path condition and reconverging the per-path returns
into a CASE WHEN chain (the reference's CFG reconvergence,
``CFG.scala:329``). Loops (backward jumps) and anything else unsupported
fall back to the pandas-UDF host path — identical contract to the
reference's fallback (Plugin.scala:28-94).
"""

from __future__ import annotations

import dis
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..columnar import dtypes as dt
from . import arithmetic as ar
from . import conditionals as co
from . import math_ops as mo
from . import predicates as pr
from .expressions import Expression, Literal


class UdfTranslationError(Exception):
    pass


_BINOPS = {
    "+": ar.Add, "-": ar.Subtract, "*": ar.Multiply, "/": ar.Divide,
    "%": ar.Remainder, "**": mo.Pow, "//": ar.IntegralDivide,
}
_CMPOPS = {
    "==": pr.EqualTo, "!=": pr.NotEqual, "<": pr.LessThan,
    "<=": pr.LessThanOrEqual, ">": pr.GreaterThan,
    ">=": pr.GreaterThanOrEqual,
}
_CALLS = {
    "abs": lambda args: ar.Abs(*args),
    "min": lambda args: co.Least(*args),
    "max": lambda args: co.Greatest(*args),
}

# Python 3.10 emits one opcode per binary operator; 3.11+ collapsed them
# into BINARY_OP with the symbol in argrepr. Both map onto _BINOPS.
_BINOP_310 = {
    "BINARY_ADD": "+", "BINARY_SUBTRACT": "-", "BINARY_MULTIPLY": "*",
    "BINARY_TRUE_DIVIDE": "/", "BINARY_MODULO": "%", "BINARY_POWER": "**",
    "BINARY_FLOOR_DIVIDE": "//",
    "INPLACE_ADD": "+", "INPLACE_SUBTRACT": "-", "INPLACE_MULTIPLY": "*",
    "INPLACE_TRUE_DIVIDE": "/", "INPLACE_MODULO": "%",
    "INPLACE_POWER": "**", "INPLACE_FLOOR_DIVIDE": "//",
}

_MAX_PATHS = 64          # branch-path explosion guard


def try_compile_udf(fn: Callable, arg_exprs: List[Expression]
                    ) -> Optional[Expression]:
    """Expression tree for ``fn(*arg_exprs)`` or None when the bytecode uses
    unsupported instructions (the caller falls back to the pandas UDF)."""
    try:
        return _compile(fn, arg_exprs)
    except UdfTranslationError:
        return None


def _compile(fn: Callable, arg_exprs: List[Expression]) -> Expression:
    try:
        code = fn.__code__
    except AttributeError:
        raise UdfTranslationError("not a python function")
    if code.co_argcount != len(arg_exprs):
        raise UdfTranslationError("arity mismatch")
    local_names = code.co_varnames
    env: Dict[str, Any] = {local_names[i]: e
                           for i, e in enumerate(arg_exprs)}
    closure = {}
    if fn.__closure__:
        for name, cell in zip(code.co_freevars, fn.__closure__):
            closure[name] = cell.cell_contents
    tr = _Translator(fn, env, closure)
    paths = tr.run()
    if not paths:
        raise UdfTranslationError("no return path")
    if len(paths) == 1:
        return _as_expr(paths[0][1])
    # reconvergence: exclusive path conditions in exploration order -> one
    # CASE WHEN chain; the final path is the residual ELSE
    branches = [(cond, _as_expr(val)) for cond, val in paths[:-1]]
    return co.CaseWhen(branches, _as_expr(paths[-1][1]))


def _as_expr(v) -> Expression:
    if isinstance(v, Expression):
        return v
    if isinstance(v, (int, float, bool, str)) or v is None:
        return Literal(v)
    raise UdfTranslationError(f"unliftable constant {v!r}")


class _Translator:
    """Symbolic executor over the instruction stream: conditional jumps
    fork the machine state down BOTH arms with accumulated path
    conditions; returns collect (condition, value) pairs in path order
    (the reference's State + Instruction semantics, State.scala:140)."""

    def __init__(self, fn: Callable, env: Dict[str, Any],
                 closure: Dict[str, Any]):
        self.instructions = list(dis.get_instructions(fn))
        self.by_offset = {ins.offset: i
                          for i, ins in enumerate(self.instructions)}
        self.globals_ = fn.__globals__
        self.closure = closure
        self.base_env = env
        self.paths: List[Tuple[Optional[Expression], Any]] = []

    def run(self):
        self._walk(0, [], dict(self.base_env), None, 0)
        return self.paths

    # -- path management -----------------------------------------------------
    def _emit(self, cond: Optional[Expression], value) -> None:
        if len(self.paths) >= _MAX_PATHS:
            raise UdfTranslationError("too many branch paths")
        self.paths.append((cond, value))

    def _fork(self, idx: int, stack, env, cond, base_cond, depth):
        if depth > 64:
            raise UdfTranslationError("branch depth limit")
        full = cond if base_cond is None else pr.And(base_cond, cond)
        self._walk(idx, list(stack), dict(env), full, depth + 1)

    def _jump_index(self, ins) -> int:
        target = ins.argval      # byte offset of the jump target
        if target not in self.by_offset:
            raise UdfTranslationError(f"jump target {target} not found")
        return self.by_offset[target]

    # -- the machine ---------------------------------------------------------
    def _walk(self, i: int, stack: List[Any], env: Dict[str, Any],
              cond: Optional[Expression], depth: int) -> None:
        while i < len(self.instructions):
            ins = self.instructions[i]
            op = ins.opname
            if op in ("RESUME", "PRECALL", "CACHE", "NOP",
                      "COPY_FREE_VARS", "MAKE_CELL", "PUSH_NULL",
                      "TO_BOOL", "NOT_TAKEN"):
                i += 1
                continue
            if op == "LOAD_FAST":
                if ins.argval not in env:
                    raise UdfTranslationError(
                        f"unbound local {ins.argval}")
                stack.append(env[ins.argval])
            elif op == "STORE_FAST":
                env[ins.argval] = stack.pop()
            elif op == "LOAD_CONST":
                stack.append(ins.argval)
            elif op == "LOAD_DEREF":
                if ins.argval not in self.closure:
                    raise UdfTranslationError(
                        f"unknown cell {ins.argval}")
                stack.append(self.closure[ins.argval])
            elif op == "LOAD_GLOBAL":
                name = ins.argval
                if name in _CALLS:
                    stack.append(("call", name))
                elif name in self.globals_ and isinstance(
                        self.globals_[name], (int, float, bool, str)):
                    stack.append(self.globals_[name])
                else:
                    raise UdfTranslationError(
                        f"unsupported global {name}")
            elif op == "BINARY_OP" or op in _BINOP_310:
                sym = _BINOP_310.get(op) or ins.argrepr.rstrip("=")
                if sym not in _BINOPS:
                    raise UdfTranslationError(
                        f"binary op {ins.argrepr}")
                r, l = stack.pop(), stack.pop()
                stack.append(_BINOPS[sym](_as_expr(l), _as_expr(r)))
            elif op == "COMPARE_OP":
                sym = ins.argrepr.strip()
                sym = sym.replace("bool(", "").replace(")", "")
                if sym not in _CMPOPS:
                    raise UdfTranslationError(
                        f"compare op {ins.argrepr}")
                r, l = stack.pop(), stack.pop()
                stack.append(_CMPOPS[sym](_as_expr(l), _as_expr(r)))
            elif op == "UNARY_NEGATIVE":
                stack.append(ar.UnaryMinus(_as_expr(stack.pop())))
            elif op == "UNARY_NOT":
                stack.append(pr.Not(_as_expr(stack.pop())))
            elif op in ("CALL", "CALL_FUNCTION"):
                # CALL (3.11+) / CALL_FUNCTION (3.10): callable below args
                argc = ins.arg
                args = [_as_expr(stack.pop())
                        for _ in range(argc)][::-1]
                target = stack.pop()
                if not (isinstance(target, tuple)
                        and target[0] == "call"):
                    raise UdfTranslationError("indirect call")
                stack.append(_CALLS[target[1]](args))

            # -- control flow -----------------------------------------------
            elif op in ("POP_JUMP_IF_FALSE", "POP_JUMP_FORWARD_IF_FALSE"):
                test = _as_expr(stack.pop())
                self._fork(i + 1, stack, env, test, cond, depth)
                self._fork(self._jump_index(ins), stack, env,
                           pr.Not(test), cond, depth)
                return
            elif op in ("POP_JUMP_IF_TRUE", "POP_JUMP_FORWARD_IF_TRUE"):
                test = _as_expr(stack.pop())
                self._fork(i + 1, stack, env, pr.Not(test), cond, depth)
                self._fork(self._jump_index(ins), stack, env, test,
                           cond, depth)
                return
            elif op in ("POP_JUMP_IF_NONE", "POP_JUMP_FORWARD_IF_NONE"):
                test = _as_expr(stack.pop())
                self._fork(i + 1, stack, env, pr.IsNotNull(test), cond,
                           depth)
                self._fork(self._jump_index(ins), stack, env,
                           pr.IsNull(test), cond, depth)
                return
            elif op in ("POP_JUMP_IF_NOT_NONE",
                        "POP_JUMP_FORWARD_IF_NOT_NONE"):
                test = _as_expr(stack.pop())
                self._fork(i + 1, stack, env, pr.IsNull(test), cond,
                           depth)
                self._fork(self._jump_index(ins), stack, env,
                           pr.IsNotNull(test), cond, depth)
                return
            elif op in ("JUMP_IF_TRUE_OR_POP", "JUMP_IF_FALSE_OR_POP"):
                want_true = op == "JUMP_IF_TRUE_OR_POP"
                test = _as_expr(stack[-1])
                # taken arm keeps the value; fallthrough pops it
                taken_cond = test if want_true else pr.Not(test)
                self._fork(self._jump_index(ins), stack, env,
                           taken_cond, cond, depth)
                stack = list(stack)
                stack.pop()
                self._fork(i + 1, stack, env, pr.Not(taken_cond), cond,
                           depth)
                return
            elif op == "JUMP_FORWARD":
                i = self._jump_index(ins)
                continue
            elif op == "JUMP_BACKWARD":
                raise UdfTranslationError("loop (backward jump)")
            elif op == "RETURN_VALUE":
                if len(stack) != 1:
                    raise UdfTranslationError(
                        "stack imbalance at return")
                self._emit(cond, stack.pop())
                return
            elif op == "RETURN_CONST":
                self._emit(cond, ins.argval)
                return
            else:
                raise UdfTranslationError(
                    f"unsupported instruction {op}")
            i += 1
        raise UdfTranslationError("fell off the end of the bytecode")
