"""udf-compiler: python-bytecode scalar UDFs -> native expression trees.

Reference: the ``udf-compiler`` module translates Scala UDF *bytecode* into
Catalyst expressions via javassist reflection + CFG symbolic execution
(``udf-compiler/.../LambdaReflection.scala``, ``CFG.scala:329``,
``Instruction.scala:830``, ``CatalystExpressionBuilder.scala:45-126``),
falling back to the original UDF when translation fails.

TPU-standalone analog: ``dis`` disassembles the python function; a symbolic
stack machine maps the instruction stream onto this framework's expression
algebra. Scope: straight-line scalar lambdas — arithmetic, comparisons,
boolean logic, ``abs``/``min``/``max``, constants, closure cells. Branching
control flow (the reference handles it via CFG reconvergence) falls back to
the pandas-UDF host path — identical contract to the reference's fallback
(Plugin.scala:28-94).
"""

from __future__ import annotations

import dis
from typing import Any, Callable, List, Optional

from ..columnar import dtypes as dt
from . import arithmetic as ar
from . import conditionals as co
from . import math_ops as mo
from . import predicates as pr
from .expressions import Expression, Literal


class UdfTranslationError(Exception):
    pass


_BINOPS = {
    "+": ar.Add, "-": ar.Subtract, "*": ar.Multiply, "/": ar.Divide,
    "%": ar.Remainder, "**": mo.Pow, "//": ar.IntegralDivide,
}
_CMPOPS = {
    "==": pr.EqualTo, "!=": pr.NotEqual, "<": pr.LessThan,
    "<=": pr.LessThanOrEqual, ">": pr.GreaterThan,
    ">=": pr.GreaterThanOrEqual,
}
_CALLS = {
    "abs": lambda args: ar.Abs(*args),
    "min": lambda args: co.Least(*args),
    "max": lambda args: co.Greatest(*args),
}


def try_compile_udf(fn: Callable, arg_exprs: List[Expression]
                    ) -> Optional[Expression]:
    """Expression tree for ``fn(*arg_exprs)`` or None when the bytecode uses
    unsupported instructions (the caller falls back to the pandas UDF)."""
    try:
        return _compile(fn, arg_exprs)
    except UdfTranslationError:
        return None


def _compile(fn: Callable, arg_exprs: List[Expression]) -> Expression:
    try:
        code = fn.__code__
    except AttributeError:
        raise UdfTranslationError("not a python function")
    if code.co_argcount != len(arg_exprs):
        raise UdfTranslationError("arity mismatch")
    local_names = code.co_varnames
    env = {local_names[i]: e for i, e in enumerate(arg_exprs)}
    closure = {}
    if fn.__closure__:
        for name, cell in zip(code.co_freevars, fn.__closure__):
            closure[name] = cell.cell_contents
    globals_ = fn.__globals__

    stack: List[Any] = []

    def as_expr(v) -> Expression:
        if isinstance(v, Expression):
            return v
        if isinstance(v, (int, float, bool, str)) or v is None:
            return Literal(v)
        raise UdfTranslationError(f"unliftable constant {v!r}")

    for ins in dis.get_instructions(fn):
        op = ins.opname
        if op in ("RESUME", "PRECALL", "CACHE", "NOP", "COPY_FREE_VARS",
                  "MAKE_CELL", "PUSH_NULL"):
            continue
        elif op == "LOAD_FAST":
            if ins.argval not in env:
                raise UdfTranslationError(f"unbound local {ins.argval}")
            stack.append(env[ins.argval])
        elif op == "LOAD_CONST":
            stack.append(ins.argval)
        elif op == "LOAD_DEREF":
            if ins.argval not in closure:
                raise UdfTranslationError(f"unknown cell {ins.argval}")
            stack.append(closure[ins.argval])
        elif op == "LOAD_GLOBAL":
            name = ins.argval
            if name in _CALLS:
                stack.append(("call", name))
            elif name in globals_ and isinstance(
                    globals_[name], (int, float, bool, str)):
                stack.append(globals_[name])
            else:
                raise UdfTranslationError(f"unsupported global {name}")
        elif op == "BINARY_OP":
            sym = ins.argrepr.rstrip("=")
            if sym not in _BINOPS:
                raise UdfTranslationError(f"binary op {ins.argrepr}")
            r, l = stack.pop(), stack.pop()
            stack.append(_BINOPS[sym](as_expr(l), as_expr(r)))
        elif op == "COMPARE_OP":
            sym = ins.argrepr.strip()
            # 3.12 spells it "bool(<)" in argrepr sometimes; normalize
            sym = sym.replace("bool(", "").replace(")", "")
            if sym not in _CMPOPS:
                raise UdfTranslationError(f"compare op {ins.argrepr}")
            r, l = stack.pop(), stack.pop()
            stack.append(_CMPOPS[sym](as_expr(l), as_expr(r)))
        elif op == "UNARY_NEGATIVE":
            stack.append(ar.UnaryMinus(as_expr(stack.pop())))
        elif op == "UNARY_NOT":
            stack.append(pr.Not(as_expr(stack.pop())))
        elif op == "CALL":
            argc = ins.arg
            args = [as_expr(stack.pop()) for _ in range(argc)][::-1]
            target = stack.pop()
            if not (isinstance(target, tuple) and target[0] == "call"):
                raise UdfTranslationError("indirect call")
            stack.append(_CALLS[target[1]](args))
        elif op == "RETURN_VALUE":
            if len(stack) != 1:
                raise UdfTranslationError("stack imbalance at return")
            return as_expr(stack.pop())
        elif op == "RETURN_CONST":
            return as_expr(ins.argval)
        else:
            # branches (if/else), loops, attribute access, etc. -> fallback
            raise UdfTranslationError(f"unsupported instruction {op}")
    raise UdfTranslationError("no return")
