"""Window function expressions + segment kernels.

Reference: ``GpuWindowExpression.scala:169-823`` (window expression lowering
to cuDF rolling windows; row-based frames, range frames only on timestamp
days) and ``GpuWindowExec.scala`` (partition via groupby, RequireSingleBatch).

TPU lowering (DESIGN.md §3): sort by (partition keys, order keys); segment
boundaries give per-partition structure; then
  row_number      = index - segment_start_index
  rank/dense_rank = from order-key change flags
  lead/lag        = shifted gather clamped to the segment
  running aggs    = prefix-scan minus the segment-start prefix
  whole-partition aggs = segment reduction broadcast back to rows
All are O(n) scans that XLA fuses — no per-partition looping.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..columnar import dtypes as dt
from ..columnar.column import Column, Scalar
from . import kernels as K
from .expressions import Expression

UNBOUNDED = None


@dataclass
class WindowFrame:
    """Frame [lower, upper] relative to the current row; None = unbounded.

    ``is_range=False``: ROW frame — offsets are row positions
    (GpuWindowExpression row-based frames, GpuWindowExpression.scala:734).
    ``is_range=True``: RANGE frame — offsets are in ORDER-KEY value units
    over a single ascending 32-bit-or-narrower numeric/date key (the same
    scope the reference supports: range frames on timestamp-days)."""
    lower: Optional[int] = UNBOUNDED    # e.g. None = UNBOUNDED PRECEDING
    upper: Optional[int] = 0            # 0 = CURRENT ROW
    is_range: bool = False

    @property
    def is_unbounded_to_current(self) -> bool:
        return self.lower is None and self.upper == 0 and not self.is_range

    @property
    def is_whole_partition(self) -> bool:
        return self.lower is None and self.upper is None


class WindowSpec:
    def __init__(self, partition_by: List[Expression],
                 order_by: List["lpSortOrder"] = None,
                 frame: Optional[WindowFrame] = None):
        self.partition_by = partition_by
        self.order_by = order_by or []
        self.frame = frame

    def resolve(self, schema: dt.Schema) -> "WindowSpec":
        def r(e):
            return e.transform(lambda n: n.resolve(schema)
                               if hasattr(n, "resolve") else None)
        from ..plan.logical import SortOrder
        self.partition_by = [r(e) for e in self.partition_by]
        self.order_by = [SortOrder(r(o.child), o.ascending, o.nulls_first)
                         for o in self.order_by]
        return self


class WindowFunction(Expression):
    """Marker base for ranking/offset window functions."""
    needs_order = True


class RowNumber(WindowFunction):
    @property
    def dtype(self):
        return dt.INT32

    @property
    def nullable(self):
        return False


class Rank(WindowFunction):
    @property
    def dtype(self):
        return dt.INT32

    @property
    def nullable(self):
        return False


class DenseRank(WindowFunction):
    @property
    def dtype(self):
        return dt.INT32

    @property
    def nullable(self):
        return False


class Lead(WindowFunction):
    def __init__(self, child: Expression, offset: int = 1, default=None):
        super().__init__(child)
        self.offset = offset
        self.default = default

    @property
    def dtype(self):
        return self.children[0].dtype


class Lag(Lead):
    pass


class WindowExpression(Expression):
    """A window function or aggregate evaluated over a WindowSpec
    (GpuWindowExpression)."""

    def __init__(self, function: Expression, spec: WindowSpec):
        super().__init__(function)
        self.spec = spec

    @property
    def function(self) -> Expression:
        return self.children[0]

    @property
    def dtype(self):
        from ..plan.logical import AggregateExpression
        f = self.function
        if isinstance(f, AggregateExpression):
            return f.dtype
        return f.dtype

    def resolve_refs(self, schema: dt.Schema) -> "WindowExpression":
        def r(e):
            return e.transform(lambda n: n.resolve(schema)
                               if hasattr(n, "resolve") else None)
        new_fn = r(self.function)
        self.children = [new_fn]
        self.spec.resolve(schema)
        return self

    def eval(self, batch):
        raise RuntimeError("WindowExpression is planned by TpuWindowExec")


# ---------------------------------------------------------------------------
# Kernels (operate on partition-sorted data)
# ---------------------------------------------------------------------------

def row_number_k(seg_ids: jnp.ndarray, starts: jnp.ndarray,
                 capacity: int) -> jnp.ndarray:
    idx = jnp.arange(capacity, dtype=jnp.int32)
    start_idx = jnp.where(starts, idx, 0)
    seg_start = jax.ops.segment_max(start_idx, seg_ids, num_segments=capacity)
    return idx - seg_start[seg_ids] + 1


def rank_k(seg_ids: jnp.ndarray, starts: jnp.ndarray,
           order_changed: jnp.ndarray, capacity: int,
           dense: bool) -> jnp.ndarray:
    """order_changed[i]: order keys differ from row i-1 (within segment)."""
    rn = row_number_k(seg_ids, starts, capacity)
    new_val = starts | order_changed
    if dense:
        # dense rank: count of distinct values so far in segment
        inc = new_val.astype(jnp.int32)
        cum = jnp.cumsum(inc)
        seg_base = jax.ops.segment_max(
            jnp.where(starts, cum, 0), seg_ids, num_segments=capacity)
        return (cum - seg_base[seg_ids] + 1).astype(jnp.int32)
    # rank: row_number at the start of each tie run
    idx = jnp.arange(capacity, dtype=jnp.int32)
    run_start = jnp.where(new_val, rn, 0)
    # propagate forward within ties: cummax over (new_val index)
    last_new = jax.lax.cummax(jnp.where(new_val, idx, -1))
    return rn[jnp.clip(last_new, 0, capacity - 1)]


def shift_in_segment(col: Column, seg_ids: jnp.ndarray, offset: int,
                     default, capacity: int) -> Column:
    """lead(+offset)/lag(-offset) within segments; out-of-segment -> default."""
    idx = jnp.arange(capacity, dtype=jnp.int32)
    src = idx + offset
    srcc = jnp.clip(src, 0, capacity - 1)
    same_seg = (src >= 0) & (src < capacity) & (seg_ids[srcc] == seg_ids)
    out = K.gather_column(col, srcc, out_valid=same_seg)
    if default is not None:
        dflt_valid = ~same_seg
        if col.dtype == dt.STRING:
            # string defaults: materialize via from_scalar and select
            dcol = Column.from_scalar(Scalar(default, col.dtype), capacity,
                                      capacity)
            data = jnp.where(same_seg[:, None], out.data, dcol.data)
            lengths = jnp.where(same_seg, out.lengths, dcol.lengths)
            return Column(col.dtype, data, out.validity | dflt_valid, lengths)
        dval = jnp.asarray(default, col.data.dtype)
        data = jnp.where(same_seg, out.data, dval)
        return Column(col.dtype, data, out.validity | dflt_valid)
    return out


def running_agg(op: str, col: Column, seg_ids: jnp.ndarray,
                starts: jnp.ndarray, live: jnp.ndarray,
                capacity: int) -> Column:
    """UNBOUNDED PRECEDING..CURRENT ROW aggregates via prefix scans."""
    contrib = live & col.validity
    if op in ("count", "count_star"):
        inc = (contrib if op == "count" else live).astype(jnp.int64)
        cum = jnp.cumsum(inc)
        base = _seg_base(cum - inc, starts, seg_ids, capacity)
        data = cum - base
        return Column(dt.INT64, data, live)
    if op == "sum":
        from .aggregates import _sum_dtype
        out_t = _sum_dtype(col.dtype)
        d = jnp.where(contrib, col.data.astype(out_t.numpy_dtype),
                      jnp.zeros((), out_t.numpy_dtype))
        cum = jnp.cumsum(d)
        base = _seg_base(cum - d, starts, seg_ids, capacity)
        data = cum - base
        seen = jnp.cumsum(contrib.astype(jnp.int32))
        seen_base = _seg_base(seen - contrib.astype(jnp.int32), starts,
                              seg_ids, capacity)
        has = (seen - seen_base) > 0
        return Column(out_t, jnp.where(has, data, 0), has & live)
    if op in ("min", "max"):
        if col.dtype.is_floating:
            fill = jnp.inf if op == "min" else -jnp.inf
        else:
            info = jnp.iinfo(col.data.dtype)
            fill = info.max if op == "min" else info.min
        d = jnp.where(contrib, col.data, jnp.asarray(fill, col.data.dtype))
        # segment-aware scan: reset at starts by scanning a keyed trick —
        # compute global scan of (segment_id, value) pairs is complex; use
        # the associative_scan with a reset flag instead
        data = _segmented_scan(d, starts, op)
        seen = jnp.cumsum(contrib.astype(jnp.int32))
        seen_base = _seg_base(seen - contrib.astype(jnp.int32), starts,
                              seg_ids, capacity)
        has = (seen - seen_base) > 0
        out = jnp.where(has, data, jnp.zeros((), col.data.dtype))
        return Column(col.dtype, out, has & live)
    if op == "avg":
        s = running_agg("sum", col, seg_ids, starts, live, capacity)
        c = running_agg("count", col, seg_ids, starts, live, capacity)
        data = jnp.where(s.validity,
                         s.data.astype(jnp.float64) /
                         jnp.maximum(c.data.astype(jnp.float64), 1.0), 0.0)
        return Column(dt.FLOAT64, data, s.validity)
    raise ValueError(f"running agg {op} unsupported")


def _seg_base(pre: jnp.ndarray, starts: jnp.ndarray, seg_ids: jnp.ndarray,
              capacity: int) -> jnp.ndarray:
    """Per-row value of `pre` at the row's segment start."""
    # exactly one start row per segment, so a segment_sum of the masked value
    # recovers it exactly (sign-safe, unlike segment_max)
    base_at_start = jnp.where(starts, pre, jnp.zeros((), pre.dtype))
    seg_val = jax.ops.segment_sum(base_at_start, seg_ids, num_segments=capacity)
    return seg_val[seg_ids]


def _segmented_scan(data: jnp.ndarray, starts: jnp.ndarray, op: str) -> jnp.ndarray:
    """Segment-resetting min/max scan via associative_scan over (flag, value)."""
    fn = jnp.minimum if op == "min" else jnp.maximum

    def combine(a, b):
        a_flag, a_val = a
        b_flag, b_val = b
        val = jnp.where(b_flag, b_val, fn(a_val, b_val))
        return a_flag | b_flag, val

    flags = starts
    _, out = jax.lax.associative_scan(combine, (flags, data))
    return out


def whole_partition_agg(op: str, col: Optional[Column], seg_ids: jnp.ndarray,
                        live: jnp.ndarray, capacity: int,
                        ignore_nulls: bool = True) -> Column:
    """UNBOUNDED..UNBOUNDED: segment reduce then broadcast back to rows."""
    from .aggregates import AggSpec, segment_aggregate
    spec = AggSpec(op, col, ignore_nulls)
    red = segment_aggregate(spec, seg_ids, live, capacity)
    out = K.gather_column(red, seg_ids, out_valid=live)
    return out


# ---------------------------------------------------------------------------
# Bounded frames: N PRECEDING .. M FOLLOWING (row and range)
# Reference: GpuWindowExpression.scala:734-800 lowers these to cudf
# rolling-window aggregations; TPU-first they become prefix-sum gathers
# (sum/count/avg) and doubling-table range-minimum queries (min/max) over
# per-row [lo, hi] index bounds — no rolling kernel needed, and the same
# aggregation code serves ROW and RANGE frames once bounds are computed.
# ---------------------------------------------------------------------------

def segment_positions(seg_ids: jnp.ndarray, starts: jnp.ndarray,
                      live: jnp.ndarray, capacity: int):
    """(seg_start_pos, seg_end_pos) row indices per row."""
    pos = jnp.arange(capacity, dtype=jnp.int64)
    seg_start = _seg_base(pos, starts, seg_ids, capacity).astype(jnp.int64)
    seg_len = jax.ops.segment_sum(live.astype(jnp.int64), seg_ids,
                                  num_segments=capacity)[seg_ids]
    return seg_start, seg_start + jnp.maximum(seg_len - 1, 0)


def frame_bounds_rows(seg_ids, starts, live, capacity: int,
                      lower: Optional[int], upper: Optional[int]):
    """Per-row [lo, hi] row-index bounds of a ROW frame, clamped to the
    row's segment. hi < lo marks an empty window."""
    pos = jnp.arange(capacity, dtype=jnp.int64)
    seg_start, seg_end = segment_positions(seg_ids, starts, live, capacity)
    lo = seg_start if lower is None else jnp.maximum(pos + lower, seg_start)
    hi = seg_end if upper is None else jnp.minimum(pos + upper, seg_end)
    return lo, hi


def frame_bounds_range(order_col: Column, seg_ids, starts, live,
                       capacity: int, lower: Optional[int],
                       upper: Optional[int]):
    """Per-row [lo, hi] bounds of a RANGE frame over one ASCENDING order key
    of <=32-bit storage: rows whose key lies in [key-lower_off, key+upper_off].

    Key + segment pack into one uint64 composite
    ``(seg_id << 33) | (valid_bit << 32) | encoded_key32`` which is globally
    sorted (data is segment-then-key sorted), so a single searchsorted per
    bound finds the window. NULL order keys form their own frame group
    (Spark semantics): their window is exactly the segment's null run.
    """
    from . import kernels as K

    k = order_col.data.astype(jnp.int64)
    # order-preserving 32-bit encoding (sign-flip), computed in int64 so the
    # value offsets cannot wrap
    def enc(v):
        v = jnp.clip(v, -(1 << 31), (1 << 31) - 1)
        return (v + (1 << 31)).astype(jnp.uint64)

    valid_bit = jnp.where(order_col.validity, jnp.uint64(1), jnp.uint64(0))
    seg64 = seg_ids.astype(jnp.uint64)
    comp = (seg64 << jnp.uint64(33)) | (valid_bit << jnp.uint64(32)) | enc(k)
    # padding rows must sort last
    comp = jnp.where(live, comp, jnp.uint64(0xFFFFFFFFFFFFFFFF))

    # signed offsets, same convention as ROW frames: window keys lie in
    # [k + lower, k + upper] (lower is typically negative: "X PRECEDING")
    lo_key = (seg64 << jnp.uint64(33)) | (valid_bit << jnp.uint64(32)) | (
        jnp.uint64(0) if lower is None else enc(k + int(lower)))
    hi_key = (seg64 << jnp.uint64(33)) | (valid_bit << jnp.uint64(32)) | (
        jnp.uint64(0xFFFFFFFF) if upper is None else enc(k + int(upper)))
    lo = jnp.searchsorted(comp, lo_key, side="left").astype(jnp.int64)
    hi = jnp.searchsorted(comp, hi_key, side="right").astype(jnp.int64) - 1
    return lo, hi


def _prefix_pad(vals: jnp.ndarray) -> jnp.ndarray:
    """[0, cumsum(vals)] so windowed sums are P[hi+1] - P[lo]."""
    return jnp.concatenate([jnp.zeros(1, vals.dtype), jnp.cumsum(vals)])


def bounded_frame_agg(op: str, col: Optional[Column], lo: jnp.ndarray,
                      hi: jnp.ndarray, live: jnp.ndarray,
                      capacity: int) -> Column:
    """Aggregate over per-row [lo, hi] row windows. Empty windows (hi < lo)
    produce NULL (count: 0, Spark semantics)."""
    empty = hi < lo
    loc = jnp.clip(lo, 0, capacity - 1)
    hic = jnp.clip(hi, 0, capacity - 1)

    if op in ("count", "count_star"):
        contrib = live if op == "count_star" else (live & col.validity)
        P = _prefix_pad(contrib.astype(jnp.int64))
        cnt = jnp.where(empty, 0, P[hic + 1] - P[loc])
        return Column(dt.INT64, cnt, live)

    contrib = live & col.validity
    if op in ("sum", "avg"):
        from .aggregates import _sum_dtype
        out_t = _sum_dtype(col.dtype)
        d = jnp.where(contrib, col.data.astype(out_t.numpy_dtype),
                      jnp.zeros((), out_t.numpy_dtype))
        P = _prefix_pad(d)
        s = P[hic + 1] - P[loc]
        C = _prefix_pad(contrib.astype(jnp.int64))
        cnt = C[hic + 1] - C[loc]
        has = (cnt > 0) & ~empty & live
        if op == "sum":
            return Column(out_t, jnp.where(has, s, 0), has)
        data = jnp.where(has, s.astype(jnp.float64) /
                         jnp.maximum(cnt.astype(jnp.float64), 1.0), 0.0)
        return Column(dt.FLOAT64, data, has)

    if op in ("min", "max"):
        if col.dtype.is_floating:
            fill = jnp.inf if op == "min" else -jnp.inf
        else:
            info = jnp.iinfo(col.data.dtype)
            fill = info.max if op == "min" else info.min
        d = jnp.where(contrib, col.data, jnp.asarray(fill, col.data.dtype))
        fn = jnp.minimum if op == "min" else jnp.maximum
        # doubling (sparse) table: T[k][i] = agg over rows [i, i + 2^k)
        levels = [d]
        span = 1
        while span < capacity:
            prev = levels[-1]
            shifted = jnp.concatenate(
                [prev[span:], jnp.full(span, fill, prev.dtype)])
            levels.append(fn(prev, shifted))
            span *= 2
        T = jnp.stack(levels)                       # [K, cap]
        length = jnp.maximum(hi - lo + 1, 1)
        kidx = jnp.floor(jnp.log2(length.astype(jnp.float64))
                         ).astype(jnp.int64)
        left = T[kidx, loc]
        right_pos = jnp.clip(hic - (1 << kidx.astype(jnp.int64)) + 1,
                             0, capacity - 1)
        right = T[kidx, right_pos]
        out = fn(left, right)
        C = _prefix_pad(contrib.astype(jnp.int64))
        has = ((C[hic + 1] - C[loc]) > 0) & ~empty & live
        return Column(col.dtype,
                      jnp.where(has, out, jnp.zeros((), out.dtype)), has)

    raise ValueError(f"bounded frame agg {op} unsupported")
