"""SPMD distributed execution over a jax device mesh.

Reference mapping (DESIGN.md §5, SURVEY.md §5 "distributed communication
backend"): the reference's parallelism is Spark tasks + exchange operators
over UCX RDMA (shuffle-plugin). TPU-native, the exchange lowers to dense
padded ``all_to_all`` over ICI inside a single jitted SPMD program:

  map side:   per-worker partial op (filter/project/partial agg)
  exchange:   bucket rows by hash(key) % n_workers into fixed-capacity slots,
              one ``lax.all_to_all`` moves every slot to its owner over ICI
  reduce:     per-worker final op (merge agg / join / sort)

No host round-trip between stages — the entire distributed pipeline is ONE
XLA computation, the fusion win the reference cannot express (its every
exchange bounces through the shuffle manager). The host-orchestrated shuffle
(shuffle/exchange.py) remains the fallback for multi-host DCN and elastic
retry, mirroring the reference's UCX-vs-fallback split.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..columnar import dtypes as dt
from ..columnar.batch import ColumnarBatch
from ..columnar.column import Column, bucket
from ..ops import kernels as K
from ..ops import aggregates as agg_k
from ..ops.hashing import murmur3_batch


def make_mesh(n_devices: Optional[int] = None) -> Mesh:
    devs = jax.devices()
    n = n_devices or len(devs)
    return Mesh(np.array(devs[:n]), ("workers",))


def maybe_mesh(conf=None) -> Optional[Mesh]:
    """The active device mesh per ``spark.rapids.tpu.sql.mesh.enabled``:
    'true' forces SPMD execution over every visible device (tests force a
    virtual CPU mesh this way) and propagates any mesh-construction failure;
    'auto' enables it on multi-device accelerator platforms, degrading to
    None on any failure; 'false' disables. Unknown values are rejected.
    Planner entry point."""
    from .. import config as cfg
    conf = conf or cfg.TpuConf()
    mode = str(conf.get(cfg.MESH_ENABLED)).lower()
    if mode in ("false", "0"):
        return None
    if mode not in ("true", "1", "auto"):
        raise ValueError(
            f"invalid {cfg.MESH_ENABLED.key}: {mode!r} "
            "(expected true/false/auto)")
    if mode in ("true", "1"):
        devs = jax.devices()
        if len(devs) < 2:
            raise RuntimeError(
                f"{cfg.MESH_ENABLED.key}=true but only {len(devs)} device(s) "
                "are visible — SPMD execution needs a multi-device mesh")
        return make_mesh()
    try:
        devs = jax.devices()
        if len(devs) < 2 or devs[0].platform == "cpu":
            return None
        return make_mesh()
    except Exception:
        return None


# jitted SPMD stage cache: re-tracing per query would pay full XLA
# compilation each time; keys repeat because caps are bucketed.
# Registered with the JIT map-pressure relief valve
# (exec/compile_cache.jit_map_guard): SPMD executables pin mappings too.
_FN_CACHE: Dict[tuple, Any] = {}

from ..exec.compile_cache import register_program_cache as _rpc  # noqa: E402
_rpc(_FN_CACHE.clear)
del _rpc


def _mesh_key(mesh: Mesh) -> tuple:
    return (int(mesh.devices.size),
            tuple(d.id for d in mesh.devices.flat))


def _cached_fn(key: tuple, builder):
    fn = _FN_CACHE.get(key)
    if fn is None:
        # mesh SPMD compiles ride the same audit + persistent-cache
        # funnel as the _fused_fn programs (analysis/recompile counts
        # cold builds vs disk hits, first-call seconds metered): no
        # compile escapes the recompile audit
        from ..exec import compile_cache as _cc
        kernel = f"mesh/{key[0]}" if key and isinstance(key[0], str) \
            else "mesh"
        _kind, wrap = _cc.note_build(("mesh",) + key, kernel)
        fn = _FN_CACHE[key] = wrap(builder())
    else:
        from ..analysis import recompile as _recompile
        _recompile.note_call(
            f"mesh/{key[0]}" if key and isinstance(key[0], str)
            else "mesh")
    return fn


def _shard_map(fn, mesh: Mesh, in_specs, out_specs):
    try:
        from jax import shard_map
    except ImportError:          # older jax
        from jax.experimental.shard_map import shard_map
    try:
        return shard_map(fn, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, check_vma=False)
    except TypeError:            # older jax spelling
        return shard_map(fn, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, check_rep=False)


# ---------------------------------------------------------------------------
# In-jit exchange: bucket-by-hash + all_to_all (the ICI shuffle data plane)
# ---------------------------------------------------------------------------

def bucket_rows_for_exchange(arrays: Sequence[jnp.ndarray],
                             pids: jnp.ndarray, live: jnp.ndarray,
                             n_workers: int, cap: int
                             ) -> Tuple[List[jnp.ndarray], jnp.ndarray]:
    """Pack rows into [n_workers, cap] slots by target worker id.

    Slot t holds the rows destined for worker t, compacted to the front and
    zero-padded (the bounce-buffer window analog, WindowedBlockIterator —
    except static shapes make it one gather instead of a windowing protocol).
    Returns (stacked arrays [n, cap, ...], counts int32[n]).
    """
    outs = [[] for _ in arrays]
    counts = []
    for t in range(n_workers):
        keep = live & (pids == t)
        perm, cnt = K.compaction_indices(keep)
        slot_live = jnp.arange(cap) < cnt
        for i, a in enumerate(arrays):
            g = a[perm]
            if g.ndim == 1:
                g = jnp.where(slot_live, g, jnp.zeros((), g.dtype))
            else:
                g = jnp.where(slot_live[:, None], g, jnp.zeros((), g.dtype))
            outs[i].append(g)
        counts.append(cnt)
    stacked = [jnp.stack(o) for o in outs]
    return stacked, jnp.stack(counts).astype(jnp.int32)


def exchange(stacked: List[jnp.ndarray], counts: jnp.ndarray, axis: str
             ) -> Tuple[List[jnp.ndarray], jnp.ndarray]:
    """all_to_all over ICI: slot [t] of worker w -> slot [w] of worker t."""
    moved = [jax.lax.all_to_all(a, axis, 0, 0, tiled=False) for a in stacked]
    moved_counts = jax.lax.all_to_all(counts, axis, 0, 0, tiled=False)
    return moved, moved_counts


def flatten_received(stacked: List[jnp.ndarray], counts: jnp.ndarray,
                     out_cap: int) -> Tuple[List[jnp.ndarray], jnp.ndarray]:
    """[n, cap, ...] received slots -> single [out_cap, ...] compacted arrays.

    Received rows are compacted front-of-slot; build a gather index mapping
    output position -> (slot, offset)."""
    n, cap = stacked[0].shape[0], stacked[0].shape[1]
    starts = jnp.cumsum(counts) - counts          # exclusive prefix
    total = jnp.sum(counts)
    out_i = jnp.arange(out_cap, dtype=jnp.int32)
    live = out_i < total
    slot = jnp.searchsorted(jnp.cumsum(counts), out_i, side="right"
                            ).astype(jnp.int32)
    slot = jnp.clip(slot, 0, n - 1)
    offset = out_i - starts[slot]
    offset = jnp.clip(offset, 0, cap - 1)
    outs = []
    for a in stacked:
        flat = a[slot, offset]
        if flat.ndim == 1:
            flat = jnp.where(live, flat, jnp.zeros((), flat.dtype))
        else:
            flat = jnp.where(live[:, None], flat, jnp.zeros((), flat.dtype))
        outs.append(flat)
    return outs, total.astype(jnp.int32)


# ---------------------------------------------------------------------------
# Reduce-partition exchange: the ICI data plane of TpuShuffleExchangeExec
# ---------------------------------------------------------------------------

def partition_exchange_fn(mesh: Mesh, col_dtypes: Sequence[dt.DType],
                          cap: int, num_partitions: int):
    """Jitted device-resident shuffle exchange over ICI: every worker
    buckets its rows by owning worker (``pid % n``), one ``all_to_all``
    delivers them, and the receiver stable-sorts its rows by reduce
    partition id so each owned partition is one contiguous run.

    This is ``TpuShuffleExchangeExec``'s data plane collapsed into one
    XLA computation per stage (SURVEY.md §5/§7-step-6: the device-store +
    RDMA transport of the reference mapped onto mesh collectives): the
    partition payload never leaves the accelerator, and the host reads
    back ONE ``[n, num_partitions]`` counts array per exchange to slice
    the runs. Receive windows are ``n * cap`` so key skew cannot drop
    rows. Output per worker: every payload array sorted by partition id
    (padding last) plus the int32 per-partition counts.
    """
    n = mesh.devices.size
    out_cap = n * cap
    n_arrays = sum(3 if t.var_width else 2 for t in col_dtypes)

    def per_worker(*args):
        args = [a[0] for a in args]
        *arrays, pids, local_n = args
        live = jnp.arange(cap) < local_n
        owner = jnp.mod(pids, n)
        payload = list(arrays) + [pids]
        stacked, counts = bucket_rows_for_exchange(payload, owner, live,
                                                   n, cap)
        moved, moved_counts = exchange(stacked, counts, "workers")
        flat, recv_n = flatten_received(moved, moved_counts, out_cap)
        recv_pids = flat[-1]
        recv_live = jnp.arange(out_cap) < recv_n
        sort_key = jnp.where(recv_live, recv_pids, num_partitions)
        order = jnp.argsort(sort_key, stable=True)
        sorted_arrays = [a[order] for a in flat[:-1]]
        pcounts = jnp.bincount(
            jnp.clip(sort_key, 0, num_partitions),
            length=num_partitions + 1)[:num_partitions].astype(jnp.int32)
        return tuple(a[None] for a in sorted_arrays) + (pcounts[None],)

    in_specs = tuple([P("workers")] * (n_arrays + 2))
    # lint: naked-jit-ok mesh SPMD stage builder: every call rides _cached_fn -> compile_cache.note_build (audited + persisted)
    return jax.jit(_shard_map(per_worker, mesh, in_specs, P("workers")))


def run_partition_exchange(mesh: Mesh, batches: List[ColumnarBatch],
                           pids: List[jnp.ndarray], num_partitions: int
                           ) -> List[Tuple[List[Column], np.ndarray]]:
    """Host driver for the ICI exchange plane: one shard + its int32[cap]
    partition ids per worker in, per worker out ``(columns sorted by
    reduce partition id, host counts int32[num_partitions])`` — worker w
    holds exactly the partitions with ``p % n == w`` as contiguous runs.
    The counts readback is the exchange's ONE host sync."""
    n = mesh.devices.size
    assert len(batches) == n and len(pids) == n, "one shard per worker"
    cap = max(b.capacity for b in batches)
    col_dtypes = [c.dtype for c in batches[0].columns]
    stacked = _stack_shards(batches, cap)
    pid_stack = jnp.stack([
        p if p.shape[0] == cap else
        jnp.zeros(cap, jnp.int32).at[:p.shape[0]].set(p)
        for p in pids]).astype(jnp.int32)
    counts = jnp.asarray([b.num_rows for b in batches], dtype=jnp.int32)
    fn = _cached_fn(
        ("pexch", _mesh_key(mesh), tuple(col_dtypes), cap, num_partitions),
        lambda: partition_exchange_fn(mesh, col_dtypes, cap,
                                      num_partitions))
    outs = fn(*stacked, pid_stack, counts)
    from ..analysis.sync_audit import allowed_host_transfer
    with allowed_host_transfer("ici exchange sizing"):
        pcounts = np.asarray(outs[-1])     # ONE readback per exchange
    # query-lifecycle breadcrumb: the mesh exchange's metadata (worker
    # count, partition count, total routed rows) lands in the flight
    # ring stamped with the ambient query id (exec/query_context via the
    # flight funnel), so a multichip post-mortem ties every collective
    # exchange to the query that dispatched it
    from ..service.telemetry import flight_record
    flight_record("exchange", "ici-partition-exchange",
                  {"workers": int(n), "partitions": int(num_partitions),
                   "rows": int(pcounts.sum())})
    results: List[Tuple[List[Column], np.ndarray]] = []
    for w in range(n):
        arrays = [o[w] for o in outs[:-1]]
        results.append((_rebuild_columns(col_dtypes, arrays), pcounts[w]))
    return results


# ---------------------------------------------------------------------------
# Distributed group-by: the flagship SPMD pipeline
# ---------------------------------------------------------------------------

def _column_arrays(cols: Sequence[Column]) -> List[jnp.ndarray]:
    out = []
    for c in cols:
        out.extend(c.arrays())
    return out


def _rebuild_columns(schema_dtypes: Sequence[dt.DType],
                     arrays: List[jnp.ndarray]) -> List[Column]:
    cols = []
    i = 0
    for t in schema_dtypes:
        if t.var_width:
            cols.append(Column(t, arrays[i], arrays[i + 1], arrays[i + 2]))
            i += 3
        else:
            cols.append(Column(t, arrays[i], arrays[i + 1]))
            i += 2
    return cols


def _update_plan(agg_ops: Sequence[str], val_dtypes: Sequence[dt.DType]
                 ) -> List[List[Tuple[str, dt.DType]]]:
    """Per input agg, the update-phase partial columns carried through the
    exchange: avg decomposes into sum+count (AggregateFunctions.scala avg;
    dividing only after the merge keeps distributed avg exact)."""
    plan = []
    for op, t in zip(agg_ops, val_dtypes):
        if op == "avg":
            plan.append([("sum", dt.FLOAT64), ("count", dt.INT64)])
        elif op in ("count", "count_star"):
            plan.append([(op, dt.INT64)])
        else:
            plan.append([(op, agg_k.result_dtype(op, t))])
    return plan


def output_dtypes(agg_ops: Sequence[str], val_dtypes: Sequence[dt.DType]
                  ) -> List[dt.DType]:
    return [agg_k.result_dtype(op, t) for op, t in zip(agg_ops, val_dtypes)]


def distributed_groupby_fn(mesh: Mesh, key_dtypes: Sequence[dt.DType],
                           val_dtypes: Sequence[dt.DType],
                           agg_ops: Sequence[str], cap: int):
    """Build the jitted SPMD group-by step over `mesh`.

    Input: per-worker shards of key/value arrays + local row counts.
    Pipeline per worker: partial agg -> hash-bucket groups -> all_to_all ->
    merge agg. Output: per-worker final groups (disjoint key ownership).

    This is the GpuHashAggregate(partial) -> GpuShuffleExchange(hash) ->
    GpuHashAggregate(final) pipeline fused into ONE XLA computation
    (SURVEY.md §3.3 downstream), collectives riding ICI.

    Every received-side buffer is sized ``n * cap``: each of the n peers can
    legally send up to its full ``cap`` groups to ONE owner under key skew,
    so a smaller receive window would silently drop rows.
    """
    n = mesh.devices.size
    plan = _update_plan(agg_ops, val_dtypes)
    partial_dtypes = [t for cols in plan for (_op, t) in cols]
    # merge phase: counts and avg partials merge by SUM; everything else
    # merges with its own op (CudfAggregate update/merge pairs)
    merge_ops = []
    for cols in plan:
        for (op, _t) in cols:
            merge_ops.append("sum" if op in ("count", "count_star") else op)
    out_cap = n * cap

    def per_worker(*arrays_and_count):
        *arrays, local_n = arrays_and_count
        # drop the leading worker axis shard_map leaves (size-1)
        arrays = [a[0] for a in arrays]
        local_n = local_n[0]
        nk = sum(3 if t.var_width else 2 for t in key_dtypes)
        key_cols = _rebuild_columns(key_dtypes, arrays[:nk])
        val_cols = _rebuild_columns(val_dtypes, arrays[nk:])

        # 1. local partial aggregate (update phase)
        specs = []
        for cols_plan, c in zip(plan, val_cols):
            for (uop, ut) in cols_plan:
                cc = c
                if ut == dt.FLOAT64 and c.dtype != dt.FLOAT64 and uop == "sum":
                    cc = Column(dt.FLOAT64, c.data.astype(jnp.float64),
                                c.validity)
                specs.append(agg_k.AggSpec(uop, cc))
        out_keys, out_aggs, n_groups = agg_k.groupby_aggregate(
            key_cols, specs, local_n, cap)

        # 2. bucket groups by hash(key) % n  ->  all_to_all over ICI
        pids = jnp.mod(jnp.mod(murmur3_batch(out_keys, cap), n) + n, n)
        live = jnp.arange(cap) < n_groups
        payload = _column_arrays(out_keys) + _column_arrays(out_aggs)
        stacked, counts = bucket_rows_for_exchange(payload, pids, live, n, cap)
        moved, moved_counts = exchange(stacked, counts, "workers")
        flat, recv_n = flatten_received(moved, moved_counts, out_cap)

        # 3. merge aggregate over received partials
        recv_keys = _rebuild_columns(key_dtypes, flat[:nk])
        recv_aggs = _rebuild_columns(partial_dtypes, flat[nk:])
        mspecs = [agg_k.AggSpec(mop, c)
                  for mop, c in zip(merge_ops, recv_aggs)]
        f_keys, f_aggs, f_groups = agg_k.groupby_aggregate(
            recv_keys, mspecs, recv_n, out_cap)

        # 4. finalize: divide avg partials post-merge
        out_cols: List[Column] = []
        ai = 0
        for op, cols_plan in zip(agg_ops, plan):
            if op == "avg":
                s, c = f_aggs[ai], f_aggs[ai + 1]
                valid = s.validity & (c.data > 0)
                data = jnp.where(
                    valid,
                    s.data / jnp.maximum(c.data.astype(jnp.float64), 1.0),
                    0.0)
                out_cols.append(Column(dt.FLOAT64, data, valid))
            else:
                out_cols.append(f_aggs[ai])
            ai += len(cols_plan)
        out = (_column_arrays(f_keys) + _column_arrays(out_cols) +
               [f_groups])
        return tuple(a[None] for a in out)

    in_specs = tuple([P("workers")] * (
        sum(3 if t.var_width else 2 for t in key_dtypes) +
        sum(3 if t.var_width else 2 for t in val_dtypes) + 1))
    # lint: naked-jit-ok mesh SPMD stage builder: every call rides _cached_fn -> compile_cache.note_build (audited + persisted)
    return jax.jit(_shard_map(per_worker, mesh, in_specs, P("workers")))


# ---------------------------------------------------------------------------
# Distributed co-partition exchange (the SPMD shuffled-join data plane)
# ---------------------------------------------------------------------------

def copartition_exchange_fn(mesh: Mesh, col_dtypes: Sequence[dt.DType],
                            key_positions: Sequence[int], cap: int):
    """Jitted row-level hash exchange over ICI: every worker buckets its rows
    by ``pmod(murmur3(keys), n)`` and one ``all_to_all`` delivers them to the
    owning worker. This is GpuShuffledHashJoinExec's exchange
    (GpuShuffleExchangeExec + GpuHashPartitioning) collapsed into one XLA
    computation per side; the per-worker join then runs on co-partitioned
    shards. Receive windows are ``n * cap`` so key skew cannot drop rows.
    """
    n = mesh.devices.size
    out_cap = n * cap
    n_arrays = sum(3 if t.var_width else 2 for t in col_dtypes)

    def per_worker(*arrays_and_count):
        *arrays, local_n = arrays_and_count
        arrays = [a[0] for a in arrays]
        local_n = local_n[0]
        cols = _rebuild_columns(col_dtypes, arrays)
        key_cols = [cols[i] for i in key_positions]
        live = jnp.arange(cap) < local_n
        pids = jnp.mod(jnp.mod(murmur3_batch(key_cols, cap), n) + n, n)
        payload = _column_arrays(cols)
        stacked, counts = bucket_rows_for_exchange(payload, pids, live, n, cap)
        moved, moved_counts = exchange(stacked, counts, "workers")
        flat, recv_n = flatten_received(moved, moved_counts, out_cap)
        return tuple(a[None] for a in flat) + (recv_n[None],)

    in_specs = tuple([P("workers")] * (n_arrays + 1))
    # lint: naked-jit-ok mesh SPMD stage builder: every call rides _cached_fn -> compile_cache.note_build (audited + persisted)
    return jax.jit(_shard_map(per_worker, mesh, in_specs, P("workers")))


def _stack_shards(batches: List[ColumnarBatch], cap: int) -> List[jnp.ndarray]:
    """Stack per-worker batches (rebucketed to a common cap) on a leading
    workers axis, one stacked array per underlying column array."""
    per_worker = []
    for b in batches:
        arrays = []
        for c in b.columns:
            if c.capacity != cap:
                c = K.rebucket_column(c, b.num_rows, cap)
            arrays.extend(c.arrays())
        per_worker.append(arrays)
    return [jnp.stack([pw[i] for pw in per_worker])
            for i in range(len(per_worker[0]))]


def run_copartition_exchange(mesh: Mesh, batches: List[ColumnarBatch],
                             key_positions: Sequence[int]
                             ) -> List[ColumnarBatch]:
    """Host driver for one side of an SPMD shuffled join: returns per-worker
    co-partitioned batches (same key -> same worker index)."""
    n = mesh.devices.size
    assert len(batches) == n, "one shard per worker"
    cap = max(b.capacity for b in batches)
    col_dtypes = [c.dtype for c in batches[0].columns]
    stacked = _stack_shards(batches, cap)
    counts = jnp.asarray([b.num_rows for b in batches], dtype=jnp.int32)
    fn = _cached_fn(
        ("copart", _mesh_key(mesh), tuple(col_dtypes),
         tuple(key_positions), cap),
        lambda: copartition_exchange_fn(mesh, col_dtypes, key_positions, cap))
    outs = fn(*stacked, counts)
    schema = batches[0].schema
    results = []
    for w in range(n):
        arrays = [o[w] for o in outs[:-1]]
        recv_n = int(outs[-1][w])
        cols = _rebuild_columns(col_dtypes, arrays)
        results.append(ColumnarBatch(schema, cols, recv_n))
    return results


# ---------------------------------------------------------------------------
# Distributed sort: sample -> all_gather bounds -> all_to_all -> local sort,
# ALL inside one XLA computation
# ---------------------------------------------------------------------------

_SAMPLE_PER_WORKER = 64


def _lex_lt(a_words: List[jnp.ndarray], b_words: List[jnp.ndarray]
            ) -> jnp.ndarray:
    """Lexicographic a < b over parallel word lists (mixed uint/float words
    from kernels._key_arrays are order-correct under elementwise compare)."""
    lt = jnp.zeros(a_words[0].shape, dtype=jnp.bool_)
    eq = jnp.ones(a_words[0].shape, dtype=jnp.bool_)
    for aw, bw in zip(a_words, b_words):
        lt = lt | (eq & (aw < bw))
        eq = eq & (aw == bw)
    return lt


def distributed_sort_fn(mesh: Mesh, col_dtypes: Sequence[dt.DType],
                        key_positions: Sequence[int],
                        ascending: Sequence[bool],
                        nulls_first: Sequence[bool], cap: int):
    """Build the jitted SPMD global sort over ``mesh``.

    Per worker, in ONE XLA computation (the reference needs a driver-side
    reservoir sample plus a full exchange round-trip —
    GpuRangePartitioner.scala:237):

      1. encode sort keys into order-preserving words (kernels._key_arrays)
      2. sample evenly-spaced live rows; ``all_gather`` samples over ICI
      3. every worker sorts the identical global sample and picks the same
         n-1 bound rows -> partition id per row by lexicographic rank
      4. ``all_to_all`` routes rows to their range owner (n*cap receive
         window: worst-case skew lands everything on one worker)
      5. local lexsort of the received shard

    Worker w's output is the w-th global key range, locally sorted, so
    host-side concatenation in worker order is the total order.
    """
    n = mesh.devices.size
    out_cap = n * cap
    n_arrays = sum(3 if t.var_width else 2 for t in col_dtypes)
    s = _SAMPLE_PER_WORKER

    def encode(cols: List[Column]) -> List[jnp.ndarray]:
        words: List[jnp.ndarray] = []
        for pos, asc, nf in zip(key_positions, ascending, nulls_first):
            words.extend(K._key_arrays(K.SortKey(cols[pos], asc, nf)))
        return words

    def per_worker(*arrays_and_count):
        *arrays, local_n = arrays_and_count
        arrays = [a[0] for a in arrays]
        local_n = local_n[0]
        cols = _rebuild_columns(col_dtypes, arrays)
        words = encode(cols)

        # 2. sample s evenly-spaced live rows (invalid when local_n == 0)
        pick = (jnp.arange(s) * jnp.maximum(local_n, 1)) // s
        pick = jnp.clip(pick, 0, cap - 1).astype(jnp.int32)
        s_valid = (jnp.arange(s) < local_n) & (local_n > 0)
        s_words = [w[pick] for w in words]
        g_words = [jax.lax.all_gather(w, "workers", tiled=True)
                   for w in s_words]
        g_valid = jax.lax.all_gather(s_valid, "workers", tiled=True)

        # 3. identical global-sample sort on every worker -> bound rows
        order = jnp.lexsort(tuple(reversed(
            [(~g_valid).astype(jnp.uint8)] + g_words)))
        total = jnp.sum(g_valid)
        b_words = []
        bidx = []
        for w_i in range(n - 1):
            gi = jnp.clip(((w_i + 1) * total) // n, 0, n * s - 1)
            bidx.append(order[gi])
        for w in g_words:
            b_words.append(jnp.stack([w[i] for i in bidx]) if bidx
                           else jnp.zeros((0,), w.dtype))

        # partition id = count of bounds strictly below the row's key
        pid = jnp.zeros(cap, dtype=jnp.int32)
        for w_i in range(n - 1):
            bw = [jnp.broadcast_to(bwords[w_i], (cap,))
                  for bwords in b_words]
            pid = pid + _lex_lt(bw, words).astype(jnp.int32)
        pid = jnp.clip(pid, 0, n - 1)

        # 4. route rows to their range owner
        live = jnp.arange(cap) < local_n
        payload = _column_arrays(cols)
        stacked, counts = bucket_rows_for_exchange(payload, pid, live, n, cap)
        moved, moved_counts = exchange(stacked, counts, "workers")
        flat, recv_n = flatten_received(moved, moved_counts, out_cap)

        # 5. local sort of the received shard
        recv_cols = _rebuild_columns(col_dtypes, flat)
        keys = [K.SortKey(recv_cols[pos], asc, nf)
                for pos, asc, nf in zip(key_positions, ascending,
                                        nulls_first)]
        idx = K.sort_indices(keys, recv_n, out_cap)
        sorted_cols = [K.gather_column(c, idx) for c in recv_cols]
        out = _column_arrays(sorted_cols) + [recv_n]
        return tuple(a[None] for a in out)

    in_specs = tuple([P("workers")] * (n_arrays + 1))
    # lint: naked-jit-ok mesh SPMD stage builder: every call rides _cached_fn -> compile_cache.note_build (audited + persisted)
    return jax.jit(_shard_map(per_worker, mesh, in_specs, P("workers")))


def run_distributed_sort(mesh: Mesh, batches: List[ColumnarBatch],
                         key_positions: Sequence[int],
                         ascending: Sequence[bool],
                         nulls_first: Sequence[bool]) -> List[ColumnarBatch]:
    """Host driver: shard batches across workers, run the fused SPMD sort,
    return per-worker sorted range shards (concatenation = total order)."""
    n = mesh.devices.size
    assert len(batches) == n, "one shard per worker"
    cap = max(b.capacity for b in batches)
    col_dtypes = [c.dtype for c in batches[0].columns]
    stacked = _stack_shards(batches, cap)
    counts = jnp.asarray([b.num_rows for b in batches], dtype=jnp.int32)
    fn = _cached_fn(
        ("sort", _mesh_key(mesh), tuple(col_dtypes), tuple(key_positions),
         tuple(ascending), tuple(nulls_first), cap),
        lambda: distributed_sort_fn(mesh, col_dtypes, key_positions,
                                    tuple(ascending), tuple(nulls_first),
                                    cap))
    outs = fn(*stacked, counts)
    schema = batches[0].schema
    results = []
    for w in range(n):
        arrays = [o[w] for o in outs[:-1]]
        recv_n = int(outs[-1][w])
        cols = _rebuild_columns(col_dtypes, arrays)
        results.append(ColumnarBatch(schema, cols, recv_n))
    return results


def distributed_groupby_round_fn(mesh: Mesh, key_dtypes, val_dtypes,
                                 agg_ops, w_cap: int, acc_cap: int):
    """ONE streaming round of the SPMD group-by: partial-aggregate a
    bounded input WINDOW, exchange the partials, and merge them into the
    carried per-worker accumulator of merge-phase partials.

    This replaces the whole-input staging of ``distributed_groupby_fn``
    for stages above ``mesh.maxStageBytes`` (round-3 VERDICT weak#6): per
    round the device residency is O(workers x w_cap) input + the group
    accumulator, and the receive window is ``workers * w_cap`` per round
    instead of ``workers * total_cap``. The reference's analog is the
    windowed pull-based transfer (RapidsShuffleServer.scala:97-167,
    WindowedBlockIterator.scala). Fixed-width keys/values only (var-width
    accumulators would need static width harmonization across rounds)."""
    n = mesh.devices.size
    assert all(not t.var_width for t in key_dtypes), "fixed-width keys only"
    plan = _update_plan(agg_ops, val_dtypes)
    partial_dtypes = [t for cols in plan for (_op, t) in cols]
    assert all(not t.var_width for t in partial_dtypes)
    merge_ops = []
    for cols in plan:
        for (op, _t) in cols:
            merge_ops.append("sum" if op in ("count", "count_star") else op)
    recv_cap = n * w_cap
    mid_cap = acc_cap + recv_cap
    nk = len(key_dtypes) * 2

    def per_worker(*args):
        args = [a[0] for a in args]
        n_win = len(key_dtypes) * 2 + len(val_dtypes) * 2
        win, rest = args[:n_win], args[n_win:]
        local_n = rest[0]
        acc = rest[1:-1]
        acc_n = rest[-1]
        key_cols = _rebuild_columns(key_dtypes, win[:nk])
        val_cols = _rebuild_columns(val_dtypes, win[nk:])

        # 1. partial aggregate of this window
        specs = []
        for cols_plan, c in zip(plan, val_cols):
            for (uop, ut) in cols_plan:
                cc = c
                if ut == dt.FLOAT64 and c.dtype != dt.FLOAT64 and \
                        uop == "sum":
                    cc = Column(dt.FLOAT64, c.data.astype(jnp.float64),
                                c.validity)
                specs.append(agg_k.AggSpec(uop, cc))
        out_keys, out_aggs, n_groups = agg_k.groupby_aggregate(
            key_cols, specs, local_n, w_cap)

        # 2. route partials to their owners
        pids = jnp.mod(jnp.mod(murmur3_batch(out_keys, w_cap), n) + n, n)
        live = jnp.arange(w_cap) < n_groups
        payload = _column_arrays(out_keys) + _column_arrays(out_aggs)
        stacked, counts = bucket_rows_for_exchange(payload, pids, live, n,
                                                   w_cap)
        moved, moved_counts = exchange(stacked, counts, "workers")
        flat, recv_n = flatten_received(moved, moved_counts, recv_cap)

        # 3. merge received partials INTO the accumulator: concatenate the
        # accumulator block with the received block (both prefix-live in
        # their own range — the live MASK keeps the merge from needing a
        # compaction in between)
        acc_keys = _rebuild_columns(key_dtypes, acc[:nk])
        acc_aggs = _rebuild_columns(partial_dtypes, acc[nk:])
        recv_keys = _rebuild_columns(key_dtypes, flat[:nk])
        recv_aggs = _rebuild_columns(partial_dtypes, flat[nk:])

        def cat(a: Column, b: Column) -> Column:
            return Column(a.dtype,
                          jnp.concatenate([a.data, b.data]),
                          jnp.concatenate([a.validity, b.validity]))
        m_keys = [cat(a, b) for a, b in zip(acc_keys, recv_keys)]
        m_aggs = [cat(a, b) for a, b in zip(acc_aggs, recv_aggs)]
        live_mask = jnp.concatenate([jnp.arange(acc_cap) < acc_n,
                                     jnp.arange(recv_cap) < recv_n])
        mspecs = [agg_k.AggSpec(mop, c)
                  for mop, c in zip(merge_ops, m_aggs)]
        f_keys, f_aggs, f_groups = agg_k.groupby_aggregate(
            m_keys, mspecs, mid_cap, mid_cap, live_mask=live_mask)

        # 4. carry: groups compact to the front; the accumulator keeps the
        # first acc_cap slots and f_groups is returned UNclamped so the
        # host can detect ownership overflow instead of dropping groups
        out = []
        for c in f_keys + f_aggs:
            out.append(c.data[:acc_cap])
            out.append(c.validity[:acc_cap])
        out.append(f_groups)
        return tuple(a[None] for a in out)

    n_in = len(key_dtypes) * 2 + len(val_dtypes) * 2 + 1 + \
        len(key_dtypes) * 2 + len(partial_dtypes) * 2 + 1
    in_specs = tuple([P("workers")] * n_in)
    # lint: naked-jit-ok mesh SPMD stage builder: every call rides _cached_fn -> compile_cache.note_build (audited + persisted)
    return jax.jit(_shard_map(per_worker, mesh, in_specs, P("workers")))


def _finalize_groupby_fn(mesh: Mesh, key_dtypes, val_dtypes, agg_ops,
                         acc_cap: int):
    """Post-stream finalize: divide avg partials (merge-phase sums/counts)
    into the output form — one tiny SPMD program after the last round."""
    plan = _update_plan(agg_ops, val_dtypes)
    partial_dtypes = [t for cols in plan for (_op, t) in cols]
    nk = len(key_dtypes) * 2

    def per_worker(*args):
        args = [a[0] for a in args]
        acc = args[:-1]
        keys = _rebuild_columns(key_dtypes, acc[:nk])
        aggs = _rebuild_columns(partial_dtypes, acc[nk:])
        out_cols: List[Column] = []
        ai = 0
        for op, cols_plan in zip(agg_ops, plan):
            if op == "avg":
                s, c = aggs[ai], aggs[ai + 1]
                valid = s.validity & (c.data > 0)
                data = jnp.where(
                    valid,
                    s.data / jnp.maximum(c.data.astype(jnp.float64), 1.0),
                    0.0)
                out_cols.append(Column(dt.FLOAT64, data, valid))
            else:
                out_cols.append(aggs[ai])
            ai += len(cols_plan)
        out = _column_arrays(keys) + _column_arrays(out_cols)
        return tuple(a[None] for a in out)

    n_in = nk + len(partial_dtypes) * 2 + 1
    in_specs = tuple([P("workers")] * n_in)
    # lint: naked-jit-ok mesh SPMD stage builder: every call rides _cached_fn -> compile_cache.note_build (audited + persisted)
    return jax.jit(_shard_map(per_worker, mesh, in_specs, P("workers")))


def run_distributed_groupby_streaming(mesh: Mesh,
                                      batches: List[ColumnarBatch],
                                      key_idx: List[int],
                                      val_idx: List[int],
                                      agg_ops: List[str],
                                      window_rows: int,
                                      acc_cap: Optional[int] = None
                                      ) -> List[ColumnarBatch]:
    """Multi-round windowed SPMD group-by (inputs larger than one staged
    stage): each round slices ``window_rows`` rows per worker, runs one
    exchange+merge round, and carries group partials in a bounded
    accumulator."""
    n = mesh.devices.size
    assert len(batches) == n, "one shard per worker"
    key_dtypes = [batches[0].columns[i].dtype for i in key_idx]
    val_dtypes = [batches[0].columns[i].dtype for i in val_idx]
    plan = _update_plan(agg_ops, val_dtypes)
    partial_dtypes = [t for cols in plan for (_op, t) in cols]
    w_cap = bucket(window_rows)
    acc_cap = acc_cap or n * w_cap
    rounds = max(1, -(-max(b.num_rows for b in batches) // window_rows))

    fn = _cached_fn(
        ("groupby-round", _mesh_key(mesh), tuple(key_dtypes),
         tuple(val_dtypes), tuple(agg_ops), w_cap, acc_cap),
        lambda: distributed_groupby_round_fn(
            mesh, key_dtypes, val_dtypes, agg_ops, w_cap, acc_cap))

    # zeroed accumulator [n, acc_cap] per key/partial array + counts
    acc: List[jnp.ndarray] = []
    for t in key_dtypes + partial_dtypes:
        acc.append(jnp.zeros((n, acc_cap), t.numpy_dtype))
        acc.append(jnp.zeros((n, acc_cap), jnp.bool_))
    acc_n = jnp.zeros(n, jnp.int32)

    for r in range(rounds):
        lo = r * window_rows
        win_arrays: List[List[jnp.ndarray]] = []
        counts = []
        for b in batches:
            take = min(max(b.num_rows - lo, 0), window_rows)
            arrs = []
            for i in key_idx + val_idx:
                c = K.slice_column(b.columns[i], lo, w_cap, take)
                arrs.extend(c.arrays())
            win_arrays.append(arrs)
            counts.append(take)
        stacked = [jnp.stack([wa[i] for wa in win_arrays])
                   for i in range(len(win_arrays[0]))]
        outs = fn(*stacked, jnp.asarray(counts, jnp.int32),
                  *acc, acc_n)
        acc = list(outs[:-1])
        acc_n_dev = outs[-1]
        overflow = np.asarray(acc_n_dev)
        if (overflow > acc_cap).any():
            raise RuntimeError(
                f"streaming group-by accumulator overflow: a worker owns "
                f"{int(overflow.max())} groups > capacity {acc_cap}; raise "
                "mesh window/accumulator size")
        acc_n = jnp.minimum(acc_n_dev, acc_cap).astype(jnp.int32)

    ffn = _cached_fn(
        ("groupby-final", _mesh_key(mesh), tuple(key_dtypes),
         tuple(val_dtypes), tuple(agg_ops), acc_cap),
        lambda: _finalize_groupby_fn(mesh, key_dtypes, val_dtypes, agg_ops,
                                     acc_cap))
    outs = ffn(*acc, acc_n)
    agg_out_dtypes = output_dtypes(agg_ops, val_dtypes)
    nk_arrays = len(key_dtypes) * 2
    results = []
    acc_n_host = np.asarray(acc_n)
    for w in range(n):
        arrays = [o[w] for o in outs]
        keys = _rebuild_columns(key_dtypes, arrays[:nk_arrays])
        aggs = _rebuild_columns(agg_out_dtypes, arrays[nk_arrays:])
        fields = [dt.Field(f"k{i}", t) for i, t in enumerate(key_dtypes)]
        fields += [dt.Field(f"a{i}", t)
                   for i, t in enumerate(agg_out_dtypes)]
        results.append(ColumnarBatch(dt.Schema(fields), keys + aggs,
                                     int(acc_n_host[w])))
    return results


def _string_key_words(col: Column, w8: int) -> List[Column]:
    """Exact fixed-width encoding of a STRING key column: the padded byte
    matrix packs into ``w8/8`` little-endian int64 word columns plus one
    length column — so string group keys ride the streaming SPMD path's
    fixed-width machinery (ids over the wire; no hashing, no collisions).
    The padding invariant (bytes beyond length are zero) makes the word
    tuple a faithful key: equal strings <=> equal words + length."""
    data = col.data
    if data.shape[1] < w8:
        data = jnp.pad(data, ((0, 0), (0, w8 - data.shape[1])))
    out: List[Column] = []
    for j in range(w8 // 8):
        w = jnp.zeros(data.shape[0], jnp.int64)
        for k in range(8):
            w = w | (data[:, j * 8 + k].astype(jnp.int64) << (8 * k))
        out.append(Column(dt.INT64, w, col.validity))
    out.append(Column(dt.INT64, col.lengths.astype(jnp.int64),
                      col.validity))
    return out


def _string_from_words(word_cols: List[Column], length_col: Column
                       ) -> Column:
    """Inverse of :func:`_string_key_words`."""
    parts = []
    for wc in word_cols:
        for k in range(8):
            parts.append(((wc.data >> (8 * k)) &
                          jnp.int64(0xFF)).astype(jnp.uint8))
    data = jnp.stack(parts, axis=1)
    validity = length_col.validity
    lens = jnp.where(validity, length_col.data, 0).astype(jnp.int32)
    data = jnp.where(validity[:, None], data, jnp.uint8(0))
    return Column(dt.STRING, data, validity, lens)


def _run_streaming_string_keys(mesh: Mesh, batches: List[ColumnarBatch],
                               key_idx: List[int], val_idx: List[int],
                               agg_ops: List[str], window_rows: int
                               ) -> List[ColumnarBatch]:
    """Streaming SPMD group-by with STRING keys: word-encode per shard,
    stream fixed-width, decode the result keys (round-4 VERDICT item:
    var-width keys must stay mesh-routed past maxStageBytes)."""
    key_dtypes = [batches[0].columns[i].dtype for i in key_idx]
    # one harmonized width per string key across all shards
    w8s = {}
    for ki, t in zip(key_idx, key_dtypes):
        if t == dt.STRING:
            w = max(int(b.columns[ki].data.shape[1]) for b in batches)
            w8s[ki] = ((w + 7) // 8) * 8
    enc_batches = []
    for b in batches:
        cols: List[Column] = []
        for ki in key_idx:
            c = b.columns[ki]
            if ki in w8s:
                cols.extend(_string_key_words(c, w8s[ki]))
            else:
                cols.append(c)
        for vi in val_idx:
            cols.append(b.columns[vi])
        fields = [dt.Field(f"e{i}", c.dtype) for i, c in enumerate(cols)]
        enc_batches.append(ColumnarBatch(dt.Schema(fields), cols,
                                         b.num_rows))
    n_enc_keys = len(enc_batches[0].columns) - len(val_idx)
    enc_key_idx = list(range(n_enc_keys))
    enc_val_idx = list(range(n_enc_keys, n_enc_keys + len(val_idx)))
    res = run_distributed_groupby_streaming(
        mesh, enc_batches, enc_key_idx, enc_val_idx, agg_ops, window_rows)
    # decode: consume w8/8 + 1 encoded key columns per string key
    out = []
    for rb in res:
        dec_keys: List[Column] = []
        i = 0
        for ki, t in zip(key_idx, key_dtypes):
            if ki in w8s:
                nw = w8s[ki] // 8
                dec_keys.append(_string_from_words(
                    rb.columns[i:i + nw], rb.columns[i + nw]))
                i += nw + 1
            else:
                dec_keys.append(rb.columns[i])
                i += 1
        aggs = list(rb.columns[i:])
        fields = [dt.Field(f"k{j}", c.dtype)
                  for j, c in enumerate(dec_keys)]
        fields += [dt.Field(f"a{j}", c.dtype) for j, c in enumerate(aggs)]
        out.append(ColumnarBatch(dt.Schema(fields), dec_keys + aggs,
                                 rb.num_rows))
    return out


def run_distributed_groupby(mesh: Mesh, batches: List[ColumnarBatch],
                            key_idx: List[int], val_idx: List[int],
                            agg_ops: List[str],
                            window_rows: Optional[int] = None
                            ) -> List[ColumnarBatch]:
    """Host driver: shard batches across workers, run the fused SPMD step,
    return per-worker result batches. ``window_rows`` switches to the
    multi-round streaming path (bounded per-round residency)."""
    n = mesh.devices.size
    assert len(batches) == n, "one shard per worker"
    cap = max(b.capacity for b in batches)
    if window_rows is not None and window_rows < cap:
        key_dtypes_chk = [batches[0].columns[i].dtype for i in key_idx]
        val_dtypes_chk = [batches[0].columns[i].dtype for i in val_idx]
        if all(not t.var_width for t in key_dtypes_chk + val_dtypes_chk):
            return run_distributed_groupby_streaming(
                mesh, batches, key_idx, val_idx, agg_ops, window_rows)
        if all(t == dt.STRING or not t.var_width
               for t in key_dtypes_chk) and \
                all(not t.var_width for t in val_dtypes_chk):
            return _run_streaming_string_keys(
                mesh, batches, key_idx, val_idx, agg_ops, window_rows)
    key_dtypes = [batches[0].columns[i].dtype for i in key_idx]
    val_dtypes = [batches[0].columns[i].dtype for i in val_idx]

    # stack shards on a leading workers axis
    def stack(get_arrays):
        per_worker = [get_arrays(b) for b in batches]
        return [jnp.stack([pw[i] for pw in per_worker])
                for i in range(len(per_worker[0]))]

    def arrays_of(b: ColumnarBatch):
        out = []
        for i in key_idx + val_idx:
            c = b.columns[i]
            if c.capacity < cap:
                c = K.rebucket_column(c, b.num_rows, cap)
            out.extend(c.arrays())
        return out

    stacked = stack(arrays_of)
    counts = jnp.asarray([b.num_rows for b in batches], dtype=jnp.int32)

    fn = _cached_fn(
        ("groupby", _mesh_key(mesh), tuple(key_dtypes), tuple(val_dtypes),
         tuple(agg_ops), cap),
        lambda: distributed_groupby_fn(mesh, key_dtypes, val_dtypes,
                                       agg_ops, cap))
    outs = fn(*stacked, counts)

    # unpack per-worker results
    agg_out_dtypes = output_dtypes(agg_ops, val_dtypes)
    results = []
    nk_arrays = sum(3 if t.var_width else 2 for t in key_dtypes)
    for w in range(n):
        arrays = [o[w] for o in outs[:-1]]
        n_groups = int(outs[-1][w])
        keys = _rebuild_columns(key_dtypes, arrays[:nk_arrays])
        aggs = _rebuild_columns(agg_out_dtypes, arrays[nk_arrays:])
        fields = [dt.Field(f"k{i}", t) for i, t in enumerate(key_dtypes)]
        fields += [dt.Field(f"a{i}", t) for i, t in enumerate(agg_out_dtypes)]
        results.append(ColumnarBatch(dt.Schema(fields), keys + aggs, n_groups))
    return results
