"""Mesh-routed physical operators: the planner emits these when an SPMD
device mesh is active (``spark.rapids.tpu.sql.mesh.enabled``), replacing the
host-orchestrated exchange pipeline with fused XLA collectives over ICI.

Mapping to the reference (SURVEY.md §2.6/§2.8): the exchange operators
(GpuShuffleExchangeExec + GpuHashPartitioning / GpuRangePartitioning) and the
downstream op collapse into one jitted shard_map program per stage —
GpuHashAggregate(partial) -> exchange -> GpuHashAggregate(final) becomes one
XLA computation whose shuffle is a single ``all_to_all`` riding ICI
(parallel/mesh.py). Host staging happens only at the stage boundary: child
partitions are drained, concatenated, and split into one shard per worker.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

from ..analysis.contracts import exec_contract
from ..columnar import dtypes as dt
from ..columnar.batch import ColumnarBatch
from ..columnar.column import Column, bucket
from ..ops import expressions as ex
from ..ops import kernels as K
from ..plan import logical as lp
from ..plan.physical import (Partition, TpuExec, TpuShuffledJoinExec,
                             accumulate_spillable, bind_refs,
                             concat_spillable, exec_metrics)
from . import mesh as M
from ..exec.tracing import trace_span

# ops the SPMD group-by pipeline merges correctly (first/last are excluded:
# their distributed result would depend on shard order)
MESH_AGG_OPS = ("sum", "count", "count_star", "avg", "min", "max")


def shard_for_mesh(child: TpuExec, n: int) -> List[ColumnarBatch]:
    """Drain the child and split it into n equal-row shards at one common
    capacity (uniform shapes are what lets the whole stage trace once).
    The concat stages through spillable handles; the resulting shards are
    the per-worker inputs of the fused SPMD stage."""
    batch = concat_spillable(child.schema,
                             accumulate_spillable(child.execute()))
    per = -(-batch.num_rows // n) if batch.num_rows else 0
    cap = bucket(max(per, 1))
    shards = []
    for w in range(n):
        lo = min(w * per, batch.num_rows)
        take = max(0, min(per, batch.num_rows - lo))
        cols = [K.slice_column(c, lo, cap, take) for c in batch.columns]
        shards.append(ColumnarBatch(batch.schema, cols, take))
    return shards


def _append_eval_columns(batch: ColumnarBatch, exprs: List[ex.Expression]
                         ) -> Tuple[ColumnarBatch, List[int]]:
    """Batch extended with evaluated expression columns; plain bound refs
    reuse their existing column instead of duplicating it."""
    cols = list(batch.columns)
    fields = list(batch.schema.fields)
    positions = []
    for i, e in enumerate(exprs):
        if isinstance(e, ex.BoundReference):
            positions.append(e.ordinal)
            continue
        c = ex.materialize(e.eval(batch), batch)
        positions.append(len(cols))
        cols.append(c)
        fields.append(dt.Field(f"_mk{i}", c.dtype, True))
    return ColumnarBatch(dt.Schema(fields), cols, batch.num_rows), positions


class TpuMeshGroupByExec(TpuExec):
    """Fused SPMD group-by over the mesh: per-worker partial aggregate ->
    hash-bucketed ``all_to_all`` -> merge aggregate, one XLA computation
    (mesh.distributed_groupby_fn). Output: one partition per worker with
    disjoint key ownership."""

    CONTRACT = exec_contract(schema="defined", partitioning="defined",
                             bound={"grouping": 0})
    METRICS = exec_metrics("meshGroupByTime")

    def __init__(self, child: TpuExec, grouping: List[ex.Expression],
                 outputs: List[ex.Expression], mesh,
                 window_rows: "Optional[int]" = None):
        super().__init__(child)
        self.mesh = mesh
        self.window_rows = window_rows
        self.grouping_src = grouping
        self.grouping = [bind_refs(e, child.schema) for e in grouping]
        self.outputs = outputs
        # classify each output as a grouping key or an aggregate leaf
        self._spec: List[Tuple[str, int]] = []
        self.agg_leaves: List[lp.AggregateExpression] = []
        for e in outputs:
            inner = e.children[0] if isinstance(e, ex.Alias) else e
            if isinstance(inner, lp.AggregateExpression):
                self._spec.append(("agg", len(self.agg_leaves)))
                self.agg_leaves.append(inner)
            else:
                self._spec.append(("key", _grouping_index(inner, grouping)))
        self.bound_leaf_inputs = [
            bind_refs(l.children[0], child.schema) if l.children else None
            for l in self.agg_leaves]
        self._schema = dt.Schema([
            dt.Field(ex.output_name(e, i), e.dtype, e.nullable)
            for i, e in enumerate(outputs)])

    @property
    def schema(self):
        return self._schema

    @property
    def output_partitions(self) -> int:
        return int(self.mesh.devices.size)

    def execute(self) -> List[Partition]:
        n = int(self.mesh.devices.size)
        shards = shard_for_mesh(self.children[0], n)
        nk = len(self.grouping)
        proj_shards = []
        for shard in shards:
            keys = [ex.materialize(g.eval(shard), shard)
                    for g in self.grouping]
            vals = []
            for leaf, bound in zip(self.agg_leaves, self.bound_leaf_inputs):
                if bound is None:              # COUNT(*): any column works
                    vals.append(keys[0])
                else:
                    vals.append(ex.materialize(bound.eval(shard), shard))
            fields = [dt.Field(f"k{i}", c.dtype, True)
                      for i, c in enumerate(keys)]
            fields += [dt.Field(f"v{i}", c.dtype, True)
                       for i, c in enumerate(vals)]
            proj_shards.append(ColumnarBatch(dt.Schema(fields), keys + vals,
                                             shard.num_rows))
        with trace_span("mesh_groupby", self.metrics, "meshGroupByTime"):
            results = M.run_distributed_groupby(
                self.mesh, proj_shards,
                key_idx=list(range(nk)),
                val_idx=list(range(nk, nk + len(self.agg_leaves))),
                agg_ops=[l.op for l in self.agg_leaves],
                window_rows=self.window_rows)
        out = []
        for r in results:
            # r columns: [k0..k{nk-1}, a0..]; order per output spec
            cols = []
            for kind, idx in self._spec:
                cols.append(r.columns[idx] if kind == "key"
                            else r.columns[nk + idx])
            self.metrics.inc("numOutputRows", r.num_rows)
            out.append(iter([ColumnarBatch(self._schema, cols, r.num_rows)]))
        return out


def _grouping_index(e: ex.Expression, grouping: List[ex.Expression]) -> int:
    for gi, g in enumerate(grouping):
        if e is g or (isinstance(e, ex.ColumnRef) and
                      isinstance(g, ex.ColumnRef) and
                      e.col_name == g.col_name):
            return gi
    raise ValueError(f"output {e!r} is not a grouping expression")


class TpuMeshSortExec(TpuExec):
    """Fused SPMD global sort (mesh.distributed_sort_fn): sample ->
    all_gather bounds -> all_to_all -> local sort, one XLA computation.
    Worker w's partition is the w-th key range, locally sorted."""

    CONTRACT = exec_contract(schema="passthrough", partitioning="defined",
                             bound={"orders": 0})
    METRICS = exec_metrics("meshSortTime")

    def __init__(self, child: TpuExec, orders: List[lp.SortOrder], mesh):
        super().__init__(child)
        self.mesh = mesh
        self.orders = [lp.SortOrder(bind_refs(o.child, child.schema),
                                    o.ascending, o.nulls_first)
                       for o in orders]

    @property
    def schema(self):
        return self.children[0].schema

    @property
    def output_partitions(self) -> int:
        return int(self.mesh.devices.size)

    def execute(self) -> List[Partition]:
        n = int(self.mesh.devices.size)
        shards = shard_for_mesh(self.children[0], n)
        n_payload = len(self.schema)
        ext_shards, positions = [], None
        for shard in shards:
            extb, positions = _append_eval_columns(
                shard, [o.child for o in self.orders])
            ext_shards.append(extb)
        with trace_span("mesh_sort", self.metrics, "meshSortTime"):
            results = M.run_distributed_sort(
                self.mesh, ext_shards, positions,
                [o.ascending for o in self.orders],
                [o.nulls_first for o in self.orders])
        out = []
        for r in results:
            b = ColumnarBatch(self.schema, r.columns[:n_payload], r.num_rows)
            self.metrics.inc("numOutputRows", b.num_rows)
            out.append(iter([b]))
        return out


class TpuMeshJoinExec(TpuShuffledJoinExec):
    """SPMD shuffled join: both sides co-partitioned by one fused
    ``all_to_all`` exchange each (mesh.copartition_exchange_fn), then the
    per-worker partition pairs run the sort-merge join kernels. Inherits the
    per-pair join semantics (incl. full outer, which is correct per worker
    because co-partitioning makes key ownership disjoint)."""

    # co-partitioning happens inside the fused all_to_all, not via child
    # exchanges — so no "copartitioned" extra here
    CONTRACT = exec_contract(schema="defined", partitioning="defined",
                             bound={"left_keys": 0, "right_keys": 1},
                             extras=("join_schema",))
    METRICS = exec_metrics("joinTime", "buildTime", "skewJoinSplits",
                           "runtimeBroadcastJoins", "meshExchangeTime")

    def __init__(self, left: TpuExec, right: TpuExec, how: str,
                 left_keys, right_keys, condition, mesh,
                 part_left_keys=None, part_right_keys=None):
        super().__init__(left, right, how, left_keys, right_keys, condition)
        self.mesh = mesh
        # partitioning keys may carry promotion casts so both sides hash
        # the same type; they default to the join keys
        self.part_left_keys = [bind_refs(e, left.schema)
                               for e in (part_left_keys or left_keys)]
        self.part_right_keys = [bind_refs(e, right.schema)
                                for e in (part_right_keys or right_keys)]

    @property
    def output_partitions(self) -> int:
        return int(self.mesh.devices.size)

    def _copartition(self, child: TpuExec, part_keys) -> List[ColumnarBatch]:
        n = int(self.mesh.devices.size)
        shards = shard_for_mesh(child, n)
        n_payload = len(child.schema)
        ext, positions = [], None
        for shard in shards:
            extb, positions = _append_eval_columns(shard, part_keys)
            ext.append(extb)
        co = M.run_copartition_exchange(self.mesh, ext, positions)
        return [ColumnarBatch(child.schema, b.columns[:n_payload], b.num_rows)
                for b in co]

    def execute(self) -> List[Partition]:
        import time as _time
        t0 = _time.perf_counter()
        with trace_span("mesh_exchange", self.metrics, "meshExchangeTime"):
            l_co = self._copartition(self.children[0], self.part_left_keys)
            r_co = self._copartition(self.children[1], self.part_right_keys)
        # the copartition all_to_all IS an ICI shuffle exchange: account
        # it in the process plane totals next to TpuShuffleExchangeExec
        # (shuffle/exchange.note_plane -> tpu_shuffle_gbps{plane=ici})
        from ..shuffle.exchange import note_plane
        moved = sum(b.device_size_bytes() for b in l_co + r_co)
        note_plane("ici", moved, _time.perf_counter() - t0)
        return [self._join_copart(iter([lb]), iter([rb]))
                for lb, rb in zip(l_co, r_co)]
