"""Adaptive query execution: the runtime re-planner that closes the
stats->plan loop (docs/aqe.md; the reference's AQE integration, SURVEY
§2.6 and the query-stage prep rules of §3.2).

PR 14 shipped the measurement half — per-partition rows/bytes/skew at
every exchange materialization (``session.last_stage_stats()``) and the
estimate-vs-actual drift report (plan/estimates.py). This module is the
decision half: it consumes those observed statistics at stage
materialization boundaries and re-plans the DOWNSTREAM stages before
they run. Four rules, each behind a ``spark.rapids.tpu.sql.adaptive.*``
conf (master switch ``adaptive.enabled``, per-rule toggles):

* **coalesce** — group adjacent small post-shuffle partitions up to
  ``adaptive.minPartitionSize`` observed bytes so downstream tasks don't
  pay per-partition overhead for near-empty slices
  (:func:`plan_coalesce`, wired into the exchange's reduce-group
  planner).
* **skew-split** — split reduce partitions whose observed bytes exceed
  ``max(skewJoin.threshold, skewedPartitionFactor x median)`` into
  mapper-subset tasks. On the ICI plane — where the device-resident
  exchange has no per-slice host sizes to split on — the rule uses the
  PRIOR execution's stage statistics for the same exchange fingerprint
  (:func:`ici_skew_fallback`): a fingerprint observed skewed falls the
  skewed stage only back to the DCN plane instead of declining outright.
* **join-strategy switch** — promote shuffled->broadcast when the
  observed build side lands under the broadcast threshold
  (physical.py's ``_maybe_runtime_broadcast``), and DEMOTE
  broadcast->shuffled when a planned broadcast build materializes over
  ``threshold x joinSwitch.demoteFactor`` observed bytes
  (:func:`maybe_demote_broadcast`). The factor is a hysteresis dead
  band: a borderline build inside ``(threshold, threshold x factor]``
  records a declined decision and changes nothing, so repeat executions
  don't flap between strategies.
* **drift feedback** — fold observed operator cardinalities back into
  ``est_rows`` keyed by the serving plan fingerprint
  (:func:`begin_query` / :func:`note_execution`), so the plan cache's
  repeat queries plan from actuals instead of the 0.25-selectivity
  heuristic.

Every decision — applied or declined — is a structured
:class:`AqeDecision` hung on the plan node that owns it: flight-recorded
(kind ``aqe``), rendered per node in EXPLAIN ANALYZE
(:func:`aqe_annotations`), written into the query log (the ``aqe``
field), and counted in telemetry (``tpu_aqe_decisions_total{rule=...}``).
Re-planned subtrees re-validate against the plan contracts
(``analysis/contracts.validate_replan``) before they execute.

The module also owns the observed-cost table behind service admission
weighting (:func:`admission_cost_units`): a plan fingerprint observed
moving many exchange bytes charges more queue slots against its
tenant's budget on the next admit (docs/service.md).
"""

from __future__ import annotations

import logging
import threading
from collections import OrderedDict
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..analysis.contracts import exec_contract
from .physical import TpuExec, exec_metrics

log = logging.getLogger("spark_rapids_tpu.aqe")

#: Every decision-rule string :func:`record_decision` may emit. The
#: ``aqe-decision`` lint rule (analysis/lint.py) checks each literal
#: rule argument in the package against this tuple — an undeclared rule
#: string fails tier-1, mirroring the telemetry-key pattern.
AQE_RULES: Tuple[str, ...] = (
    "coalesce",
    "skew-split",
    "join-promote",
    "join-demote",
    "drift-feedback",
)

#: Test seam: when set, applied to a re-planned subtree BEFORE contract
#: re-validation (the seeded-corruption error-mode test corrupts the
#: replacement plan here and asserts validate_replan catches it).
_REPLAN_CORRUPTION_HOOK: Optional[Callable[[Any], None]] = None


# ---------------------------------------------------------------------------
# Decision records
# ---------------------------------------------------------------------------

@dataclass
class AqeDecision:
    """One adaptive decision (applied or declined) on one plan node."""

    rule: str                        # one of AQE_RULES
    applied: bool = True             # False = considered and declined
    stage_id: Optional[int] = None
    before: Any = None               # shape before the decision
    after: Any = None                # shape after (None when declined)
    reason: str = ""

    def to_dict(self) -> Dict[str, Any]:
        return {"rule": self.rule, "applied": self.applied,
                "stageId": self.stage_id, "before": self.before,
                "after": self.after, "reason": self.reason}


def record_decision(node, rule: str, *, applied: bool = True,
                    stage_id: Optional[int] = None, before: Any = None,
                    after: Any = None, reason: str = "") -> AqeDecision:
    """Record one decision on ``node``: appended to the node's
    ``_aqe_decisions`` (EXPLAIN ANALYZE / query-log surface), flight-
    recorded, and counted in ``tpu_aqe_decisions_total{rule}``."""
    d = AqeDecision(rule, applied=applied, stage_id=stage_id,
                    before=before, after=after, reason=reason)
    if getattr(node, "_aqe_decisions", None) is None:
        node._aqe_decisions = []
    node._aqe_decisions.append(d)
    try:
        from ..service.telemetry import MetricsRegistry, flight_record
        flight_record("aqe", rule, {
            "applied": applied, "stageId": stage_id,
            "operator": type(node).__name__,
            "before": before, "after": after, "reason": reason})
        MetricsRegistry.get().counter(
            "tpu_aqe_decisions_total",
            "adaptive-execution decisions (applied and declined)",
            rule=rule).inc()
        # an adaptive decision is a lockstep-relevant event: fold the
        # decision (not its per-worker before/after numbers — those are
        # mesh-consistent only after the allreduce) into the per-query
        # divergence digest (analysis/divergence.py)
        from ..analysis import divergence
        divergence.note_event(
            f"aqe:{rule}:{'applied' if applied else 'declined'}:"
            f"{stage_id}:{type(node).__name__}")
    except Exception:
        pass               # observability must never fail the decision
    return d


def clear_decisions(root) -> None:
    """Drop every decision in the tree (fresh per execution; a cached
    plan re-executing must not accumulate the prior run's records)."""
    if getattr(root, "_aqe_decisions", None):
        root._aqe_decisions = []
    for c in getattr(root, "children", ()):
        clear_decisions(c)


def _walk_paths(node, path: str = "", idx: Optional[int] = None):
    # same path convention as contracts.validate_plan / metrics_tree
    here = (f"{path}/{idx}.{type(node).__name__}" if path
            else type(node).__name__)
    yield here, node
    for i, c in enumerate(getattr(node, "children", ())):
        yield from _walk_paths(c, here, i)


def collect_decisions(root) -> List[Dict[str, Any]]:
    """Every decision in an executed plan tree, in tree order, each
    tagged with its operator and root->node path — the query-log ``aqe``
    field and ``session.last_aqe_decisions()``'s data."""
    out: List[Dict[str, Any]] = []
    for here, node in _walk_paths(root):
        for d in getattr(node, "_aqe_decisions", None) or ():
            out.append({"operator": type(node).__name__, "path": here,
                        **d.to_dict()})
    return out


def aqe_annotations(root) -> Dict[str, List[str]]:
    """Per-node EXPLAIN ANALYZE lines keyed by plan path (the
    ``_annotated_plan_lines`` merge format, api/session.py)."""
    out: Dict[str, List[str]] = {}
    for here, node in _walk_paths(root):
        for d in getattr(node, "_aqe_decisions", None) or ():
            if d.applied:
                line = f"* aqe {d.rule}: {d.before} -> {d.after}"
                if d.reason:
                    line += f" ({d.reason})"
            else:
                line = f"* aqe {d.rule} declined: {d.reason}"
            out.setdefault(here, []).append(line)
    return out


# ---------------------------------------------------------------------------
# Rule 1: coalesce small post-shuffle partitions
# ---------------------------------------------------------------------------

def plan_coalesce(sizes: List[int], target: int) -> List[List[int]]:
    """Group ADJACENT reduce partitions up to ``target`` observed bytes:
    each group accumulates consecutive partitions until it reaches the
    target; an undersized tail merges into the last group. Adjacency
    keeps the grouping a pure reader-side re-map (the reference's
    CoalescedPartitionSpec over contiguous reducer ranges) — hash
    disjointness is preserved because every input partition lands in
    exactly one group."""
    if target <= 0:
        return [[p] for p in range(len(sizes))]
    groups: List[List[int]] = []
    cur: List[int] = []
    cur_bytes = 0
    for p, sz in enumerate(sizes):
        cur.append(p)
        cur_bytes += int(sz)
        if cur_bytes >= target:
            groups.append(cur)
            cur, cur_bytes = [], 0
    if cur:
        if groups:
            groups[-1].extend(cur)   # tail merges into the last group
        else:
            groups.append(cur)
    return groups


# ---------------------------------------------------------------------------
# Stage-statistics history (the cross-execution feed)
# ---------------------------------------------------------------------------
# Keyed by the exchange's structural plan fingerprint
# (shuffle/exchange.plan_fingerprint): the same logical exchange re-
# executing — a plan-cache repeat, or the second run of a benchmark —
# finds what its previous materialization actually produced. This is
# what lets the ICI plane make a skew decision BEFORE running its map
# phase, where the device-resident path has nothing host-side to
# measure.

_history_mu = threading.Lock()
_STAGE_HISTORY: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()
_STAGE_HISTORY_MAX = 512

#: observed per-fingerprint query cost (total exchange bytes moved) —
#: the service-admission weighting feed (admission_cost_units)
_COSTS: "OrderedDict[str, int]" = OrderedDict()
_COSTS_MAX = 512


def note_stage_stats(node) -> None:
    """Fold one exchange's just-committed ``stage_stats`` into the
    fingerprint-keyed history (called at every materialization boundary;
    exchanges without a structural fingerprint are skipped)."""
    st = getattr(node, "stage_stats", None)
    if not st or not hasattr(node, "plan_fingerprint"):
        return
    try:
        fp = node.plan_fingerprint()
    except Exception:
        return
    with _history_mu:
        _STAGE_HISTORY.pop(fp, None)
        _STAGE_HISTORY[fp] = dict(st)
        while len(_STAGE_HISTORY) > _STAGE_HISTORY_MAX:
            _STAGE_HISTORY.popitem(last=False)


def observed_stage_stats(fingerprint: str) -> Optional[Dict[str, Any]]:
    """The most recent stage statistics observed for an exchange
    fingerprint, or None when it has never materialized here."""
    with _history_mu:
        st = _STAGE_HISTORY.get(fingerprint)
        return dict(st) if st is not None else None


def effective_skew_threshold(threshold: int, factor: Optional[float],
                             median_bytes: float) -> int:
    """The skew cut line: at least ``threshold`` bytes, raised to
    ``factor x median`` when the factor-scaled median is higher — a
    partition must be BOTH large in absolute terms and an outlier
    relative to its siblings (the reference's skewedPartitionFactor x
    median rule, OptimizeSkewedJoin)."""
    eff = int(threshold)
    if factor is not None and factor > 0 and median_bytes > 0:
        eff = max(eff, int(float(factor) * float(median_bytes)))
    return eff


# ---------------------------------------------------------------------------
# Rule 2 (ICI half): prior-stats skew fallback
# ---------------------------------------------------------------------------

def ici_skew_fallback(exchange, threshold: int,
                      factor: Optional[float]) -> Tuple[bool, str]:
    """Decide whether an exchange that WOULD take the ICI plane should
    fall back to DCN so the skew splitter can run. The device-resident
    exchange has no per-slice host sizes, so the decision reads the
    PRIOR execution's stage statistics for the same structural
    fingerprint: first execution declines (and records the baseline);
    a repeat whose prior run observed a partition past the effective
    threshold falls the skewed stage only back to the host plane."""
    try:
        fp = exchange.plan_fingerprint()
    except Exception:
        return False, "exchange has no structural fingerprint"
    prior = observed_stage_stats(fp)
    if prior is None:
        return False, ("no prior stage stats for fingerprint "
                       f"{fp} (first execution records the baseline)")
    eff = effective_skew_threshold(threshold, factor,
                                   prior.get("p50Bytes", 0.0))
    mx = int(prior.get("maxBytes", 0))
    if mx > eff:
        return True, (f"prior run observed maxBytes={mx} > {eff} "
                      f"(skew={prior.get('skew')}): skewed stage falls "
                      "back to dcn")
    return False, (f"prior run observed maxBytes={mx} <= {eff}: "
                   "no skew to split")


# ---------------------------------------------------------------------------
# Rule 3 (demote half): broadcast -> shuffled at runtime
# ---------------------------------------------------------------------------

class _MaterializedBuildExec(TpuExec):
    """An already-materialized broadcast build batch served as a
    single-partition exec, so a demoted join can hash-exchange the build
    side without recomputing it (the spillable handle stays owned by the
    broadcast exchange; this node only reads it)."""

    CONTRACT = exec_contract(schema="defined", partitioning="defined")
    METRICS = exec_metrics()

    def __init__(self, schema, handle):
        super().__init__()
        self._schema = schema
        self._handle = handle

    @property
    def schema(self):
        return self._schema

    @property
    def output_partitions(self) -> int:
        return 1

    def execute(self):
        def gen():
            batch = self._handle.get_batch()
            if batch.num_rows > 0:
                self.metrics.inc("numOutputRows", batch.num_rows)
                yield batch
        return [gen()]


def _chained(group):
    """One generator draining a group of partitions in order (the
    demoted join's output re-packed to the planned partition count)."""
    for part in group:
        for batch in part:
            yield batch


def maybe_demote_broadcast(join, bx, handle):
    """AQE join-strategy DEMOTION: the planner chose broadcast from
    estimated build bytes, but the materialized build is observed over
    ``threshold x demoteFactor`` device bytes — re-plan this join as a
    co-partitioned shuffled join over DCN hash exchanges, reusing the
    already-built batch as the build-side source. Returns the demoted
    join's partitions (re-packed to the planned partition count) or None
    when broadcast stands. An observed size inside the hysteresis dead
    band ``(threshold, threshold x factor]`` records a declined decision
    and keeps broadcast — repeat executions of a borderline build must
    not flap between strategies."""
    policy = getattr(join, "aqe_demote_policy", None)
    if not policy:
        return None
    thr = policy.get("threshold")
    factor = float(policy.get("factor", 2.0) or 2.0)
    if thr is None or thr < 0:
        return None
    try:
        observed = int(bx.metrics.resolve().get("dataSize", 0) or 0)
    except Exception:
        return None
    if observed <= 0 or observed <= thr:
        return None                    # broadcast stands, no record
    stage_id = getattr(bx, "stage_id", None)
    if join.how not in ("inner", "left", "left_semi", "left_anti"):
        # a demoted right/full outer would need the full-outer single-
        # partition merge the broadcast form already provides
        record_decision(join, "join-demote", applied=False,
                        stage_id=stage_id, before="broadcast",
                        reason=f"how={join.how} cannot re-shuffle")
        return None
    if observed <= int(thr * factor):
        record_decision(
            join, "join-demote", applied=False, stage_id=stage_id,
            before="broadcast",
            reason=(f"observed build {observed}B in hysteresis band "
                    f"({thr}B, {int(thr * factor)}B]: keeping broadcast"))
        return None

    from ..shuffle.exchange import TpuHashExchangeExec
    from .physical import TpuShuffledJoinExec
    n = max(1, int(policy.get("partitions", 0) or
                   join.children[0].output_partitions))
    build_src = _MaterializedBuildExec(bx.schema, handle)
    # keys re-bind against identical child schemas; BoundReferences pass
    # through bind_refs unchanged, so rebuilding from the join's bound
    # keys is safe. The replacement carries NO aqe_broadcast_threshold:
    # promoting it straight back would be the flap hysteresis exists to
    # prevent.
    rep = TpuShuffledJoinExec(
        TpuHashExchangeExec(join.children[0], n, list(join.left_keys),
                            plane="dcn"),
        TpuHashExchangeExec(build_src, n, list(join.right_keys),
                            plane="dcn"),
        join.how, list(join.left_keys), list(join.right_keys),
        join.condition)
    hook = _REPLAN_CORRUPTION_HOOK
    if hook is not None:
        hook(rep)
    from ..analysis import contracts
    contracts.validate_replan(rep, policy.get("validate", "warn"))
    record_decision(
        join, "join-demote", stage_id=stage_id,
        before="broadcast", after=f"shuffled[{n}]",
        reason=(f"observed build {observed}B > threshold {thr}B x "
                f"demoteFactor {factor}"))
    join._aqe_demoted = rep
    parts = rep.execute()
    orig = max(1, int(join.output_partitions))
    if len(parts) <= orig:
        return parts
    # re-pack to the partition count the parent planned around; strided
    # groups keep hash disjointness (each input partition lands in
    # exactly one output group)
    groups = [parts[i::orig] for i in range(orig)]
    return [_chained(g) for g in groups]


# ---------------------------------------------------------------------------
# Rule 4: drift feedback (plan-cache repeats plan from actuals)
# ---------------------------------------------------------------------------

_FEEDBACK: "OrderedDict[str, Dict[str, int]]" = OrderedDict()
_FEEDBACK_MAX = 256


def fingerprint_key(serving: Optional[Dict[str, Any]]) -> Optional[str]:
    fp = (serving or {}).get("fingerprint")
    return repr(fp) if fp is not None else None


def begin_query(session, exec_plan, serving) -> None:
    """Pre-execution hook (dataframe collect): clear the prior run's
    decision records tree-wide, then fold any stored observed
    cardinalities for this serving fingerprint back into the plan's
    ``est_rows`` — the drift-feedback rule. Best-effort: adaptive
    machinery must never fail a query."""
    try:
        clear_decisions(exec_plan)
        from .. import config as cfg
        conf = session.conf
        if not (conf.get(cfg.ADAPTIVE_ENABLED) and
                conf.get(cfg.ADAPTIVE_FEEDBACK_ENABLED)):
            return
        key = fingerprint_key(serving)
        if key is None:
            return
        with _history_mu:
            actuals = dict(_FEEDBACK.get(key) or ())
        if not actuals:
            return
        applied = 0
        for here, node in _walk_paths(exec_plan):
            rows = actuals.get(here)
            if rows is not None and getattr(node, "est_rows", None) is not None:
                if int(node.est_rows) != int(rows):
                    node.est_rows = int(rows)
                    applied += 1
        if applied:
            record_decision(
                exec_plan, "drift-feedback",
                before="estimated cardinalities", after=f"{applied} observed",
                reason=(f"re-planned {applied} operator estimate(s) from "
                        "the previous execution of this fingerprint"))
    except Exception:
        log.debug("aqe.begin_query failed", exc_info=True)


def note_execution(session, exec_plan, serving) -> None:
    """Post-execution hook: store this run's observed per-operator
    cardinalities and total exchange bytes under the serving
    fingerprint, feeding the NEXT execution's drift feedback and the
    service-admission cost weighting. Best-effort."""
    try:
        key = fingerprint_key(serving)
        if key is None:
            return
        actuals: Dict[str, int] = {}
        for here, node in _walk_paths(exec_plan):
            if getattr(node, "est_rows", None) is None:
                continue
            try:
                rows = node.metrics.resolve().get("numOutputRows")
            except Exception:
                continue
            if rows:
                actuals[here] = int(rows)
        cost = 0
        from ..shuffle.exchange import collect_stage_stats
        for st in collect_stage_stats(exec_plan):
            cost += int(st.get("totalBytes", 0) or 0)
        with _history_mu:
            if actuals:
                _FEEDBACK.pop(key, None)
                _FEEDBACK[key] = actuals
                while len(_FEEDBACK) > _FEEDBACK_MAX:
                    _FEEDBACK.popitem(last=False)
            _COSTS.pop(key, None)
            _COSTS[key] = cost
            while len(_COSTS) > _COSTS_MAX:
                _COSTS.popitem(last=False)
        _maybe_checkpoint(session, key, actuals, cost)
    except Exception:
        log.debug("aqe.note_execution failed", exc_info=True)


# ---------------------------------------------------------------------------
# Feedback checkpoint (docs/compile.md §5: the cold-path killer's AQE leg)
# ---------------------------------------------------------------------------
#
# The drift-feedback bank only helps a REPEAT execution — which a fresh
# process never is. With a compile cache dir configured, each
# note_execution appends its fingerprint's actuals as one JSONL line
# beside the fused-program signature index; bootstrap reloads it
# (reload_checkpoint), so the first execution of a known fingerprint in
# a new process already plans from observed cardinalities. Appends are
# single-line (torn-tolerant on read: bad lines skip); the file compacts
# by atomic rename when it outgrows a few banks' worth of lines, so it
# stays bounded regardless of process count or uptime.

#: checkpoint filename, beside compile_cache.INDEX_NAME in the cache dir
CHECKPOINT_NAME = "aqe_feedback.jsonl"

#: compact (rewrite from the live bank) past this many appended lines
_CHECKPOINT_MAX_LINES = 4 * _FEEDBACK_MAX

# appended-lines estimate for the compaction trigger; None until the
# first append counts the existing file (GIL-atomic int, advisory only)
_ckpt_lines: Optional[int] = None


def _checkpoint_path() -> Optional[str]:
    import os
    from ..exec import compile_cache
    d = compile_cache.active_dir()
    if not d:
        return None
    return os.path.join(d, CHECKPOINT_NAME)


def _checkpoint_enabled(conf) -> bool:
    try:
        from .. import config as cfg
        return bool(conf.get(cfg.ADAPTIVE_FEEDBACK_CHECKPOINT))
    except Exception:
        return True


def _maybe_checkpoint(session, key: str, actuals: Dict[str, int],
                      cost: int) -> None:
    """Append one fingerprint's observation to the checkpoint (no-op
    without a cache dir or with the conf off). File I/O runs OUTSIDE
    ``_history_mu``; a failed write only costs the next process its
    head start."""
    global _ckpt_lines
    try:
        if not _checkpoint_enabled(session.conf):
            return
        path = _checkpoint_path()
        if path is None or not actuals:
            return
        import json
        import os
        if _ckpt_lines is None:
            try:
                with open(path) as f:
                    _ckpt_lines = sum(1 for _ in f)
            except OSError:
                _ckpt_lines = 0
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "a") as f:
            f.write(json.dumps({"key": key, "actuals": actuals,
                                "cost": int(cost)}) + "\n")
        _ckpt_lines += 1
        if _ckpt_lines > _CHECKPOINT_MAX_LINES:
            _compact_checkpoint(path)
    except Exception:
        log.debug("aqe feedback checkpoint append failed", exc_info=True)


def _compact_checkpoint(path: str) -> None:
    """Rewrite the checkpoint from the live bank via atomic rename (a
    reader sees either the old file or the new one, never a torn
    middle)."""
    global _ckpt_lines
    import json
    import os
    with _history_mu:
        entries = [(k, dict(v), int(_COSTS.get(k, 0)))
                   for k, v in _FEEDBACK.items()]
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        for k, actuals, cost in entries:
            f.write(json.dumps({"key": k, "actuals": actuals,
                                "cost": cost}) + "\n")
    os.replace(tmp, path)
    _ckpt_lines = len(entries)


def reload_checkpoint(conf) -> int:
    """Fold the persisted feedback bank back in (session bootstrap).
    Last line wins per fingerprint; torn/bad lines skip; entries already
    observed LIVE in this process are not overwritten (live is newer).
    Returns the number of fingerprints loaded."""
    try:
        if not _checkpoint_enabled(conf):
            return 0
        path = _checkpoint_path()
        if path is None:
            return 0
        import json
        entries: Dict[str, Dict[str, Any]] = {}
        last_pos: Dict[str, int] = {}
        try:
            with open(path) as f:
                for pos, line in enumerate(f):
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        ent = json.loads(line)
                        key = ent["key"]
                        actuals = {str(p): int(r)
                                   for p, r in dict(ent["actuals"]).items()}
                    except Exception:
                        continue       # torn tail / bad line: skip
                    entries[key] = {"actuals": actuals,
                                    "cost": int(ent.get("cost", 0) or 0)}
                    last_pos[key] = pos
        except OSError:
            return 0
        loaded = 0
        with _history_mu:
            # newest file entries win the bounded slots: insert in
            # LAST-OCCURRENCE order keyed by fingerprint, NOT dict
            # (first-seen) order — a compacted vs an appended file with
            # the same final content must produce the same bank, so
            # later (newer) lines land later in the LRU regardless of
            # where a key first appeared
            for key in sorted(entries, key=last_pos.__getitem__):
                ent = entries[key]
                if key not in _FEEDBACK and ent["actuals"]:
                    _FEEDBACK[key] = ent["actuals"]
                    loaded += 1
                if key not in _COSTS and ent["cost"]:
                    _COSTS[key] = ent["cost"]
            while len(_FEEDBACK) > _FEEDBACK_MAX:
                _FEEDBACK.popitem(last=False)
            while len(_COSTS) > _COSTS_MAX:
                _COSTS.popitem(last=False)
        return loaded
    except Exception:
        log.debug("aqe feedback checkpoint reload failed", exc_info=True)
        return 0


# ---------------------------------------------------------------------------
# Service-admission cost weighting (docs/service.md)
# ---------------------------------------------------------------------------

def observed_cost_bytes(fingerprint_key: Optional[str]) -> int:
    """Total exchange bytes the fingerprint's last execution moved (0
    when never observed)."""
    if not fingerprint_key:
        return 0
    with _history_mu:
        return int(_COSTS.get(fingerprint_key, 0))


def admission_cost_units(fingerprint_key: Optional[str],
                         expensive_bytes: int) -> int:
    """Queue-slot cost of admitting a query whose plan fingerprint was
    previously observed: ``1 + observedBytes // expensiveBytes``. An
    unknown fingerprint — or cost weighting disabled
    (``service.admission.expensiveBytes`` = 0) — charges the flat 1."""
    if not expensive_bytes or expensive_bytes <= 0:
        return 1
    b = observed_cost_bytes(fingerprint_key)
    if b <= 0:
        return 1
    return 1 + int(b) // int(expensive_bytes)


def reset_for_tests() -> None:
    """Drop every cross-execution table (unit-test isolation)."""
    global _ckpt_lines
    _ckpt_lines = None
    with _history_mu:
        _STAGE_HISTORY.clear()
        _FEEDBACK.clear()
        _COSTS.clear()
