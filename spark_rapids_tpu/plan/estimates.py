"""Planner row estimates + estimate-vs-actual drift.

The planner already carries a size-only statistics visitor
(``logical.stats_bytes``, the broadcast decision's input); this module is
its ROW twin on the EXEC tree, annotated at optimization time and
compared against executed actuals afterwards — the cardinality-feedback
groundwork (docs/observability.md §8):

* :func:`annotate_estimates` — called by ``Overrides.apply`` after
  conversion: walks the converted exec tree bottom-up and stamps
  ``node.est_rows`` from leaf cardinalities (arrow tables, cached
  handles, file byte sizes, range bounds) and classic per-operator
  heuristics (filter selectivity 0.25, inner join = max side, limit =
  min(n, child), expand = child × projections, ...). Deliberately crude:
  drift against these estimates is the SIGNAL the report exists to
  surface, and what a future cardinality-feedback loop corrects.
* :func:`drift_report` — estimate vs the executed ``numOutputRows``
  actual per node, with the drift ratio (actual/estimate) and a flag
  when it crosses ``spark.rapids.tpu.sql.observability.driftThreshold``
  in either direction.
* :func:`drift_annotations` — the same data shaped as EXPLAIN ANALYZE
  per-node annotation lines (the fusion_annotations path convention).

Plan-cache note: estimates ride the cached exec tree (structural, not
data-dependent beyond scan cardinalities at plan time), so a cache hit
keeps its original estimates while actuals refresh per execution —
exactly the comparison a repeated misestimate should keep showing.
"""

from __future__ import annotations

from typing import Dict, List, Optional

#: the classic Selinger-style default selectivity for an un-modeled
#: predicate — deliberately simple; the drift report measures how wrong
#: it is per query
FILTER_SELECTIVITY = 0.25
#: per-row explode fan-out guess for generators
GENERATE_FANOUT = 4.0


def _leaf_rows(node) -> Optional[float]:
    """Leaf cardinality where the plan actually knows it."""
    name = type(node).__name__
    if name == "TpuLocalScanExec":
        table = getattr(node, "table", None)
        if table is not None and hasattr(table, "num_rows"):
            return float(table.num_rows)
    if name == "TpuCachedScanExec":
        handle = getattr(getattr(node, "plan", None), "handle", None)
        if handle is not None:
            try:
                return float(int(handle.num_rows))
            except Exception:
                return None
    if name == "TpuRangeExec":
        try:
            step = node.step or 1
            return float(max(0, -(-(node.end - node.start) // step)))
        except Exception:
            return None
    if name == "TpuFileScanExec":
        plan = getattr(node, "plan", None)
        if plan is None:
            return None
        try:
            nbytes = plan.stats_bytes()
            if nbytes >= (1 << 60):
                return None            # unknown-size sentinel
            width = max(8, sum(
                getattr(f.dtype, "byte_width", 0) or 8
                for f in node.schema))
            return float(max(1, nbytes // width))
        except Exception:
            return None
    return None


def _estimate(node, child_est: List[Optional[float]]) -> Optional[float]:
    """One node's output-row estimate from its children's (None =
    unknown; unknown children poison everything above them — a made-up
    number would turn the drift report into noise)."""
    name = type(node).__name__
    leaf = _leaf_rows(node)
    if leaf is not None:
        return leaf
    c0 = child_est[0] if child_est else None

    if name in ("TpuFilterExec",):
        return None if c0 is None else max(1.0, c0 * FILTER_SELECTIVITY)
    if name == "TpuWholeStageExec":
        # the fused chain collapsed its member filters away: apply the
        # selectivity once per folded filter step
        if c0 is None:
            return None
        steps = getattr(getattr(node, "chain", None), "steps", ())
        n_filters = sum(1 for s in steps if s and s[0] == "filter")
        return max(1.0, c0 * (FILTER_SELECTIVITY ** n_filters))
    if name in ("TpuSortMergeJoinExec", "TpuShuffledJoinExec",
                "TpuMeshJoinExec"):
        left, right = (child_est + [None, None])[:2]
        if left is None or right is None:
            return None
        how = getattr(node, "how", "inner")
        if how in ("left_semi", "left_anti", "left"):
            return left
        if how == "right":
            return right
        if how == "full":
            return left + right
        return max(left, right)        # inner equi-join: FK-side guess
    if name == "TpuCrossJoinExec":
        left, right = (child_est + [None, None])[:2]
        return None if left is None or right is None else left * right
    if name == "TpuHashAggregateExec":
        grouping = getattr(node, "grouping", None)
        if not grouping:
            return 1.0                 # ungrouped aggregate: one row
        return c0                      # grouped: child upper bound
    if name in ("TpuMeshGroupByExec",):
        return c0
    if name == "TpuLimitExec":
        n = getattr(node, "n", None)
        if n is None:
            return c0
        return float(n) if c0 is None else min(float(n), c0)
    if name == "TpuUnionExec":
        if any(e is None for e in child_est):
            return None
        return float(sum(child_est))
    if name == "TpuExpandExec":
        nproj = len(getattr(node, "projections", ()) or ())
        return None if c0 is None else c0 * max(1, nproj)
    if name == "TpuGenerateExec":
        return None if c0 is None else c0 * GENERATE_FANOUT
    if name in ("TpuMapInPandasExec", "TpuFlatMapGroupsInPandasExec",
                "TpuFlatMapCoGroupsInPandasExec",
                "TpuAggregateInPandasExec", "CpuFallbackExec",
                "CpuOpBridgeExec", "TpuWriteFileExec"):
        return None                    # opaque: a UDF can emit anything
    # passthrough default (project, sort, coalesce, exchanges, window,
    # broadcast, distinct bridges): the child's estimate
    return c0


def annotate_estimates(root) -> None:
    """Stamp ``est_rows`` bottom-up on every node the heuristics can
    price (others carry no attribute and render no drift line). Never
    raises — planning must not fail on observability."""

    def walk(node) -> Optional[float]:
        child_est = [walk(c) for c in getattr(node, "children", ())]
        try:
            est = _estimate(node, child_est)
        except Exception:
            est = None
        if est is not None:
            node.est_rows = int(est)
        return est

    try:
        walk(root)
    except Exception:
        pass


def _actual_rows(node) -> Optional[int]:
    try:
        v = node.metrics.get("numOutputRows", None)
        return None if v is None else int(v)
    except Exception:
        return None


def _drift_threshold(conf=None) -> float:
    from .. import config as cfg
    try:
        conf = conf or cfg.TpuConf()
        return float(conf.get(cfg.OBSERVABILITY_DRIFT_THRESHOLD))
    except Exception:
        return 4.0


def drift_report(root, conf=None) -> List[Dict]:
    """Estimate-vs-actual per executed node: ``[{operator, path,
    estRows, actualRows, ratio, flagged}]``, worst drift first. Only
    nodes that both carry an estimate and actually emitted a row count
    appear — a cached/short-circuited node has nothing to compare."""
    threshold = _drift_threshold(conf)
    out: List[Dict] = []

    def walk(node, path: str, idx: Optional[int] = None) -> None:
        name = type(node).__name__
        here = f"{path}/{idx}.{name}" if path else name
        est = getattr(node, "est_rows", None)
        actual = _actual_rows(node)
        if est is not None and actual is not None:
            # both sides floored at 1: a perfectly-estimated EMPTY node
            # (est=0, actual=0) must read as ratio 1.0, not as the
            # worst misestimate in the report
            ratio = round(max(1, actual) / max(1, est), 4)
            flagged = ratio >= threshold or ratio <= 1.0 / threshold
            out.append({"operator": name, "path": here,
                        "estRows": int(est), "actualRows": int(actual),
                        "ratio": ratio, "flagged": flagged})
        for i, c in enumerate(getattr(node, "children", ())):
            walk(c, here, i)

    walk(root, "")
    out.sort(key=lambda d: -max(d["ratio"], 1.0 / max(d["ratio"], 1e-9)))
    return out


def drift_annotations(root, conf=None) -> Dict[str, List[str]]:
    """The drift comparison as per-node EXPLAIN ANALYZE annotation lines
    keyed by the contract-validator path convention; misestimates past
    the threshold lead with ``! drift`` so they read as diagnostics."""
    threshold = _drift_threshold(conf)
    out: Dict[str, List[str]] = {}
    for d in drift_report(root, conf=conf):
        line = (f"rows: est={d['estRows']} actual={d['actualRows']} "
                f"drift={d['ratio']}x")
        if d["flagged"]:
            line = (f"! drift: {line} (past threshold {threshold}x — "
                    "misestimate)")
        out.setdefault(d["path"], []).append(line)
    return out
