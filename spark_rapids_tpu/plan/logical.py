"""Logical plan: the Catalyst-plan analog that the rewrite engine consumes.

The reference plugs into Spark and receives Catalyst physical plans
(GpuOverrides.apply, GpuOverrides.scala:1991-2010). This framework is
standalone, so it owns a small logical algebra with the same operator
vocabulary Spark produces for the supported surface: scan / project / filter /
aggregate / join / sort / limit / union / range / expand / generate / window /
repartition / write.

Analysis (``analyze``) mirrors the slice of Catalyst the plugin depends on:
name resolution (ColumnRef -> BoundReference), numeric type coercion via
implicit Casts (dtypes.promote), and schema computation.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

from ..columnar import dtypes as dt
from ..ops import expressions as ex
from ..ops.cast import Cast


class SortOrder:
    def __init__(self, child: ex.Expression, ascending: bool = True,
                 nulls_first: Optional[bool] = None):
        self.child = child
        self.ascending = ascending
        # Spark default: NULLS FIRST for asc, NULLS LAST for desc
        self.nulls_first = ascending if nulls_first is None else nulls_first

    def __repr__(self):
        return (f"{self.child!r} {'ASC' if self.ascending else 'DESC'} "
                f"NULLS {'FIRST' if self.nulls_first else 'LAST'}")


class AggregateExpression(ex.Expression):
    """Wrapper marking an aggregate call inside an Aggregate node's output list
    (GpuDeclarativeAggregate analog, AggregateFunctions.scala)."""

    AGG_OPS = ("count", "count_star", "sum", "min", "max", "avg", "first", "last")

    def __init__(self, op: str, child: Optional[ex.Expression],
                 ignore_nulls: bool = True, distinct: bool = False):
        super().__init__(*([child] if child is not None else []))
        assert op in self.AGG_OPS, op
        self.op = op
        self.ignore_nulls = ignore_nulls
        self.distinct = distinct

    @property
    def dtype(self) -> dt.DType:
        from ..ops.aggregates import result_dtype
        child_t = self.children[0].dtype if self.children else None
        return result_dtype(self.op, child_t)

    @property
    def nullable(self) -> bool:
        return self.op not in ("count", "count_star")

    def eval(self, batch):
        raise RuntimeError("AggregateExpression is planned, not evaluated directly")

    def __repr__(self):
        arg = repr(self.children[0]) if self.children else "*"
        return f"{self.op}({'DISTINCT ' if self.distinct else ''}{arg})"


# ---------------------------------------------------------------------------
# Plan nodes
# ---------------------------------------------------------------------------

class LogicalPlan:
    def __init__(self, *children: "LogicalPlan"):
        self.children: List[LogicalPlan] = list(children)
        self._schema: Optional[dt.Schema] = None

    @property
    def schema(self) -> dt.Schema:
        if self._schema is None:
            self._schema = self._compute_schema()
        return self._schema

    def _compute_schema(self) -> dt.Schema:
        raise NotImplementedError

    @property
    def name(self) -> str:
        return type(self).__name__

    def expressions(self) -> List[ex.Expression]:
        return []

    def stats_bytes(self) -> int:
        """Size-in-bytes estimate for join-strategy selection (Catalyst's
        SizeInBytesOnlyStatsPlanVisitor role: leaf sizes propagate up, the
        broadcast decision compares against autoBroadcastJoinThreshold)."""
        if not self.children:
            return 1 << 60          # unknown leaf: never broadcast
        return sum(c.stats_bytes() for c in self.children)

    def __repr__(self):
        return self._tree_string(0)

    def _node_string(self) -> str:
        return self.name

    def _tree_string(self, depth: int) -> str:
        out = "  " * depth + self._node_string()
        for c in self.children:
            out += "\n" + c._tree_string(depth + 1)
        return out


class LocalScan(LogicalPlan):
    """In-memory data scan (createDataFrame analog).

    ``base_data`` is the ORIGINAL registered table when this scan is a
    column-pruned view of it (the planner's pruning rule builds a new
    ``pa.Table`` per query via select(); the base object is the stable
    identity the scan-level device cache keys and lifetime-tracks by).
    The arrow table itself is immutable and SHARED on deepcopy: plan
    analysis copies trees per query, and copying a multi-GB table per
    query dominated end-to-end time (5s of the q6 SF0.5 wall clock was
    table deepcopy)."""

    def __init__(self, data: "pyarrow.Table", name: str = "local",
                 base_data=None):
        super().__init__()
        self.data = data
        self.scan_name = name
        self.base_data = base_data if base_data is not None else data

    def __deepcopy__(self, memo):
        c = LocalScan(self.data, self.scan_name, self.base_data)
        memo[id(self)] = c
        return c

    def _compute_schema(self) -> dt.Schema:
        return dt.Schema([
            dt.Field(n, dt.from_arrow(t))
            for n, t in zip(self.data.schema.names, self.data.schema.types)])

    def stats_bytes(self) -> int:
        return self.data.nbytes

    def _node_string(self):
        return f"LocalScan [{', '.join(self.schema.names())}]"


class _CacheOwner:
    """Shared ownership token for a cached batch: every CachedScan copy
    (plan analysis deep-copies trees) references the SAME owner, and a
    weakref finalizer on it closes the spillable handle when the last
    reference — frames, derived plans, executed-plan captures — dies.
    No explicit unpersist is required for reclamation (Spark's
    cache-lifetime contract: unpersist is advisory, GC is the backstop)."""

    def __init__(self, handle):
        import weakref
        from ..exec.spill import defer_finalizer
        self.handle = handle
        # enqueue-only finalizer: handle.close takes catalog/watermark
        # locks, which a GC callback may interrupt MID-HOLD on its own
        # thread (exec/spill.defer_finalizer — the inline close would
        # self-deadlock); the engine drains at safe points
        weakref.finalize(self, defer_finalizer, handle.close)


class CachedScan(LogicalPlan):
    """Scan over a df.cache()-materialized columnar batch held in the
    SPILLABLE store: queries read the device-resident (or re-promoted)
    batch with zero host conversion — the reference's cached-table path
    (GpuInMemoryTableScanExec, spark310 shim). Falls back to an arrow
    rendering for the CPU engine."""

    def __init__(self, schema: "dt.Schema", owner: "_CacheOwner",
                 name: str = "cached"):
        super().__init__()
        # NOT ``_schema`` — analyze() nulls that cache slot to force
        # recomputation, which must return this fixed schema again
        self._fixed_schema = schema
        self.owner = owner
        self.scan_name = name
        self._arrow = None

    @property
    def handle(self):
        return self.owner.handle

    def _compute_schema(self) -> dt.Schema:
        return self._fixed_schema

    def stats_bytes(self) -> int:
        return self.handle.size_bytes

    @property
    def data(self):
        """Arrow rendering for CPU-engine / host consumers (built once)."""
        if self._arrow is None:
            self._arrow = self.handle.get_batch().to_arrow()
        return self._arrow

    def __deepcopy__(self, memo):
        # plan analysis deep-copies trees; the owner (and its spillable
        # handle) is SHARED state by design — never copied
        c = CachedScan(self._fixed_schema, self.owner, self.scan_name)
        c._arrow = self._arrow
        return c

    def _node_string(self):
        return f"InMemoryTableScan [{', '.join(self.schema.names())}]"


class FileScan(LogicalPlan):
    """File source scan (GpuFileSourceScanExec / GpuBatchScanExec analog)."""

    def __init__(self, fmt: str, paths: List[str],
                 schema: Optional[dt.Schema] = None,
                 options: Optional[Dict[str, Any]] = None,
                 pushed_filters: Optional[List[ex.Expression]] = None):
        super().__init__()
        self.fmt = fmt                     # parquet / orc / csv
        self.paths = paths
        self._file_schema = schema
        self.options = options or {}
        self.pushed_filters = pushed_filters or []

    def _compute_schema(self) -> dt.Schema:
        if self._file_schema is None:
            from ..io import infer_schema
            self._file_schema = infer_schema(self.fmt, self.paths, self.options)
        return self._file_schema

    def stats_bytes(self) -> int:
        """Sum of on-disk file sizes (FileSourceScan sizeInBytes analog);
        parquet compression makes this an underestimate of in-memory size,
        matching Spark's behavior (it applies the same raw file size)."""
        import os
        from ..io import expand_paths
        try:
            return sum(os.path.getsize(f) for f in expand_paths(self.paths))
        except OSError:
            return 1 << 60

    def _node_string(self):
        return f"FileScan {self.fmt} {self.paths}"


class Project(LogicalPlan):
    def __init__(self, child: LogicalPlan, exprs: List[ex.Expression]):
        super().__init__(child)
        self.exprs = exprs

    def expressions(self):
        return self.exprs

    def _compute_schema(self) -> dt.Schema:
        return dt.Schema([
            dt.Field(ex.output_name(e, i), e.dtype, e.nullable)
            for i, e in enumerate(self.exprs)])

    def _node_string(self):
        return f"Project [{', '.join(map(repr, self.exprs))}]"


class Filter(LogicalPlan):
    def __init__(self, child: LogicalPlan, condition: ex.Expression):
        super().__init__(child)
        self.condition = condition

    def expressions(self):
        return [self.condition]

    def _compute_schema(self) -> dt.Schema:
        return self.children[0].schema

    def _node_string(self):
        return f"Filter {self.condition!r}"


class Aggregate(LogicalPlan):
    """Group-by aggregate. ``aggregate_exprs`` are the output expressions;
    aggregate calls appear as AggregateExpression subtrees (possibly wrapped
    in Alias / arithmetic result expressions)."""

    def __init__(self, child: LogicalPlan, grouping: List[ex.Expression],
                 aggregate_exprs: List[ex.Expression]):
        super().__init__(child)
        self.grouping = grouping
        self.aggregate_exprs = aggregate_exprs

    def expressions(self):
        return self.grouping + self.aggregate_exprs

    def _compute_schema(self) -> dt.Schema:
        return dt.Schema([
            dt.Field(ex.output_name(e, i), e.dtype, e.nullable)
            for i, e in enumerate(self.aggregate_exprs)])

    def _node_string(self):
        return (f"Aggregate key=[{', '.join(map(repr, self.grouping))}] "
                f"out=[{', '.join(map(repr, self.aggregate_exprs))}]")


class Join(LogicalPlan):
    JOIN_TYPES = ("inner", "left", "right", "full", "left_semi", "left_anti",
                  "cross")

    def __init__(self, left: LogicalPlan, right: LogicalPlan, how: str,
                 condition: Optional[ex.Expression] = None,
                 using: Optional[List[str]] = None):
        super().__init__(left, right)
        assert how in self.JOIN_TYPES, how
        self.how = how
        self.condition = condition
        self.using = using

    def expressions(self):
        return [self.condition] if self.condition is not None else []

    def _compute_schema(self) -> dt.Schema:
        left, right = self.children[0].schema, self.children[1].schema
        if self.how in ("left_semi", "left_anti"):
            return left
        fields = list(left.fields)
        l_null = self.how == "full"
        r_null = self.how in ("left", "full")
        if l_null:
            fields = [dt.Field(f.name, f.dtype, True) for f in fields]
        rf = [dt.Field(f.name, f.dtype, True if r_null else f.nullable)
              for f in right.fields]
        return dt.Schema(fields + rf)

    def _node_string(self):
        return f"Join {self.how} on={self.condition!r}"


class Sort(LogicalPlan):
    def __init__(self, child: LogicalPlan, orders: List[SortOrder],
                 is_global: bool = True):
        super().__init__(child)
        self.orders = orders
        self.is_global = is_global

    def expressions(self):
        return [o.child for o in self.orders]

    def _compute_schema(self) -> dt.Schema:
        return self.children[0].schema

    def _node_string(self):
        return f"Sort [{', '.join(map(repr, self.orders))}] global={self.is_global}"


class Limit(LogicalPlan):
    def __init__(self, child: LogicalPlan, n: int):
        super().__init__(child)
        self.n = n

    def _compute_schema(self) -> dt.Schema:
        return self.children[0].schema

    def _node_string(self):
        return f"Limit {self.n}"


class Union(LogicalPlan):
    def __init__(self, *children: LogicalPlan):
        super().__init__(*children)

    def _compute_schema(self) -> dt.Schema:
        return self.children[0].schema


class Range(LogicalPlan):
    """range(start, end, step) -> single bigint column 'id' (GpuRangeExec)."""

    def __init__(self, start: int, end: int, step: int = 1,
                 num_partitions: int = 1):
        super().__init__()
        self.start, self.end, self.step = start, end, step
        self.num_partitions = num_partitions

    def _compute_schema(self) -> dt.Schema:
        return dt.Schema([dt.Field("id", dt.INT64, nullable=False)])

    def stats_bytes(self) -> int:
        n = max(0, -(-(self.end - self.start) // self.step)) if self.step else 0
        return n * 8

    def _node_string(self):
        return f"Range({self.start}, {self.end}, {self.step})"


class Distinct(LogicalPlan):
    def __init__(self, child: LogicalPlan):
        super().__init__(child)

    def _compute_schema(self) -> dt.Schema:
        return self.children[0].schema


class Repartition(LogicalPlan):
    def __init__(self, child: LogicalPlan, num_partitions: int,
                 by: Optional[List[ex.Expression]] = None):
        super().__init__(child)
        self.num_partitions = num_partitions
        self.by = by

    def expressions(self):
        return self.by or []

    def _compute_schema(self) -> dt.Schema:
        return self.children[0].schema

    def _node_string(self):
        return f"Repartition {self.num_partitions} by={self.by}"


class Expand(LogicalPlan):
    """Grouping-sets expansion (GpuExpandExec): each projection list is applied
    to every input row."""

    def __init__(self, child: LogicalPlan, projections: List[List[ex.Expression]],
                 output_names: List[str]):
        super().__init__(child)
        self.projections = projections
        self.output_names = output_names

    def expressions(self):
        return [e for p in self.projections for e in p]

    def _compute_schema(self) -> dt.Schema:
        first = self.projections[0]
        return dt.Schema([
            dt.Field(n, e.dtype, True)
            for n, e in zip(self.output_names, first)])


class Generate(LogicalPlan):
    """explode/posexplode generator (GpuGenerateExec.scala): output = child
    columns ++ [pos?, col] with one row per array element; NULL/empty arrays
    produce no rows (explode; outer variants out of scope)."""

    def __init__(self, child: LogicalPlan, generator: ex.Expression,
                 col_name: str = "col", pos_name: str = "pos"):
        super().__init__(child)
        self.generator = generator          # ops.arrays.Explode
        self.col_name = col_name
        self.pos_name = pos_name

    def expressions(self):
        return [self.generator]

    def _compute_schema(self) -> dt.Schema:
        fields = list(self.children[0].schema.fields)
        if getattr(self.generator, "pos", False):
            fields.append(dt.Field(self.pos_name, dt.INT32, False))
        fields.append(dt.Field(self.col_name, self.generator.dtype, True))
        return dt.Schema(fields)

    def _node_string(self):
        return f"Generate [{self.generator!r}]"


class MapInPandas(LogicalPlan):
    """mapInPandas(fn, schema) (GpuMapInPandasExec analog): the user fn
    maps an iterator of pandas DataFrames to an iterator of DataFrames."""

    def __init__(self, child: LogicalPlan, fn, schema: dt.Schema):
        super().__init__(child)
        self.fn = fn
        self.out_schema = schema

    def _compute_schema(self) -> dt.Schema:
        return self.out_schema

    def _node_string(self):
        return f"MapInPandas [{getattr(self.fn, '__name__', 'fn')}]"


class FlatMapGroupsInPandas(LogicalPlan):
    """groupBy(keys).applyInPandas(fn, schema)
    (GpuFlatMapGroupsInPandasExec analog, sql-plugin python/*.scala +
    GpuOverrides.scala:1825-1953): every group's rows become one pandas
    DataFrame; ``fn(pdf)`` or ``fn(key_tuple, pdf)`` maps it to an output
    frame of ``schema``."""

    def __init__(self, child: LogicalPlan, grouping: List[ex.Expression],
                 fn, schema: dt.Schema):
        super().__init__(child)
        self.grouping = grouping
        self.fn = fn
        self.out_schema = schema

    def expressions(self):
        return list(self.grouping)

    def _compute_schema(self) -> dt.Schema:
        return self.out_schema

    def _node_string(self):
        return ("FlatMapGroupsInPandas "
                f"[{getattr(self.fn, '__name__', 'fn')}]")


class AggregateInPandas(LogicalPlan):
    """groupBy(keys).agg(grouped-agg pandas UDFs)
    (GpuAggregateInPandasExec analog): one fn(Series...) -> scalar call
    per (group, udf). Output schema = key columns + one column per udf."""

    def __init__(self, child: LogicalPlan, grouping: List[ex.Expression],
                 aggs: List[ex.Expression], names: List[str]):
        super().__init__(child)
        self.grouping = grouping
        self.aggs = aggs                 # PandasAggUDF expressions
        self.out_names = names           # key names + agg output names

    def expressions(self):
        return list(self.grouping) + list(self.aggs)

    def _compute_schema(self) -> dt.Schema:
        fields = [dt.Field(self.out_names[i], g.dtype, True)
                  for i, g in enumerate(self.grouping)]
        nk = len(self.grouping)
        fields += [dt.Field(self.out_names[nk + i], a.dtype, True)
                   for i, a in enumerate(self.aggs)]
        return dt.Schema(fields)

    def _node_string(self):
        return f"AggregateInPandas [{', '.join(map(repr, self.aggs))}]"


class FlatMapCoGroupsInPandas(LogicalPlan):
    """cogroup(...).applyInPandas (GpuFlatMapCoGroupsInPandasExec analog):
    both sides group on their keys; ``fn(left_pdf, right_pdf)`` (or
    ``fn(key, l, r)``) maps each key's pair of frames to an output
    frame."""

    def __init__(self, left: LogicalPlan, right: LogicalPlan,
                 left_grouping: List[ex.Expression],
                 right_grouping: List[ex.Expression], fn,
                 schema: dt.Schema):
        super().__init__(left, right)
        self.left_grouping = left_grouping
        self.right_grouping = right_grouping
        self.fn = fn
        self.out_schema = schema

    def expressions(self):
        return list(self.left_grouping) + list(self.right_grouping)

    def _compute_schema(self) -> dt.Schema:
        return self.out_schema

    def _node_string(self):
        return ("FlatMapCoGroupsInPandas "
                f"[{getattr(self.fn, '__name__', 'fn')}]")


class Window(LogicalPlan):
    """Window operator: adds window function columns to the child's output
    (GpuWindowExec). window_exprs: list of (name, WindowExpression)."""

    def __init__(self, child: LogicalPlan, window_exprs: List[Tuple[str, Any]]):
        super().__init__(child)
        self.window_exprs = window_exprs

    def expressions(self):
        return [w for _, w in self.window_exprs]

    def _compute_schema(self) -> dt.Schema:
        fields = list(self.children[0].schema.fields)
        for name, w in self.window_exprs:
            fields.append(dt.Field(name, w.dtype, True))
        return dt.Schema(fields)


class WriteFile(LogicalPlan):
    """File write command (GpuDataWritingCommandExec analog)."""

    def __init__(self, child: LogicalPlan, fmt: str, path: str,
                 mode: str = "error", options: Optional[Dict[str, Any]] = None,
                 partition_by: Optional[List[str]] = None):
        super().__init__(child)
        self.fmt = fmt
        self.path = path
        self.mode = mode
        self.options = options or {}
        self.partition_by = partition_by or []

    def _compute_schema(self) -> dt.Schema:
        return dt.Schema([])


# ---------------------------------------------------------------------------
# Analysis: resolve + coerce + validate
# ---------------------------------------------------------------------------

class AnalysisError(Exception):
    pass


def _resolve_expr(e: ex.Expression, schema: dt.Schema) -> ex.Expression:
    def fn(node):
        if isinstance(node, ex.ColumnRef):
            if node.col_name not in schema:
                raise AnalysisError(
                    f"cannot resolve column {node.col_name!r}; "
                    f"available: {schema.names()}")
            return node.resolve(schema)
        return None
    return e.transform(fn)


def _coerce(e: ex.Expression) -> ex.Expression:
    """Insert implicit casts for numeric binary ops & comparisons
    (TypeCoercion analog, the slice the plugin relies on)."""
    from ..ops import arithmetic as ar
    from ..ops import predicates as pr
    from ..ops import math_ops as mo
    from ..ops import conditionals as co

    def fn(node):
        if isinstance(node, (ar.BinaryArithmetic, pr.BinaryComparison,
                             pr.EqualNullSafe)):
            l, r = node.children
            lt, rt = l.dtype, r.dtype
            if lt == rt:
                return None
            if lt == dt.NULLTYPE:
                return node.with_children([Cast(l, rt), r])
            if rt == dt.NULLTYPE:
                return node.with_children([l, Cast(r, lt)])
            if lt.is_numeric and rt.is_numeric or \
                    {lt, rt} <= {dt.BOOL, *dt.NUMERIC_TYPES}:
                target = dt.promote(lt if lt != dt.BOOL else dt.INT8,
                                    rt if rt != dt.BOOL else dt.INT8)
                if isinstance(node, ar.Divide):
                    target = dt.FLOAT64
                nl = l if lt == target else Cast(l, target)
                nr = r if rt == target else Cast(r, target)
                return node.with_children([nl, nr])
            if {lt, rt} == {dt.STRING, dt.DATE} or {lt, rt} == {dt.STRING, dt.TIMESTAMP}:
                # string vs date/timestamp comparison: cast string side
                target = rt if lt == dt.STRING else lt
                nl = Cast(l, target) if lt == dt.STRING else l
                nr = Cast(r, target) if rt == dt.STRING else r
                return node.with_children([nl, nr])
            if (lt in (dt.DATE, dt.TIMESTAMP) and rt.is_integral) or \
                    (rt in (dt.DATE, dt.TIMESTAMP) and lt.is_integral):
                # int literal vs date/timestamp: reinterpret the int side
                # (dates store int32 days, timestamps int64 micros)
                target = lt if lt in (dt.DATE, dt.TIMESTAMP) else rt
                nl = l if lt == target else Cast(l, target)
                nr = r if rt == target else Cast(r, target)
                return node.with_children([nl, nr])
            raise AnalysisError(f"cannot coerce {lt} vs {rt} in {node!r}")
        if isinstance(node, ar.Divide):
            l, r = node.children
            if l.dtype.is_integral:
                return node.with_children([Cast(l, dt.FLOAT64), Cast(r, dt.FLOAT64)])
            return None
        if isinstance(node, mo.UnaryMath):
            c = node.children[0]
            if c.dtype != dt.FLOAT64:
                return node.with_children([Cast(c, dt.FLOAT64)])
            return None
        if isinstance(node, AggregateExpression) and node.children:
            c = node.children[0]
            if node.op in ("sum", "avg") and c.dtype == dt.BOOL:
                return node.with_children([Cast(c, dt.INT32)])
            return None
        if isinstance(node, (co.Coalesce, co.Least, co.Greatest, co.If,
                             co.CaseWhen)):
            return _coerce_branches(node)
        return None

    return e.transform(fn)


def _coerce_branches(node):
    """Unify branch result types for conditionals."""
    from ..ops import conditionals as co

    def value_positions():
        n = len(node.children)
        if isinstance(node, co.If):
            return [1, 2]
        if isinstance(node, co.CaseWhen):
            pos = [2 * i + 1 for i in range(node.num_branches)]
            if node.has_else:
                pos.append(n - 1)
            return pos
        return list(range(n))

    positions = value_positions()
    dts = [node.children[i].dtype for i in positions
           if node.children[i].dtype != dt.NULLTYPE]
    if not dts:
        return None
    target = dts[0]
    for t in dts[1:]:
        if t != target:
            target = dt.promote(target, t)
    changed = False
    new_children = list(node.children)
    for i in positions:
        c = new_children[i]
        if c.dtype != target:
            new_children[i] = Cast(c, target)
            changed = True
    if not changed:
        return None
    return node.with_children(new_children)


def analyze(plan: LogicalPlan) -> LogicalPlan:
    """Bottom-up resolve + coerce. Mutates expression references in place
    (plans are single-use builder products, like Catalyst's analyzed plans)."""
    for c in plan.children:
        analyze(c)
    child_schema = plan.children[0].schema if plan.children else None

    def ra(e):
        e = _resolve_expr(e, child_schema) if child_schema else e
        return _coerce(e)

    if isinstance(plan, Project):
        plan.exprs = [ra(e) for e in plan.exprs]
    elif isinstance(plan, Filter):
        plan.condition = ra(plan.condition)
        if plan.condition.dtype != dt.BOOL:
            raise AnalysisError(
                f"filter condition must be boolean, got {plan.condition.dtype}")
    elif isinstance(plan, Aggregate):
        # Resolution must not sever the grouping<->output identity link:
        # computed grouping keys (CASE/arithmetic) are matched BY IDENTITY
        # in the result projection (physical._rewrite_result), so any output
        # subtree that IS a grouping member pre-resolution must resolve to
        # the SAME object the grouping list resolves to.
        old_grouping = list(plan.grouping)
        plan.grouping = [ra(e) for e in plan.grouping]
        ident = {id(o): n for o, n in zip(old_grouping, plan.grouping)}

        def share_grouping(e):
            return e.transform_down(lambda n: ident.get(id(n)))

        plan.aggregate_exprs = [ra(share_grouping(e))
                                for e in plan.aggregate_exprs]
    elif isinstance(plan, Join):
        if plan.condition is not None:
            left, right = plan.children[0].schema, plan.children[1].schema
            merged = dt.Schema(list(left.fields) + list(right.fields))
            plan.condition = _coerce(_resolve_expr(plan.condition, merged))
    elif isinstance(plan, Sort):
        plan.orders = [SortOrder(ra(o.child), o.ascending, o.nulls_first)
                       for o in plan.orders]
    elif isinstance(plan, Repartition) and plan.by:
        plan.by = [ra(e) for e in plan.by]
    elif isinstance(plan, Expand):
        plan.projections = [[ra(e) for e in p] for p in plan.projections]
    elif isinstance(plan, Window):
        plan.window_exprs = [(n, w.resolve_refs(child_schema))
                             for n, w in plan.window_exprs]
    elif isinstance(plan, Generate):
        plan.generator = ra(plan.generator)
    elif isinstance(plan, FlatMapGroupsInPandas):
        plan.grouping = [ra(e) for e in plan.grouping]
    elif isinstance(plan, FlatMapCoGroupsInPandas):
        lsch = plan.children[0].schema
        rsch = plan.children[1].schema
        plan.left_grouping = [
            _coerce(_resolve_expr(e, lsch)) for e in plan.left_grouping]
        plan.right_grouping = [
            _coerce(_resolve_expr(e, rsch)) for e in plan.right_grouping]
    elif isinstance(plan, AggregateInPandas):
        plan.grouping = [ra(e) for e in plan.grouping]
        plan.aggs = [ra(e) for e in plan.aggs]
    plan._schema = None  # recompute after coercion
    return plan
