"""Plan-rewrite engine: the GpuOverrides / RapidsMeta analog.

Reference: ``GpuOverrides.scala:63-275,1656-2051`` (typed replacement-rule
registry, per-op enable confs, wrap -> tagForGpu -> explain -> convert) and
``RapidsMeta.scala:66-300`` (meta wrappers accumulating willNotWorkOnGpu
reasons; children-first tagging; convertIfNeeded for mixed plans).

Differences forced by being standalone: the input is our logical plan, not a
Spark physical plan, and the CPU side is the pandas engine (cpu/engine.py)
rather than stock Spark execs. The per-op conf keys
(``spark.rapids.tpu.sql.exec.<Op>`` / ``...expression.<Expr>``), incompat
gating, explain formatting, and fallback layering all mirror the reference.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Type

from .. import config as cfg
from ..analysis.contracts import exec_contract
from ..columnar import dtypes as dt
from ..ops import expressions as ex
from ..ops import arithmetic as ar
from ..ops import predicates as pr
from ..ops import conditionals as co
from ..ops import math_ops as mo
from ..ops import strings as st
from ..ops import datetime as dtm
from ..ops import hashing as hs
from ..ops.cast import Cast
from . import logical as lp
from . import physical as ph


# ---------------------------------------------------------------------------
# Expression rule registry (ExprRule analog, GpuOverrides.scala:129-137
# auto-generates the per-expression enable keys)
# ---------------------------------------------------------------------------

class ExprRule:
    def __init__(self, klass: Type[ex.Expression], incompat: Optional[str] = None,
                 disabled_reason: Optional[str] = None):
        self.klass = klass
        self.incompat = incompat
        self.disabled_reason = disabled_reason

    @property
    def conf_key(self) -> str:
        return f"spark.rapids.tpu.sql.expression.{self.klass.__name__}"


_EXPR_RULES: Dict[Type[ex.Expression], ExprRule] = {}


def _expr(klass, incompat: Optional[str] = None):
    _EXPR_RULES[klass] = ExprRule(klass, incompat)


for k in (ex.Literal, ex.ColumnRef, ex.BoundReference, ex.Alias,
          ar.Add, ar.Subtract, ar.Multiply, ar.Divide, ar.IntegralDivide,
          ar.Remainder, ar.Pmod, ar.UnaryMinus, ar.UnaryPositive, ar.Abs,
          pr.EqualTo, pr.NotEqual, pr.LessThan, pr.LessThanOrEqual,
          pr.GreaterThan, pr.GreaterThanOrEqual, pr.EqualNullSafe,
          pr.And, pr.Or, pr.Not, pr.IsNull, pr.IsNotNull, pr.IsNaN, pr.In,
          co.If, co.CaseWhen, co.Coalesce, co.Nvl, co.NullIf, co.Least,
          co.Greatest, Cast,
          mo.Floor, mo.Ceil, mo.Round, mo.Atan2,
          st.Length, st.Substring, st.ConcatStr, st.Contains, st.StartsWith,
          st.EndsWith, st.Like, st.StringLocate, st.StringReplace,
          st.StringTrim, st.StringTrimLeft, st.StringTrimRight,
          st.StringLPad, st.StringRPad,
          dtm.Year, dtm.Month, dtm.DayOfMonth, dtm.Quarter, dtm.DayOfWeek,
          dtm.WeekDay, dtm.DayOfYear, dtm.LastDay, dtm.Hour, dtm.Minute,
          dtm.Second, dtm.DateAdd, dtm.DateSub, dtm.DateDiff, dtm.AddMonths,
          dtm.UnixTimestamp, dtm.FromUnixTime, dtm.ToDate,
          hs.Murmur3Hash, hs.Md5, hs.MonotonicallyIncreasingID,
          hs.SparkPartitionID, hs.Rand,
          lp.AggregateExpression):
    _expr(k)

for sub in mo.UnaryMath.__subclasses__():
    _expr(sub)

from ..ops import window as _W  # noqa: E402
for k in (_W.WindowExpression, _W.RowNumber, _W.Rank, _W.DenseRank,
          _W.Lead, _W.Lag):
    _expr(k)

from ..ops import arrays as _AR  # noqa: E402
for k in (_AR.Explode, _AR.StringSplit, _AR.GetArrayItem, _AR.Size):
    _expr(k)

from ..ops import maps as _MP  # noqa: E402
for k in (_MP.CreateMap, _MP.GetMapValue, _MP.GetItem, _MP.MapKeys,
          _MP.MapValues):
    _expr(k)

from ..ops import python_udf as _PU  # noqa: E402
_expr(_PU.PandasUDF)
_expr(_PU.PandasAggUDF)

# incompat expressions: results can differ from Spark in corner cases
# (GpuOverrides incompat doc chaining, GpuOverrides.scala:84-97)
_EXPR_RULES[st.Upper] = ExprRule(st.Upper, incompat="ASCII-only case mapping")
_EXPR_RULES[st.Lower] = ExprRule(st.Lower, incompat="ASCII-only case mapping")
_EXPR_RULES[st.InitCap] = ExprRule(st.InitCap, incompat="ASCII-only case mapping")
_EXPR_RULES[mo.Pow] = ExprRule(mo.Pow, incompat="pow lowers to exp(y*log x)")
_EXPR_RULES[st.RegExpExtractHost] = ExprRule(st.RegExpExtractHost,
                                             incompat="host regex engine")
_EXPR_RULES[st.RegExpReplaceHost] = ExprRule(st.RegExpReplaceHost,
                                             incompat="host regex engine")


SUPPORTED_TYPES = set(dt.ALL_TYPES) - {dt.NULLTYPE}


def _device_type_ok(t: dt.DType) -> bool:
    """Types with a device column layout: primitives/strings, ARRAY/MAP of
    primitives, and STRUCT whose fields are all device-capable
    (StructColumn; the GpuColumnVector type matrix analog)."""
    if t in SUPPORTED_TYPES:
        return True
    if dt.is_struct(t):
        return all(_device_type_ok(ft) for _, ft in t.fields)
    if dt.is_array(t):
        return (t.element in SUPPORTED_TYPES and
                not t.element.var_width)
    if dt.is_map(t):
        return t.numpy_dtype is not None
    return False


def _has_dtype(e) -> bool:
    try:
        e.dtype
        return True
    except Exception:
        return False


# ---------------------------------------------------------------------------
# Meta wrappers (RapidsMeta.scala)
# ---------------------------------------------------------------------------

class BaseMeta:
    def __init__(self, conf: cfg.TpuConf):
        self.conf = conf
        self.reasons: List[str] = []

    def will_not_work(self, reason: str) -> None:
        if reason not in self.reasons:
            self.reasons.append(reason)

    @property
    def can_replace(self) -> bool:
        return not self.reasons


class ExprMeta(BaseMeta):
    """Wraps one expression node (BaseExprMeta analog)."""

    def __init__(self, expr: ex.Expression, conf: cfg.TpuConf):
        super().__init__(conf)
        self.expr = expr
        self.children = [ExprMeta(c, conf) for c in expr.children]

    def tag(self) -> None:
        for c in self.children:
            c.tag()
        rule = None
        for klass in type(self.expr).__mro__:
            rule = _EXPR_RULES.get(klass)
            if rule is not None:
                break
        if rule is None:
            self.will_not_work(
                f"expression {type(self.expr).__name__} is not supported")
        else:
            if rule.incompat and not self.conf.incompatible_ops and not \
                    self.conf.is_operator_enabled(rule.conf_key, False):
                self.will_not_work(
                    f"{type(self.expr).__name__} is incompatible "
                    f"({rule.incompat}); enable with "
                    f"{cfg.INCOMPATIBLE_OPS.key} or {rule.conf_key}")
            elif not self.conf.is_operator_enabled(rule.conf_key, True):
                self.will_not_work(
                    f"{type(self.expr).__name__} disabled by {rule.conf_key}")
        try:
            t = self.expr.dtype
            ok = (_device_type_ok(t) or t == dt.NULLTYPE or
                  (t == dt.ARRAY_STRING and
                   isinstance(self.expr, _AR.StringSplit)))
            if not ok:
                self.will_not_work(f"unsupported output type {t}")
            if isinstance(self.expr, _MP.CreateMap):
                mt = self.expr.dtype
                if mt.key.is_floating or mt.element.is_floating:
                    # float -> bitpattern (f64->s64 bitcast) is
                    # unimplemented inside some backends' x64-emulation
                    # rewrite; reading scanned maps only needs the working
                    # s64->f64 direction, but BUILDING one on device does
                    # not compile there
                    self.will_not_work(
                        "create_map with floating keys/values runs on CPU "
                        "(device f64->bits reinterpret unsupported)")
            if isinstance(self.expr, (_MP.GetMapValue, _MP.GetItem)):
                child_t = self.expr.children[0].dtype
                if dt.is_map(child_t):
                    key_t = self.expr.children[1].dtype
                    if (key_t.numpy_dtype is None) != \
                            (child_t.key.numpy_dtype is None) or \
                            key_t.var_width != child_t.key.var_width:
                        self.will_not_work(
                            f"map key lookup type {key_t} does not match "
                            f"map key type {child_t.key}")
        except Exception:
            pass

    @property
    def tree_can_replace(self) -> bool:
        return self.can_replace and all(c.tree_can_replace for c in self.children)

    def collect_reasons(self) -> List[str]:
        out = list(self.reasons)
        for c in self.children:
            out.extend(c.collect_reasons())
        return out


class PlanMeta(BaseMeta):
    """Wraps one logical plan node (SparkPlanMeta analog)."""

    EXEC_NAMES = {
        lp.LocalScan: "LocalScanExec", lp.FileScan: "FileSourceScanExec",
        lp.CachedScan: "InMemoryTableScanExec",
        lp.Project: "ProjectExec", lp.Filter: "FilterExec",
        lp.Aggregate: "HashAggregateExec", lp.Join: "SortMergeJoinExec",
        lp.Sort: "SortExec", lp.Limit: "GlobalLimitExec",
        lp.Union: "UnionExec", lp.Range: "RangeExec",
        lp.Distinct: "HashAggregateExec", lp.Repartition: "ShuffleExchangeExec",
        lp.Expand: "ExpandExec", lp.Window: "WindowExec",
        lp.Generate: "GenerateExec",
        lp.MapInPandas: "MapInPandasExec",
        lp.FlatMapGroupsInPandas: "FlatMapGroupsInPandasExec",
        lp.FlatMapCoGroupsInPandas: "FlatMapCoGroupsInPandasExec",
        lp.AggregateInPandas: "AggregateInPandasExec",
        lp.WriteFile: "DataWritingCommandExec",
    }

    def __init__(self, plan: lp.LogicalPlan, conf: cfg.TpuConf):
        super().__init__(conf)
        self.plan = plan
        self.children = [PlanMeta(c, conf) for c in plan.children]
        self.expr_metas = [ExprMeta(e, conf) for e in plan.expressions()]

    @property
    def exec_name(self) -> str:
        return self.EXEC_NAMES.get(type(self.plan), self.plan.name)

    def tag(self) -> None:
        """Children-first tagging walk (RapidsMeta.scala:189-216)."""
        for c in self.children:
            c.tag()
        for e in self.expr_metas:
            e.tag()
        if not self.conf.sql_enabled:
            self.will_not_work(f"{cfg.SQL_ENABLED.key} is false")
            return
        key = f"spark.rapids.tpu.sql.exec.{self.exec_name}"
        if not self.conf.is_operator_enabled(key, True):
            self.will_not_work(f"{self.exec_name} disabled by {key}")
        for em in self.expr_metas:
            if not em.tree_can_replace:
                for r in em.collect_reasons():
                    self.will_not_work(r)
        self._tag_self()
        # output schema types (ARRAY/MAP of primitives allowed; STRUCT of
        # device-capable fields rides the StructColumn layout)
        for f in self.plan.schema.fields:
            if not _device_type_ok(f.dtype):
                self.will_not_work(
                    f"unsupported column type {f.dtype} for {f.name}")
        # structs move through row-reorder paths (scan/join/sort payload,
        # exchange, project) but have no comparison/hash kernels: any use
        # as a sort/group/partition/join KEY stays on the CPU engine
        p = self.plan
        key_exprs = []
        if isinstance(p, lp.Sort):
            key_exprs = [o.child for o in p.orders]
        elif isinstance(p, lp.Aggregate):
            key_exprs = list(p.grouping)
        elif isinstance(p, lp.Repartition):
            key_exprs = list(getattr(p, "by", None) or [])
        elif isinstance(p, lp.Join):
            if p.condition is not None:
                key_exprs = [p.condition]
            if p.using:
                # using-style joins (on=['col']) name their keys: the
                # condition holds unresolved ColumnRef/_UsingRight nodes
                # whose dtype the expression walk below cannot see, so
                # resolve each name against the child schemas directly —
                # struct keys must fall back, not crash device kernels
                for ch in p.children:
                    sch = ch.schema
                    for cname in p.using:
                        try:
                            f = sch[cname]
                        except Exception:
                            continue
                        if dt.is_struct(f.dtype):
                            self.will_not_work(
                                "struct-typed keys (sort/group/partition/"
                                "join) are not supported on the device")
                            break
        for e in key_exprs:
            try:
                if e.collect(lambda x: dt.is_struct(x.dtype)
                             if _has_dtype(x) else False):
                    self.will_not_work(
                        "struct-typed keys (sort/group/partition/join) "
                        "are not supported on the device")
                    break
            except Exception:
                pass

    def _tag_self(self) -> None:
        p = self.plan
        if isinstance(p, lp.Aggregate):
            d_leaves = [l for e in p.aggregate_exprs
                        for l in e.collect(
                            lambda x: isinstance(x, lp.AggregateExpression))
                        if l.distinct]
            if d_leaves:
                # DISTINCT plans as a two-level aggregate (dedupe on
                # (keys, value) then the outer agg — the reference routes
                # this through Spark's partial/partial-merge distinct
                # planning, aggregate.scala:77-170); one distinct column
                # set at a time, like Spark's non-Expand planning path
                if any(l.op not in ("count", "sum", "avg", "min", "max")
                       for l in d_leaves):
                    self.will_not_work(
                        "DISTINCT is only supported for "
                        "count/sum/avg/min/max")
                if len({repr(l.children[0]) for l in d_leaves
                        if l.children}) > 1:
                    self.will_not_work(
                        "multiple DISTINCT aggregate column sets "
                        "are not supported")
        if isinstance(p, lp.Join):
            if p.how not in ("inner", "left", "right", "full", "left_semi",
                             "left_anti", "cross"):
                self.will_not_work(f"join type {p.how} not supported")
            if p.condition is not None:
                from ..cpu.engine import _extract_equi_keys
                lnames = p.children[0].schema.names()
                rnames = p.children[1].schema.names()
                lk, rk, residual = _extract_equi_keys(p.condition, lnames, rnames)
                if residual is not None and p.how not in ("inner", "cross"):
                    # conditional joins only for inner (reference:
                    # GpuHashJoin.tagJoin, shims/spark300/GpuHashJoin.scala:30-42)
                    self.will_not_work(
                        "non-equi join condition only supported for inner join")
        if isinstance(p, lp.FileScan) and p.fmt not in ("parquet", "csv", "orc"):
            self.will_not_work(f"file format {p.fmt} not supported")
        if isinstance(p, lp.Generate):
            from ..ops import arrays as AR
            gen = p.generator
            inner = gen.children[0]
            if isinstance(inner, AR.StringSplit):
                d = inner.delimiter
                if not (isinstance(d, str) and len(d) == 1 and
                        ord(d) < 128):
                    self.will_not_work(
                        "explode(split()) needs a single-byte literal "
                        "delimiter (regex delimiters run on CPU)")
            elif not dt.is_array(inner.dtype) or \
                    inner.dtype.element.var_width:
                self.will_not_work(
                    f"explode over {inner.dtype} not supported "
                    "(needs ARRAY<primitive> or split())")
        else:
            # split()/explode() are generator-position only: anywhere else
            # they cannot evaluate inline -> CPU engine
            from ..ops import arrays as AR
            for e in p.expressions():
                if e.collect(lambda x: isinstance(
                        x, (AR.StringSplit, AR.Explode))):
                    self.will_not_work(
                        "split()/explode() outside a generate position "
                        "runs on the CPU engine")
                    break
        if isinstance(p, lp.Window):
            from ..ops import window as W
            RANGE_KEY_TYPES = (dt.INT8, dt.INT16, dt.INT32, dt.DATE)
            for _name, w in p.window_exprs:
                frame = w.spec.frame
                if frame is None or not frame.is_range:
                    continue
                # range frames: single ascending order key of <=32-bit
                # storage (the reference's scope: timestamp-days,
                # GpuWindowExpression.scala:734-800)
                if len(w.spec.order_by) != 1:
                    self.will_not_work(
                        "RANGE frame needs exactly one order key")
                elif not w.spec.order_by[0].ascending:
                    self.will_not_work(
                        "RANGE frame only supported for ascending order")
                elif w.spec.order_by[0].child.dtype not in RANGE_KEY_TYPES:
                    self.will_not_work(
                        f"RANGE frame order key type "
                        f"{w.spec.order_by[0].child.dtype} not supported "
                        "(needs <=32-bit integral/date)")

    # -- explain (RapidsMeta.scala:261-295) ---------------------------------
    def explain(self, all_ops: bool = False, depth: int = 0) -> str:
        lines = []
        if self.can_replace:
            if all_ops:
                lines.append("  " * depth + f"* {self.exec_name} will run on TPU")
        else:
            reasons = "; ".join(self.reasons)
            lines.append("  " * depth +
                         f"! {self.exec_name} cannot run on TPU because {reasons}")
        for c in self.children:
            sub = c.explain(all_ops, depth + 1)
            if sub:
                lines.append(sub)
        return "\n".join([l for l in lines if l])


# ---------------------------------------------------------------------------
# Conversion: meta tree -> physical exec tree (convertIfNeeded)
# ---------------------------------------------------------------------------

class Overrides:
    """The GpuOverrides rule: wrap -> tag -> explain -> convert."""

    def __init__(self, conf: Optional[cfg.TpuConf] = None):
        self.conf = conf or cfg.TpuConf()
        self.last_explain: str = ""
        self.last_meta: Optional[PlanMeta] = None
        # structured plan-contract violations from the last apply():
        # EXPLAIN ANALYZE attaches these to the rendered tree per node
        self.last_violations: list = []

    def apply(self, plan: lp.LogicalPlan) -> ph.TpuExec:
        plan = _shred_struct_columns(plan)
        plan = _prune_scan_columns(plan)
        meta = PlanMeta(plan, self.conf)
        meta.tag()
        self.last_meta = meta
        mode = self.conf.explain
        self.last_explain = meta.explain(all_ops=(mode == "ALL"))
        if mode != "NONE" and self.last_explain:
            print(self.last_explain)
        # whole-stage fusion (plan/stage_compiler.py, docs/fusion.md):
        # aggregate folds happen during conversion (_make_aggregate); the
        # post-pass collapses the remaining filter/project chains into
        # TpuWholeStageExec nodes — BEFORE coalesce insertion, so batch
        # coalescing lands below the fused stage on the raw scan stream
        from . import stage_compiler as sc
        self._fusion_decisions = sc.FusionDecisions()
        node = self._convert(meta)
        if sc.fusion_enabled(self.conf):
            node = sc.fuse_stages(node, self.conf, self._fusion_decisions)
        node = self._insert_coalesce(node)
        if self.conf.get(cfg.HASH_OPTIMIZE_SORT):
            node = self._insert_hash_optimize_sorts(node)
        # plan-contract validation (analysis/contracts.py): static checks
        # over the converted tree, BEFORE execution. Violations append to
        # the explain output so last_explain carries both fallback reasons
        # and contract diagnostics; `error` mode rejects the plan.
        from ..analysis import contracts as _contracts
        try:
            diag, self.last_violations = _contracts.enforce(
                node, meta, str(self.conf.get(cfg.ANALYSIS_VALIDATE_PLAN)))
        except _contracts.PlanContractError as e:
            # the rejection diagnostic still lands in last_explain so the
            # test hook / UI shows WHY the plan was refused
            self.last_explain = (self.last_explain + "\n" + str(e)
                                 if self.last_explain else str(e))
            raise
        if diag:
            self.last_explain = (self.last_explain + "\n" + diag
                                 if self.last_explain else diag)
            if mode != "NONE":
                print(diag)
        # planner row estimates stamped at optimization time
        # (plan/estimates.py): EXPLAIN ANALYZE compares them against
        # executed actuals per node — the estimate-vs-actual drift
        # report, the cardinality-feedback groundwork
        from .estimates import annotate_estimates
        annotate_estimates(node)
        return node

    def _insert_hash_optimize_sorts(self, node: ph.TpuExec) -> ph.TpuExec:
        """Optional per-partition sort above hash-based ops so a downstream
        file write sees clustered rows and compresses better
        (insertHashOptimizeSorts, GpuTransitionOverrides.scala:268-304)."""
        for i, child in enumerate(node.children):
            node.children[i] = self._insert_hash_optimize_sorts(child)
        is_final_agg = (isinstance(node, ph.TpuHashAggregateExec) and
                        node.mode != "partial")
        if is_final_agg or isinstance(node, ph.TpuSortMergeJoinExec):
            # partial aggregates sit directly under a hash exchange that
            # destroys any ordering — sorting them buys nothing
            orders = [lp.SortOrder(ex.BoundReference(i, f.dtype, True),
                                   ascending=True)
                      for i, f in enumerate(node.schema)
                      if f.dtype in dt.ORDERABLE_TYPES]
            if orders:
                return ph.TpuSortExec(node, orders, is_global=False)
        return node

    def _insert_coalesce(self, node: ph.TpuExec) -> ph.TpuExec:
        """Transition pass: insert TpuCoalesceBatchesExec per the op's
        children coalesce goals (GpuTransitionOverrides.scala:118-244).
        Exchanges are exempt: they already emit one concatenated batch per
        partition (the reference's optimizeCoalesce elision around shuffles,
        GpuTransitionOverrides.scala:51-94)."""
        from ..shuffle.exchange import (TpuBroadcastExchangeExec,
                                        TpuShuffleExchangeExec)
        for i, child in enumerate(node.children):
            child = self._insert_coalesce(child)
            goal = node.children_coalesce_goal(i)
            if goal is not None and not isinstance(
                    child, (ph.TpuCoalesceBatchesExec,
                            TpuShuffleExchangeExec, TpuBroadcastExchangeExec)):
                # size from the CHILD's schema: those are the rows being
                # concatenated toward batchSizeBytes
                child = ph.TpuCoalesceBatchesExec(
                    child, goal=goal,
                    target_rows=self._target_batch_rows(child.schema))
            node.children[i] = child
        return node

    def _target_batch_rows(self, schema) -> int:
        """Rows per batch for scans and coalesce targets: the HBM-budget
        autotuned pick (plan/stage_compiler.tuned_batch_rows — largest
        safe batch for a fused stage; docs/fusion.md §4), or the legacy
        batchSizeBytes-derived value capped at reader.batchSizeRows when
        ``spark.rapids.tpu.sql.batch.autotune`` is off."""
        from . import stage_compiler as sc
        return sc.tuned_batch_rows(self.conf, schema)

    def _convert(self, meta: PlanMeta) -> ph.TpuExec:
        p = meta.plan
        if not meta.can_replace:
            # whole subtree to CPU (the reference would transition per-node;
            # we fall back at the highest untaggable node and let TPU children
            # feed it through a transition bridge)
            if meta.children and all(_subtree_ok(c) for c in meta.children):
                tpu_children = [self._convert(c) for c in meta.children]
                return CpuOpBridgeExec(p, tpu_children)
            return ph.CpuFallbackExec(p)
        return self._to_exec(meta)

    def _to_exec(self, meta: PlanMeta) -> ph.TpuExec:
        p = meta.plan
        kids = [self._convert(c) for c in meta.children]
        if isinstance(p, lp.CachedScan):
            return ph.TpuCachedScanExec(p)
        if isinstance(p, lp.LocalScan):
            return ph.TpuLocalScanExec(
                p.data, p.schema,
                batch_rows=self._target_batch_rows(p.schema),
                base_data=p.base_data)
        if isinstance(p, lp.FileScan):
            from ..io.scan import TpuFileScanExec
            return TpuFileScanExec(p, self.conf)
        if isinstance(p, lp.Project):
            return ph.TpuProjectExec(kids[0], p.exprs)
        if isinstance(p, lp.Filter):
            return ph.TpuFilterExec(kids[0], p.condition)
        if isinstance(p, lp.Aggregate):
            leaves = [l for e in p.aggregate_exprs
                      for l in e.collect(
                          lambda x: isinstance(x, lp.AggregateExpression))]
            if any(l.distinct for l in leaves):
                return self._convert_distinct_agg(p, kids[0], leaves)
            return self._make_aggregate(kids[0], p.grouping, p.aggregate_exprs,
                                         p.children[0].stats_bytes())
        if isinstance(p, lp.Distinct):
            grouping = [ex.ColumnRef(n).resolve(p.children[0].schema)
                        for n in p.children[0].schema.names()]
            return self._make_aggregate(kids[0], grouping, list(grouping),
                                         p.children[0].stats_bytes())
        if isinstance(p, lp.Join):
            return self._convert_join(p, kids)
        if isinstance(p, lp.Sort):
            mesh = self._mesh_for_stage(p.children[0].stats_bytes()) \
                if p.is_global else None
            if mesh is not None:
                # fused SPMD sort: sample -> bounds -> all_to_all -> local
                # sort in one XLA computation (parallel/mesh.py)
                from ..parallel.mesh_exec import TpuMeshSortExec
                return TpuMeshSortExec(kids[0], p.orders, mesh)
            if p.is_global and kids[0].output_partitions > 1:
                # distributed sort: range-partition on sampled bounds, then
                # sort each partition independently — partition order + local
                # order = total order (GpuRangePartitioning + GpuSortExec)
                from ..shuffle.exchange import TpuRangeExchangeExec
                n = min(self.conf.shuffle_partitions,
                        max(2, kids[0].output_partitions))
                exch = TpuRangeExchangeExec(kids[0], n, p.orders)
                return ph.TpuSortExec(exch, p.orders, is_global=False)
            return ph.TpuSortExec(kids[0], p.orders, p.is_global)
        if isinstance(p, lp.Limit):
            return ph.TpuLimitExec(kids[0], p.n)
        if isinstance(p, lp.Union):
            return ph.TpuUnionExec(*kids)
        if isinstance(p, lp.Range):
            return ph.TpuRangeExec(p.start, p.end, p.step, p.num_partitions)
        if isinstance(p, lp.Repartition):
            from ..shuffle.exchange import TpuShuffleExchangeExec
            return TpuShuffleExchangeExec(
                kids[0], p.num_partitions, p.by,
                **self._exchange_kwargs(p.children[0].stats_bytes()))
        if isinstance(p, lp.Expand):
            return ph.TpuExpandExec(kids[0], p.projections, p.output_names)
        if isinstance(p, lp.Window):
            from .window_exec import TpuWindowExec
            return TpuWindowExec(kids[0], p.window_exprs)
        if isinstance(p, lp.Generate):
            return ph.TpuGenerateExec(kids[0], p)
        if isinstance(p, lp.MapInPandas):
            return ph.TpuMapInPandasExec(kids[0], p)
        if isinstance(p, lp.FlatMapGroupsInPandas):
            return ph.TpuFlatMapGroupsInPandasExec(
                self._cluster_by_keys(kids[0], p.grouping), p)
        if isinstance(p, lp.FlatMapCoGroupsInPandas):
            # positional partition pairing requires BOTH sides
            # co-partitioned: exchange both whenever either side is
            # multi-partition (one-sided clustering would pair keys with
            # the wrong/empty opposite partition)
            from ..shuffle.exchange import TpuHashExchangeExec
            from ..shuffle.manager import WorkerContext
            need = (kids[0].output_partitions > 1 or
                    kids[1].output_partitions > 1 or
                    WorkerContext.current is not None)
            left, right = kids
            if need and p.left_grouping and p.right_grouping:
                n = self.conf.shuffle_partitions
                xkw = self._exchange_kwargs(
                    p.children[0].stats_bytes(), p.children[1].stats_bytes())
                left = TpuHashExchangeExec(left, n, list(p.left_grouping),
                                           **xkw)
                right = TpuHashExchangeExec(right, n,
                                            list(p.right_grouping), **xkw)
            return ph.TpuFlatMapCoGroupsInPandasExec(left, right, p)
        if isinstance(p, lp.AggregateInPandas):
            return ph.TpuAggregateInPandasExec(
                self._cluster_by_keys(kids[0], p.grouping), p)
        if isinstance(p, lp.WriteFile):
            from ..io.write import TpuWriteFileExec
            return TpuWriteFileExec(kids[0], p)
        raise NotImplementedError(f"no TPU exec for {p.name}")

    def _mesh(self):
        """Active SPMD mesh, if mesh execution is enabled (cached).
        maybe_mesh degrades silently only in 'auto' mode; a forced 'true'
        propagates construction failures instead of quietly planning the
        host path."""
        if not hasattr(self, "_mesh_cache"):
            from ..parallel.mesh import maybe_mesh
            self._mesh_cache = maybe_mesh(self.conf)
        return self._mesh_cache

    def _mesh_for_stage(self, *stats: int):
        """Mesh for a stage whose inputs are estimated at ``stats`` bytes —
        None above mesh.maxStageBytes (the SPMD stage materializes its whole
        input host-side and sizes receive windows at workers*cap, so huge
        stages keep the bounded-residency host exchange)."""
        mesh = self._mesh()
        if mesh is None:
            return None
        limit = int(self.conf.get(cfg.MESH_MAX_STAGE_BYTES))
        if sum(stats) > limit:
            return None
        return mesh

    def _exchange_kwargs(self, *stats: int) -> dict:
        """Plan-time shuffle-plane routing for one exchange (conf
        spark.rapids.tpu.sql.shuffle.plane, docs/shuffle.md): 'auto' hands
        the exchange the active mesh when the stage is small enough to
        stage device-resident (it resolves ici/dcn per shape at runtime),
        'ici' forces the collective plane — failing LOUDLY at plan time
        without a mesh — and 'dcn' pins the host/TCP path. The pipelined
        map-split depth resolves here too (session conf, not globals)."""
        plane = str(self.conf.get(cfg.SHUFFLE_PLANE)).lower()
        if plane == "dcn":
            mesh = None
        elif plane == "ici":
            mesh = self._mesh()            # forced: the size gate yields
            if mesh is None:
                raise RuntimeError(
                    f"{cfg.SHUFFLE_PLANE.key}=ici but no device mesh is "
                    f"active — enable {cfg.MESH_ENABLED.key} or use "
                    "auto/dcn")
        else:
            mesh = self._mesh_for_stage(*stats)
        return dict(
            plane=plane, mesh=mesh,
            split_depth=int(self.conf.get(cfg.SHUFFLE_PIPELINE_DEPTH)))

    def _cluster_by_keys(self, child: ph.TpuExec,
                         grouping: List[ex.Expression]) -> ph.TpuExec:
        """Clustered-distribution requirement for grouped pandas execs:
        hash-exchange on the keys whenever rows of one group could live in
        different partitions (requiredChildDistribution of the reference's
        python execs)."""
        from ..shuffle.exchange import TpuHashExchangeExec
        from ..shuffle.manager import WorkerContext
        multiworker = WorkerContext.current is not None
        if (child.output_partitions > 1 or multiworker) and grouping:
            return TpuHashExchangeExec(child, self.conf.shuffle_partitions,
                                       list(grouping),
                                       **self._exchange_kwargs())
        return child

    def _try_mesh_aggregate(self, child: ph.TpuExec,
                            grouping: List[ex.Expression],
                            outputs: List[ex.Expression],
                            stats_bytes: int) -> Optional[ph.TpuExec]:
        """Route a supported group-by to the fused SPMD pipeline: keyed,
        non-distinct, each output either a grouping column or a bare
        sum/count/avg/min/max leaf (first/last stay host-side — their
        distributed result would depend on shard order)."""
        from ..shuffle.manager import WorkerContext
        if WorkerContext.current is not None:
            return None        # multi-worker routes through the transport
        mesh = self._mesh_for_stage(stats_bytes)
        window_rows = None
        if mesh is None:
            # above maxStageBytes the STREAMING path still applies for
            # fixed-width stages: bounded multi-round windows instead of
            # whole-input staging (round-3 VERDICT weak#6)
            mesh = self._mesh()
            if mesh is None:
                return None
            window_rows = int(self.conf.get(cfg.MESH_STREAM_WINDOW_ROWS))
        if not grouping:
            return None
        from ..parallel import mesh_exec as me
        for e in outputs:
            inner = e.children[0] if isinstance(e, ex.Alias) else e
            if isinstance(inner, lp.AggregateExpression):
                if inner.distinct or inner.op not in me.MESH_AGG_OPS:
                    return None
                if inner.children and inner.children[0].dtype == dt.STRING \
                        and inner.op not in ("count",):
                    return None
            else:
                try:
                    me._grouping_index(inner, grouping)
                except ValueError:
                    return None
        if window_rows is not None:
            # streaming requires fixed-width agg inputs; STRING group keys
            # ride the fixed-width path through exact int64 word encoding
            # (parallel/mesh._encode_string_keys), other var-width keys
            # fall back to the host exchange
            for g in grouping:
                if g.dtype.var_width and g.dtype != dt.STRING:
                    return None
            for e in outputs:
                inner = e.children[0] if isinstance(e, ex.Alias) else e
                if isinstance(inner, lp.AggregateExpression) and \
                        inner.children and inner.children[0].dtype.var_width:
                    return None
        return me.TpuMeshGroupByExec(child, grouping, outputs, mesh,
                                     window_rows=window_rows)

    def _make_aggregate(self, child: ph.TpuExec,
                        grouping: List[ex.Expression],
                        outputs: List[ex.Expression],
                        stats_bytes: int) -> ph.TpuExec:
        """Aggregate planning (the reference's replaceMode two-phase planning,
        aggregate.scala:77-170): a multi-partition child gets
        partial(update) -> hash exchange on the grouping keys -> final(merge)
        with the final merge running per exchange partition; a single
        partition keeps the fused complete mode (the transition elision the
        reference performs when the distribution is already satisfied).
        With an active mesh, supported shapes fuse the whole
        partial -> exchange -> final pipeline into one SPMD computation."""
        mesh_exec = self._try_mesh_aggregate(child, grouping, outputs,
                                             stats_bytes)
        if mesh_exec is not None:
            return mesh_exec
        # fold the fusable filter/project CHAIN below the aggregate into
        # its fused update programs: the whole scan -> filter -> project ->
        # partial-agg stage becomes the agg's own programs — no separate
        # per-op dispatch, compaction, or count sync per batch
        # (plan/stage_compiler.py; docs/fusion.md). With stage fusion off,
        # today's single-filter fold (DESIGN.md §2) is kept as-is.
        from . import stage_compiler as sc
        pre_filter = None
        pre_stage = None
        stage_members: List[str] = []
        if sc.fusion_enabled(self.conf):
            if not hasattr(self, "_fusion_decisions"):
                self._fusion_decisions = sc.FusionDecisions()
            child, pre_stage, stage_members = sc.peel_for_aggregate(
                child, self._fusion_decisions)
        elif (isinstance(child, ph.TpuFilterExec) and
                child.condition.tree_fusable() and
                not child.condition.collect(
                    lambda x: not x.side_effect_free)):
            pre_filter = child.condition          # bound to the grandchild
            child = child.children[0]
        from ..shuffle.manager import WorkerContext
        multiworker = WorkerContext.current is not None
        def _mark_stage(agg: ph.TpuHashAggregateExec) -> ph.TpuHashAggregateExec:
            # EXPLAIN ANALYZE membership: the folded chain compiled into
            # this aggregate's stage program (stage_compiler.fusion_annotations)
            if pre_stage is not None:
                agg._fusion_stage = self._fusion_decisions.next_stage_id()
                agg._fusion_members = list(stage_members)
                self._fusion_decisions.note(
                    f"stage #{agg._fusion_stage}: "
                    f"{'+'.join(stage_members)} folded into "
                    f"{type(agg).__name__}[{agg.mode}]")
            return agg

        if child.output_partitions > 1 or multiworker:
            from ..shuffle.exchange import (TpuHashExchangeExec,
                                            TpuShuffleExchangeExec)
            partial = _mark_stage(ph.TpuHashAggregateExec(
                child, grouping, outputs, mode="partial",
                pre_filter=pre_filter, pre_stage=pre_stage))
            xkw = self._exchange_kwargs(stats_bytes)
            if grouping:
                keys = [ex.ColumnRef(f"_k{i}") for i in range(len(grouping))]
                # adaptive_ok: the final aggregate tolerates runtime
                # partition coalescing (merged partitions keep disjoint
                # key ownership) — the AQE shuffle-reader behavior
                exch = TpuHashExchangeExec(
                    partial, self.conf.shuffle_partitions, keys,
                    adaptive_ok=(
                        bool(self.conf.get(cfg.ADAPTIVE_ENABLED)) and
                        bool(self.conf.get(cfg.ADAPTIVE_COALESCE_ENABLED))),
                    adaptive_min_bytes=int(
                        self.conf.get(cfg.ADAPTIVE_MIN_PARTITION_BYTES)),
                    **xkw)
            else:
                # global aggregate: all partials meet on one partition
                exch = TpuShuffleExchangeExec(partial, 1, **xkw)
            return ph.TpuHashAggregateExec(exch, grouping, outputs,
                                           mode="final",
                                           per_partition_final=True)
        return _mark_stage(ph.TpuHashAggregateExec(
            child, grouping, outputs, pre_filter=pre_filter,
            pre_stage=pre_stage))

    def _convert_distinct_agg(self, p: lp.Aggregate, child: ph.TpuExec,
                              leaves: List[lp.AggregateExpression]
                              ) -> ph.TpuExec:
        """Two-level plan for DISTINCT aggregates (the reference's distinct
        planning, aggregate.scala:77-170 replaceMode partial/partial-merge):

          inner:  group by (keys..., v) — dedupes the distinct column while
                  computing the non-distinct aggregates per (keys, v) subgroup
          outer:  group by keys — distinct aggs evaluate over the now-unique
                  v values; non-distinct aggs merge their inner partials
                  (count->sum, sum->sum, avg->sum/count divide)
        """
        from ..ops.cast import Cast as _Cast
        d_leaves = [l for l in leaves if l.distinct]
        nd_leaves = [l for l in leaves if not l.distinct]
        v_expr = d_leaves[0].children[0]

        inner_grouping = list(p.grouping) + [v_expr]
        inner_outputs: List[ex.Expression] = []
        for i, g in enumerate(p.grouping):
            inner_outputs.append(ex.Alias(g, f"_g{i}"))
        inner_outputs.append(ex.Alias(v_expr, "_v"))
        # non-distinct partial pieces, one or two inner agg columns per leaf
        nd_parts: Dict[int, List[str]] = {}
        for i, l in enumerate(nd_leaves):
            if l.op == "avg":
                c = l.children[0]
                inner_outputs.append(ex.Alias(
                    lp.AggregateExpression("sum", c), f"_nd{i}_s"))
                inner_outputs.append(ex.Alias(
                    lp.AggregateExpression("count", c), f"_nd{i}_c"))
                nd_parts[i] = [f"_nd{i}_s", f"_nd{i}_c"]
            else:
                inner_outputs.append(ex.Alias(
                    lp.AggregateExpression(
                        l.op, l.children[0] if l.children else None,
                        ignore_nulls=l.ignore_nulls), f"_nd{i}"))
                nd_parts[i] = [f"_nd{i}"]
        inner = self._make_aggregate(child, inner_grouping, inner_outputs,
                                     p.children[0].stats_bytes())

        def _ref(name: str) -> ex.ColumnRef:
            return ex.ColumnRef(name).resolve(inner.schema)

        def _sum_of(name: str) -> ex.Expression:
            return lp.AggregateExpression("sum", _ref(name))

        def _merge_leaf(i: int, l: lp.AggregateExpression) -> ex.Expression:
            names = nd_parts[i]
            if l.op == "avg":
                s = _sum_of(names[0])
                c = _sum_of(names[1])
                num = s if s.dtype == dt.FLOAT64 else _Cast(s, dt.FLOAT64)
                den = _Cast(c, dt.FLOAT64)
                return ar.Divide(num, den)
            if l.op in ("count", "count_star", "sum"):
                return _sum_of(names[0])
            return lp.AggregateExpression(l.op, _ref(names[0]),
                                          ignore_nulls=l.ignore_nulls)

        def rewrite(e: ex.Expression) -> ex.Expression:
            def fn(node):
                for l in d_leaves:
                    if node is l:
                        op = "count" if l.op == "count_star" else l.op
                        return lp.AggregateExpression(op, _ref("_v"))
                for i, l in enumerate(nd_leaves):
                    if node is l:
                        return _merge_leaf(i, l)
                for gi, g in enumerate(p.grouping):
                    if node is g or (
                            isinstance(node, ex.ColumnRef) and
                            isinstance(g, ex.ColumnRef) and
                            node.col_name == g.col_name):
                        return _ref(f"_g{gi}")
                return None
            # top-down: leaves are matched by identity, which a bottom-up
            # pass would break by copying nodes whose children were rewritten
            # (e.g. sum(k) where k is also a grouping column)
            return e.transform_down(fn)

        outer_grouping = [_ref(f"_g{i}") for i in range(len(p.grouping))]
        outer_outputs = [
            ex.Alias(rewrite(e), ex.output_name(e, i))
            for i, e in enumerate(p.aggregate_exprs)]
        return self._make_aggregate(inner, outer_grouping, outer_outputs,
                                    p.children[0].stats_bytes())

    def _convert_join(self, p: lp.Join, kids: List[ph.TpuExec]) -> ph.TpuExec:
        from ..cpu.engine import _extract_equi_keys
        left, right = kids
        if p.how == "cross" or p.condition is None:
            return ph.TpuCrossJoinExec(left, right, p.condition)
        lnames = p.children[0].schema.names()
        rnames = p.children[1].schema.names()
        lk, rk, residual = _extract_equi_keys(p.condition, lnames, rnames)
        if not lk:
            return ph.TpuCrossJoinExec(left, right, p.condition)
        how = p.how
        if how == "right":
            # remap: right outer = left outer with sides swapped, then
            # reorder output columns (GpuHashJoin.scala:112-132 remap)
            inner = self._plan_equi_join(
                right, left, "left", rk, lk, None,
                build_stats=p.children[0].stats_bytes(),
                stream_stats=p.children[1].stats_bytes())
            return _ReorderExec(inner, p.schema,
                                len(rnames), len(lnames))
        return self._plan_equi_join(left, right, how, lk, rk, residual,
                                    build_stats=p.children[1].stats_bytes(),
                                    stream_stats=p.children[0].stats_bytes())

    def _plan_equi_join(self, stream: ph.TpuExec, build: ph.TpuExec, how: str,
                        stream_keys, build_keys, residual,
                        build_stats: int, stream_stats: int) -> ph.TpuExec:
        """Join strategy selection (GpuBroadcastJoinMeta + Spark's
        autoBroadcastJoinThreshold): a build side at or under the threshold
        broadcasts — materialized once as a spillable, reused by every stream
        partition; a larger build co-partitions BOTH sides through a hash
        exchange and joins one build partition at a time."""
        from ..shuffle.manager import WorkerContext
        multiworker = WorkerContext.current is not None
        threshold = int(self.conf.get(cfg.AUTO_BROADCAST_JOIN_THRESHOLD))
        if threshold >= 0 and build_stats <= threshold and not multiworker:
            # multi-worker: the build side is SHARDED across workers, so a
            # local 'broadcast' would join against 1/N of it — the shuffled
            # path co-partitions both sides correctly over the transport
            from ..shuffle.exchange import TpuBroadcastExchangeExec
            j = ph.TpuSortMergeJoinExec(
                stream, TpuBroadcastExchangeExec(build), how,
                stream_keys, build_keys, residual)
            j.pipeline_depth = int(self.conf.get(cfg.JOIN_PIPELINE_DEPTH))
            if bool(self.conf.get(cfg.ADAPTIVE_ENABLED)) and \
                    bool(self.conf.get(cfg.ADAPTIVE_JOIN_SWITCH_ENABLED)):
                # AQE join-strategy demotion (plan/aqe.py): estimates said
                # broadcast; a materialized build observed past threshold x
                # demoteFactor re-plans as a co-partitioned shuffled join
                j.aqe_demote_policy = {
                    "threshold": threshold,
                    "factor": float(
                        self.conf.get(cfg.ADAPTIVE_JOIN_DEMOTE_FACTOR)),
                    "partitions": self.conf.shuffle_partitions,
                    "validate": str(
                        self.conf.get(cfg.ANALYSIS_VALIDATE_PLAN)),
                }
            return j
        from ..shuffle.exchange import TpuHashExchangeExec
        n = self.conf.shuffle_partitions
        # co-partitioning correctness: murmur3 is type-sensitive, so both
        # sides must hash the SAME type — promote mismatched key pairs
        # (Catalyst would have inserted these casts during coercion)
        pk_stream, pk_build = list(stream_keys), list(build_keys)
        try:
            for i, (a, b) in enumerate(zip(pk_stream, pk_build)):
                if a.dtype != b.dtype:
                    t = dt.promote(a.dtype, b.dtype)
                    if t is not None:
                        pk_stream[i] = a if a.dtype == t else Cast(a, t)
                        pk_build[i] = b if b.dtype == t else Cast(b, t)
        except Exception:
            pass
        mesh = None if multiworker else \
            self._mesh_for_stage(build_stats, stream_stats)
        if mesh is not None:
            # SPMD co-partition: one fused all_to_all per side over ICI
            from ..parallel.mesh_exec import TpuMeshJoinExec
            mj = TpuMeshJoinExec(stream, build, how, stream_keys,
                                 build_keys, residual, mesh,
                                 pk_stream, pk_build)
            # inherits the pipelined per-pair join loop
            mj.pipeline_depth = int(self.conf.get(cfg.JOIN_PIPELINE_DEPTH))
            return mj
        xkw = self._exchange_kwargs(build_stats, stream_stats)
        j = ph.TpuShuffledJoinExec(
            TpuHashExchangeExec(stream, n, pk_stream, **xkw),
            TpuHashExchangeExec(build, n, pk_build, **xkw),
            how, stream_keys, build_keys, residual)
        j.pipeline_depth = int(self.conf.get(cfg.JOIN_PIPELINE_DEPTH))
        adaptive = bool(self.conf.get(cfg.ADAPTIVE_ENABLED))
        if adaptive and threshold >= 0 and \
                bool(self.conf.get(cfg.ADAPTIVE_JOIN_SWITCH_ENABLED)):
            # AQE: estimates said shuffle; observed map-side sizes may
            # overrule at runtime (physical._maybe_runtime_broadcast).
            # Multi-worker included: the runtime decision is made from the
            # GLOBAL observed size (control-plane allreduce), so every
            # worker takes the same branch and a switch materializes the
            # complete build side from all peers' slices
            j.aqe_broadcast_threshold = threshold
            j.aqe_demote_factor = float(
                self.conf.get(cfg.ADAPTIVE_JOIN_DEMOTE_FACTOR))
        if adaptive and not multiworker and \
                bool(self.conf.get(cfg.ADAPTIVE_SKEW_JOIN_ENABLED)):
            # AQE skew split: hot stream partitions spread across
            # mapper-subset tasks (local mode; partition->worker ownership
            # must stay fixed multi-worker)
            skew = int(self.conf.get(cfg.SKEW_JOIN_THRESHOLD))
            if skew > 0:
                j.aqe_skew_threshold = skew
                j.aqe_skew_factor = float(
                    self.conf.get(cfg.ADAPTIVE_SKEW_FACTOR))
        return j


def _shred_struct_columns(root: lp.LogicalPlan) -> lp.LogicalPlan:
    """STRUCT shredding (the TPU-first GetStructField plan): when every
    use of a scan's struct column goes through ``GetField``, flatten the
    referenced fields into flat scan columns named ``s.f`` (arrow
    ``StructArray.flatten`` is zero-copy) and rewrite the accesses to
    plain column refs — the query then runs fully on the device with no
    struct layout at all. A whole-struct use anywhere keeps the struct
    column, and the planner's type gate routes that plan to the CPU
    engine (complexTypeExtractors.scala scope)."""
    from ..ops.structs import GetField

    struct_cols: set = set()
    for p in _walk_plans(root):
        if isinstance(p, lp.LocalScan):
            struct_cols.update(
                f.name for f in p.schema.fields if dt.is_struct(f.dtype))
    if not struct_cols:
        return root

    field_uses: dict = {}
    whole_uses: set = set()

    def scan_expr(e: ex.Expression, under_getfield: bool) -> None:
        if isinstance(e, GetField) and isinstance(
                e.children[0], ex.ColumnRef):
            name = e.children[0].col_name
            if name in struct_cols:
                field_uses.setdefault(name, set()).add(e.field)
                scan_expr(e.children[0], True)
                return
        if isinstance(e, ex.ColumnRef) and not under_getfield and \
                e.col_name in struct_cols:
            whole_uses.add(e.col_name)
        for c in e.children:
            scan_expr(c, False)

    # only nodes whose expressions the rewrite loop below handles may
    # contribute shreddable field uses; a getField anywhere else must pin
    # the struct column (else the rewrite would strand an unresolvable ref)
    _REWRITABLE = (lp.Project, lp.Filter, lp.Aggregate, lp.Sort, lp.Join)
    for p in _walk_plans(root):
        rewritable = isinstance(p, _REWRITABLE)
        for e in p.expressions():
            if rewritable:
                scan_expr(e, False)
            else:
                for ref in e.collect(
                        lambda x: isinstance(x, ex.ColumnRef)):
                    if ref.col_name in struct_cols:
                        whole_uses.add(ref.col_name)
        if isinstance(p, (lp.MapInPandas, lp.FlatMapGroupsInPandas,
                          lp.FlatMapCoGroupsInPandas, lp.WriteFile,
                          lp.Union, lp.Distinct)):
            # black-box / positional consumers see the whole child frame
            for c in p.children:
                whole_uses.update(n for n in c.schema.names()
                                  if n in struct_cols)
    # the query's own output keeping the struct is a whole use
    whole_uses.update(n for n in root.schema.names() if n in struct_cols)

    shred = {n: sorted(fs) for n, fs in field_uses.items()
             if n not in whole_uses}
    if not shred:
        return root

    import copy as _copy
    import pyarrow as pa

    def rewrite_plan(p: lp.LogicalPlan) -> lp.LogicalPlan:
        kids = [rewrite_plan(c) for c in p.children]
        out = p
        if isinstance(p, lp.LocalScan) and any(
                f.name in shred for f in p.schema.fields):
            tbl = p.data
            names = list(tbl.schema.names)
            arrays = [tbl.column(i) for i in range(tbl.num_columns)]
            new_names, new_arrays = [], []
            for n, a in zip(names, arrays):
                if n in shred:
                    sa = a.combine_chunks() if isinstance(
                        a, pa.ChunkedArray) else a
                    # flatten() merges the PARENT null mask into every
                    # child (field() would resurrect values under a NULL
                    # struct row)
                    children = dict(zip(
                        [fld.name for fld in sa.type], sa.flatten()))
                    for f in shred[n]:
                        new_names.append(f"{n}.{f}")
                        new_arrays.append(children[f])
                else:
                    new_names.append(n)
                    new_arrays.append(a)
            out = lp.LocalScan(
                pa.table(dict(zip(new_names, new_arrays))),
                p.scan_name, base_data=p.base_data)
        elif kids != p.children:
            out = _copy.copy(p)
            out.children = kids
            out._schema = None
        return out

    def rewrite_expr(e: ex.Expression) -> ex.Expression:
        if isinstance(e, GetField) and isinstance(
                e.children[0], ex.ColumnRef):
            name = e.children[0].col_name
            if name in shred:
                return ex.ColumnRef(f"{name}.{e.field}")
        e.children = [rewrite_expr(c) for c in e.children]
        e._rebind_child_aliases()
        return e

    new_root = rewrite_plan(root)
    for p in _walk_plans(new_root):
        if isinstance(p, lp.Project):
            p.exprs = [rewrite_expr(e) for e in p.exprs]
        elif isinstance(p, lp.Filter):
            p.condition = rewrite_expr(p.condition)
        elif isinstance(p, lp.Aggregate):
            p.grouping = [rewrite_expr(e) for e in p.grouping]
            p.aggregate_exprs = [rewrite_expr(e)
                                 for e in p.aggregate_exprs]
        elif isinstance(p, lp.Sort):
            p.orders = [lp.SortOrder(rewrite_expr(o.child), o.ascending,
                                     o.nulls_first) for o in p.orders]
        elif isinstance(p, lp.Join) and p.condition is not None:
            p.condition = rewrite_expr(p.condition)
        p._schema = None
    # re-resolve: the rewritten ColumnRef("s.f") refs are fresh/unresolved
    return lp.analyze(new_root)


def _walk_plans(p: lp.LogicalPlan):
    yield p
    for c in p.children:
        yield from _walk_plans(c)


def _prune_scan_columns(root: lp.LogicalPlan) -> lp.LogicalPlan:
    """Column pruning at the scans (Catalyst ColumnPruning role): columns a
    query never references are not decoded or uploaded — on a tunneled
    device every extra column is a host->device transfer per batch.

    Conservative by-name analysis: keep every column referenced by any
    expression in the tree plus the root's output; skip entirely when a
    Union is present (its schema aligns children by POSITION)."""
    import copy
    referenced: set = set()
    has_union = False

    def walk(p: lp.LogicalPlan) -> None:
        nonlocal has_union
        if isinstance(p, lp.Union):
            has_union = True
        if isinstance(p, lp.Distinct):
            referenced.update(p.schema.names())
        if isinstance(p, lp.WriteFile):
            # a write materializes every child column
            referenced.update(p.children[0].schema.names())
        if isinstance(p, (lp.MapInPandas, lp.FlatMapGroupsInPandas,
                          lp.FlatMapCoGroupsInPandas)):
            # the pandas fn is a black box over the whole child frame(s)
            for c in p.children:
                referenced.update(c.schema.names())
        if isinstance(p, lp.Window):
            # spec keys live OUTSIDE WindowExpression.children (the spec is
            # not an expression child), so the generic collect below misses
            # them — pruning the order/partition key off the scan would
            # strand the window exec's bind (KeyError at conversion)
            for _name, w in p.window_exprs:
                for e in (list(w.spec.partition_by) +
                          [o.child for o in w.spec.order_by]):
                    for n in e.collect(lambda x: isinstance(x, ex.ColumnRef)):
                        referenced.add(n.col_name)
        for e in p.expressions():
            for n in e.collect(lambda x: isinstance(x, ex.ColumnRef)):
                referenced.add(n.col_name)
        for c in p.children:
            walk(c)

    walk(root)
    if has_union:
        return root
    referenced.update(root.schema.names())

    def rewrite(p: lp.LogicalPlan) -> lp.LogicalPlan:
        if isinstance(p, lp.LocalScan):
            names = p.schema.names()
            keep = [n for n in names if n in referenced] or names[:1]
            if len(keep) < len(names):
                # stable cache lineage: the pruned view is a NEW pa.Table
                # every query, so the scan device cache keys by the base
                # table identity + kept columns instead
                return lp.LocalScan(p.data.select(keep), p.scan_name,
                                    base_data=p.base_data)
            return p
        if isinstance(p, lp.FileScan):
            names = p.schema.names()
            keep = [n for n in names if n in referenced] or names[:1]
            if len(keep) < len(names):
                pruned = copy.copy(p)
                pruned._schema = None
                pruned._file_schema = dt.Schema(
                    [f for f in p.schema.fields if f.name in keep])
                pruned.projection = keep
                return pruned
            return p
        kids = [rewrite(c) for c in p.children]
        if all(k is c for k, c in zip(kids, p.children)):
            return p
        out = copy.copy(p)
        out.children = kids
        out._schema = None
        return out

    return rewrite(root)


def _subtree_ok(meta: PlanMeta) -> bool:
    return meta.can_replace and all(_subtree_ok(c) for c in meta.children)


class _ReorderExec(ph.TpuExec):
    """Column reorder after a swapped right-outer join."""

    CONTRACT = exec_contract(schema="defined", partitioning="preserve",
                             extras=("reorder_permutation",))
    METRICS = ph.exec_metrics()

    def __init__(self, child: ph.TpuExec, schema: dt.Schema,
                 n_right: int, n_left: int):
        super().__init__(child)
        self._schema = schema
        self.n_right = n_right
        self.n_left = n_left

    @property
    def schema(self):
        return self._schema

    def execute(self):
        return [self._map(p) for p in self.children[0].execute()]

    def _map(self, part):
        from ..columnar.batch import ColumnarBatch
        for b in part:
            cols = b.columns[self.n_right:] + b.columns[:self.n_right]
            yield ColumnarBatch(self._schema, cols, b.num_rows)


class CpuOpBridgeExec(ph.TpuExec):
    """Runs ONE unsupported logical node on CPU over TPU-computed children
    (the GpuColumnarToRow -> CPU op -> RowToColumnar sandwich,
    GpuTransitionOverrides.scala transitions)."""

    CONTRACT = exec_contract(schema="defined", partitioning="single")
    METRICS = ph.exec_metrics()

    def __init__(self, plan: lp.LogicalPlan, tpu_children: List[ph.TpuExec]):
        super().__init__(*tpu_children)
        self.plan = plan

    @property
    def schema(self):
        return self.plan.schema

    @property
    def output_partitions(self) -> int:
        return 1

    def execute(self):
        from ..cpu.engine import execute as cpu_execute
        import copy
        # materialize TPU children -> arrow -> LocalScan stand-ins
        node = copy.copy(self.plan)
        node.children = []
        for child_exec, child_plan in zip(self.children, self.plan.children):
            batch = child_exec.execute_collect()
            scan = lp.LocalScan(batch.to_arrow())
            scan._schema = child_plan.schema
            node.children.append(scan)
        node._schema = None
        df = cpu_execute(node)

        def gen():
            yield ph._df_to_batch(df, self.plan.schema)
        return [gen()]

    def _node_string(self):
        return f"CpuOpBridgeExec[{self.plan.name}]"
