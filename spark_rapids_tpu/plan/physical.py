"""Physical columnar operators: the GpuExec layer.

Reference: ``GpuExec.scala:65-96`` (base trait + metrics),
``basicPhysicalOperators.scala`` (project/filter/range/union/coalesce),
``aggregate.scala:305-560`` (hash aggregate pipeline), ``GpuSortExec.scala``,
per-shim ``GpuHashJoin.scala`` (build-side single batch + stream loop),
``limit.scala``, ``GpuExpandExec.scala``, ``GpuCoalesceBatches.scala``.

Execution model: an exec's ``execute()`` returns a list of partitions, each a
generator of ``ColumnarBatch``. Single-process here; the shuffle layer
(shuffle/) exchanges partitions between stages, and parallel/ runs the same
operators SPMD over a device mesh. Expressions are bound to child output
ordinals before eval (GpuBindReferences analog).

Dynamic-size protocol (DESIGN.md): shrink/grow ops read the device count at
batch boundaries and rebucket lazily via CoalesceGoal targets.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from ..analysis.contracts import exec_contract
from ..columnar import dtypes as dt
from ..columnar.batch import ColumnarBatch
from ..columnar.column import Column, Scalar, bucket
from ..ops import expressions as ex
from ..ops import kernels as K
from ..ops import aggregates as agg_k
from ..ops import joins as join_k
from ..exec.tracing import trace_span
from . import logical as lp

Partition = Iterator[ColumnarBatch]


def _matmul_agg_enabled() -> bool:
    """MXU matmul segment reductions: 'auto' enables on accelerator backends
    only — float agg results differ from sequential sums at ~1e-5 rel (the
    reference's variableFloatAgg stance); golden-compare tests run on the
    exact CPU path."""
    from .. import config as cfg
    mode = str(cfg.TpuConf().get_key(
        "spark.rapids.tpu.sql.agg.matmul.enabled", "auto")).lower()
    if mode in ("true", "1"):
        return True
    if mode in ("false", "0"):
        return False
    import jax
    return jax.devices()[0].platform != "cpu"


# ---------------------------------------------------------------------------
# Reference binding (GpuBindReferences / GpuBoundAttribute.scala)
# ---------------------------------------------------------------------------

def bind_refs(e: ex.Expression, schema: dt.Schema) -> ex.Expression:
    def fn(node):
        if isinstance(node, ex.ColumnRef):
            i = schema.index_of(node.col_name)
            f = schema[i]
            return ex.BoundReference(i, f.dtype, f.nullable, f.name)
        return None
    return e.transform(fn)


# ---------------------------------------------------------------------------
# Metrics (GpuMetricNames, GpuExec.scala:27-56)
# ---------------------------------------------------------------------------

def _dev_count(batch) -> "Any":
    """A batch's row count as a device int32 scalar for a fused-program
    argument — WITHOUT forcing a host sync when the count is still
    device-resident (lazy counts ride the stream; see ColumnarBatch)."""
    import jax.numpy as jnp
    nr = batch.num_rows_raw
    if isinstance(nr, int):
        return jnp.int32(nr)
    if getattr(nr, "dtype", None) == jnp.int32:
        return nr
    return nr.astype(jnp.int32)


# The metrics bag + per-exec attribution live in exec/metrics.py; the
# ``Metrics`` name stays importable from here for existing call sites.
from ..exec.metrics import TpuMetrics as Metrics, exec_metrics  # noqa: E402


# ---------------------------------------------------------------------------
# Exec base
# ---------------------------------------------------------------------------

class TpuExec:
    """Base physical operator (GpuExec trait analog).

    Every concrete subclass declares a ``CONTRACT``
    (:func:`..analysis.contracts.exec_contract`): how its output schema
    relates to its children and what distribution it produces. The
    project linter enforces the declaration exists; the plan-contract
    validator (``analysis/contracts.validate_plan``, run by the planner
    after every conversion) enforces it holds."""

    CONTRACT = None          # abstract base: concrete execs must declare
    METRICS = None           # abstract base: concrete execs must declare

    def __init__(self, *children: "TpuExec"):
        self.children = list(children)
        self.metrics = Metrics()
        # the owning operator's name rides the bag so cross-cutting
        # attribution (HBM watermark peaks, service/telemetry) can name
        # the exec that was innermost-open, not just charge its bag
        self.metrics.owner = type(self).__name__

    @property
    def schema(self) -> dt.Schema:
        raise NotImplementedError

    @property
    def name(self) -> str:
        return type(self).__name__

    @property
    def output_partitions(self) -> int:
        """Estimated number of output partitions (Spark outputPartitioning
        analog, reduced to a count): the planner uses this to decide when a
        two-phase aggregate / co-partitioned join / range-partitioned sort
        needs an exchange."""
        return self.children[0].output_partitions if self.children else 1

    def children_coalesce_goal(self, i: int):
        """Per-child batch goal (CoalesceGoal lattice,
        GpuCoalesceBatches.scala:117-130): None (no requirement), "target"
        (concat small batches toward the configured batch size), or "single"
        (RequireSingleBatch: the op needs the whole partition in one batch).
        The transition pass inserts TpuCoalesceBatchesExec accordingly."""
        return None

    def execute(self) -> List[Partition]:
        raise NotImplementedError

    def execute_collect(self) -> ColumnarBatch:
        """Materialize all partitions into one batch (driver collect).
        Partitions drain concurrently as tasks (Spark's task parallelism);
        accumulated results are spillable so N in-flight partitions cannot
        pin the whole dataset in HBM. Query-scoped state (broadcast builds,
        unread shuffle slices) is released afterwards."""
        from ..exec.tasks import run_partition_tasks

        try:
            per_part = run_partition_tasks(
                self.execute(), lambda pid, part: drain_spillable(part))
            with trace_span("collect_concat"):
                return concat_spillable(
                    self.schema, [s for lst in per_part for s in lst])
        finally:
            self.cleanup()

    def execute_collect_iter(self):
        """Streaming collect: yield ONE host batch per drained partition,
        in partition order, as each completes — the consumer sees first
        rows in first-partition time instead of whole-result time
        (``DataFrame.collect_iter``). Row content and order across the
        yielded batches are identical to :meth:`execute_collect`'s single
        concat. Cleanup runs when the stream is exhausted AND when the
        consumer closes it early (generator finally)."""
        from ..exec.tasks import stream_partition_tasks

        try:
            for spillables in stream_partition_tasks(
                    self.execute(),
                    lambda pid, part: drain_spillable(part)):
                if not spillables:
                    continue
                with trace_span("collect_concat"):
                    yield concat_spillable(self.schema, spillables)
        finally:
            self.cleanup()

    def cleanup(self) -> None:
        """Release query-scoped resources tree-wide after the final drain
        (the reference ties these to task/stage completion listeners)."""
        self._cleanup()
        for c in self.children:
            c.cleanup()

    def _cleanup(self) -> None:
        pass

    def subtree_deterministic(self) -> bool:
        """False when any expression below draws per-execution state (Rand,
        monotonically_increasing_id): re-executing such a subtree yields
        different rows, so shuffle stage-retry must recompute ALL reduce
        partitions (Spark's indeterminate-stage rule)."""
        return self._node_deterministic() and all(
            c.subtree_deterministic() for c in self.children)

    def _node_deterministic(self) -> bool:
        from ..ops import expressions as _ex

        def flat_exprs(v):
            if isinstance(v, _ex.Expression):
                yield v
            elif isinstance(v, lp.SortOrder):
                yield v.child
            elif isinstance(v, (list, tuple)):
                for x in v:
                    yield from flat_exprs(x)

        for attr in ("exprs", "grouping", "aggregate_exprs", "condition",
                     "orders", "projections", "left_keys", "right_keys",
                     "generator", "pre_filter", "_pre_stage_exprs",
                     "window_exprs", "by"):
            v = getattr(self, attr, None)
            if v is None:
                continue
            for e in flat_exprs(v):
                if e.collect(lambda x: not x.side_effect_free):
                    return False
        # execs that carry a logical node/subtree (generate, write,
        # python-UDF wrappers, CPU fallback): walk the WHOLE subtree —
        # expressions() is per-node
        p = getattr(self, "plan", None)
        if p is not None and hasattr(p, "expressions"):
            stack = [p]
            while stack:
                node = stack.pop()
                for e in node.expressions():
                    if e.collect(lambda x: not x.side_effect_free):
                        return False
                stack.extend(getattr(node, "children", ()))
        return True

    def metrics_tree(self, with_path: bool = False) -> List[tuple]:
        """Per-exec metrics in plan-tree order: [(depth, node name,
        resolved metrics dict)] — the SQLMetrics-per-operator surface the
        reference renders in the Spark UI (GpuMetricNames,
        GpuExec.scala:27-56). ``with_path=True`` appends the root->node
        class-name path (the same format ``analysis/contracts`` keys its
        violations on) as a fourth element."""
        out: List[tuple] = []

        def walk(node, depth, path, idx=None):
            # path mirrors contracts.validate_plan: child ordinal included
            # so same-class siblings key different paths
            here = (f"{path}/{idx}.{type(node).__name__}" if path
                    else type(node).__name__)
            row = (depth, node._node_string(),
                   dict(node.metrics.resolve()))
            out.append(row + (here,) if with_path else row)
            for i, c in enumerate(node.children):
                walk(c, depth + 1, here, i)
        walk(self, 0, "")
        return out

    def metrics_lines(self, annotate: Optional[Callable] = None
                      ) -> List[str]:
        """Rendered metrics tree, one list entry per line: node name then
        its sorted metrics (floats rounded to 4). ``annotate(path)`` may
        return extra lines to attach under a node — EXPLAIN ANALYZE hangs
        plan-contract diagnostics there."""
        lines: List[str] = []
        for depth, name, m, path in self.metrics_tree(with_path=True):
            pad = "  " * depth
            lines.append(pad + name)
            for k in sorted(m):
                v = m[k]
                v = round(v, 4) if isinstance(v, float) else v
                lines.append(pad + f"  {k}: {v}")
            for extra in (annotate(path) if annotate is not None else ()):
                lines.append(pad + f"  {extra}")
        return lines

    def metrics_string(self) -> str:
        """The executed plan annotated with each operator's metrics."""
        return "\n".join(self.metrics_lines())

    def _tree_string(self, depth: int = 0) -> str:
        out = "  " * depth + self._node_string()
        for c in self.children:
            out += "\n" + c._tree_string(depth + 1)
        return out

    def _node_string(self) -> str:
        return self.name

    def __repr__(self):
        return self._tree_string()


def _prepare_stateful(exprs: List[ex.Expression], pid: int
                      ) -> Tuple[List[ex.Expression], List[ex.Expression]]:
    """Per-partition clone + bind of stateful expressions (Rand,
    monotonically_increasing_id, spark_partition_id): bound exprs are shared
    across partitions, so stateful nodes must be copied per partition and
    given their partition index (GpuRand / GpuMonotonicallyIncreasingID get
    this from TaskContext in the reference). Returns (exprs, stateful nodes);
    the caller calls ``advance(n_rows)`` on each node after every batch so
    per-row streams progress instead of replaying."""
    import copy
    if not any(e.collect(lambda x: not x.side_effect_free) for e in exprs):
        return exprs, []
    exprs = [copy.deepcopy(e) for e in exprs]
    stateful = [n for e in exprs
                for n in e.collect(lambda x: not x.side_effect_free)]
    for n in stateful:
        if hasattr(n, "partition_index"):
            n.partition_index = pid
    return exprs, [n for n in stateful if hasattr(n, "advance")]


def _task_begin() -> None:
    """Device admission at task (partition evaluation) start: the semaphore
    bounds concurrently-executing device tasks. Ordering contract preserved
    from the reference (GpuSemaphore.scala:74-78): acquire after host-side
    input is ready, before device work. The semaphore itself records the
    wait-vs-hold span split (``semaphore_wait`` / ``semaphore_hold``) —
    the NVTX-range analog of GpuSemaphore.scala:107, but separable into
    admission contention vs device occupancy."""
    from ..exec.device import TpuSemaphore
    TpuSemaphore.get().acquire_if_necessary()


def _reserve(nbytes: int) -> None:
    """Admission-check ~nbytes of imminent device materialization against the
    spill catalog (DeviceMemoryEventHandler.onAllocFailure analog): spills
    lower-priority buffers until the allocation fits the budget."""
    from ..exec.spill import BufferCatalog
    BufferCatalog.get().reserve(nbytes)


def drain_spillable(part, acquire: bool = False
                    ) -> List["SpillableColumnarBatch"]:
    """Drain one partition into spillable handles, resolving device-resident
    row counts in chunked batched readbacks (one host round-trip per 8
    batches, not one per batch) and dropping empties. ``acquire=True``
    takes the task semaphore once the first batch exists (the reference's
    acquire-after-host-IO ordering, GpuSemaphore.scala:74-78)."""
    from ..columnar.batch import resolve_counts
    from ..exec.spill import BorrowedSpillableView, SpillableColumnarBatch
    out: List[SpillableColumnarBatch] = []
    chunk: List[ColumnarBatch] = []

    def spillable(b: ColumnarBatch):
        # batches served from the scan device cache are ALREADY registered;
        # borrow that registration instead of double-counting the HBM
        if b.origin is not None and not b.origin.closed:
            return BorrowedSpillableView(b.origin, b)
        return SpillableColumnarBatch(b)

    def flush(last: bool = False):
        if last and not out and len(chunk) == 1:
            # the whole partition is ONE batch (tight-aggregate queries):
            # registration keeps its count lazy, so skipping the resolve
            # here lets the final fetch read count + data in a single
            # round trip (each blocking readback costs a full RTT)
            out.append(spillable(chunk[0]))
            chunk.clear()
            return
        with trace_span("drain_resolve"):
            resolve_counts(chunk)      # one round-trip per chunk
        out.extend(spillable(b) for b in chunk if b.num_rows > 0)
        chunk.clear()

    first = True
    for b in part:
        if first and acquire:
            _task_begin()
            first = False
        if isinstance(b.num_rows_raw, int) and b.num_rows_raw == 0:
            continue
        chunk.append(b)
        if len(chunk) >= 8:
            flush()
    flush(last=True)
    return out


def accumulate_spillable(parts) -> List["SpillableColumnarBatch"]:
    """Drain partitions into spillable handles: accumulated build/sort inputs
    must not pin HBM while more batches stream in (SpillableColumnarBatch
    treatment of build sides, GpuShuffledHashJoinExec / GpuSortExec).
    Partitions drain concurrently as tasks."""
    from ..exec.tasks import run_partition_tasks

    parts = list(parts)
    per_part = run_partition_tasks(parts, lambda pid, p: drain_spillable(p))
    return [s for lst in per_part for s in lst]


def concat_spillable(schema: dt.Schema,
                     spillables: List["SpillableColumnarBatch"]
                     ) -> ColumnarBatch:
    """Materialize accumulated spillables and concatenate, reserving device
    room for inputs + output first."""
    total = sum(s.size_bytes for s in spillables)
    _reserve(2 * total)
    batches = [s.get_batch() for s in spillables]
    for s in spillables:
        s.close()
    return concat_batches(schema, batches)


def concat_batches(schema: dt.Schema, batches: List[ColumnarBatch],
                   target_capacity: Optional[int] = None) -> ColumnarBatch:
    """Concatenate batches in ONE fused device program (GpuCoalesceBatches
    concat path). The eager per-column form dispatched 2-3 dynamic-slice
    programs per column per batch — hundreds of tiny executions per merge
    cycle, the dominant steady-state cost on dispatch-latency-bound links.
    The fused program takes every batch's arrays + row counts (device
    scalars welcome) and emits the packed output columns."""
    from ..columnar.batch import resolve_counts
    batches = [b for b in batches
               if not (isinstance(b.num_rows_raw, int)
                       and b.num_rows_raw == 0)]
    if not batches:
        return ColumnarBatch.empty(schema)
    if len(batches) == 1 and target_capacity is None:
        return batches[0]
    if target_capacity is None:
        resolve_counts(batches)          # one batched readback
        batches = [b for b in batches if b.num_rows > 0]
        if not batches:
            return ColumnarBatch.empty(schema)
        if len(batches) == 1:
            return batches[0]
        cap = bucket(sum(b.num_rows for b in batches))
    else:
        cap = target_capacity
    return _concat_fused(schema, batches, cap)


def _concat_fused(schema: dt.Schema, batches: List[ColumnarBatch],
                  out_cap: int) -> ColumnarBatch:
    """Generic over the FLAT-ARRAY protocol (Column.arrays /
    build_column): every storage array is either rows[cap] or a row
    matrix [cap, W]; concat row-stacks each position independently and
    zeroes the output padding — so strings, arrays (+ element validity),
    maps, and struct-of-columns all concat through one fused program."""
    import jax
    import jax.numpy as jnp

    nb = len(batches)
    caps = tuple(b.capacity for b in batches)
    max_cap = max(caps)
    flats_per_batch = [b.flat_arrays() for b in batches]
    n_arr = len(flats_per_batch[0])
    two_d = tuple(flats_per_batch[0][ai].ndim == 2 for ai in range(n_arr))
    # static padded width per array position (inputs may differ)
    widths = tuple(
        max(int(fb[ai].shape[1]) for fb in flats_per_batch)
        if two_d[ai] else 0 for ai in range(n_arr))
    # NO donation at this funnel: concat is called with batches whose
    # provenance it cannot know (range-partitioner bound samples, UDF
    # rebatch pendings, coalesce accumulations) and several callers
    # legitimately re-read their inputs — the exec-stream ownership
    # argument that justifies FusedStage/aggregate donation does not
    # hold here
    sig = ("concat", _schema_sig(schema), caps, widths, out_cap)

    def build():
        def fn(*args):
            counts = args[:nb]
            flats = args[nb:]
            per_batch = [flats[bi * n_arr:(bi + 1) * n_arr]
                         for bi in range(nb)]
            offs = []
            total = jnp.int32(0)
            for bi in range(nb):
                offs.append(total)
                total = total + counts[bi].astype(jnp.int32)
            live = jnp.arange(out_cap) < total
            ext = out_cap + max_cap    # updates never clamp (see below)
            out_arrays = []
            for ai in range(n_arr):
                W = widths[ai]
                src0 = per_batch[0][ai]
                buf = (jnp.zeros((ext, W), src0.dtype) if two_d[ai]
                       else jnp.zeros(ext, src0.dtype))
                # forward order: batch i+1's block starts exactly at
                # offs[i]+counts[i], overwriting batch i's padding tail;
                # the extended operand keeps dynamic_update_slice from
                # clamping starts (offs[bi] <= out_cap, cap_bi <= max_cap)
                for bi in range(nb):
                    a = per_batch[bi][ai]
                    if two_d[ai] and a.shape[1] < W:
                        a = jnp.pad(a, ((0, 0), (0, W - a.shape[1])))
                    buf = jax.lax.dynamic_update_slice(
                        buf, a, (offs[bi], jnp.int32(0)) if two_d[ai]
                        else (offs[bi],))
                # clip to out_cap and zero the padding (batch invariant:
                # bools -> False, so validity masks fold in too)
                buf = buf[:out_cap]
                buf = jnp.where(live[:, None] if two_d[ai] else live,
                                buf, jnp.zeros((), buf.dtype))
                out_arrays.append(buf)
            return tuple(out_arrays) + (total,)
        return jax.jit(fn)

    fn = _fused_fn(sig, build)
    args = [_dev_count(b) for b in batches]
    for fb in flats_per_batch:
        args.extend(fb)
    outs = fn(*args)
    total_host = sum(b.num_rows_raw for b in batches) \
        if all(isinstance(b.num_rows_raw, int) for b in batches) else outs[-1]
    return ColumnarBatch.from_flat_arrays(schema, list(outs[:-1]), total_host)


# ---------------------------------------------------------------------------
# Whole-stage fusion (DESIGN.md §2; the TPU analog of codegen stages)
# ---------------------------------------------------------------------------
#
# Eager evaluation dispatches every jnp op as its own compiled program —
# hundreds of device round-trips per batch, the dominant engine cost (each
# expression node is a separate kernel launch, exactly the fusion gap
# SURVEY.md §3.3 calls out in the reference's per-expression JNI launches).
# A fused stage traces the WHOLE per-batch computation once per shape:
# one (or two, for dispatched group-bys) device calls per batch.

def _fusion_enabled(node) -> bool:
    flag = getattr(node, "_fusion", None)
    if flag is not None:
        return flag
    from .. import config as cfg
    return bool(cfg.TpuConf().get(cfg.WHOLESTAGE_FUSION))


# Fused programs cache GLOBALLY on (expression structure, schema dtypes,
# shapes): repeated queries reuse compiled stages across exec instances —
# per-exec closures would force a recompile every query.
_FUSED_CACHE: Dict[tuple, Any] = {}
# Bound on retained programs. The old behavior cleared the WHOLE cache
# past the bound — the recompile audit measured the fallout as same-key
# REBUILDS (distinctShapes 0) on tpcds_q65 mid-corpus. Eviction now
# drops only the oldest half (dict preserves insertion order), so the
# working set survives; the bound itself stays moderate because every
# retained program pins an XLA CPU executable (JIT code mappings are a
# finite process resource, not just bytes — see the map-pressure relief
# valve in exec/compile_cache, which this cache registers with below).
_FUSED_CACHE_MAX = 512

from ..exec.compile_cache import register_program_cache as _rpc  # noqa: E402
_rpc(_FUSED_CACHE.clear)
del _rpc

# Cached fused programs must NOT close over an exec instance: the cache is
# process-global, so a captured exec would pin its whole plan tree (and any
# CachedScan owner) for the process lifetime. Trace-time helpers resolve the
# exec through this call-scoped THREAD-LOCAL stack instead (partition tasks
# run on pool threads, so concurrent drains of two aggregate execs must not
# see each other's exec); the cache key guarantees any exec seen here is
# structurally identical to the one the trace was built for, so a retrace
# under a different exec produces the same program.
_TRACE_TLS = __import__("threading").local()


def _trace_exec_stack() -> List[Any]:
    stack = getattr(_TRACE_TLS, "stack", None)
    if stack is None:
        stack = _TRACE_TLS.stack = []
    return stack


class _trace_exec:
    def __init__(self, node):
        self.node = node

    def __enter__(self):
        _trace_exec_stack().append(self.node)

    def __exit__(self, *exc):
        _trace_exec_stack().pop()


def _fused_fn(key: tuple, builder):
    from ..analysis import recompile as _recompile
    from ..exec import compile_cache as _cc
    fn = _FUSED_CACHE.get(key)
    if fn is None:
        if len(_FUSED_CACHE) > _FUSED_CACHE_MAX:
            for old in list(_FUSED_CACHE)[:_FUSED_CACHE_MAX // 2]:
                _FUSED_CACHE.pop(old, None)
        kernel = _recompile.kernel_of(key)
        # classify against the persistent signature index (a 'disk' build
        # loads its executable from the on-disk XLA cache instead of
        # recompiling), meter the first call's compile-dominated wall
        # seconds, and persist the signature for the next process
        kind = _cc.classify(key)
        fn = _FUSED_CACHE[key] = _cc.timed(builder(), kernel, kind)
        _recompile.note_compile(kernel, key, kind=kind)
        _cc.record(key, kernel)
    else:
        # LRU touch (dict order = insertion order): eviction drops the
        # oldest half, so a hot program must not age by its build date.
        # The pop/reinsert pair is not atomic across task threads — the
        # worst case is a racing miss rebuilding one program, which the
        # audit then honestly counts.
        if _FUSED_CACHE.pop(key, None) is not None:
            _FUSED_CACHE[key] = fn
        _recompile.note_call(_recompile.kernel_of(key))
    return fn


def fused_cached(key: tuple) -> bool:
    """Whether a program for ``key`` is already resident — WITHOUT the
    LRU touch or audit note_call of a real :func:`_fused_fn` consult.
    The async compile pool's swap point: once its build lands here, the
    requesting stage's next batch takes the plain cache-hit path."""
    return key in _FUSED_CACHE


def _donate_argnums(batch: ColumnarBatch, start: int) -> tuple:
    """jit argnums donating ``batch``'s flat arrays to a fused program
    that CONSUMES the batch (XLA reuses/frees the HBM eagerly), or ()
    when donation is off or unsafe. Safe only for exclusively-owned
    batches: scan-cache-served (``origin``) and catalog-acquired
    (``shared``) arrays are re-read later, and an array aliased into two
    argument slots cannot be donated twice. The donate bit must ride the
    fused-cache key — donation is baked into the compiled program."""
    from ..exec import compile_cache as _cc
    if not _cc.donate_enabled():
        return ()
    if batch.origin is not None or getattr(batch, "shared", False):
        return ()
    flat = batch.flat_arrays()
    seen = set()
    for a in flat:
        if id(a) in seen:
            return ()
        seen.add(id(a))
    return tuple(range(start, start + len(flat)))


def _donation_consumed(batch: ColumnarBatch) -> bool:
    """After a FAILED fused call: True when a donating execution already
    deleted the batch's buffers — the eager fallback cannot re-read them,
    so the caller must re-raise the real error instead of letting the
    fallback crash on 'Array has been deleted'. (Trace-time failures
    never execute, so donated inputs survive them and fallback stays
    available — the common fusion-fallback case.)"""
    try:
        return any(getattr(a, "is_deleted", lambda: False)()
                   for a in batch.flat_arrays())
    except Exception:
        return True


def _note_donated(batch: ColumnarBatch, donate: tuple) -> None:
    """After a SUCCESSFUL donated fused invocation: tombstone ``batch``
    in the buffer-lifecycle ledger (analysis/ledger.py) — its arrays are
    dead, and a later read should diagnose as use-after-donate instead
    of surfacing jax's bare deleted-array error. No-op for the plain
    (un-donated) variant and when the ledger is off."""
    if donate:
        from ..analysis import ledger
        ledger.mark_donated(batch)


def _schema_sig(schema: dt.Schema) -> tuple:
    return tuple(f.dtype.name for f in schema)


def _expr_cache_key(e: ex.Expression):
    """Structural cache key covering every instance attribute (reprs alone
    are not faithful — e.g. Like's pattern is not in its repr). Returns None
    when an attribute is opaque (unkeyable): the stage then jits per-exec
    instead of sharing the global cache."""
    if isinstance(e, ex.Parameter):
        # a traceable parameter's VALUE is a runtime argument, never part
        # of the compiled program: two plans differing only in bound
        # values share one fused signature (the zero-recompile serving
        # property, docs/plan_cache.md). Non-traceable (string) values
        # stay baked, so the value must ride the key.
        # slot stringified: it is an IDENTITY, not a shape — the
        # size-class audit flags raw non-pow2 ints >= 8 in keys as
        # bucket-discipline leaks (a 9th parameter is not a dimension)
        if e.slot < 0:
            # UNSLOTTED (never passed through plan_cache.parameterize):
            # two such params would collide on one key and share a stale
            # program — unkeyable forces per-exec compilation instead
            return None
        if e.traceable():
            return ("param", f"s{e.slot}", e.dtype.name)
        return ("param", f"s{e.slot}", e.dtype.name, repr(e.value))
    parts: list = [type(e).__name__]
    for k, v in sorted(vars(e).items()):
        if k == "children":
            continue
        if isinstance(v, ex.Expression):
            sub = _expr_cache_key(v)
            if sub is None:
                return None
            parts.append((k, sub))
            continue
        r = repr(v)
        if " at 0x" in r:
            return None
        parts.append((k, r))
    for c in e.children:
        sub = _expr_cache_key(c)
        if sub is None:
            return None
        parts.append(sub)
    return tuple(parts)


class FusedStage:
    """One jitted program evaluating bound expression trees over a batch.

    mode 'project': outputs = evaluated expression columns.
    mode 'filter':  single boolean expression; outputs = compacted input
    columns + device row count (the host syncs the count, as the eager
    path already does).

    Any trace failure (an expression doing host-side work despite its
    fusable flag) permanently falls back to eager for this stage.
    """

    def __init__(self, exprs: List[ex.Expression], in_schema: dt.Schema,
                 out_schema: dt.Schema, mode: str = "project"):
        self.exprs = exprs
        self.in_schema = in_schema
        self.out_schema = out_schema
        self.mode = mode
        self.broken = False
        # donate-bit -> jitted program: donation is baked into a compiled
        # program, and a stream can mix donatable (fresh) batches with
        # cache-served ones, so each stage holds up to two variants
        self._fns: Dict[bool, Any] = {}
        self._ekeys = None
        # query parameters inside the expressions (plan-cache
        # parameterization): their CURRENT values append to every program
        # call as extra traced scalars, in stamped trace_pos order
        self._params = ex.ordered_params(exprs)

    @staticmethod
    def maybe(node, exprs, in_schema, out_schema, stateful,
              mode: str = "project"):
        """A FusedStage when fusion applies: enabled, every tree fusable,
        and no stateful expressions (their host-side per-batch state would
        bake into the trace)."""
        if not _fusion_enabled(node):
            return None
        if stateful or not all(e.tree_fusable() for e in exprs):
            return None
        return FusedStage(exprs, in_schema, out_schema, mode)

    def _build(self, donate: tuple = ()):
        import jax

        def run_project(num_rows, *arrays):
            b = ColumnarBatch.from_flat_arrays(self.in_schema, arrays,
                                               num_rows)
            cols = [ex.materialize(e.eval(b), b) for e in self.exprs]
            return tuple(a for c in cols for a in c.arrays())

        def run_filter(num_rows, *arrays):
            b = ColumnarBatch.from_flat_arrays(self.in_schema, arrays,
                                               num_rows)
            pred = self.exprs[0].eval(b)
            if isinstance(pred, Scalar):       # constant predicate: eager
                raise _ScalarPredicate()
            keep = pred.data & pred.validity & b.row_mask()
            cols, count = K.compact_columns(b.columns, keep)
            return tuple(a for c in cols for a in c.arrays()) + (count,)

        return jax.jit(run_project if self.mode == "project"
                       else run_filter, donate_argnums=donate)

    def __call__(self, batch: ColumnarBatch):
        """project -> ColumnarBatch | filter -> (ColumnarBatch, count) |
        None on permanent fallback."""
        if self.broken:
            return None
        import jax.numpy as jnp
        from ..exec.tracing import trace_span
        try:
            from ..analysis import recompile as _recompile
            # consumed-batch donation (exec/compile_cache): the stage's
            # program frees/reuses the input column HBM on ingestion;
            # cache-served batches (origin/shared) keep the plain variant
            donate = _donate_argnums(batch, 1)
            fn = self._fns.get(bool(donate))
            if fn is None:
                if self._ekeys is None:
                    self._ekeys = [_expr_cache_key(e) for e in self.exprs]
                ekeys = self._ekeys
                if any(k is None for k in ekeys):
                    fn = self._build(donate)      # unkeyable: per-exec jit
                    self._kernel = f"fused_{self.mode}_unkeyable"
                    _recompile.note_compile(
                        self._kernel,
                        ("unkeyable", self.mode, id(self), bool(donate)))
                else:
                    key = (self.mode, _schema_sig(self.in_schema),
                           tuple(ekeys), ("donate", bool(donate)))
                    self._kernel = _recompile.kernel_of(key)
                    # _fused_fn accounts this first call (compile or hit)
                    fn = _fused_fn(key, lambda: self._build(donate))
                self._fns[bool(donate)] = fn
            else:
                # later batches bypass the cache consult: count the call
                # here or `calls` would track stage INSTANCES, not
                # executions, and flagged()'s compile/call ratio would
                # fire spuriously for fused project/filter families
                _recompile.note_call(self._kernel)
            with trace_span(f"fused_{self.mode}"):
                outs = fn(_dev_count(batch),
                          *batch.flat_arrays(),
                          *ex.param_arg_values(self._params))
            _note_donated(batch, donate)
        except _ScalarPredicate:
            self.broken = True
            return None
        except Exception as e:
            if _donation_consumed(batch):
                raise          # executed-and-donated: no eager re-read
            # host-side expression slipped through the fusable gate
            import logging
            logging.getLogger("spark_rapids_tpu.fusion").warning(
                "whole-stage fusion fell back to eager for %s stage: %s",
                self.mode, e)
            self.broken = True
            return None
        if self.mode == "project":
            return ColumnarBatch.from_flat_arrays(self.out_schema,
                                                  list(outs),
                                                  batch.num_rows)
        # filter: compacted columns + device count (caller syncs)
        tmp = ColumnarBatch.from_flat_arrays(self.out_schema,
                                             list(outs[:-1]), 0)
        return tmp.columns, outs[-1]


class _ScalarPredicate(Exception):
    pass


def _dense_sig_supported(op: str, t) -> bool:
    """Dtype-level mirror of aggregates._dense_spec_supported (the fused
    path decides candidacy statically, before any column exists)."""
    if op in ("count", "count_star"):
        return True
    if t is None:
        return False
    if op in ("sum", "avg"):
        return t.is_integral or t == dt.BOOL or t.is_floating
    if op in ("min", "max"):
        return t != dt.STRING
    return op in ("first", "last")


# ---------------------------------------------------------------------------
# Leaves
# ---------------------------------------------------------------------------

class TpuLocalScanExec(TpuExec):
    """In-memory arrow table scan -> device batches (HostColumnarToGpu analog)."""

    CONTRACT = exec_contract(schema="defined", partitioning="source")
    METRICS = exec_metrics("scanTime", "cacheHitBatches")

    def __init__(self, table, schema: dt.Schema, batch_rows: int = 1 << 20,
                 num_partitions: int = 1, base_data=None):
        super().__init__()
        self.table = table
        self._schema = schema
        self.batch_rows = batch_rows
        self.num_partitions = max(1, num_partitions)
        # stable identity for the device cache: the ORIGINAL registered
        # table when this scan is a pruned per-query view of it
        self.base_data = base_data if base_data is not None else table

    @property
    def schema(self):
        return self._schema

    @property
    def output_partitions(self) -> int:
        return self.num_partitions

    def execute(self) -> List[Partition]:
        n = self.table.num_rows
        per_part = max(1, -(-n // self.num_partitions))
        parts = []
        for p in range(self.num_partitions):
            lo = min(p * per_part, n)
            hi = min(lo + per_part, n)
            parts.append(self._part_iter(lo, hi))
        return parts

    # DEVICE cache for in-memory tables: arrow tables are immutable, so
    # each scan batch caches as a SPILLABLE device batch reusable across
    # query runs (the reference's InMemoryTableScan / cached-table path,
    # GpuInMemoryTableScanExec). Round 3 cached only the host-prepped
    # numpy form and re-uploaded per run — on tunnel links the upload IS
    # the hot-path cost (0.2-4.4s for 96 MB depending on link mood), so
    # hits must serve device-resident columns. Entries key by the BASE
    # table identity + kept columns (pruning builds a fresh pa.Table per
    # query) and a weakref finalizer closes the handles when the base
    # table is collected; memory pressure spills entries through the
    # normal device->host->disk tiers, and a later hit re-promotes.
    _DEVICE_CACHE: Dict[tuple, dict] = {}
    _DEVICE_CACHE_MAX_BYTES = 6 << 30   # admission bound (spill tiers
    _device_cache_bytes = 0             # otherwise grow host/disk forever)
    _device_cache_lock = __import__("threading").Lock()

    @classmethod
    def _evict_table(cls, cache_key: tuple) -> None:
        # weakref-finalizer entry point: fires at an arbitrary bytecode,
        # possibly inside a frame HOLDING the cache/catalog/watermark
        # locks — taking them inline here self-deadlocks that thread
        # (exec/spill.defer_finalizer). Enqueue only; the next scan-cache
        # access or partition-task launch drains.
        from ..exec.spill import defer_finalizer
        defer_finalizer(cls._evict_table_now, cache_key)

    @classmethod
    def _evict_table_now(cls, cache_key: tuple) -> None:
        with cls._device_cache_lock:
            ent = cls._DEVICE_CACHE.pop(cache_key, None)
            if ent:
                cls._device_cache_bytes -= sum(
                    h.size_bytes for h in ent.values())
        for handle in (ent or {}).values():
            try:
                handle.close()
            except Exception:
                pass

    def _table_cache(self):
        import weakref
        from ..exec.spill import drain_deferred_finalizers
        drain_deferred_finalizers()
        cls = TpuLocalScanExec
        key = (id(self.base_data), tuple(self._schema.names()),
               self.batch_rows)
        with cls._device_cache_lock:
            ent = cls._DEVICE_CACHE.get(key)
            if ent is not None:
                return ent
            try:
                weakref.finalize(self.base_data, cls._evict_table, key)
            except TypeError:
                return None
            ent = cls._DEVICE_CACHE[key] = {}
            return ent

    def _part_iter(self, lo: int, hi: int) -> Partition:
        from ..exec.spill import (BufferLostError, CACHE_PRIORITY,
                                  SpillableColumnarBatch)
        from ..exec.tasks import prefetch_map

        def chunks():
            pos = lo
            while pos < hi:
                end = min(pos + self.batch_rows, hi)
                yield (pos, end - pos)
                pos = end

        cache = self._table_cache()

        def prep(item):
            pos, rows = item
            key = (pos, rows)
            if cache is not None:
                handle = cache.get(key)
                if handle is not None:
                    return ("cached", key, handle)
            return ("prep", key,
                    ColumnarBatch.prep_from_arrow(self.table.slice(pos,
                                                                   rows)))

        # HOST-side arrow->numpy conversion runs one batch ahead on a
        # background thread; the device upload stays on the task thread
        # BEHIND semaphore acquisition and memory admission, preserving the
        # ordering contract (GpuSemaphore.scala:74: acquire after host IO,
        # before device work)
        from ..exec.tracing import trace_span
        first = True
        for kind, key, payload in prefetch_map(chunks(), prep):
            if first:
                _task_begin()
                first = False
            with trace_span("scan_upload", self.metrics, "scanTime"):
                if kind == "cached":
                    try:
                        batch = payload.get_batch()
                        batch.origin = payload
                        self.metrics.inc("cacheHitBatches")
                    except BufferLostError:  # lint: recover-ok scan-cache miss repair: rebuilds the evicted device cache entry in place, no stage re-execution involved
                        # catalog was reset under us (tests do): rebuild
                        with TpuLocalScanExec._device_cache_lock:
                            if cache.get(key) is payload:
                                del cache[key]
                                TpuLocalScanExec._device_cache_bytes -= \
                                    payload.size_bytes
                        kind = "prep"
                        payload = ColumnarBatch.prep_from_arrow(
                            self.table.slice(*key))
                if kind != "cached":
                    prepped = payload
                    nbytes = ColumnarBatch.prepped_size_bytes(prepped)
                    _reserve(nbytes)
                    batch = ColumnarBatch.upload_prepped(prepped)
                    cls = TpuLocalScanExec
                    if cache is not None and prepped[0] == "packed":
                        # budget check under the lock: concurrent tasks
                        # must not both pass a stale-byte admission test
                        handle = None
                        with cls._device_cache_lock:
                            if key not in cache and \
                                    cls._device_cache_bytes + nbytes <= \
                                    cls._DEVICE_CACHE_MAX_BYTES:
                                handle = SpillableColumnarBatch(
                                    batch, CACHE_PRIORITY)
                                cache[key] = handle
                                cls._device_cache_bytes += handle.size_bytes
                        if handle is not None:
                            batch.origin = handle
            self.metrics.inc("numOutputRows", batch.num_rows_raw)
            self.metrics.inc("numOutputBatches")
            yield batch


class TpuCachedScanExec(TpuExec):
    """Scan over a df.cache()-materialized spillable batch: the device (or
    re-promoted) columns serve directly, no host conversion or upload
    (GpuInMemoryTableScanExec, reference spark310 shim)."""

    CONTRACT = exec_contract(schema="defined", partitioning="single")
    METRICS = exec_metrics()

    def __init__(self, plan):
        super().__init__()
        self.plan = plan

    @property
    def schema(self):
        return self.plan.schema

    @property
    def output_partitions(self) -> int:
        return 1

    def execute(self) -> List[Partition]:
        def part():
            _task_begin()
            # no _reserve: a device-resident cached batch is already in
            # the catalog's accounting, and acquire_batch performs
            # admission itself when re-promoting a spilled one
            batch = self.plan.handle.get_batch()
            self.metrics.inc("numOutputRows", batch.num_rows_raw)
            self.metrics.inc("numOutputBatches")
            yield batch
        return [part()]

    # the handle is DataFrame-owned (released by unpersist/GC), never by
    # query-scoped cleanup

    def _node_string(self):
        return "TpuCachedScanExec"


class TpuRangeExec(TpuExec):
    """range() generated on device (GpuRangeExec, basicPhysicalOperators.scala:187)."""

    CONTRACT = exec_contract(schema="defined", partitioning="source")
    METRICS = exec_metrics()

    def __init__(self, start: int, end: int, step: int, num_partitions: int = 1,
                 batch_rows: int = 1 << 20):
        super().__init__()
        self.start, self.end, self.step = start, end, step
        self.num_partitions = max(1, num_partitions)
        self.batch_rows = batch_rows
        self._schema = dt.Schema([dt.Field("id", dt.INT64, nullable=False)])

    @property
    def schema(self):
        return self._schema

    @property
    def output_partitions(self) -> int:
        return self.num_partitions

    def execute(self) -> List[Partition]:
        import jax.numpy as jnp
        total = max(0, -(-(self.end - self.start) // self.step))
        per_part = max(1, -(-total // self.num_partitions))

        def part(p):
            base = p * per_part
            count = max(0, min(per_part, total - base))
            pos = 0
            while pos < count:
                take = min(self.batch_rows, count - pos)
                cap = bucket(take)
                idx = jnp.arange(cap, dtype=jnp.int64)
                vals = self.start + (base + pos + idx) * self.step
                live = idx < take
                col = Column(dt.INT64, jnp.where(live, vals, 0), live)
                self.metrics.inc("numOutputRows", take)
                yield ColumnarBatch(self._schema, [col], take)
                pos += take

        return [part(p) for p in range(self.num_partitions)]


# ---------------------------------------------------------------------------
# Project / Filter
# ---------------------------------------------------------------------------

class TpuProjectExec(TpuExec):
    """Columnar projection (GpuProjectExec, basicPhysicalOperators.scala:64)."""

    CONTRACT = exec_contract(schema="defined", partitioning="preserve",
                             bound={"exprs": 0})
    METRICS = exec_metrics()

    def __init__(self, child: TpuExec, exprs: List[ex.Expression]):
        super().__init__(child)
        self.exprs = [bind_refs(e, child.schema) for e in exprs]
        self._schema = dt.Schema([
            dt.Field(ex.output_name(e, i), e.dtype, e.nullable)
            for i, e in enumerate(exprs)])

    @property
    def schema(self):
        return self._schema

    def execute(self) -> List[Partition]:
        return [self._map(p, i)
                for i, p in enumerate(self.children[0].execute())]

    def _map(self, part: Partition, pid: int = 0) -> Partition:
        exprs, stateful = _prepare_stateful(self.exprs, pid)
        fused = FusedStage.maybe(self, exprs, self.children[0].schema,
                                 self._schema, stateful)
        for batch in part:
            with trace_span(f"op_{type(self).__name__}", self.metrics, "opTime"):
                out = fused(batch) if fused is not None else None
                if out is None:
                    cols = [ex.materialize(e.eval(batch), batch)
                            for e in exprs]
                    out = ColumnarBatch(self._schema, cols, batch.num_rows)
            for n in stateful:
                n.advance(batch.num_rows)
            self.metrics.inc("numOutputRows", out.num_rows_raw)
            self.metrics.inc("numOutputBatches")
            yield out


class TpuFilterExec(TpuExec):
    """Columnar filter via compaction (GpuFilterExec + GpuFilter helper,
    basicPhysicalOperators.scala:98-132). Device count read at the batch
    boundary per the dynamic-size protocol."""

    CONTRACT = exec_contract(schema="passthrough", partitioning="preserve",
                             bound={"condition": 0})
    METRICS = exec_metrics()

    def __init__(self, child: TpuExec, condition: ex.Expression):
        super().__init__(child)
        self.condition = bind_refs(condition, child.schema)
        self._schema = child.schema

    @property
    def schema(self):
        return self._schema

    def execute(self) -> List[Partition]:
        return [self._map(p, i)
                for i, p in enumerate(self.children[0].execute())]

    def _map(self, part: Partition, pid: int = 0) -> Partition:
        (condition,), stateful = _prepare_stateful([self.condition], pid)
        fused = FusedStage.maybe(self, [condition], self.children[0].schema,
                                 self._schema, stateful, mode="filter")
        for batch in part:
            with trace_span(f"op_{type(self).__name__}", self.metrics, "opTime"):
                if fused is not None:
                    res = fused(batch)
                    if res is not None:
                        cols, count = res
                        # the count stays device-resident (possibly-empty
                        # batches flow through) so a filter never serializes
                        # the stream on a host readback
                        out = ColumnarBatch(self._schema, cols, count)
                        self.metrics.inc("numOutputRows", out.num_rows_raw)
                        self.metrics.inc("numOutputBatches")
                        yield out
                        continue
                pred = condition.eval(batch)
                for s in stateful:
                    s.advance(batch.num_rows)
                if isinstance(pred, Scalar):
                    if pred.value is True:
                        yield batch
                        continue
                    else:
                        continue
                keep = pred.data & pred.validity & batch.row_mask()
                cols, count = K.compact_columns(batch.columns, keep)
                out = ColumnarBatch(self._schema, cols, count)
                self.metrics.inc("numOutputRows", out.num_rows_raw)
                self.metrics.inc("numOutputBatches")
            yield out


class TpuCoalesceBatchesExec(TpuExec):
    """Concatenate small batches up to a goal (GpuCoalesceBatches). goal:
    'single' (RequireSingleBatch) or target row count."""

    CONTRACT = exec_contract(schema="passthrough", partitioning="preserve")
    METRICS = exec_metrics("concatTime")

    def __init__(self, child: TpuExec, goal: Any = "single",
                 target_rows: int = 1 << 22):
        super().__init__(child)
        self.goal = goal
        self.target_rows = target_rows

    @property
    def schema(self):
        return self.children[0].schema

    def execute(self) -> List[Partition]:
        return [self._map(p) for p in self.children[0].execute()]

    def _map(self, part: Partition) -> Partition:
        # accumulated batches are spillable while more stream in — raw device
        # batches must not pin a whole partition in HBM below sort/window
        # (the reference's GpuCoalesceBatches accumulates spillable batches).
        # Device-resident counts resolve in chunked batched readbacks (one
        # host round-trip per 8 batches), and coalesced outputs still yield
        # INCREMENTALLY so downstream consumes while upstream streams; the
        # target-size check runs at chunk granularity.
        from ..columnar.batch import resolve_counts
        from ..exec.spill import SpillableColumnarBatch
        pending: List[SpillableColumnarBatch] = []
        pending_rows = 0
        chunk: List[ColumnarBatch] = []

        def admit() -> None:
            nonlocal pending_rows
            resolve_counts(chunk)        # one round-trip per chunk
            for b in chunk:
                if b.num_rows > 0:
                    pending.append(SpillableColumnarBatch(b))
                    pending_rows += b.num_rows
            chunk.clear()

        for batch in part:
            if isinstance(batch.num_rows_raw, int) and batch.num_rows_raw == 0:
                continue
            chunk.append(batch)
            if len(chunk) >= 8:
                admit()
                if self.goal != "single" and pending_rows >= self.target_rows:
                    with trace_span("concat", self.metrics, "concatTime"):
                        yield concat_spillable(self.schema, pending)
                    pending, pending_rows = [], 0
        admit()
        if pending:
            with trace_span("concat", self.metrics, "concatTime"):
                yield concat_spillable(self.schema, pending)


# ---------------------------------------------------------------------------
# Aggregate
# ---------------------------------------------------------------------------

class TpuHashAggregateExec(TpuExec):
    """Sort-based group-by aggregate (GpuHashAggregateExec pipeline,
    aggregate.scala:305-560; decomposition per AggregateFunctions.scala).

    mode: 'complete' (this node sees all rows for its groups), 'partial'
    (update aggregation producing internal sum/count columns), or 'final'
    (merge partials + result projection). partial+final compose across a
    hash exchange exactly like the reference's two-phase planning.
    """

    CONTRACT = exec_contract(schema="defined", partitioning="defined",
                             extras=("agg_distribution",))
    METRICS = exec_metrics("computeAggTime")

    def __init__(self, child: TpuExec, grouping: List[ex.Expression],
                 aggregate_exprs: List[ex.Expression], mode: str = "complete",
                 per_partition_final: bool = False,
                 pre_filter: Optional[ex.Expression] = None,
                 pre_stage=None):
        super().__init__(child)
        self.mode = mode
        # pre_stage: a whole filter/project CHAIN the stage compiler folded
        # into this aggregate (plan/stage_compiler.StageChain, bound along
        # the original operator chain): the update phase evaluates the
        # chain and compacts via live-row mask inside ITS OWN fused
        # program, eliminating the separate per-op programs + count syncs
        # per batch (the whole-stage scan->filter->project->partial-agg
        # pipeline; docs/fusion.md). ``pre_filter`` is the legacy
        # single-condition form and converts to a one-step chain.
        if pre_stage is None and pre_filter is not None:
            from .stage_compiler import chain_of_filter
            pre_stage = chain_of_filter(pre_filter, child.schema)
        self.pre_stage = pre_stage
        # back-compat view: the folded condition when the chain is exactly
        # one filter (planner tests and repr key off it)
        self.pre_filter = pre_filter
        if pre_filter is None and pre_stage is not None and \
                len(pre_stage.steps) == 1 and \
                pre_stage.steps[0][0] == "filter":
            self.pre_filter = pre_stage.steps[0][1]
        # deterministic-subtree walk sees the chain's expressions
        self._pre_stage_exprs = pre_stage.exprs() if pre_stage is not None \
            else None
        # per_partition_final: the planner guarantees the child is hash-
        # partitioned on the grouping keys (an exchange directly below), so
        # each partition's groups are disjoint and the final merge runs
        # per-partition instead of draining every partition into one stream
        # (the reference's HashClusteredDistribution requirement that the
        # exchange satisfies, aggregate.scala two-phase planning)
        self.per_partition_final = per_partition_final
        self.grouping_src = grouping
        self.aggregate_exprs = aggregate_exprs
        self._dense_state = {}   # dense-dispatch memo shared across batches
        # collect aggregate leaves across output expressions
        self.leaves: List[lp.AggregateExpression] = []
        for e in aggregate_exprs:
            self.leaves.extend(
                e.collect(lambda x: isinstance(x, lp.AggregateExpression)))
        if mode == "final":
            # the child emits the internal partial schema: keys then update
            # cols, positionally — original names do not exist downstream
            self.grouping = [ex.BoundReference(i, g.dtype, True)
                             for i, g in enumerate(grouping)]
            self.bound_leaf_inputs = [None] * len(self.leaves)
        else:
            # with a folded pre_stage the agg's inputs are the CHAIN's
            # output rows, not the (now deeper) child's — bind against the
            # chain output schema
            in_schema = self.pre_stage.out_schema \
                if self.pre_stage is not None else child.schema
            self.grouping = [bind_refs(e, in_schema) for e in grouping]
            self.bound_leaf_inputs = [
                bind_refs(l.children[0], in_schema) if l.children else None
                for l in self.leaves]
        self._out_schema = dt.Schema([
            dt.Field(ex.output_name(e, i), e.dtype, e.nullable)
            for i, e in enumerate(aggregate_exprs)])
        if mode == "partial":
            self._out_schema = self._partial_schema()

    def _partial_schema(self) -> dt.Schema:
        """Internal partial-form schema: key cols + per-leaf update cols
        (identical construction in the upstream partial and downstream final
        execs, so the exchange carries a consistent internal schema)."""
        fields = [dt.Field(f"_k{i}", g.dtype, True)
                  for i, g in enumerate(self.grouping_src)]
        for i, l in enumerate(self.leaves):
            for j, (op, t) in enumerate(self._update_cols(l)):
                fields.append(dt.Field(f"_a{i}_{j}", t, True))
        return dt.Schema(fields)

    def _update_cols(self, leaf: lp.AggregateExpression):
        """(op, dtype) pairs of the update-phase outputs for one aggregate
        (avg decomposes into sum+count, AggregateFunctions.scala avg)."""
        t = leaf.children[0].dtype if leaf.children else None
        if leaf.op == "avg":
            return [("sum", dt.FLOAT64), ("count", dt.INT64)]
        if leaf.op in ("count", "count_star"):
            return [(leaf.op, dt.INT64)]
        return [(leaf.op, agg_k.result_dtype(leaf.op, t))]

    @property
    def schema(self):
        return self._out_schema

    def children_coalesce_goal(self, i: int):
        # stream per batch, but small scan batches waste per-batch dispatch:
        # coalesce toward the target batch size (the reference's TargetSize)
        return "target"

    @property
    def output_partitions(self) -> int:
        if self.mode == "partial" or self.per_partition_final:
            return self.children[0].output_partitions
        return 1

    def execute(self) -> List[Partition]:
        parts = self.children[0].execute()
        if self.mode == "partial":
            # update-only aggregation is per-partition (upstream of the
            # hash exchange, like the reference's partial mode)
            return [self._stream_merge(p, project=False) for p in parts]
        if self.mode == "final" and self.per_partition_final:
            # child is hash-partitioned on the grouping keys: groups are
            # disjoint per partition, each merges independently (the
            # distributed reduce side)
            return [self._stream_merge(p, project=True) for p in parts]
        # complete/final must see every row of a group: all partitions feed
        # ONE streaming update+merge loop (aggregate.scala:427-485) whose
        # state is one spillable partial batch — never a concat of the input
        def stream():
            for p in parts:
                yield from p
        return [self._stream_merge(stream(), project=(self.mode != "partial"))]

    # -- streaming update + merge loop ---------------------------------------
    # pending update-phase partials accumulate (spillable) up to this many
    # before one merge pass: merging every batch would dispatch a merge
    # program per input batch; partials are tiny (bucket(n_groups)) so the
    # fan-in costs little memory and cuts merge dispatches ~MERGE_FAN_IN x
    MERGE_FAN_IN = 8

    def _stream_merge(self, batches, project: bool) -> Partition:
        """Per-batch update-agg; pending partials merge in fan-in groups
        (the reference's hot loop, aggregate.scala:427-485, with batched
        merge cadence). All state lives in the spill catalog between
        batches, so aggregation residency stays bounded.

        The update phase is PIPELINED on the shared deferred-scalar window
        (exec/pipeline.PipelineWindow — the same primitive the join stream
        loop uses): each input batch's fused probe is dispatched
        immediately, its stats scalar parked on the window, and the kernel
        half only runs once the window lands it — by then the stat
        readback has resolved in ONE batched device_get with its
        half-window peers, so the per-batch device->host round-trip
        (hundreds of ms on a tunneled device) overlaps compute instead of
        serializing the stream."""
        from .. import config as cfg
        from ..exec.pipeline import PipelineWindow
        from ..exec.spill import SpillableColumnarBatch
        pschema = self._partial_schema()
        pending: List[SpillableColumnarBatch] = []

        def merge_pending() -> None:
            if len(pending) <= 1:
                return
            batches_ = []
            total = 0
            for s in pending:
                b = s.get_batch()
                total += b.device_size_bytes()
                batches_.append(b)
                s.close()
            pending.clear()
            _reserve(2 * total)
            merged_in = concat_batches(pschema, batches_)
            pending.append(SpillableColumnarBatch(
                self._merge_to_partial(merged_in)))

        def bank(pb: ColumnarBatch) -> None:
            pending.append(SpillableColumnarBatch(pb))
            if len(pending) >= self.MERGE_FAN_IN:
                merge_pending()

        def finish(batch: ColumnarBatch, tok, stats=None) -> ColumnarBatch:
            """Kernel half for one landed batch: ``stats`` is the
            window-resolved probe readback (None if the batched get
            failed — _fused_finish then re-reads and its handler degrades
            this one batch to the eager path)."""
            pb = self._fused_finish(tok, stats)
            if pb is not None and pb.capacity > agg_k.DENSE_MAX_SLOTS:
                pb = self._shrink_partial(pb)
            if pb is None:
                pb = self._update_partial_eager(batch)
            return pb

        depth = max(1, int(cfg.TpuConf().get(cfg.AGG_PIPELINE_DEPTH)))
        # metrics=: the window's batched stat readbacks charge THIS exec's
        # hostSyncs (exec/metrics.exec_scope), not just the span string
        win = PipelineWindow(depth, metrics=self.metrics)
        for batch in batches:
            # semaphore ordering contract: acquire only once the first input
            # batch exists (upstream host IO done), GpuSemaphore.scala:74-78
            _task_begin()
            _reserve(batch.device_size_bytes())
            with trace_span("aggregate", self.metrics, "computeAggTime"):
                if self.mode == "final":
                    ready = win.push(lambda b=batch: b)
                else:
                    tok = self._fused_dispatch(batch, "update")
                    if tok is None:
                        pb = self._update_partial_eager(batch)
                        ready = win.push(lambda p=pb: p)
                    elif tok[0] in ("dense", "sortmm"):
                        # park the probe stats scalar on the window
                        ready = win.push(
                            lambda v, b=batch, t=tok: finish(b, t, v),
                            tok[-1])
                    else:
                        # 'done': whole kernel already dispatched, count
                        # device-resident — nothing to resolve
                        ready = win.push(
                            lambda b=batch, t=tok: finish(b, t))
                for pb in ready:
                    bank(pb)
        with trace_span("aggregate", self.metrics, "computeAggTime"):
            for pb in win.flush():
                bank(pb)
            merge_pending()
        if not pending:
            final_in = ColumnarBatch.empty(pschema)
        else:
            final_in = pending[0].get_batch()
            pending[0].close()
        if project:
            yield from self._final(final_in)
        else:
            self.metrics.inc("numOutputRows", final_in.num_rows_raw)
            yield final_in

    # -- update (per input batch) --------------------------------------------
    def _build_update_specs(self, batch: ColumnarBatch):
        keys = [ex.materialize(g.eval(batch), batch) for g in self.grouping]
        specs: List[agg_k.AggSpec] = []
        for leaf, bound in zip(self.leaves, self.bound_leaf_inputs):
            col = ex.materialize(bound.eval(batch), batch) \
                if bound is not None else None
            for (op, _t) in self._update_cols(leaf):
                if leaf.op == "avg":
                    import jax.numpy as jnp
                    c = col
                    if op == "sum" and c.dtype != dt.FLOAT64:
                        c = Column(dt.FLOAT64,
                                   c.data.astype(jnp.float64), c.validity)
                    specs.append(agg_k.AggSpec(op, c))
                else:
                    specs.append(agg_k.AggSpec(
                        op, col, ignore_nulls=leaf.ignore_nulls))
        return keys, specs

    def _update_partial_batch(self, batch: ColumnarBatch) -> ColumnarBatch:
        """Update-phase aggregation of one input batch into partial form."""
        fused = self._maybe_fused_phase(batch, "update")
        if fused is not None:
            return self._shrink_partial(fused)
        return self._update_partial_eager(batch)

    def _update_partial_eager(self, batch: ColumnarBatch) -> ColumnarBatch:
        """Eager (per-op dispatch) update aggregation — the fallback when
        whole-stage fusion does not apply."""
        batch = self._apply_pre_stage_eager(batch)
        keys, specs = self._build_update_specs(batch)
        cap = batch.capacity
        if not self.grouping:
            aggs = agg_k.reduce_aggregate(specs, batch.num_rows, cap)
            return ColumnarBatch(self._partial_schema(), aggs, 1)
        out_keys, aggs, n_groups = agg_k.groupby_aggregate_fast(
            keys, specs, batch.num_rows, cap,
            allow_matmul=_matmul_agg_enabled(), dense_state=self._dense_state)
        return self._shrink_partial(
            ColumnarBatch(self._partial_schema(), out_keys + aggs, n_groups))

    def _shrink_partial(self, batch: ColumnarBatch) -> ColumnarBatch:
        """Compact a partial batch to bucket(n_groups) capacity: group-by
        outputs inherit the INPUT capacity, and carrying a million-slot
        batch holding six groups into the merge/final phases wastes memory
        and forces the downstream fused programs to compile at the huge
        capacity (compile cost grows steeply with shape on some backends)."""
        ncap = bucket(max(batch.num_rows, 1))
        if ncap >= batch.capacity:
            return batch
        cols = [K.rebucket_column(c, batch.num_rows, ncap)
                for c in batch.columns]
        return ColumnarBatch(batch.schema, cols, batch.num_rows)

    def _apply_pre_stage_eager(self, batch: ColumnarBatch) -> ColumnarBatch:
        """Eager fallback of the folded filter/project chain (fused paths
        evaluate the chain inside their own traced programs)."""
        if self.pre_stage is None or batch.num_rows == 0:
            return batch
        return self.pre_stage.eval_eager(batch)

    def _stage_param_args(self) -> tuple:
        """Current values of the folded chain's query parameters — the
        extra traced scalars every UPDATE-phase fused program takes after
        the batch's flat arrays (merge/final programs never evaluate the
        chain, so they take none)."""
        if self.pre_stage is None or not self.pre_stage.params:
            return ()
        return ex.param_arg_values(self.pre_stage.params)

    def _traced_pre_stage(self, b: ColumnarBatch):
        """Folded-chain evaluation inside a fused trace: returns
        (post-chain batch, live-row mask or None). The mask replaces
        physical compaction — a scatter, the slowest TPU primitive — and
        the agg kernels rank/mask dead rows for free."""
        if self.pre_stage is None:
            return b, None
        return self.pre_stage.eval_traced(b)

    # -- whole-stage fused group-by (expression eval + kernel in <=2
    # device programs per batch; see the fusion section above) --------------
    def _spec_signature(self, phase: str):
        """Static (op, input dtype) signature of the phase's AggSpec list."""
        sig = []
        if phase == "update":
            for leaf, bound in zip(self.leaves, self.bound_leaf_inputs):
                t = bound.dtype if bound is not None else None
                if leaf.op == "avg":
                    sig += [("sum", dt.FLOAT64), ("count", t)]
                else:
                    sig.append((leaf.op, t))
        else:
            for leaf in self.leaves:
                update_types = [ut for (_op, ut) in self._update_cols(leaf)]
                for op, ut in zip(self._merge_ops(leaf), update_types):
                    sig.append((op, ut))
        return tuple(sig)

    def _fusion_sig(self, phase: str, in_schema: dt.Schema):
        gk = [_expr_cache_key(g) for g in self.grouping]
        bk = [None if b is None else _expr_cache_key(b)
              for b in self.bound_leaf_inputs]
        if any(k is None for k in gk) or any(
                b is not None and k is None for b, k in
                zip(self.bound_leaf_inputs, bk)):
            return None
        return ("agg", phase, self.mode, tuple(gk), tuple(bk),
                tuple((l.op, l.ignore_nulls) for l in self.leaves),
                _schema_sig(in_schema))

    def _maybe_fused_phase(self, batch: ColumnarBatch,
                           phase: str) -> Optional[ColumnarBatch]:
        """Fused group-by phase: an optional dense-stats probe plus ONE
        fused kernel program per batch (vs one dispatch per op in the eager
        path — the dominant engine cost). Dispatch mirrors
        groupby_aggregate_fast: single small-span integral key -> dense MXU
        one-hot path; otherwise the traced sort+scatter path. Falls back to
        eager permanently on any trace failure.

        Single-shot form (merge/final phases). The streaming update loop
        instead calls the `_fused_dispatch` / `_fused_finish` halves
        directly so several batches' probe round-trips stay in flight."""
        tok = self._fused_dispatch(batch, phase)
        if tok is None:
            return None
        return self._fused_finish(tok)

    def _build_eval_fn(self, phase: str):
        # resolves the exec via the thread-local stack, NOT a captured
        # self: these closures end up inside globally-cached jitted
        # programs, and a strong self would leak the exec (+ its
        # CachedScan owners) forever
        def build_eval(b):
            # the folded filter/project CHAIN (pre_stage) evaluates inside
            # the traced program (update phase only: merge/final consume
            # already-filtered partials); its filters become a LIVE-ROW
            # MASK — physical compaction would cost a scatter, the slowest
            # TPU primitive, per batch, while the sort and dense kernels
            # rank/mask dead rows for free. Returns (keys, specs,
            # effective_row_count, live_mask); kernels must see the
            # POST-filter count or dead rows would join the NULL group,
            # and live_mask is None when the chain has no filter.
            node = _trace_exec_stack()[-1]
            n_eff = b.num_rows
            mask = None
            if phase == "update":
                b, mask = node._traced_pre_stage(b)
                if mask is not None:
                    import jax.numpy as jnp
                    n_eff = jnp.sum(mask).astype(jnp.int32)
                keys, specs = node._build_update_specs(b)
            else:
                keys, specs = node._merge_specs(b)
            return keys, specs, n_eff, mask
        return build_eval

    def _fused_dispatch(self, batch: ColumnarBatch, phase: str):
        """First half of the fused phase: dispatch the probe (or, where no
        probe is needed, the whole kernel) without any blocking sync. The
        streaming loop parks these on the shared PipelineWindow, which
        fetches every landing probe's stats in one batched readback.
        Returns an opaque token for `_fused_finish`, or None -> eager."""
        if getattr(self, "_fusion_broken", False) or not _fusion_enabled(self):
            return None
        if not all(e.tree_fusable() for e in self.grouping) or any(
                b is not None and not b.tree_fusable()
                for b in self.bound_leaf_inputs):
            return None
        if self.pre_stage is not None and not self.pre_stage.fusable():
            return None
        import jax
        import jax.numpy as jnp

        in_schema = batch.schema
        cap = batch.capacity
        sig = self._fusion_sig(phase, in_schema)
        if sig is None:
            return None
        if self.pre_stage is not None:
            skey = self.pre_stage.cache_key()
            if skey is None:
                return None
            sig = sig + ("pre_stage", skey)
        build_eval = self._build_eval_fn(phase)
        pschema = self._partial_schema()
        # folded-chain query parameters ride ONLY the update-phase
        # programs (the chain evaluates there); current values append
        # after the flat arrays, positions baked by StageChain stamping
        pargs = self._stage_param_args() if phase == "update" else ()

        try:
            if not self.grouping:
                donate = _donate_argnums(batch, 1)

                def build_reduce():
                    def fn(num_rows, *arrays):
                        b = ColumnarBatch.from_flat_arrays(
                            in_schema, arrays, num_rows)
                        _keys, specs, n_eff, mask = build_eval(b)
                        aggs = agg_k.reduce_aggregate(specs, n_eff,
                                                      b.capacity,
                                                      live_mask=mask)
                        return tuple(a for c in aggs for a in c.arrays())
                    return jax.jit(fn, donate_argnums=donate)
                fn = _fused_fn(sig + ("reduce", cap,
                                      ("donate", bool(donate))),
                               build_reduce)
                with _trace_exec(self):
                    outs = fn(_dev_count(batch), *batch.flat_arrays(),
                              *pargs)
                _note_donated(batch, donate)
                return ("done", ColumnarBatch.from_flat_arrays(
                    pschema, list(outs), 1))

            if phase != "update" and cap <= (1 << 15):
                # merge inputs are concatenated partials — small. The plain
                # fused sort+scatter program handles them in ONE dispatch
                # with no probe and no host readback (scatter serialization
                # only bites at scan-batch capacities)
                return self._dispatch_plain_sort(batch, sig, in_schema, cap,
                                                 build_eval, pargs)

            spec_sig = self._spec_signature(phase)
            key_dtype = (self.grouping[0].dtype
                         if len(self.grouping) == 1 else None)
            dense_cand = (
                _matmul_agg_enabled() and
                self._dense_state.get("enabled", True) and
                key_dtype in (dt.INT8, dt.INT16, dt.INT32, dt.INT64,
                              dt.BOOL, dt.DATE, dt.TIMESTAMP) and
                all(_dense_sig_supported(op, t) for op, t in spec_sig))

            if dense_cand:
                def build_probe():
                    def fn(num_rows, *arrays):
                        b = ColumnarBatch.from_flat_arrays(
                            in_schema, arrays, num_rows)
                        keys, specs, n_eff, mask = build_eval(b)
                        float_cols = [
                            s.column for s in specs
                            if s.op in ("sum", "avg") and s.column is not None
                            and s.column.dtype.is_floating]
                        return agg_k.dense_key_stats(
                            keys[0], num_rows if mask is not None else n_eff,
                            extra_mask=mask, float_cols=float_cols)
                    return jax.jit(fn)
                probe = _fused_fn(sig + ("probe", cap), build_probe)
                with _trace_exec(self):
                    rmin, dec = probe(_dev_count(batch),
                                      *batch.flat_arrays(), *pargs)
                return ("dense", batch, phase, sig, in_schema, cap,
                        rmin, dec)

            return self._dispatch_sort(batch, phase, sig, in_schema, cap)
        except Exception as e:
            if _donation_consumed(batch):
                raise          # executed-and-donated: no eager re-read
            import logging
            logging.getLogger("spark_rapids_tpu.fusion").warning(
                "fused %s group-by fell back to eager: %s", phase, e)
            self._fusion_broken = True
            return None

    def _dispatch_sort(self, batch: ColumnarBatch, phase: str, sig, in_schema,
                       cap):
        """Sort-path dispatch half. With matmul enabled: a probe computing
        the sort order + segment starts + group count/absmax stats (the
        finish half picks the static group bucket from them). Otherwise the
        whole scatter kernel in one dispatch, count left device-resident."""
        import jax
        import jax.numpy as jnp
        build_eval = self._build_eval_fn(phase)
        pargs = self._stage_param_args() if phase == "update" else ()

        if not _matmul_agg_enabled():
            return self._dispatch_plain_sort(batch, sig, in_schema, cap,
                                             build_eval, pargs)

        # staged sort path: probe (sort + segments + group-count stats) ->
        # MXU matmul segment kernel with a static group bucket. TPU scatters
        # serialize (the one-program scatter kernel ran ~850ms/batch on q1);
        # matmul segment reductions at small Kb are ~10x faster
        def build_sort_probe():
            def fn(num_rows, *arrays):
                b = ColumnarBatch.from_flat_arrays(
                    in_schema, arrays, num_rows)
                keys, specs, n_eff, mask = build_eval(b)
                capb = b.capacity
                order = K.sort_indices(
                    [K.SortKey(c) for c in keys], n_eff, capb,
                    live_mask=mask)
                skeys = [K.gather_column(c, order) for c in keys]
                starts = K.segment_starts_from_sorted_keys(
                    skeys, n_eff, capb)
                parts = [jnp.sum(starts).astype(jnp.float64)]
                for s in specs:
                    if s.op in ("sum", "avg") and \
                            s.column is not None and \
                            s.column.dtype.is_floating:
                        c = s.column
                        a = jnp.where(
                            c.validity & ~jnp.isnan(c.data),
                            jnp.abs(c.data), 0.0)
                        parts.append(jnp.max(a).astype(jnp.float64))
                return order, starts, n_eff, jnp.stack(parts)
            return jax.jit(fn)
        probe = _fused_fn(sig + ("sort-probe", cap), build_sort_probe)
        with _trace_exec(self):
            order, starts, n_eff_dev, dec = probe(
                _dev_count(batch), *batch.flat_arrays(), *pargs)
        return ("sortmm", batch, phase, sig, in_schema, cap,
                order, starts, n_eff_dev, dec)

    def _dispatch_plain_sort(self, batch: ColumnarBatch, sig, in_schema, cap,
                             build_eval, pargs: tuple = ()):
        """Whole sort+scatter group-by in ONE dispatch, count left
        device-resident (no probe, no readback)."""
        import jax
        pschema = self._partial_schema()
        donate = _donate_argnums(batch, 1)

        def build_sort():
            def fn(num_rows, *arrays):
                b = ColumnarBatch.from_flat_arrays(in_schema, arrays,
                                                   num_rows)
                keys, specs, n_eff, mask = build_eval(b)
                ok, oa, ng = agg_k.groupby_aggregate(
                    keys, specs, n_eff, b.capacity, live_mask=mask)
                flat = [a for c in ok + oa for a in c.arrays()]
                return tuple(flat) + (ng,)
            return jax.jit(fn, donate_argnums=donate)
        fn = _fused_fn(sig + ("sort", cap, ("donate", bool(donate))),
                       build_sort)
        with _trace_exec(self):
            outs = fn(_dev_count(batch), *batch.flat_arrays(), *pargs)
        _note_donated(batch, donate)
        pb = ColumnarBatch.from_flat_arrays(pschema, list(outs[:-1]),
                                            outs[-1])
        return ("done", pb)

    def _fused_finish(self, tok,
                      stats=None) -> Optional[ColumnarBatch]:
        """Second half of the fused phase: read the probe stats (or take
        them pre-read — the streaming loop fetches every in-flight batch's
        stats in ONE batched device_get) and dispatch the kernel. Returns
        the partial batch, or None when fusion failed (caller goes eager on
        the retained batch)."""
        try:
            kind = tok[0]
            if kind == "done":
                return tok[1]
            if kind == "dense":
                pb = self._finish_dense(tok, stats)
                if pb is not None:
                    return pb
                # dense didn't fit this batch: stage it through the sort
                # path (a blocking probe for THIS batch only; once the span
                # check disables dense, later batches dispatch sort probes
                # up front)
                _, batch, phase, sig, in_schema, cap, _rmin, _dec = tok
                tok = self._dispatch_sort(batch, phase, sig, in_schema, cap)
                return self._fused_finish(tok)
            assert kind == "sortmm", kind
            return self._finish_sortmm(tok, stats)
        except Exception as e:
            if len(tok) > 1 and isinstance(tok[1], ColumnarBatch) and \
                    _donation_consumed(tok[1]):
                raise          # executed-and-donated: no eager re-read
            import logging
            logging.getLogger("spark_rapids_tpu.fusion").warning(
                "fused group-by finish fell back to eager: %s", e)
            self._fusion_broken = True
            return None

    def _finish_dense(self, tok, stats=None) -> Optional[ColumnarBatch]:
        import jax
        import jax.numpy as jnp
        import numpy as np
        from ..columnar.column import bucket as _bucket
        _, batch, phase, sig, in_schema, cap, rmin, dec = tok
        build_eval = self._build_eval_fn(phase)
        pschema = self._partial_schema()
        if stats is None:
            stats = np.asarray(dec)  # lint: host-sync-ok window-degraded re-read of ONE batch's stats scalar
        span, absmaxes = stats[0], stats[2:]
        f32_safe = bool(all(a <= agg_k.F32_SAFE_ABSMAX for a in absmaxes))
        if span + 2 > agg_k.DENSE_MAX_SLOTS:
            self._dense_state["enabled"] = False
        if not (span + 2 <= agg_k.DENSE_MAX_SLOTS and f32_safe):
            return None
        Kb = _bucket(int(span) + 2, 128)
        # the dense kernel is this batch's LAST consumer (the probe only
        # read it): donate the columns so HBM frees on ingestion
        donate = _donate_argnums(batch, 2)

        def build_dense():
            def fn(num_rows, rmin_d, *arrays):
                b = ColumnarBatch.from_flat_arrays(
                    in_schema, arrays, num_rows)
                keys, specs, n_eff, mask = build_eval(b)
                ok, oa, ng = agg_k.groupby_dense(
                    keys[0], specs,
                    num_rows if mask is not None else n_eff, Kb, rmin_d,
                    extra_mask=mask)
                flat = [a for c in ok + oa for a in c.arrays()]
                return tuple(flat) + (ng,)
            return jax.jit(fn, donate_argnums=donate)
        fn = _fused_fn(sig + ("dense", cap, Kb, ("donate", bool(donate))),
                       build_dense)
        pargs = self._stage_param_args() if phase == "update" else ()
        with _trace_exec(self):
            outs = fn(_dev_count(batch), rmin, *batch.flat_arrays(),
                      *pargs)
        _note_donated(batch, donate)
        return ColumnarBatch.from_flat_arrays(pschema, list(outs[:-1]),
                                              outs[-1])

    def _finish_sortmm(self, tok, stats=None) -> ColumnarBatch:
        import jax
        import jax.numpy as jnp
        import numpy as np
        from ..columnar.column import bucket as _bucket
        (_, batch, phase, sig, in_schema, cap,
         order, starts, n_eff_dev, dec) = tok
        build_eval = self._build_eval_fn(phase)
        pschema = self._partial_schema()
        if stats is None:
            stats = np.asarray(dec)  # lint: host-sync-ok window-degraded re-read of ONE batch's stats scalar
        n_groups = int(stats[0])
        f32_safe = bool(all(a <= agg_k.F32_SAFE_ABSMAX for a in stats[1:]))
        Kb = _bucket(max(n_groups, 1))
        # per-spec mixing below: matmul where supported (count, float
        # sum/avg), scatter-at-Kb otherwise (min/max, int sums)
        use_mm = Kb <= agg_k.MATMUL_MAX_GROUPS and f32_safe
        # last consumer of the batch columns AND of the probe's order/
        # starts arrays (args 1-2): donate them together
        donate = _donate_argnums(batch, 4)
        if donate:
            donate = (1, 2) + donate

        def build_sort_kernel(Kb=Kb, use_mm=use_mm):
            def fn(num_rows, order, starts, n_eff, *arrays):
                b = ColumnarBatch.from_flat_arrays(
                    in_schema, arrays, num_rows)
                keys, specs, _n, _mask = build_eval(b)
                capb = b.capacity
                live = jnp.arange(capb) < n_eff
                seg_ids = K.segment_ids(starts)
                ng = jnp.sum(starts).astype(jnp.int32)
                start_perm, _cnt = K.compaction_indices(starts)
                kidx = start_perm[:Kb]
                glive = jnp.arange(Kb) < ng
                skeys = [K.gather_column(c, order) for c in keys]
                ok = [K.gather_column(c, kidx, out_valid=glive)
                      for c in skeys]
                oa = []
                for s in specs:
                    sc = s
                    if s.column is not None:
                        sc = s._replace(column=K.gather_column(
                            s.column, order))
                    if use_mm and agg_k._matmul_supported(sc):
                        agg = agg_k.segment_aggregate_matmul(
                            sc, seg_ids, live, Kb)
                    else:
                        agg = agg_k.segment_aggregate(
                            sc, seg_ids, live, capb,
                            num_segments=Kb)
                    oa.append(agg_k._mask_to(agg, glive))
                flat = [a for c in ok + oa for a in c.arrays()]
                return tuple(flat) + (ng,)
            return jax.jit(fn, donate_argnums=donate)
        fn = _fused_fn(sig + ("sort-mm", cap, Kb, use_mm,
                              ("donate", bool(donate))),
                       build_sort_kernel)
        pargs = self._stage_param_args() if phase == "update" else ()
        with _trace_exec(self):
            outs = fn(_dev_count(batch), order, starts,
                      n_eff_dev, *batch.flat_arrays(), *pargs)
        _note_donated(batch, donate)
        # group count came back with the probe stats — no second readback
        return ColumnarBatch.from_flat_arrays(pschema, list(outs[:-1]),
                                              n_groups)

    # -- final (merge partials) ---------------------------------------------
    def _merge_ops(self, leaf: lp.AggregateExpression):
        if leaf.op == "avg":
            return ["sum", "sum"]
        if leaf.op in ("count", "count_star"):
            return ["sum"]
        return [leaf.op]

    def _merge_specs(self, batch: ColumnarBatch):
        nk = len(self.grouping_src)
        keys = list(batch.columns[:nk])
        specs: List[agg_k.AggSpec] = []
        ci = nk
        for leaf in self.leaves:
            for op in self._merge_ops(leaf):
                specs.append(agg_k.AggSpec(op, batch.columns[ci],
                                           ignore_nulls=leaf.ignore_nulls))
                ci += 1
        return keys, specs

    def _merge_to_partial(self, batch: ColumnarBatch) -> ColumnarBatch:
        """Merge-phase aggregation of concatenated partials back to one row
        per group (the merge half of the CudfAggregate update/merge pairs)."""
        fused = self._maybe_fused_phase(batch, "merge")
        if fused is not None:
            # already-small outputs keep their device-resident count — a
            # shrink would force a blocking readback per merge cycle
            if fused.capacity <= agg_k.DENSE_MAX_SLOTS:
                return fused
            return self._shrink_partial(fused)
        keys, specs = self._merge_specs(batch)
        if not keys:
            aggs = agg_k.reduce_aggregate(specs, batch.num_rows,
                                          batch.capacity)
            return ColumnarBatch(self._partial_schema(), aggs, 1)
        out_keys, aggs, n_groups = agg_k.groupby_aggregate_fast(
            keys, specs, batch.num_rows, batch.capacity,
            allow_matmul=_matmul_agg_enabled(), dense_state=self._dense_state)
        return self._shrink_partial(
            ColumnarBatch(self._partial_schema(), out_keys + aggs, n_groups))

    def _final(self, batch: ColumnarBatch) -> Partition:
        with trace_span("aggregate", self.metrics, "computeAggTime"):
            fused = self._maybe_fused_final(batch)
            if fused is not None:
                self.metrics.inc("numOutputRows", fused.num_rows_raw)
                yield fused
                return
            keys, specs = self._merge_specs(batch)
            if not keys:
                aggs = agg_k.reduce_aggregate(specs, batch.num_rows,
                                              batch.capacity)
                n_groups = 1
                out_keys = []
            else:
                out_keys, aggs, n_groups = agg_k.groupby_aggregate_fast(
                    keys, specs, batch.num_rows, batch.capacity,
                    allow_matmul=_matmul_agg_enabled(),
                    dense_state=self._dense_state)
        out = self._project_results(out_keys, aggs, n_groups)
        self.metrics.inc("numOutputRows", out.num_rows_raw)
        yield out

    def _maybe_fused_final(self, batch: ColumnarBatch
                           ) -> Optional[ColumnarBatch]:
        """Fused merge + result projection: one device program for the whole
        final phase (merge groupby -> leaf assembly -> result expressions)."""
        if getattr(self, "_fusion_broken", False) or not _fusion_enabled(self):
            return None
        if not all(e.tree_fusable() for e in self.aggregate_exprs):
            return None
        import jax
        import jax.numpy as jnp
        sig = self._fusion_sig("final", batch.schema)
        if sig is None:
            return None
        rkeys = [_expr_cache_key(e) for e in self.aggregate_exprs]
        if any(k is None for k in rkeys):
            return None
        in_schema = batch.schema
        cap = batch.capacity
        donate = _donate_argnums(batch, 1)

        def build():
            def fn(num_rows, *arrays):
                node = _trace_exec_stack()[-1]   # no self capture: see _FUSED_CACHE
                b = ColumnarBatch.from_flat_arrays(in_schema, arrays,
                                                   num_rows)
                keys, specs = node._merge_specs(b)
                if not keys:
                    aggs = agg_k.reduce_aggregate(specs, num_rows,
                                                  b.capacity)
                    out = node._project_results([], aggs, 1)
                    ng = jnp.int32(1)
                else:
                    ok, aggs, ng = agg_k.groupby_aggregate(
                        keys, specs, num_rows, b.capacity)
                    out = node._project_results(ok, aggs, ng)
                return tuple(out.flat_arrays()) + (ng,)
            return jax.jit(fn, donate_argnums=donate)

        try:
            fn = _fused_fn(sig + ("final", tuple(rkeys), cap,
                                  ("donate", bool(donate))), build)
            with _trace_exec(self):
                outs = fn(_dev_count(batch), *batch.flat_arrays())
            _note_donated(batch, donate)
            return ColumnarBatch.from_flat_arrays(
                self._out_schema, list(outs[:-1]), outs[-1])
        except Exception as e:
            if _donation_consumed(batch):
                raise          # executed-and-donated: no eager re-read
            import logging
            logging.getLogger("spark_rapids_tpu.fusion").warning(
                "fused final group-by fell back to eager: %s", e)
            self._fusion_broken = True
            return None

    # -- result projection ---------------------------------------------------
    def _project_results(self, out_keys: List[Column], aggs: List[Column],
                         n_groups: int) -> ColumnarBatch:
        """Build the output batch by evaluating result expressions over an
        internal batch of [key cols..., leaf agg cols...] (boundFinal/result
        projections, aggregate.scala:487-560)."""
        import jax.numpy as jnp
        # assemble leaf values: for avg, divide sum/count here
        leaf_cols: List[Column] = []
        ai = 0
        for leaf in self.leaves:
            ncols = len(self._update_cols(leaf)) if self.mode != "final" else \
                len(self._merge_ops(leaf))
            if leaf.op == "avg":
                s, c = aggs[ai], aggs[ai + 1]
                valid = s.validity & (c.data > 0)
                data = jnp.where(valid, s.data / jnp.maximum(
                    c.data.astype(jnp.float64), 1.0), 0.0)
                leaf_cols.append(Column(dt.FLOAT64, data, valid))
            elif leaf.op in ("count", "count_star"):
                # counts are never NULL: empty/all-null groups read 0
                # (jnp.maximum: n_groups may be traced in the fused final)
                c = aggs[ai]
                live = jnp.arange(c.capacity) < jnp.maximum(n_groups, 1)
                data = jnp.where(live, jnp.where(c.validity, c.data, 0), 0)
                leaf_cols.append(Column(dt.INT64, data, live))
            else:
                leaf_cols.append(aggs[ai])
            ai += ncols

        cap = (out_keys[0].capacity if out_keys else
               (leaf_cols[0].capacity if leaf_cols else 128))
        internal_fields = [dt.Field(f"_k{i}", self.grouping_src[i].dtype, True)
                           for i in range(len(out_keys))]
        internal_fields += [dt.Field(f"_l{i}", l.dtype, True)
                            for i, l in enumerate(self.leaves)]
        internal = ColumnarBatch(dt.Schema(internal_fields),
                                 out_keys + leaf_cols, n_groups)

        # rewrite output exprs: leaves -> bound refs into internal batch
        # (no metrics here: n_groups may be a tracer in the fused final;
        # callers account rows at the host boundary)
        out_cols = []
        for e in self.aggregate_exprs:
            rewritten = self._rewrite_result(e, len(out_keys))
            out_cols.append(ex.materialize(rewritten.eval(internal), internal))
        return ColumnarBatch(self._out_schema, out_cols, n_groups)

    def _rewrite_result(self, e: ex.Expression, nk: int) -> ex.Expression:
        # computed grouping keys restated in the output (SQL `GROUP BY
        # expr` re-parses the expression) match STRUCTURALLY via
        # _expr_cache_key; unkeyable exprs still need identity
        gkeys = [None if isinstance(g, ex.ColumnRef) else _expr_cache_key(g)
                 for g in self.grouping_src]

        def fn(node):
            for i, leaf in enumerate(self.leaves):
                if node is leaf:
                    return ex.BoundReference(nk + i, leaf.dtype, True)
            for gi, g in enumerate(self.grouping_src):
                if node is g or (
                        isinstance(node, ex.ColumnRef) and
                        isinstance(g, ex.ColumnRef) and
                        node.col_name == g.col_name):
                    return ex.BoundReference(gi, g.dtype, True)
                if gkeys[gi] is not None and type(node) is type(g) \
                        and _expr_cache_key(node) == gkeys[gi]:
                    return ex.BoundReference(gi, g.dtype, True)
            return None
        # top-down: leaf matching is by identity (see overrides rewrite note)
        return e.transform_down(fn)


# ---------------------------------------------------------------------------
# Sort / Limit
# ---------------------------------------------------------------------------

class TpuSortExec(TpuExec):
    """Device sort (GpuSortExec: cudf orderBy analog). Global sort concatenates
    the partition's batches (RequireSingleBatch when global, GpuSortExec.scala)."""

    CONTRACT = exec_contract(schema="passthrough", partitioning="preserve",
                             bound={"orders": 0})
    METRICS = exec_metrics("sortTime")

    def __init__(self, child: TpuExec, orders: List[lp.SortOrder],
                 is_global: bool = True):
        super().__init__(child)
        self.orders = [lp.SortOrder(bind_refs(o.child, child.schema),
                                    o.ascending, o.nulls_first)
                       for o in orders]
        self.is_global = is_global

    @property
    def schema(self):
        return self.children[0].schema

    def children_coalesce_goal(self, i: int):
        # device sort needs the whole partition in one batch
        # (RequireSingleBatch when global, GpuSortExec.scala)
        return "single"

    def execute(self) -> List[Partition]:
        return [self._sort(p) for p in self.children[0].execute()]

    def _sort(self, part: Partition) -> Partition:
        spillables = drain_spillable(part, acquire=True)
        if not spillables:
            return
        batch = concat_spillable(self.schema, spillables)
        with trace_span("sort", self.metrics, "sortTime"):
            keys = [K.SortKey(ex.materialize(o.child.eval(batch), batch),
                              o.ascending, o.nulls_first)
                    for o in self.orders]
            idx = K.sort_indices(keys, batch.num_rows, batch.capacity)
            cols = [K.gather_column(c, idx) for c in batch.columns]
        self.metrics.inc("numOutputRows", batch.num_rows_raw)
        yield ColumnarBatch(self.schema, cols, batch.num_rows)


class TpuLimitExec(TpuExec):
    """Local/global limit (limit.scala)."""

    CONTRACT = exec_contract(schema="passthrough", partitioning="defined")
    METRICS = exec_metrics()

    def __init__(self, child: TpuExec, n: int, is_global: bool = True):
        super().__init__(child)
        self.n = n
        self.is_global = is_global

    @property
    def schema(self):
        return self.children[0].schema

    @property
    def output_partitions(self) -> int:
        return 1 if self.is_global else self.children[0].output_partitions

    def execute(self) -> List[Partition]:
        parts = self.children[0].execute()
        if self.is_global and len(parts) > 1:
            # global limit: single partition of the first n rows
            def gen():
                remaining = self.n
                for p in parts:
                    for b in p:
                        if remaining <= 0:
                            return
                        take = min(remaining, b.num_rows)
                        yield self._slice(b, take)
                        remaining -= take
            return [gen()]

        def local(p):
            remaining = self.n
            for b in p:
                if remaining <= 0:
                    return
                take = min(remaining, b.num_rows)
                yield self._slice(b, take)
                remaining -= take
        return [local(p) for p in parts]

    def _slice(self, batch: ColumnarBatch, n: int) -> ColumnarBatch:
        if n >= batch.num_rows:
            return batch
        cols = [K.rebucket_column(c, n, bucket(n)) for c in batch.columns]
        return ColumnarBatch(self.schema, cols, n)


class TpuUnionExec(TpuExec):
    """Union all (GpuUnionExec)."""

    CONTRACT = exec_contract(schema="union", partitioning="defined")
    METRICS = exec_metrics()

    @property
    def schema(self):
        return self.children[0].schema

    @property
    def output_partitions(self) -> int:
        return sum(c.output_partitions for c in self.children)

    def execute(self) -> List[Partition]:
        parts: List[Partition] = []
        for c in self.children:
            parts.extend(self._retag(p) for p in c.execute())
        return parts

    def _retag(self, p: Partition) -> Partition:
        for b in p:
            # align column names to union schema
            yield ColumnarBatch(self.schema, b.columns, b.num_rows)


class TpuExpandExec(TpuExec):
    """Grouping-sets expand (GpuExpandExec.scala): one output batch per
    projection list, unioned."""

    CONTRACT = exec_contract(schema="defined", partitioning="preserve",
                             bound={"projections": 0})
    METRICS = exec_metrics()

    def __init__(self, child: TpuExec, projections: List[List[ex.Expression]],
                 output_names: List[str]):
        super().__init__(child)
        self.projections = [[bind_refs(e, child.schema) for e in p]
                            for p in projections]
        first = projections[0]
        self._schema = dt.Schema([
            dt.Field(n, e.dtype, True)
            for n, e in zip(output_names, first)])

    @property
    def schema(self):
        return self._schema

    def execute(self) -> List[Partition]:
        return [self._map(p) for p in self.children[0].execute()]

    def _map(self, part: Partition) -> Partition:
        for batch in part:
            for proj in self.projections:
                cols = [ex.materialize(e.eval(batch), batch) for e in proj]
                out = ColumnarBatch(self._schema, cols, batch.num_rows)
                self.metrics.inc("numOutputRows", out.num_rows_raw)
                yield out


class TpuMapInPandasExec(TpuExec):
    """mapInPandas (GpuMapInPandasExec, SURVEY.md §2.9): device batches
    cross to pandas through Arrow, the user fn maps an iterator of frames,
    results re-enter the device columnar world. Input batches are re-aligned
    to a steady size first (RebatchingRoundoffIterator analog)."""

    CONTRACT = exec_contract(schema="defined", partitioning="preserve")
    METRICS = exec_metrics("udfTime")

    def __init__(self, child: TpuExec, plan: "lp.MapInPandas",
                 target_rows: int = 1 << 16):
        super().__init__(child)
        self.plan = plan
        self.target_rows = target_rows

    @property
    def schema(self):
        return self.plan.out_schema

    def execute(self) -> List[Partition]:
        return [self._map(p) for p in self.children[0].execute()]

    def _map(self, part: Partition) -> Partition:
        from ..ops.python_udf import rebatch_iterator

        def frames():
            for b in rebatch_iterator(part, self.target_rows):
                yield b.to_pandas()

        # the user fn runs lazily inside next(): metering each pull (like
        # the sibling pandas execs' pandas_udf span) times fn execution
        # only — not downstream device consumption — and an exception in
        # the fn unwinds through the span, error-marking it in the
        # flight ring for the post-mortem artifact. The construction is
        # metered too: a non-generator fn runs (and can fail) right here
        with trace_span("pandas_udf", self.metrics, "udfTime"):
            it = iter(self.plan.fn(frames()))
        end = object()       # a fn yielding None must fail loudly below,
        while True:          # not silently truncate the stream
            with trace_span("pandas_udf", self.metrics, "udfTime"):
                out_df = next(it, end)
            if out_df is end:
                break
            n = len(out_df)
            if n == 0:
                continue
            out = _df_to_batch(out_df, self.plan.out_schema)
            self.metrics.inc("numOutputRows", n)
            yield out


def _group_pandas_frames(part: Partition, grouping):
    """Drain one partition to pandas and slice a frame per group key:
    yields ``(key_tuple, frame)`` in sorted key order; returns early on an
    empty partition. Shared by the grouped/cogrouped pandas execs."""
    import pandas as pd
    batches = [b for b in part
               if not (isinstance(b.num_rows_raw, int)
                       and b.num_rows_raw == 0)]
    if not batches:
        return None, {}
    merged = concat_batches(batches[0].schema, batches)
    pdf = merged.to_pandas()
    keys = [ex.materialize(g.eval(merged), merged)
            .to_pylist(merged.num_rows) for g in grouping]
    kf = pd.DataFrame({f"_gk{i}": k for i, k in enumerate(keys)})
    groups = {}
    for key, idx in kf.groupby(list(kf.columns), sort=True,
                               dropna=False).groups.items():
        if not isinstance(key, tuple):
            key = (key,)
        groups[key] = pdf.loc[idx].reset_index(drop=True)
    return pdf, groups


class TpuFlatMapGroupsInPandasExec(TpuExec):
    """groupBy().applyInPandas (GpuFlatMapGroupsInPandasExec): each
    partition's rows cross to pandas once, group frames slice out per key,
    the user fn maps each to an output frame. The planner hash-exchanges
    on the keys first when the child is multi-partition, so every group's
    rows are co-located (requiredChildDistribution = clustered(keys))."""

    CONTRACT = exec_contract(schema="defined", partitioning="preserve",
                             bound={"grouping": 0})
    METRICS = exec_metrics("udfTime")

    def __init__(self, child: TpuExec, plan: "lp.FlatMapGroupsInPandas"):
        super().__init__(child)
        self.plan = plan
        self.grouping = [bind_refs(g, child.schema)
                         for g in plan.grouping]
        self._key_names = [ex.output_name(g, i)
                           for i, g in enumerate(plan.grouping)]

    @property
    def schema(self):
        return self.plan.out_schema

    def execute(self) -> List[Partition]:
        return [self._apply(p) for p in self.children[0].execute()]

    def _group_frames(self, part: Partition):
        """(key_tuple, pandas frame) per group in this partition."""
        _pdf, groups = _group_pandas_frames(part, self.grouping)
        yield from groups.items()

    def _apply(self, part: Partition) -> Partition:
        import inspect
        import pandas as pd
        fn = self.plan.fn
        try:
            two_arg = len(inspect.signature(fn).parameters) == 2
        except (TypeError, ValueError):
            two_arg = False
        frames = []
        with trace_span("pandas_udf", self.metrics, "udfTime"):
            for key, pdf in self._group_frames(part):
                out = fn(key, pdf) if two_arg else fn(pdf)
                if out is not None and len(out):
                    frames.append(out)
        if frames:
            combined = pd.concat(frames, ignore_index=True)
            out = _df_to_batch(combined, self.plan.out_schema)
            self.metrics.inc("numOutputRows", out.num_rows_raw)
            yield out

    def _node_string(self):
        return ("TpuFlatMapGroupsInPandasExec "
                f"[{getattr(self.plan.fn, '__name__', 'fn')}]")


class TpuFlatMapCoGroupsInPandasExec(TpuExec):
    """cogroup().applyInPandas (GpuFlatMapCoGroupsInPandasExec): both
    sides drain to pandas, group frames pair up per key (union of key
    sets; a missing side passes an empty frame), fn maps each pair."""

    CONTRACT = exec_contract(schema="defined", partitioning="defined")
    METRICS = exec_metrics("udfTime")

    def __init__(self, left: TpuExec, right: TpuExec,
                 plan: "lp.FlatMapCoGroupsInPandas"):
        super().__init__(left, right)
        self.plan = plan
        self.left_grouping = [bind_refs(g, left.schema)
                              for g in plan.left_grouping]
        self.right_grouping = [bind_refs(g, right.schema)
                               for g in plan.right_grouping]

    @property
    def schema(self):
        return self.plan.out_schema

    def execute(self) -> List[Partition]:
        lparts = self.children[0].execute()
        rparts = self.children[1].execute()
        n = max(len(lparts), len(rparts))

        def empty():
            return
            yield
        lparts += [empty() for _ in range(n - len(lparts))]
        rparts += [empty() for _ in range(n - len(rparts))]
        return [self._apply(lp_, rp_)
                for lp_, rp_ in zip(lparts, rparts)]

    @staticmethod
    def _collect_side(part: Partition, grouping):
        return _group_pandas_frames(part, grouping)

    def _apply(self, lpart: Partition, rpart: Partition) -> Partition:
        import inspect
        import pandas as pd
        fn = self.plan.fn
        try:
            three_arg = len(inspect.signature(fn).parameters) == 3
        except (TypeError, ValueError):
            three_arg = False
        lp_df, lgroups = self._collect_side(lpart, self.left_grouping)
        rp_df, rgroups = self._collect_side(rpart, self.right_grouping)
        lempty = (lp_df.iloc[0:0] if lp_df is not None else
                  pd.DataFrame(columns=self.children[0].schema.names()))
        rempty = (rp_df.iloc[0:0] if rp_df is not None else
                  pd.DataFrame(columns=self.children[1].schema.names()))
        frames = []
        with trace_span("pandas_udf", self.metrics, "udfTime"):
            for key in sorted(set(lgroups) | set(rgroups), key=repr):
                l = lgroups.get(key, lempty)
                r = rgroups.get(key, rempty)
                out = fn(key, l, r) if three_arg else fn(l, r)
                if out is not None and len(out):
                    frames.append(out)
        if frames:
            combined = pd.concat(frames, ignore_index=True)
            out = _df_to_batch(combined, self.plan.out_schema)
            self.metrics.inc("numOutputRows", out.num_rows_raw)
            yield out

    def _node_string(self):
        return ("TpuFlatMapCoGroupsInPandasExec "
                f"[{getattr(self.plan.fn, '__name__', 'fn')}]")


class TpuAggregateInPandasExec(TpuExec):
    """groupBy().agg(grouped-agg pandas UDFs) (GpuAggregateInPandasExec,
    198 LoC in the reference): fn(Series...) -> scalar once per
    (group, udf); output = key columns + one column per udf."""

    CONTRACT = exec_contract(schema="defined", partitioning="preserve",
                             bound={"grouping": 0})
    METRICS = exec_metrics("udfTime")

    def __init__(self, child: TpuExec, plan: "lp.AggregateInPandas"):
        super().__init__(child)
        self.plan = plan
        self.grouping = [bind_refs(g, child.schema) for g in plan.grouping]
        self.aggs = [type(a)(a.fn, a.return_type,
                             *[bind_refs(c, child.schema)
                               for c in a.children],
                             name=a.udf_name)
                     for a in plan.aggs]

    @property
    def schema(self):
        return self.plan.schema

    def execute(self) -> List[Partition]:
        return [self._apply(p) for p in self.children[0].execute()]

    def _apply(self, part: Partition) -> Partition:
        import pandas as pd
        batches = [b for b in part
                   if not (isinstance(b.num_rows_raw, int)
                           and b.num_rows_raw == 0)]
        if not batches:
            return
        merged = concat_batches(batches[0].schema, batches)
        n = merged.num_rows
        key_lists = [ex.materialize(g.eval(merged), merged).to_pylist(n)
                     for g in self.grouping]
        # per udf: its input series, sliced per group
        agg_inputs = [[ex.materialize(c.eval(merged), merged)
                       .to_arrow(n).to_pandas()
                       for c in a.children] for a in self.aggs]
        kf = pd.DataFrame({f"_gk{i}": k for i, k in enumerate(key_lists)})
        rows = []
        with trace_span("pandas_udf", self.metrics, "udfTime"):
            for key, idx in kf.groupby(list(kf.columns), sort=True,
                                       dropna=False).groups.items():
                if not isinstance(key, tuple):
                    key = (key,)
                vals = []
                for a, inputs in zip(self.aggs, agg_inputs):
                    sliced = [s.loc[idx].reset_index(drop=True)
                              for s in inputs]
                    vals.append(a.fn(*sliced))
                rows.append(tuple(key) + tuple(vals))
        if rows:
            out_schema = self.plan.schema
            data = {f.name: [r[i] for r in rows]
                    for i, f in enumerate(out_schema)}
            out = _df_to_batch(pd.DataFrame(data), out_schema)
            self.metrics.inc("numOutputRows", out.num_rows_raw)
            yield out

    def _node_string(self):
        return (f"TpuAggregateInPandasExec "
                f"[{', '.join(a.udf_name for a in self.aggs)}]")


class TpuGenerateExec(TpuExec):
    """explode/posexplode (GpuGenerateExec.scala: per-row repeat + flatten).
    ``Explode(StringSplit(s, d))`` fuses split+explode into one kernel —
    the intermediate array<string> never materializes."""

    CONTRACT = exec_contract(schema="defined", partitioning="preserve")
    METRICS = exec_metrics("generateTime")

    def __init__(self, child: TpuExec, plan: lp.Generate):
        super().__init__(child)
        from ..ops import arrays as ar_ops
        self.plan = plan
        gen = plan.generator
        self.pos = getattr(gen, "pos", False)
        inner = gen.children[0]
        if isinstance(inner, ar_ops.StringSplit):
            self.split_delim = inner.delimiter
            self.gen_input = bind_refs(inner.children[0], child.schema)
        else:
            self.split_delim = None
            self.gen_input = bind_refs(inner, child.schema)
        self._schema = plan.schema

    @property
    def schema(self):
        return self._schema

    def execute(self) -> List[Partition]:
        return [self._map(p) for p in self.children[0].execute()]

    def _map(self, part: Partition) -> Partition:
        from ..ops import arrays as ar_ops
        for batch in part:
            with trace_span("generate", self.metrics, "generateTime"):
                arr = ex.materialize(self.gen_input.eval(batch), batch)
                live = batch.row_mask()
                # one host sync sizes the output bucket (the dynamic-size
                # protocol's batch-boundary read, DESIGN.md)
                if self.split_delim is not None:
                    pre = ar_ops.split_part_counts(arr,
                                                   ord(self.split_delim))
                    import jax.numpy as jnp
                    total = int(jnp.sum(jnp.where(live, pre[1], 0)))  # lint: host-sync-ok generate output sizing: the dynamic-size protocol's batch-boundary read
                    out_cap = bucket(max(total, 1))
                    others, elem, pos_col, count = ar_ops.split_explode(
                        arr, ord(self.split_delim), batch.columns, live,
                        out_cap, precomputed=pre)
                else:
                    total = int(jnp_total_len(arr, live))
                    out_cap = bucket(max(total, 1))
                    others, elem, pos_col, count = ar_ops.explode_array(
                        arr, batch.columns, live, out_cap)
                n = int(count)
            if n == 0:
                continue
            cols = others + ([pos_col] if self.pos else []) + [elem]
            out = ColumnarBatch(self._schema, cols, n)
            self.metrics.inc("numOutputRows", n)
            yield out


def jnp_total_len(arr: Column, live) -> "jnp.ndarray":
    import jax.numpy as jnp
    return jnp.sum(jnp.where(live & arr.validity, arr.lengths, 0))





# ---------------------------------------------------------------------------
# Joins
# ---------------------------------------------------------------------------

class TpuSortMergeJoinExec(TpuExec):
    """Equality join: build side materialized to a single sorted batch, stream
    side joined per batch (GpuShuffledHashJoinExec shape, but sort-merge
    kernels per DESIGN.md §3; build-side-single-batch mirrors
    GpuHashJoin.scala:193-249's stream loop)."""

    CONTRACT = exec_contract(schema="defined", partitioning="defined",
                             bound={"left_keys": 0, "right_keys": 1},
                             extras=("join_schema",))
    METRICS = exec_metrics("joinTime", "buildTime")

    # AQE join-strategy demotion policy: dict(threshold, factor,
    # partitions, validate) stamped by the planner on a broadcast-form
    # join when adaptive execution is on (plan/aqe.py). None = off.
    aqe_demote_policy: Optional[dict] = None

    def __init__(self, left: TpuExec, right: TpuExec, how: str,
                 left_keys: List[ex.Expression], right_keys: List[ex.Expression],
                 condition: Optional[ex.Expression] = None):
        super().__init__(left, right)
        self.how = how
        self.left_keys = [bind_refs(e, left.schema) for e in left_keys]
        self.right_keys = [bind_refs(e, right.schema) for e in right_keys]
        self._out_schema = self._compute_schema()
        self.condition = bind_refs(condition, self._merged_schema()) \
            if condition is not None else None

    def _merged_schema(self):
        return dt.Schema(list(self.children[0].schema.fields) +
                         list(self.children[1].schema.fields))

    def _compute_schema(self) -> dt.Schema:
        left, right = self.children[0].schema, self.children[1].schema
        if self.how in ("left_semi", "left_anti"):
            return left
        lf = [dt.Field(f.name, f.dtype, True if self.how == "full" else f.nullable)
              for f in left.fields]
        rf = [dt.Field(f.name, f.dtype,
                       True if self.how in ("left", "full") else f.nullable)
              for f in right.fields]
        return dt.Schema(lf + rf)

    @property
    def schema(self):
        return self._out_schema

    @property
    def output_partitions(self) -> int:
        return 1 if self.how == "full" else self.children[0].output_partitions

    def children_coalesce_goal(self, i: int):
        # build side is materialized to a single batch; stream side benefits
        # from target-size batches (GpuShuffledHashJoinExec goals)
        return "target" if i == 0 else "single"

    def execute(self) -> List[Partition]:
        # build side = right (stream left), matching Spark BuildRight default.
        # The build is materialized ONCE as a spillable handle shared by every
        # stream partition (broadcast semantics: the reference's broadcast
        # batch is likewise materialized lazily once per executor and held
        # spillable, GpuBroadcastExchangeExec.scala:238-367); partitions
        # re-acquire it, so it can spill between partition tasks.
        from ..exec.spill import SpillableColumnarBatch
        from ..shuffle.exchange import TpuBroadcastExchangeExec
        self._aqe_decisions = []       # fresh per execution (plan/aqe.py)
        bchild = self.children[1]
        if isinstance(bchild, TpuBroadcastExchangeExec):
            handle = bchild.materialize()
            if getattr(self, "aqe_demote_policy", None):
                # AQE join-strategy demotion: the planner chose broadcast
                # from estimates, but the materialized build is observed
                # oversized — re-plan as a co-partitioned shuffled join
                # reusing the already-built batch (plan/aqe.py)
                from . import aqe
                demoted = aqe.maybe_demote_broadcast(self, bchild, handle)
                if demoted is not None:
                    return demoted
        else:
            # metered separately from the stream loop (the reference's
            # buildTime vs joinTime split, GpuMetricNames)
            with trace_span("join_build", self.metrics, "buildTime"):
                build = concat_spillable(
                    bchild.schema, accumulate_spillable(bchild.execute()))
            handle = self._build_handle = SpillableColumnarBatch(build)
        stream_parts = self.children[0].execute()
        if self.how == "full":
            # unmatched-build accounting happens inside one join pass, so full
            # outer needs the ENTIRE stream side in a single partition — a
            # per-partition pass would re-emit matched build rows as unmatched
            merged = concat_spillable(self.children[0].schema,
                                      accumulate_spillable(stream_parts))
            stream_parts = [iter([merged])]
        return [self._join_part(p, handle) for p in stream_parts]

    def _cleanup(self) -> None:
        h = getattr(self, "_build_handle", None)
        if h is not None:
            h.close()
            self._build_handle = None
        rep = getattr(self, "_aqe_demoted", None)
        if rep is not None:
            rep.cleanup()              # idempotent per exec contract
            self._aqe_demoted = None

    def _pipeline_depth(self) -> int:
        """Join pipeline window depth: planner-set override (the session
        conf wired through overrides) or the global conf default."""
        d = getattr(self, "pipeline_depth", None)
        if d is None:
            from .. import config as cfg
            d = cfg.TpuConf().get(cfg.JOIN_PIPELINE_DEPTH)
        return max(1, int(d))

    def _join_part(self, part: Partition,
                   build_handle: "SpillableColumnarBatch") -> Partition:
        # full outer: execute() has already merged the whole stream side into
        # this one partition as a single (possibly empty) batch
        from ..exec.pipeline import PipelineWindow
        import jax.numpy as jnp
        _task_begin()
        build = build_handle.get_batch()
        bkey_cols = [ex.materialize(e.eval(build), build)
                     for e in self.right_keys]

        # PIPELINED stream loop (the reference's per-batch join stream loop
        # has no host sync at all, GpuHashJoin.scala:193-249): join_match
        # for batches k+1..k+depth dispatches before batch k's gather
        # sizing resolves; the window lands half a depth of size scalars
        # per batched readback, so join-path host syncs are O(1) per stage
        # instead of one blocking RTT per stream batch.
        # metrics=: sizing-scalar readbacks attribute their hostSyncs to
        # this join exec (the EXPLAIN ANALYZE per-node sync count)
        win = PipelineWindow(self._pipeline_depth(), metrics=self.metrics)
        for batch in part:
            # admission: up to `depth` stream batches (+ match state) stay
            # device-resident while their sizing scalars are in flight —
            # account each to the spill manager like the aggregate window
            _reserve(batch.device_size_bytes())
            with trace_span("join", self.metrics, "joinTime"):
                skey_cols = [ex.materialize(e.eval(batch), batch)
                             for e in self.left_keys]
                how = self.how if self.how in (
                    "inner", "left", "left_semi", "left_anti") else (
                    "left" if self.how == "full" else "inner")
                m = join_k.join_match(bkey_cols, build.num_rows_raw,
                                      skey_cols, batch.num_rows_raw,
                                      batch.capacity)
                if how in ("left_semi", "left_anti"):
                    # semi/anti outputs compact at STREAM capacity —
                    # join_gather ignores out_capacity, so no size scalar:
                    # the entry rides through the window immediately
                    cont = (lambda b=batch, mm=m, h=how:
                            self._join_finish(build, b, mm, h, None, None))
                    scalars = ()
                else:
                    # the sizing scalar stays in flight on the window
                    # (left-outer's emit total computes on DEVICE — a full
                    # per-row counts download costs ~capacity bytes over a
                    # slow link)
                    if how == "left":
                        live = batch.row_mask_raw()
                        size_dev = jnp.sum(
                            jnp.where(live, jnp.maximum(m.count, 1), 0))
                    else:
                        size_dev = m.total_pairs
                    cont = (lambda total, b=batch, mm=m, h=how, sd=size_dev:
                            self._join_finish(build, b, mm, h, sd, total))
                    scalars = (size_dev,)
            # push OUTSIDE the dispatch span: a landing runs _join_finish's
            # own metered "join" span, which must be a sibling (the two
            # halves SUM into joinTime), never nested (it would double-count)
            for outs in win.push(cont, *scalars):
                yield from outs
        for outs in win.flush():
            yield from outs

    def _join_finish(self, build: ColumnarBatch, batch: ColumnarBatch,
                     m, how: str, size_dev, total) -> List[ColumnarBatch]:
        """Second half of one stream batch's join: gather at the
        host-sized output bucket. Runs when the pipeline window resolves
        this batch's sizing scalar; returns the output batches."""
        import jax
        with trace_span("join", self.metrics, "joinTime"):
            if how in ("left_semi", "left_anti"):
                out_cap = batch.capacity
            else:
                if total is None:
                    # window-degraded entry (batched readback failed):
                    # re-read this batch's scalar alone
                    total = jax.device_get(size_dev)  # lint: host-sync-ok window-degraded re-read of ONE batch's sizing scalar
                out_cap = bucket(max(int(total), 1))
            s_out, b_out, cnt = join_k.join_gather(
                m, batch.columns, build.columns, out_cap, how,
                n_stream=batch.num_rows_raw)
            # the output count stays device-resident; downstream boundaries
            # resolve it in batched readbacks (possibly-empty batches flow)
            if self.how in ("left_semi", "left_anti"):
                out = ColumnarBatch(self._out_schema, s_out, cnt)
            else:
                out = ColumnarBatch(self._out_schema, s_out + b_out, cnt)
            if self.condition is not None and self.how == "inner":
                # conditional join: post-filter (reference: inner-only
                # conditional joins via post-join filter). Row mask from the
                # device-resident count — row_mask() would force a sync.
                pred = self.condition.eval(out)
                keep = pred.data & pred.validity & out.row_mask_raw()
                cols, count = K.compact_columns(out.columns, keep)
                out = ColumnarBatch(self._out_schema, cols, count)
            self.metrics.inc("numOutputRows", out.num_rows_raw)
            outs = [out]
            if self.how == "full":
                # append unmatched build rows with NULL left columns; the
                # count stays device-resident too (the tail's former
                # blocking `int(ucnt)` was one more RTT per stage)
                un_cols, ucnt = join_k.unmatched_build_gather(
                    m, build.columns, build.num_rows_raw)
                ucap = un_cols[0].capacity if un_cols else build.capacity
                left_nulls = [Column.full_null(f.dtype, ucap)
                              for f in self.children[0].schema]
                uout = ColumnarBatch(self._out_schema,
                                     left_nulls + un_cols, ucnt)
                self.metrics.inc("numOutputRows", uout.num_rows_raw)
                outs.append(uout)
            return outs


class TpuShuffledJoinExec(TpuSortMergeJoinExec):
    """Co-partitioned equality join: both children are hash-exchanged on the
    join keys with the same partition count, so partition i of the stream
    side joins only partition i of the build side
    (GpuShuffledHashJoinExec shape, shims/spark300/GpuShuffledHashJoinExec
    .scala — with sort-merge kernels per DESIGN.md §3). Unlike the broadcast
    form, the build side is never materialized whole: one build partition at
    a time. Full outer is correct per partition pair because co-partitioning
    makes key ownership disjoint."""

    CONTRACT = exec_contract(schema="defined", partitioning="defined",
                             bound={"left_keys": 0, "right_keys": 1},
                             extras=("join_schema", "copartitioned"))
    METRICS = exec_metrics("joinTime", "buildTime", "skewJoinSplits",
                           "runtimeBroadcastJoins")

    # runtime AQE join switch: set by the planner to the broadcast-join
    # byte threshold when adaptive execution is on (None = off)
    aqe_broadcast_threshold: Optional[int] = None
    # AQE skew-join split: a stream-side reduce partition larger than this
    # many observed bytes splits into mapper-subset tasks, each joined
    # against the SAME build partition (OptimizeSkewedJoin +
    # GpuCustomShuffleReaderExec partial-mapper specs). None = off.
    aqe_skew_threshold: Optional[int] = None
    # skewedPartitionFactor: raises the cut line to factor x median
    # observed partition bytes when higher (plan/aqe.py). None = absolute
    # threshold only.
    aqe_skew_factor: Optional[float] = None
    # joinSwitch.demoteFactor: the promote side of the hysteresis dead
    # band — an observed build in (threshold, threshold x factor] records
    # a declined decision and stays shuffled (no flapping)
    aqe_demote_factor: Optional[float] = None

    @property
    def output_partitions(self) -> int:
        return self.children[0].output_partitions

    def execute(self) -> List[Partition]:
        self._aqe_decisions = []       # fresh per execution (plan/aqe.py)
        switched, rparts = self._maybe_runtime_broadcast()
        if switched is not None:
            return switched
        skewed = self._maybe_skew_split(rparts)
        if skewed is not None:
            return skewed
        lparts = self.children[0].execute()
        if rparts is None:
            rparts = self.children[1].execute()
        assert len(lparts) == len(rparts), \
            f"co-partition mismatch: {len(lparts)} vs {len(rparts)}"
        return [self._join_copart(sp, bp)
                for sp, bp in zip(lparts, rparts)]

    def _maybe_skew_split(self, rparts) -> Optional[List[Partition]]:
        """Skew handling: hot stream partitions split into mapper-subset
        tasks (>=2 output partitions per hot input partition), the build
        partition materialized ONCE and shared by its sub-tasks. Inner/
        left only — right/full outer would emit unmatched build rows once
        per sub-task."""
        from ..shuffle.exchange import TpuShuffleExchangeExec
        from ..shuffle.manager import WorkerContext
        thr = self.aqe_skew_threshold
        if thr is None or thr <= 0 or self.how in ("right", "full") or \
                WorkerContext.current is not None:
            return None
        from . import aqe
        sx = self.children[0]
        if not isinstance(sx, TpuShuffleExchangeExec):
            return None
        if sx.would_use_ici():
            # device-resident exchange (docs/shuffle.md): rows never stage
            # as host slices, so there are no per-slice observed sizes to
            # split on. The PRIOR execution's stage stats for the same
            # exchange fingerprint can still prove skew — then the skewed
            # stage only falls back to DCN (execute_skew forces the host
            # plane); otherwise this run records the baseline and stays
            # on the ICI plane.
            fall_back, why = aqe.ici_skew_fallback(
                sx, thr, getattr(self, "aqe_skew_factor", None))
            if not fall_back:
                aqe.record_decision(self, "skew-split", applied=False,
                                    reason=f"ici plane: {why}")
                return None
            ici_fell_back = True
        else:
            ici_fell_back = False
        sgroups = sx.execute_skew(thr,
                                  getattr(self, "aqe_skew_factor", None))
        hot = sum(1 for g in sgroups if len(g) > 1)
        if hot:
            aqe.record_decision(
                self, "skew-split", stage_id=sx.stage_id,
                before=f"{len(sgroups)} partitions"
                       + (" [ici]" if ici_fell_back else ""),
                after=(f"{hot} hot partition(s) split into "
                       f"{sum(len(g) for g in sgroups)} tasks"
                       + (" [ici->dcn]" if ici_fell_back else "")),
                reason=f"observed partition bytes past threshold {thr}")
        if all(len(g) == 1 for g in sgroups):
            # nothing hot: fall through to the plain co-partitioned loop
            return [self._join_copart(g[0], bp)
                    for g, bp in zip(sgroups, rparts
                                     if rparts is not None
                                     else self.children[1].execute())]
        if rparts is None:
            rparts = self.children[1].execute()
        assert len(sgroups) == len(rparts)
        out: List[Partition] = []
        for subs, bp in zip(sgroups, rparts):
            if len(subs) == 1:
                out.append(self._join_copart(subs[0], bp))
                continue
            self.metrics.inc("skewJoinSplits")
            shared = _SharedBuild(self.children[1].schema, bp, len(subs))
            for sub in subs:
                out.append(self._join_split(sub, shared))
        return out

    def _join_split(self, stream_part: Partition,
                    shared: "_SharedBuild") -> Partition:
        try:
            yield from self._join_part(stream_part, shared.handle())
        finally:
            shared.release()

    def _maybe_runtime_broadcast(self):
        """AQE runtime join-strategy switch (the reference's AQE broadcast
        conversion + GpuCustomShuffleReaderExec territory): run the BUILD
        side's exchange map phase first; when its OBSERVED output is under
        the broadcast threshold, materialize one broadcast build batch
        from the already-shuffled slices and stream-join against the
        UNexchanged stream child — the stream-side shuffle never executes.
        Planner estimates decided shuffled; runtime sizes overrule.

        Returns ``(broadcast_partitions, None)`` on a switch, or
        ``(None, build_partitions_or_None)`` when staying co-partitioned
        (execute() owns the single co-partitioned join loop either way)."""
        from ..shuffle.exchange import TpuShuffleExchangeExec
        from ..shuffle.manager import WorkerContext
        thr = self.aqe_broadcast_threshold
        if thr is None or thr < 0 or self.how in ("right", "full"):
            # right/full outer against a broadcast build would duplicate
            # unmatched build rows per stream partition
            return None, None
        sx, bx = self.children
        if not isinstance(sx, TpuShuffleExchangeExec) or \
                not isinstance(bx, TpuShuffleExchangeExec):
            return None, None
        raw_stream = sx.children[0]
        bparts = bx.execute()          # map phase runs: size now observed
        observed = bx.metrics.resolve().get("dataSize", 0)
        ctx = WorkerContext.current
        if ctx is not None:
            # mesh-consistent decision: the LOCAL observed size is one
            # shard's contribution; sum it across workers through the
            # control-plane allreduce so every worker takes the SAME
            # branch (a split decision would desync the lockstep
            # shuffle-id streams — and the fingerprint handshake would
            # abort the query)
            observed = ctx.allreduce_bytes(bx._shuffle.shuffle_id, observed)
        from . import aqe
        if observed > thr:
            f = float(getattr(self, "aqe_demote_factor", None) or 2.0)
            if observed <= int(thr * f):
                # hysteresis dead band: a borderline build must not flap
                # between strategies across repeat executions
                aqe.record_decision(
                    self, "join-promote", applied=False,
                    stage_id=bx.stage_id, before="shuffled",
                    reason=(f"observed build {observed}B in hysteresis "
                            f"band ({thr}B, {int(thr * f)}B]: staying "
                            "shuffled"))
            # stay co-partitioned (stream exchange proceeds as planned)
            return None, bparts
        from ..exec.spill import SpillableColumnarBatch
        if ctx is not None:
            # the full build side = EVERY reduce partition (local + peers),
            # not just the owned ones: each worker broadcast-joins its raw
            # local stream shard against the complete build; one source
            # generator per peer so fetches drain concurrently
            build = concat_spillable(
                bx.schema,
                accumulate_spillable(
                    bx._shuffle.read_all_partition_sources()))
        else:
            # concurrent drain (accumulate_spillable): a serial sweep would
            # pay one blocking readback per shuffle partition on tunnel
            # links
            build = concat_spillable(bx.schema,
                                     accumulate_spillable(bparts))
        self._rt_broadcast = SpillableColumnarBatch(build)
        self.metrics.inc("runtimeBroadcastJoins")
        aqe.record_decision(
            self, "join-promote", stage_id=bx.stage_id,
            before=f"shuffled[{len(bparts)}]", after="broadcast",
            reason=f"observed build {observed}B <= threshold {thr}B")

        def gen(p):
            yield from self._join_part(p, self._rt_broadcast)
        return [gen(p) for p in raw_stream.execute()], None

    def _cleanup(self) -> None:
        h = getattr(self, "_rt_broadcast", None)
        if h is not None:
            h.close()
            self._rt_broadcast = None

    def _join_copart(self, stream_part: Partition,
                     build_part: Partition) -> Partition:
        from ..exec.spill import SpillableColumnarBatch
        with trace_span("join_build", self.metrics, "buildTime"):
            build = concat_spillable(
                self.children[1].schema,
                [SpillableColumnarBatch(b) for b in build_part
                 if b.num_rows > 0])
            handle = SpillableColumnarBatch(build)
        try:
            if self.how == "full":
                merged = concat_spillable(
                    self.children[0].schema,
                    [SpillableColumnarBatch(b) for b in stream_part
                     if b.num_rows > 0])
                stream_part = iter([merged])
            yield from self._join_part(stream_part, handle)
        finally:
            handle.close()


class _SharedBuild:
    """One build partition materialized once, shared by the skew-split
    sub-tasks of its stream partition; freed when the LAST sub-task
    releases (sub-tasks drain concurrently on the task pool, so
    materialization and refcounting are locked)."""

    def __init__(self, schema, build_part: Partition, refs: int):
        import threading
        self._schema = schema
        self._part = build_part
        self._refs = refs
        self._handle = None
        self._mu = threading.Lock()

    def handle(self):
        from ..exec.spill import SpillableColumnarBatch
        with self._mu:
            if self._handle is None:
                build = concat_spillable(
                    self._schema,
                    [SpillableColumnarBatch(b) for b in self._part
                     if b.num_rows > 0])
                self._handle = SpillableColumnarBatch(build)
            return self._handle

    def release(self):
        with self._mu:
            self._refs -= 1
            if self._refs == 0 and self._handle is not None:
                self._handle.close()
                self._handle = None


class TpuCrossJoinExec(TpuExec):
    """Cartesian product (GpuCartesianProductExec)."""

    CONTRACT = exec_contract(schema="defined", partitioning="defined")
    METRICS = exec_metrics()

    def __init__(self, left: TpuExec, right: TpuExec,
                 condition: Optional[ex.Expression] = None):
        super().__init__(left, right)
        self._out_schema = dt.Schema(
            list(left.schema.fields) + list(right.schema.fields))
        self.condition = bind_refs(condition, self._out_schema) \
            if condition is not None else None

    @property
    def schema(self):
        return self._out_schema

    def execute(self) -> List[Partition]:
        right = concat_spillable(
            self.children[1].schema,
            accumulate_spillable(self.children[1].execute()))
        return [self._map(p, right) for p in self.children[0].execute()]

    def _map(self, part: Partition, right: ColumnarBatch) -> Partition:
        for batch in part:
            total = batch.num_rows * right.num_rows
            cap = bucket(max(total, 1))
            l_out, r_out, cnt = join_k.cross_join_gather(
                batch.columns, batch.num_rows, right.columns, right.num_rows,
                cap)
            n = int(cnt)
            out = ColumnarBatch(self._out_schema, l_out + r_out, n)
            if self.condition is not None:
                pred = self.condition.eval(out)
                keep = pred.data & pred.validity & out.row_mask()
                cols, count = K.compact_columns(out.columns, keep)
                n = int(count)
                out = ColumnarBatch(self._out_schema, cols, n)
            if n > 0:
                self.metrics.inc("numOutputRows", n)
                yield out


# ---------------------------------------------------------------------------
# CPU fallback + transitions
# ---------------------------------------------------------------------------

class CpuFallbackExec(TpuExec):
    """Executes a logical subtree on the CPU engine (the 'stays on CPU' side
    of a mixed plan; transition = GpuRowToColumnarExec analog on output)."""

    CONTRACT = exec_contract(schema="defined", partitioning="single")
    METRICS = exec_metrics()

    def __init__(self, plan: lp.LogicalPlan):
        super().__init__()
        self.plan = plan

    @property
    def schema(self):
        return self.plan.schema

    def execute(self) -> List[Partition]:
        from ..cpu.engine import execute as cpu_execute
        df = cpu_execute(self.plan)

        def gen():
            yield _df_to_batch(df, self.plan.schema)
        return [gen()]

    def _node_string(self):
        return f"CpuFallbackExec[{self.plan.name}]"


def _df_to_batch(df, schema: dt.Schema) -> ColumnarBatch:
    cols = []
    n = len(df)
    cap = bucket(n)
    # positional alignment when the frame carries duplicate names (USING
    # joins, self-joins): df[name] would return a sub-frame there
    names = list(df.columns)
    positional = len(names) == len(schema.fields) and \
        len(set(names)) != len(names)
    for i, f in enumerate(schema):
        if positional:
            vals = list(df.iloc[:, i])
        else:
            vals = list(df[f.name]) if f.name in df.columns else [None] * n
        vals = [None if _is_na(v) else v for v in vals]
        cols.append(Column.from_pylist(vals, f.dtype, capacity=cap))
    return ColumnarBatch(schema, cols, n)


def _is_na(v) -> bool:
    if v is None:
        return True
    try:
        import pandas as pd
        return v is pd.NA or (isinstance(v, float) and pd.isna(v) and
                              not np.isnan(v))
    except Exception:
        return False
