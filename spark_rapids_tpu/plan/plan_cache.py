"""Parameterized-plan + result caching: the serving front door
(docs/plan_cache.md).

Million-user serving traffic is repetitive — the same query SHAPES with
different literals. PR 10 made *compiled programs* restart-proof; this
module hoists the identical trick up the stack to plans and results:

* **Plan parameterization** (:func:`parameterize`) — eligible constant
  subtrees in ``Filter`` conditions and ``Project`` expressions fold
  host-side and are replaced by :class:`ops.expressions.Parameter`
  nodes, so q6 with a different date range produces the SAME plan
  fingerprint and the same compiled ``_fused_fn`` signatures (the
  structural key is ``("param", slot, dtype)``, never the value; fused
  programs take the values as extra traced scalar arguments).

* **Parameterized-plan cache** (:class:`PlanCache`) — an LRU of fully
  planned entries keyed on the normalized :func:`plan_fingerprint`:
  a hit skips analyze-side optimization, contract validation and stage
  compilation entirely, rebinds the parameters, and re-executes the
  SAME exec tree — zero recompiles across literal changes, enforced by
  the PR 10 repeat-compile gate. ``session.prepare(sql)`` rides this
  cache; plain ``session.sql()`` hits it transparently.

* **Result cache** (:class:`ResultCache`) — exact repeats short-circuit
  before the planner: entries key on (plan fingerprint, parameter
  values, input snapshot) where the snapshot is the scan's OWNERSHIP
  token (the same base-table identity the scan device cache keys by —
  a weakref finalizer invalidates entries when the table dies) or the
  file set's (path, mtime, size) stats. Values are host-resident
  batches under a byte-capped LRU. Off by default
  (``spark.rapids.tpu.sql.resultCache.enabled``): serving a stored
  result skips execution, which also skips per-query spans/metrics.

Correctness boundaries (why the extraction scope is what it is):

* Only ``Filter.condition`` / ``Project.exprs`` are parameterized —
  exactly the expressions whose consumers (``FusedStage``,
  ``TpuWholeStageExec``, the aggregate's folded ``pre_stage`` chain,
  and every eager/CPU fallback) thread parameter values as runtime
  arguments. A ``Parameter`` anywhere else (e.g. a ``:name``
  placeholder in GROUP BY) would silently BAKE its first value into a
  shared compiled program, so :func:`parameterize` raises instead.
* Plans carrying side-effecting / nondeterministic expressions, writes,
  or unkeyable attributes (python callables) fingerprint to ``None``
  and are served the classic way — planned per execution.
* A conf change on the session (``RuntimeConf.set``) clears both
  caches: entries were planned under the old conf.
"""

from __future__ import annotations

import itertools
import logging
import os
import threading
import weakref
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

from ..columnar import dtypes as dt
from ..columnar.column import Scalar
from ..ops import arithmetic as ar
from ..ops import expressions as ex
from ..ops import predicates as pr
from . import logical as lp
from .physical import _expr_cache_key

log = logging.getLogger("spark_rapids_tpu.plan_cache")

#: dtypes a runtime parameter may carry: fixed-width scalars a fused
#: program can take as a traced 0-d argument (strings are padded byte
#: matrices — a string literal stays baked and rides the fingerprint)
PARAM_DTYPES = (dt.BOOL, dt.INT8, dt.INT16, dt.INT32, dt.INT64,
                dt.FLOAT32, dt.FLOAT64, dt.DATE, dt.TIMESTAMP)


# ---------------------------------------------------------------------------
# Data-identity tokens (the result cache's snapshot + invalidation hook)
# ---------------------------------------------------------------------------

_tok_lock = threading.Lock()  # lint: raw-lock-ok leaf token-registry lock; never taken with another engine lock held
_TOKENS: Dict[int, int] = {}          # id(obj) -> stable token
_token_counter = itertools.count(1)  # lint: nondeterminism-ok process-local cache-identity token, never compared across workers
#: live result caches, purged when a token's owner is collected
_RESULT_CACHES: "weakref.WeakSet" = weakref.WeakSet()


def _forget_token_now(oid: int, tok: int) -> None:
    with _tok_lock:
        if _TOKENS.get(oid) == tok:
            del _TOKENS[oid]
    for rc in list(_RESULT_CACHES):
        rc.invalidate_token(tok)


def _forget_token(oid: int, tok: int) -> None:
    # weakref-finalizer entry point: enqueue only (a GC callback may
    # interrupt a frame holding engine locks — exec/spill.defer_finalizer
    # discipline); the next plan-cache access drains
    from ..exec.spill import defer_finalizer
    defer_finalizer(_forget_token_now, oid, tok)


def data_token(obj: Any) -> Optional[int]:
    """Stable identity token for a scan's base data object (arrow table,
    cache owner): the same ownership lifetime the scan device cache keys
    by. A new table — even under a re-registered view name — gets a new
    token, so plan fingerprints and result snapshots can never alias
    across data versions. Returns None for un-weakref-able objects."""
    with _tok_lock:
        tok = _TOKENS.get(id(obj))
        if tok is not None:
            return tok
        tok = next(_token_counter)
        _TOKENS[id(obj)] = tok
    try:
        weakref.finalize(obj, _forget_token, id(obj), tok)
    except TypeError:
        with _tok_lock:
            _TOKENS.pop(id(obj), None)
        return None
    return tok


# ---------------------------------------------------------------------------
# Parameterization: constant subtrees -> runtime Parameters
# ---------------------------------------------------------------------------

#: parents under which a constant child may become a parameter: binary
#: comparisons and arithmetic evaluate scalars through the broadcasting
#: (trace-safe) path, so a traced 0-d value is a drop-in
_PARAM_PARENTS = (pr.BinaryComparison, pr.EqualNullSafe,
                  ar.BinaryArithmetic)


def _is_const_subtree(e: ex.Expression) -> bool:
    """Every leaf a plain Literal (never a Parameter), every node
    deterministic: the subtree folds to one host value."""
    stack = [e]
    while stack:
        n = stack.pop()
        if not n.side_effect_free:
            return False
        if isinstance(n, ex.Parameter):
            return False
        if not n.children:
            if not isinstance(n, ex.Literal):
                return False
        stack.extend(n.children)
    return True


def _fold_to_param(e: ex.Expression) -> Optional[ex.Parameter]:
    """Host-fold a constant subtree and wrap it as an (unslotted)
    Parameter of the subtree's STATIC dtype; None when the fold fails or
    the dtype cannot ride as a traced scalar."""
    import numpy as np
    try:
        t = e.dtype
    except Exception:
        return None
    if t not in PARAM_DTYPES or t.numpy_dtype is None:
        return None
    try:
        v = e.eval(None)
    except Exception:
        return None
    if not isinstance(v, Scalar) or v.is_null:
        return None
    value = v.value
    if isinstance(value, np.generic):
        value = value.item()
    if not isinstance(value, (bool, int, float)):
        return None
    try:
        # the boxing the call sites will do must round-trip
        np.asarray(value, dtype=t.numpy_dtype)
    except Exception:
        return None
    return ex.Parameter(value, t)


class _Extractor:
    def __init__(self, extract: bool = True):
        self.extract = extract
        self.params: List[ex.Parameter] = []

    def assign(self, p: ex.Parameter) -> None:
        if p not in self.params:
            p.slot = len(self.params)
            self.params.append(p)

    def walk_expr(self, e: ex.Expression) -> ex.Expression:
        if isinstance(e, ex.Parameter):
            self.assign(e)
            return e
        if not self.extract:
            e.children = [self.walk_expr(c) for c in e.children]
            e._rebind_child_aliases()
            return e
        if isinstance(e, _PARAM_PARENTS) and len(e.children) == 2:
            l, r = e.children
            lc = _is_const_subtree(l)
            rc = _is_const_subtree(r)
            # exactly one constant side becomes a parameter (both-const
            # subtrees fold at THEIR parent; a both-const binary node
            # here means the whole predicate is constant — leave it, the
            # scalar fast paths own that case)
            if lc != rc:
                i = 0 if lc else 1
                p = _fold_to_param(e.children[i])
                if p is not None:
                    self.assign(p)
                    e.children[i] = p
                    e._rebind_child_aliases()
                self.walk_expr(e.children[1 - i])
                return e
        e.children = [self.walk_expr(c) for c in e.children]
        e._rebind_child_aliases()
        return e


def parameterize(plan: lp.LogicalPlan,
                 extract: bool = True) -> List[ex.Parameter]:
    """Extract runtime parameters out of an ANALYZED logical plan,
    in place: constant subtrees under comparisons/arithmetic inside
    ``Filter`` conditions and ``Project`` expressions become
    :class:`Parameter` nodes with deterministic slot numbering (same
    structure => same slots => same fingerprint). Pre-placed named
    placeholders (``:name``) in those positions get slots too; one
    anywhere else raises — its value would bake into a shared compiled
    program on rebind, a silent wrong-answer generator.

    ``extract=False`` assigns slots to pre-placed placeholders WITHOUT
    extracting literals — run even when the plan cache is off, because
    unslotted placeholders would collide on one fused-program key."""
    xt = _Extractor(extract)

    def walk(p: lp.LogicalPlan) -> None:
        if isinstance(p, lp.Filter):
            p.condition = xt.walk_expr(p.condition)
        elif isinstance(p, lp.Project):
            p.exprs = [xt.walk_expr(e) for e in p.exprs]
        for c in p.children:
            walk(c)

    walk(plan)
    claimed = {id(p) for p in xt.params}
    stray = []

    def check(p: lp.LogicalPlan) -> None:
        for e in p.expressions():
            for n in e.collect(lambda x: isinstance(x, ex.Parameter)):
                if id(n) not in claimed:
                    stray.append((type(p).__name__, n))
        for c in p.children:
            check(c)

    check(plan)
    if stray:
        node, n = stray[0]
        raise ValueError(
            f"parameter {n!r} appears under {node}; placeholders are "
            "supported in WHERE conditions and SELECT expressions only "
            "(anywhere else the value would bake into a shared compiled "
            "program)")
    return xt.params


# ---------------------------------------------------------------------------
# Plan fingerprint: the normalized structural key
# ---------------------------------------------------------------------------

def _value_key(v: Any):
    if isinstance(v, ex.Expression):
        return _expr_cache_key(v)
    if isinstance(v, lp.SortOrder):
        ck = _expr_cache_key(v.child)
        if ck is None:
            return None
        return ("sort", ck, v.ascending, v.nulls_first)
    if isinstance(v, dt.Schema):
        return tuple((f.name, f.dtype.name) for f in v.fields)
    if isinstance(v, (list, tuple)):
        sub = tuple(_value_key(x) for x in v)
        return None if any(s is None for s in sub) else ("seq",) + sub
    if isinstance(v, dict):
        sub = tuple((repr(k), _value_key(x)) for k, x in sorted(
            v.items(), key=lambda kv: repr(kv[0])))
        return None if any(s is None for _k, s in sub) else ("map",) + sub
    r = repr(v)
    if " at 0x" in r:
        return None                 # opaque (callables, live objects)
    return r


def _node_key(p: lp.LogicalPlan):
    if isinstance(p, lp.WriteFile):
        return None                 # side effects never cache
    for e in p.expressions():
        if e.collect(lambda x: not x.side_effect_free):
            return None             # nondeterministic plans re-execute
    if isinstance(p, lp.CachedScan):
        # never cache plans over df.cache() frames: a plan entry would
        # PIN the spillable batch's _CacheOwner, breaking the documented
        # reclaim-on-last-reference contract (weakref finalizer in
        # plan/logical._CacheOwner). The scan itself is already
        # materialized — replanning it is cheap and the fused programs
        # still hit the global cache.
        return None
    if isinstance(p, lp.LocalScan):
        tok = data_token(p.base_data)
        if tok is None:
            return None
        # the pruned per-query view is a fresh pa.Table: key by the BASE
        # identity + the kept columns, like the scan device cache
        return ("LocalScan", tok, _value_key(p.schema))
    if isinstance(p, lp.FileScan):
        return ("FileScan", p.fmt, tuple(p.paths),
                _value_key(p.options),
                _value_key([pf for pf in p.pushed_filters]))
    parts: List[Any] = [type(p).__name__]
    for k, v in sorted(vars(p).items()):
        if k in ("children", "_schema") or k.startswith("__"):
            continue
        vk = _value_key(v)
        if vk is None:
            return None
        parts.append((k, vk))
    return tuple(parts)


def _conf_sig(conf) -> tuple:
    """Stable signature of a session conf's explicit settings."""
    try:
        return tuple(sorted(
            (str(k), str(v)) for k, v in conf._settings.items()))
    except Exception:
        return ("unkeyable-conf", id(conf))


def plan_fingerprint(plan: lp.LogicalPlan) -> Optional[tuple]:
    """Structural fingerprint of an analyzed (and parameterized) plan,
    or None when any part is unkeyable — such plans are served the
    classic way, planned per execution."""
    nk = _node_key(plan)
    if nk is None:
        return None
    child_keys = []
    for c in plan.children:
        ck = plan_fingerprint(c)
        if ck is None:
            return None
        child_keys.append(ck)
    return (nk, tuple(child_keys))


def snapshot_key(plan: lp.LogicalPlan) -> Optional[tuple]:
    """Input-snapshot component of a result-cache key, read at serve
    time: ownership tokens for in-memory/cached scans (invalidated by
    the owner's death), (path, mtime, size) stats for file scans. None
    when any leaf cannot snapshot — the result is then never cached."""
    parts: List[Any] = []

    def walk(p: lp.LogicalPlan) -> bool:
        if isinstance(p, lp.CachedScan):
            tok = data_token(p.owner)
            if tok is None:
                return False
            parts.append(("cached", tok))
        elif isinstance(p, lp.LocalScan):
            tok = data_token(p.base_data)
            if tok is None:
                return False
            parts.append(("local", tok))
        elif isinstance(p, lp.FileScan):
            from ..io import expand_paths
            try:
                stats = []
                for f in expand_paths(p.paths):
                    st = os.stat(f)          # one stat per file
                    stats.append((f, st.st_mtime_ns, st.st_size))
            except OSError:
                return False
            parts.append(("files", p.fmt, tuple(stats)))
        elif isinstance(p, lp.Range):
            parts.append(("range", p.start, p.end, p.step))
        elif not p.children:
            return False            # unknown leaf: no snapshot identity
        return all(walk(c) for c in p.children)

    if not walk(plan):
        return None
    return tuple(parts)


# ---------------------------------------------------------------------------
# The caches
# ---------------------------------------------------------------------------

class PlanEntry:
    """One fully planned, contract-validated, stage-compiled execution
    plan plus its rebinding surface."""

    def __init__(self, fingerprint: tuple, exec_plan, overrides,
                 params: List[ex.Parameter], validate_mode: str,
                 logical_plan=None):
        self.fingerprint = fingerprint
        self.exec_plan = exec_plan
        self.overrides = overrides            # keeps last_explain/_violations
        self.logical_plan = logical_plan      # for result-cache snapshots
        self.params = params                  # slot order; shared with the tree
        self.validate_mode = validate_mode
        # the dtypes the plan was contract-validated with: a binding that
        # drifts a slot's dtype re-triggers validation
        # (analysis/contracts.validate_cached_binding)
        self.validated_dtypes = tuple(p.dtype for p in params)
        self.hits = 0
        # execution exclusivity (the multi-tenant service runs CONCURRENT
        # collects on one session, docs/service.md §5): a cached entry's
        # exec tree is a LIVE object — bind() mutates its Parameters and
        # exchanges assign per-execution shuffle state — so exactly one
        # execution may own it at a time. Concurrent hits on a busy entry
        # plan a fresh tree instead (serving verdict "busy"); try-only,
        # never blocking, so no lock-order edge exists
        self._exec_mu = threading.Lock()  # lint: raw-lock-ok try-only leaf lock; no engine lock taken under it

    def try_begin_execution(self) -> bool:
        """Claim the entry's exec tree for one execution (non-blocking).
        False -> the tree is mid-execution on another thread; the caller
        must plan a fresh tree."""
        return self._exec_mu.acquire(blocking=False)

    def end_execution(self) -> None:
        try:
            self._exec_mu.release()
        except RuntimeError:
            pass                       # release raced a relief-valve drop

    def bind(self, values: List[Any]) -> Tuple[bool, list]:
        """Rebind parameter values for the next execution. Returns
        (revalidated, violations) from the cached-binding validation
        policy: a hit skips the full contract walk unless a slot's dtype
        drifted since validation."""
        from ..analysis import contracts as _contracts
        if len(values) != len(self.params):
            raise ValueError(
                f"plan expects {len(self.params)} parameters, got "
                f"{len(values)}")
        for p, v in zip(self.params, values):
            p.bind(v)
        return _contracts.validate_cached_binding(
            self.exec_plan, self.params, self.validated_dtypes,
            self.validate_mode)

    def reset_metrics(self) -> None:
        """Fresh per-operator metric bags before a re-execution, so
        EXPLAIN ANALYZE and listeners see THIS execution's numbers (a
        freshly planned tree starts at zero; a cached one must too)."""

        def walk(node) -> None:
            bag = getattr(node, "metrics", None)
            if bag is not None:
                fresh = type(bag)()
                fresh.owner = getattr(bag, "owner", type(node).__name__)
                node.metrics = fresh
            for c in getattr(node, "children", ()):
                walk(c)

        walk(self.exec_plan)


class PlanCache:
    """Per-session LRU of :class:`PlanEntry` keyed by fingerprint."""

    def __init__(self, max_entries: int = 64):
        self.max_entries = max(1, int(max_entries))
        self._lock = threading.Lock()  # lint: raw-lock-ok per-session leaf lock; no engine lock taken under it
        self._entries: "OrderedDict[tuple, PlanEntry]" = OrderedDict()
        self.hits = 0
        self.misses = 0
        _ALL_PLAN_CACHES.add(self)

    def get(self, fingerprint: tuple) -> Optional[PlanEntry]:
        with self._lock:
            ent = self._entries.get(fingerprint)
            if ent is not None:
                self._entries.move_to_end(fingerprint)
                ent.hits += 1
                self.hits += 1
            else:
                self.misses += 1
            return ent

    def peek(self, fingerprint: tuple) -> Optional[PlanEntry]:
        """get() without touching LRU order or hit/miss stats."""
        with self._lock:
            return self._entries.get(fingerprint)

    def put(self, entry: PlanEntry) -> None:
        with self._lock:
            self._entries[entry.fingerprint] = entry
            self._entries.move_to_end(entry.fingerprint)
            while len(self._entries) > self.max_entries:
                self._entries.popitem(last=False)

    def discard(self, fingerprint: tuple) -> None:
        with self._lock:
            self._entries.pop(fingerprint, None)

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)


#: every live plan cache: the JIT map-pressure relief valve drops them
#: all (cached exec trees pin compiled stage programs via their _fns)
_ALL_PLAN_CACHES: "weakref.WeakSet" = weakref.WeakSet()


def _clear_all_plan_caches() -> None:
    for c in list(_ALL_PLAN_CACHES):
        c.clear()


from ..exec.compile_cache import register_program_cache as _rpc  # noqa: E402
_rpc(_clear_all_plan_caches)
del _rpc


class ResultCache:
    """Byte-capped LRU of host-resident result batches keyed on
    (fingerprint, parameter values, input snapshot)."""

    def __init__(self, max_bytes: int = 256 << 20,
                 max_entry_bytes: int = 32 << 20):
        self.max_bytes = max(0, int(max_bytes))
        self.max_entry_bytes = max(0, int(max_entry_bytes))
        self._lock = threading.Lock()  # lint: raw-lock-ok per-session leaf lock; no engine lock taken under it
        self._entries: "OrderedDict[tuple, tuple]" = OrderedDict()
        self._bytes = 0
        self.hits = 0
        self.misses = 0
        _RESULT_CACHES.add(self)

    @staticmethod
    def _entry_tokens(key: tuple):
        for part in key[2]:
            if part and part[0] in ("local", "cached"):
                yield part[1]

    def get(self, key: tuple):
        with self._lock:
            ent = self._entries.get(key)
            if ent is None:
                self.misses += 1
                return None
            self._entries.move_to_end(key)
            self.hits += 1
            return ent[0]

    def put(self, key: tuple, batch, nbytes: int) -> None:
        if nbytes > self.max_entry_bytes or nbytes > self.max_bytes:
            return
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old[1]
            self._entries[key] = (batch, nbytes)
            self._bytes += nbytes
            while self._bytes > self.max_bytes and self._entries:
                _k, (_b, n) = self._entries.popitem(last=False)
                self._bytes -= n

    def invalidate_token(self, tok: int) -> None:
        """Scan-invalidation hook: the base table / cached batch carrying
        ``tok`` died — every result derived from it is unservable."""
        with self._lock:
            dead = [k for k in self._entries
                    if tok in self._entry_tokens(k)]
            for k in dead:
                self._bytes -= self._entries.pop(k)[1]

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._bytes = 0

    @property
    def bytes(self) -> int:
        return self._bytes

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)


# ---------------------------------------------------------------------------
# The serving entry points (api/dataframe wires these)
# ---------------------------------------------------------------------------

def _counter(name: str, doc: str):
    try:
        from ..service.telemetry import MetricsRegistry
        return MetricsRegistry.get().counter(name, doc)
    except Exception:
        return None


def _inc(name: str, doc: str, n: int = 1) -> None:
    c = _counter(name, doc)
    if c is not None:
        try:
            c.inc(n)
        except Exception:
            pass


def _gauge_set(name: str, doc: str, value: float) -> None:
    try:
        from ..service.telemetry import MetricsRegistry
        MetricsRegistry.get().gauge(name, doc).set(value)
    except Exception:
        pass


def session_caches(session) -> Tuple[PlanCache, ResultCache]:
    """The session's plan/result caches, created from its conf on first
    use (``RuntimeConf.set`` drops them so a conf change replans)."""
    from .. import config as cfg
    pc = getattr(session, "_plan_cache", None)
    if pc is None:
        pc = session._plan_cache = PlanCache(
            int(session.conf.get(cfg.PLAN_CACHE_MAX_ENTRIES)))
    rc = getattr(session, "_result_cache", None)
    if rc is None:
        rc = session._result_cache = ResultCache(
            int(session.conf.get(cfg.RESULT_CACHE_MAX_BYTES)),
            int(session.conf.get(cfg.RESULT_CACHE_MAX_ENTRY_BYTES)))
    return pc, rc


def serving_stats(session) -> Dict[str, int]:
    st = getattr(session, "_serving_stats", None)
    if st is None:
        st = session._serving_stats = {
            "parses": 0, "analyzes": 0, "plansBuilt": 0,
            "planHits": 0, "planMisses": 0, "planBusy": 0,
            "parseCacheHits": 0, "parseCacheMisses": 0,
            "resultHits": 0, "resultMisses": 0, "resultStores": 0,
            "revalidations": 0,
        }
    return st


#: the CURRENT thread's serving info for the execution in flight —
#: ``session._last_serving`` is a cross-thread observability surface that
#: concurrent service workers clobber, so the execution pipeline
#: (collect_batch -> release, the prepared-statement capture) reads the
#: thread-local copy instead (docs/service.md §5)
_tls_serving = threading.local()


def note_thread_serving(serving: Optional[dict]) -> None:
    _tls_serving.value = serving  # lint: unguarded-ok executing thread's own TLS field


def thread_serving() -> Optional[dict]:
    return getattr(_tls_serving, "value", None)


def release_plan_entry(serving: Optional[dict]) -> None:
    """End-of-execution hook for the entry exclusivity claimed in
    :func:`plan_for` / the prepared fast path: pops ``planEntry`` from
    the serving info (so a double call is a no-op) and releases the
    tree for the next execution. Call from a ``finally`` wherever an
    exec tree obtained through the serving front door finishes."""
    if not serving:
        return
    entry = serving.pop("planEntry", None)
    if entry is not None:
        entry.end_execution()


class _CachedOverrides:
    """What a plan-cache hit exposes where a fresh ``Overrides`` would
    be: the entry's captured explain text and the violations of the LAST
    binding validation (empty on a clean hit)."""

    def __init__(self, overrides, violations):
        self.last_explain = getattr(overrides, "last_explain", "")
        self.last_meta = getattr(overrides, "last_meta", None)
        self.last_violations = list(violations)


def plan_for(session, plan: lp.LogicalPlan):
    """The planning front door: parameterize + fingerprint the analyzed
    plan, serve a cached entry (rebound + cheaply revalidated) or build
    one via ``Overrides.apply`` and cache it. Returns
    ``(exec_plan, serving-info dict)``; the caller stores the info on
    the session for EXPLAIN ANALYZE and the result-cache round trip."""
    from .. import config as cfg
    from ..exec.spill import drain_deferred_finalizers
    from .overrides import Overrides
    drain_deferred_finalizers()
    st = serving_stats(session)
    st["analyzes"] += 1
    enabled = bool(session.conf.get(cfg.PLAN_CACHE_ENABLED))
    serving: Dict[str, Any] = {
        "planCache": "off", "resultCache": "off", "params": 0,
        "fingerprint": None, "values": None, "snapshot": None,
        "cacheable": False, "revalidated": False,
    }
    params: List[ex.Parameter] = []
    fingerprint = None
    if enabled:
        params = parameterize(plan)
        fingerprint = plan_fingerprint(plan)
    else:
        # cache off: :name placeholders still need slots — unslotted
        # parameters are unkeyable (per-exec compiles), and two of them
        # must never collide on one shared program key
        parameterize(plan, extract=False)
    if enabled:
        if fingerprint is not None:
            # the conf is part of the plan's identity: planning decisions
            # (fusion, thresholds, validation mode) read it, and tests
            # mutate a session's conf in place between collects
            fingerprint = (fingerprint, _conf_sig(session.conf))
        serving["params"] = len(params)
        serving["fingerprint"] = fingerprint
    if fingerprint is None:
        if enabled:
            serving["planCache"] = "uncacheable"
        ov = Overrides(session.conf)
        exec_plan = ov.apply(plan)
        session._last_overrides = ov
        st["plansBuilt"] += 1
        return exec_plan, serving

    cache, _rc = session_caches(session)
    values = [p.value for p in params]
    serving["values"] = tuple(values)
    serving["cacheable"] = True
    entry = cache.get(fingerprint)
    busy = False
    if entry is not None:
        # claim the tree BEFORE binding: bind() mutates the Parameters
        # the live tree shares, and a concurrent execution may be
        # mid-flight on them (the service's concurrent-collect shape)
        if not entry.try_begin_execution():
            busy = True
            entry = None
    if entry is not None:
        try:
            revalidated, violations = entry.bind(values)
        except Exception:
            # error-mode drift raises out of the binding validation: the
            # tainted entry must not stay cached (a retry with clean
            # values would re-raise forever)
            entry.end_execution()
            cache.discard(fingerprint)
            raise
        if revalidated:
            st["revalidations"] += 1
            serving["revalidated"] = True
        if revalidated and violations:
            # the binding broke the validated contract: drop the entry
            # and replan from scratch (never execute a known-bad tree)
            entry.end_execution()
            cache.discard(fingerprint)
        else:
            entry.reset_metrics()
            st["planHits"] += 1
            serving["planCache"] = "hit"
            serving["planEntry"] = entry
            _inc("tpu_plan_cache_hits_total",
                 "parameterized-plan cache hits (analyze/optimize/"
                 "validate/stage-compile skipped)")
            _gauge_set("tpu_plan_cache_entries",
                       "live parameterized-plan cache entries",
                       len(cache))
            session._last_overrides = _CachedOverrides(
                entry.overrides, violations)
            return entry.exec_plan, serving

    if busy:
        # the cached tree is executing on another thread: plan a FRESH
        # tree for this execution and leave the cache alone (the busy
        # entry keeps serving future hits). Counted separately so the
        # service's concurrency shows up in serving_stats instead of
        # masquerading as cold misses.
        st["planBusy"] += 1
        serving["planCache"] = "busy"
    else:
        st["planMisses"] += 1
        serving["planCache"] = "miss"
        _inc("tpu_plan_cache_misses_total",
             "parameterized-plan cache misses (full planning pass)")
    ov = Overrides(session.conf)
    exec_plan = ov.apply(plan)
    session._last_overrides = ov
    st["plansBuilt"] += 1
    if not busy:
        mode = str(session.conf.get(cfg.ANALYSIS_VALIDATE_PLAN))
        fresh = PlanEntry(fingerprint, exec_plan, ov, params, mode,
                          logical_plan=plan)
        # the fresh entry is about to EXECUTE: claim it before it becomes
        # visible in the cache, or a concurrent hit could bind over it
        fresh.try_begin_execution()
        serving["planEntry"] = fresh
        cache.put(fresh)
        _gauge_set("tpu_plan_cache_entries",
                   "live parameterized-plan cache entries", len(cache))
    return exec_plan, serving


def result_key(session, serving, plan: lp.LogicalPlan) -> Optional[tuple]:
    """The (fingerprint, values, snapshot) key for this execution, or
    None when the result cache is off / the plan cannot snapshot."""
    from .. import config as cfg
    if not bool(session.conf.get(cfg.RESULT_CACHE_ENABLED)):
        return None
    if not serving.get("cacheable"):
        serving["resultCache"] = "uncacheable"
        return None
    snap = snapshot_key(plan)
    if snap is None:
        serving["resultCache"] = "uncacheable"
        return None
    serving["snapshot"] = snap
    return (serving["fingerprint"], serving["values"], snap)


def lookup_result(session, key: Optional[tuple]):
    """Exact-repeat short circuit: the stored host batch, or None."""
    if key is None:
        return None
    _pc, rc = session_caches(session)
    out = rc.get(key)
    st = serving_stats(session)
    if out is not None:
        st["resultHits"] += 1
        _inc("tpu_result_cache_hits_total",
             "result cache hits (execution short-circuited)")
    else:
        st["resultMisses"] += 1
        _inc("tpu_result_cache_misses_total",
             "result cache misses (query executed)")
    return out


def serve_result_hit(session, serving: dict):
    """Exact-repeat short circuit, shared by ``DataFrame.collect_batch``
    and the prepared-statement fast path: look up ``serving['resultKey']``
    and, on a hit, stamp the no-execution post-query state (empty
    sync/span reports, NO span recorder — the previous query's timeline
    must not attach to this collect) and return the stored host batch.
    None -> execute normally (``serving['resultCache']`` already marked
    miss when a key was present)."""
    rkey = serving.get("resultKey")
    if rkey is None:
        return None
    hit = lookup_result(session, rkey)
    serving["resultCache"] = "hit" if hit is not None else "miss"
    if hit is None:
        return None
    session._last_sync_report = {"hostSyncs": 0, "syncSites": {}}
    session._last_span_report = {}
    session._last_span_recorder = None
    session._last_execute_time_s = 0.0
    return hit


def store_result(session, key: Optional[tuple], batch):
    """Fetch the collected batch host-side and remember it under
    ``key``; returns the host batch (callers fetch anyway). Called
    OUTSIDE the query's sync-counting window."""
    if key is None:
        return batch
    from .. import config as cfg
    max_entry = int(session.conf.get(cfg.RESULT_CACHE_MAX_ENTRY_BYTES))
    try:
        if batch.device_size_bytes() > 2 * max_entry:
            return batch               # cheap pre-check before the fetch
        host = batch.fetch_to_host()
        nbytes = 0
        for c in host.columns:
            try:
                nbytes += sum(int(getattr(a, "nbytes", 64))
                              for a in c.arrays())
            except Exception:
                nbytes += 64           # host-object columns: rough floor
    except Exception:
        return batch                   # caching must never fail a query
    _pc, rc = session_caches(session)
    rc.put(key, host, max(nbytes, 1))
    serving_stats(session)["resultStores"] += 1
    _gauge_set("tpu_result_cache_bytes",
               "host bytes held by the result cache", rc.bytes)
    return host


def serving_line(serving: Optional[dict]) -> Optional[str]:
    """The EXPLAIN ANALYZE serving-cache summary line."""
    if not serving:
        return None
    return (f"serving: planCache={serving.get('planCache', 'off')} "
            f"resultCache={serving.get('resultCache', 'off')} "
            f"params={serving.get('params', 0)}")
