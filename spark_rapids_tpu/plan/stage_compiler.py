"""Whole-stage fusion: a pipeline STAGE, not an operator, is the unit of
compiled execution (docs/fusion.md).

Reference: the executor whole-stage codegen pipeline (SURVEY.md §3.3) — the
reference collapses a pipeline-breaker-free operator chain into one generated
function; here the chain lowers to ONE ``_fused_fn`` XLA program per batch.
Eager per-operator execution dispatches one compiled program per operator per
batch (plus a compaction scatter and count per filter); on dispatch-latency
bound links (the tunneled-device case BENCH_r03 measured at ~500x below the
fused microbench) those per-op dispatches dominate the whole query.

Three pieces live here:

* :class:`StageChain` — an ordered list of fusable filter/project steps with
  a single traced evaluation (`eval_traced`) used both by
  :class:`TpuWholeStageExec` and by ``TpuHashAggregateExec``'s folded
  ``pre_stage`` (the scan-unpack -> filter -> project -> partial-agg stage:
  the scan's cached unpack program feeds the stage program feeds the
  aggregate kernel — one device program per stage per batch, donation
  threaded through the whole chain).
* :func:`fuse_stages` / :func:`peel_for_aggregate` — the stage compiler
  passes ``Overrides.apply`` runs over the converted exec tree, gated by
  ``spark.rapids.tpu.sql.fusion.wholeStage`` (default on). Every fusion
  decision — membership or decline reason — is recorded per node and
  surfaces in EXPLAIN ANALYZE.
* :func:`tuned_batch_rows` — batch-size autotuning: the scan/coalesce row
  target derived from the device HBM budget and the live watermark
  (service/telemetry), so fused stages run at the largest safe batch.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple

from .. import config as cfg
from ..analysis.contracts import exec_contract
from ..columnar import dtypes as dt
from ..columnar.batch import ColumnarBatch
from ..columnar.column import Scalar
from ..ops import expressions as ex
from ..ops import kernels as K
from ..exec.tracing import trace_span
from . import physical as ph
from .physical import (Partition, TpuExec, _dev_count, _donate_argnums,
                       _donation_consumed, _expr_cache_key, _fused_fn,
                       _schema_sig, _ScalarPredicate, exec_metrics)


# ---------------------------------------------------------------------------
# Stage chain: the fusable operator sequence
# ---------------------------------------------------------------------------

class StageChain:
    """An ordered chain of filter/project steps evaluated in ONE trace.

    Steps apply bottom-up (scan side first). Filters accumulate a live-row
    MASK instead of compacting per step — compaction is a scatter (the
    slowest TPU primitive) and runs at most once, at the stage boundary;
    an aggregate consumer skips it entirely and feeds the mask to its
    kernels. Projects rebuild the virtual batch positionally, so masks
    stay row-aligned across steps.

    steps: [("filter", bound_condition) | ("project", bound_exprs,
    out_schema)] — expressions are already bound to the PREVIOUS step's
    output schema (the original per-op execs bound them).
    """

    def __init__(self, steps: List[tuple], in_schema: dt.Schema,
                 out_schema: dt.Schema):
        self.steps = list(steps)
        self.in_schema = in_schema
        self.out_schema = out_schema
        # query parameters inside the chain (plan-cache parameterization):
        # slot-ordered, each stamped with its trace position — the fused
        # program's appended-argument contract (docs/plan_cache.md)
        self.params = ex.ordered_params(self.exprs())

    # -- static properties ---------------------------------------------------
    def exprs(self) -> List[ex.Expression]:
        out: List[ex.Expression] = []
        for step in self.steps:
            if step[0] == "filter":
                out.append(step[1])
            else:
                out.extend(step[1])
        return out

    def fusable(self) -> bool:
        return all(e.tree_fusable() for e in self.exprs()) and not any(
            e.collect(lambda x: not x.side_effect_free) for e in self.exprs())

    def cache_key(self) -> Optional[tuple]:
        """Structural key of the whole chain, or None when any expression
        is unkeyable (the stage then stays on the per-op path — a per-exec
        jit of a multi-op chain would recompile per query)."""
        parts: List[tuple] = []
        for step in self.steps:
            if step[0] == "filter":
                k = _expr_cache_key(step[1])
                if k is None:
                    return None
                parts.append(("filter", k))
            else:
                ks = [_expr_cache_key(e) for e in step[1]]
                if any(k is None for k in ks):
                    return None
                parts.append(("project", tuple(ks),
                              _schema_sig(step[2])))
        return tuple(parts)

    def describe(self) -> str:
        return "->".join("filter" if s[0] == "filter"
                         else f"project[{len(s[1])}]" for s in self.steps)

    # -- traced evaluation ---------------------------------------------------
    def eval_traced(self, b: ColumnarBatch
                    ) -> Tuple[ColumnarBatch, Optional[Any]]:
        """Apply the chain inside a fused trace. Returns (batch, mask):
        ``mask`` is the accumulated live-row mask (None when the chain has
        no filter — every input row is live). Dead rows keep whatever
        garbage the projections computed for them; consumers mask or
        compact before the values matter."""
        mask = None
        for step in self.steps:
            if step[0] == "filter":
                pred = step[1].eval(b)
                if isinstance(pred, Scalar):
                    # constant predicate bakes a python bool into the trace:
                    # permanent per-op fallback, like FusedStage
                    raise _ScalarPredicate()
                m = pred.data & pred.validity
                mask = m if mask is None else (mask & m)
            else:
                _tag, exprs, out_schema = step
                cols = [ex.materialize(e.eval(b), b) for e in exprs]
                nb = ColumnarBatch(out_schema, cols, b.num_rows_raw)
                nb.params = b.params   # later steps' Parameters still read
                b = nb
        if mask is not None:
            mask = mask & b.row_mask_raw()
        return b, mask

    # -- eager fallback ------------------------------------------------------
    def eval_eager(self, batch: ColumnarBatch) -> ColumnarBatch:
        """Per-op eager evaluation (the pre-fusion semantics): compaction
        per filter step, one dispatch per expression node."""
        b = batch
        for step in self.steps:
            if step[0] == "filter":
                pred = step[1].eval(b)
                if isinstance(pred, Scalar):
                    if pred.value is True:
                        continue
                    b = ColumnarBatch(b.schema, b.columns, 0)
                    continue
                keep = pred.data & pred.validity & b.row_mask()
                cols, count = K.compact_columns(b.columns, keep)
                b = ColumnarBatch(b.schema, cols, count)
            else:
                _tag, exprs, out_schema = step
                cols = [ex.materialize(e.eval(b), b) for e in exprs]
                b = ColumnarBatch(out_schema, cols, b.num_rows_raw)
        return b


def chain_of_filter(condition: ex.Expression,
                    schema: dt.Schema) -> StageChain:
    """Single-filter degenerate chain (the legacy ``pre_filter`` form)."""
    return StageChain([("filter", condition)], schema, schema)


def build_stage_program(chain: StageChain, donate: tuple = ()):
    """The whole-stage jitted program for ``chain`` — module-level (no
    exec instance in the closure) so the compile pool can rebuild the
    IDENTICAL program from a pickled chain in a fresh process (prewarm,
    exec/compile_pool.py) and hit the same ``_fused_fn`` key."""
    import jax
    in_schema = chain.in_schema
    has_filter = any(s[0] == "filter" for s in chain.steps)

    def run(num_rows, *arrays):
        b = ColumnarBatch.from_flat_arrays(in_schema, arrays, num_rows)
        out, mask = chain.eval_traced(b)
        if not has_filter:
            return tuple(out.flat_arrays())
        cols, count = K.compact_columns(out.columns, mask)
        return tuple(a for c in cols for a in c.arrays()) + (count,)
    # lint: naked-jit-ok only ever invoked as a _fused_fn builder (the exec's _build and the compile pool's prewarm replay both route through the funnel)
    return jax.jit(run, donate_argnums=donate)


# ---------------------------------------------------------------------------
# The whole-stage exec
# ---------------------------------------------------------------------------

class TpuWholeStageExec(TpuExec):
    """A fused filter/project chain as ONE exec: per batch, one compiled
    program evaluates every member operator's expressions and compacts
    once at the stage boundary (count left device-resident, like
    TpuFilterExec). Falls back permanently to the per-op eager path on
    any trace failure — identical semantics, more dispatches."""

    CONTRACT = exec_contract(schema="defined", partitioning="preserve")
    METRICS = exec_metrics()

    def __init__(self, child: TpuExec, chain: StageChain,
                 members: List[str], stage_id: int = 0):
        super().__init__(child)
        self.chain = chain
        self.members = members          # bottom-up member exec names
        self.stage_id = stage_id
        self.broken = False
        self._fns: Dict[bool, Any] = {}   # donate bit -> program
        self._has_filter = any(s[0] == "filter" for s in chain.steps)
        self._ckey = chain.cache_key()

    @property
    def schema(self) -> dt.Schema:
        return self.chain.out_schema

    def execute(self) -> List[Partition]:
        return [self._map(p) for p in self.children[0].execute()]

    def _build(self, donate: tuple = ()):
        return build_stage_program(self.chain, donate)

    def _stage_args(self, batch: ColumnarBatch) -> tuple:
        """The fused program's real argument tuple for ``batch`` (the
        exact avals ``_fused`` calls with)."""
        return (_dev_count(batch), *batch.flat_arrays(),
                *ex.param_arg_values(self.chain.params))

    @staticmethod
    def _warm_args(args: tuple) -> tuple:
        """Zero-filled stand-ins for a pool warm call: ``zeros_like``
        preserves shape/dtype/weak-type, so the background compile's jit
        signature exactly matches the real call — without aliasing this
        batch's (possibly soon-donated) buffers on another thread."""
        import jax
        import jax.numpy as jnp
        return tuple(jnp.zeros_like(a) if isinstance(a, jax.Array) else a
                     for a in args)

    def _fused(self, batch: ColumnarBatch) -> Optional[ColumnarBatch]:
        from ..analysis import recompile as _recompile
        from ..exec import compile_pool as _pool
        try:
            donate = _donate_argnums(batch, 1)
            fn = self._fns.get(bool(donate))
            if fn is None:
                # no capacity in the key: like FusedStage, one program per
                # expression structure — jax retraces per batch shape under
                # the same cached callable
                key = ("stage", _schema_sig(self.chain.in_schema),
                       self._ckey, ("donate", bool(donate)))
                self._kernel = _recompile.kernel_of(key)
                st = _pool.status(key)
                if st is None and not ph.fused_cached(key) and \
                        _pool.routable(key):
                    # latency-sensitive cold build: hand it to the pool
                    # and serve this batch eagerly (docs/compile.md §5)
                    args = self._stage_args(batch)
                    _pool.note_stage_signature(key, self._kernel,
                                               self.chain, donate, args)
                    st = _pool.consult(key, lambda: self._build(donate),
                                       self._warm_args(args),
                                       kernel=self._kernel)
                if st == "pending":
                    return None    # eager until the background build lands
                if st == "failed":
                    err = _pool.failure(key)
                    if err is not None:
                        # replicate the synchronous failure semantics:
                        # the except arms below decide broken vs raise
                        raise err
                if not ph.fused_cached(key):
                    # record the rebuild recipe for prewarm BEFORE the
                    # build (sync path; the async path recorded above)
                    _pool.note_stage_signature(key, self._kernel,
                                               self.chain, donate,
                                               self._stage_args(batch))
                fn = _fused_fn(key, lambda: self._build(donate))
                self._fns[bool(donate)] = fn
            else:
                # later batches bypass the cache consult (FusedStage note)
                _recompile.note_call(self._kernel)
            with trace_span("fused_stage"):
                outs = fn(_dev_count(batch), *batch.flat_arrays(),
                          *ex.param_arg_values(self.chain.params))
            ph._note_donated(batch, donate)
        except _ScalarPredicate:
            self.broken = True
            return None
        except Exception as e:
            if _donation_consumed(batch):
                raise          # executed-and-donated: no eager re-read
            import logging
            logging.getLogger("spark_rapids_tpu.fusion").warning(
                "whole-stage program fell back to per-op eager for stage "
                "#%d (%s): %s", self.stage_id, "+".join(self.members), e)
            self.broken = True
            return None
        if not self._has_filter:
            return ColumnarBatch.from_flat_arrays(
                self.chain.out_schema, list(outs), batch.num_rows_raw)
        # filtered: compacted columns + device count (no readback — the
        # count rides downstream like TpuFilterExec's)
        return ColumnarBatch.from_flat_arrays(
            self.chain.out_schema, list(outs[:-1]), outs[-1])

    def _map(self, part: Partition) -> Partition:
        for batch in part:
            if isinstance(batch.num_rows_raw, int) and \
                    batch.num_rows_raw == 0:
                continue
            with trace_span(f"op_{type(self).__name__}", self.metrics,
                            "opTime"):
                out = None
                if not self.broken:
                    out = self._fused(batch)
                if out is None:
                    out = self.chain.eval_eager(batch)
            self.metrics.inc("numOutputRows", out.num_rows_raw)
            self.metrics.inc("numOutputBatches")
            yield out

    def _node_string(self) -> str:
        return (f"TpuWholeStageExec[#{self.stage_id} "
                f"{'+'.join(self.members)}]")


# ---------------------------------------------------------------------------
# The planner passes
# ---------------------------------------------------------------------------

def fusion_enabled(conf: cfg.TpuConf) -> bool:
    # the legacy wholeStageFusion.enabled is the MASTER fusion switch
    # (it gates the per-op FusedStage programs at runtime): turning it
    # off must disable stage-level fusion too, or an operator A/B-ing
    # "fusion off" would still get fused chains
    return bool(conf.get(cfg.FUSION_WHOLE_STAGE)) and \
        bool(conf.get(cfg.WHOLESTAGE_FUSION))


def _node_decline_reason(node: TpuExec) -> Optional[str]:
    """Why this filter/project exec cannot join a fused stage (None when
    it can)."""
    if isinstance(node, ph.TpuProjectExec):
        exprs = node.exprs
    elif isinstance(node, ph.TpuFilterExec):
        exprs = [node.condition]
    else:
        return f"not a stage operator ({type(node).__name__})"
    for e in exprs:
        bad = e.collect(lambda x: not x.side_effect_free)
        if bad:
            return f"stateful expression ({type(bad[0]).__name__})"
        if not e.tree_fusable():
            nf = e.collect(lambda x: not x.fusable)
            which = type(nf[0]).__name__ if nf else type(e).__name__
            return f"expression not fusable ({which})"
        if _expr_cache_key(e) is None:
            return "unkeyable expression (per-exec jit only)"
    return None


def _step_of(node: TpuExec) -> tuple:
    if isinstance(node, ph.TpuFilterExec):
        return ("filter", node.condition)
    return ("project", node.exprs, node.schema)


class FusionDecisions:
    """Per-query record of what the stage compiler did: stage membership
    for fused nodes, decline reasons for the rest. Rendered into EXPLAIN
    ANALYZE next to the contract diagnostics."""

    def __init__(self):
        self.notes: List[str] = []     # plan-level summary lines
        self._n = 0

    def next_stage_id(self) -> int:
        self._n += 1
        return self._n

    def note(self, line: str) -> None:
        self.notes.append(line)


def peel_for_aggregate(child: TpuExec, decisions: FusionDecisions
                       ) -> Tuple[TpuExec, Optional[StageChain], List[str]]:
    """Walk down a fusable filter/project chain directly below an
    aggregate and fold it into the aggregate's own fused programs
    (``pre_stage``): the whole scan -> filter -> project -> partial-agg
    stage becomes the agg's update program — no separate per-op dispatch,
    compaction, or count sync per batch. Returns (new child, chain or
    None, member names bottom-up)."""
    steps_top_down: List[tuple] = []
    members_top_down: List[str] = []
    node = child
    while isinstance(node, (ph.TpuFilterExec, ph.TpuProjectExec)):
        reason = _node_decline_reason(node)
        if reason is not None:
            node._fusion_decline = reason
            break
        steps_top_down.append(_step_of(node))
        members_top_down.append(type(node).__name__)
        node = node.children[0]
    if not steps_top_down:
        return child, None, []
    steps = list(reversed(steps_top_down))
    members = list(reversed(members_top_down))
    chain = StageChain(steps, node.schema, child.schema)
    if chain.cache_key() is None:
        return child, None, []
    return node, chain, members


def fuse_stages(root: TpuExec, conf: cfg.TpuConf,
                decisions: FusionDecisions) -> TpuExec:
    """Collapse every remaining maximal filter/project chain (length >= 2)
    into a :class:`TpuWholeStageExec`. Single operators keep the existing
    per-op ``FusedStage`` path — already one program per batch; wrapping
    them would only rename the node."""

    def rec(node: TpuExec) -> TpuExec:
        if isinstance(node, (ph.TpuFilterExec, ph.TpuProjectExec)):
            run: List[TpuExec] = []
            cur = node
            while isinstance(cur, (ph.TpuFilterExec, ph.TpuProjectExec)):
                reason = _node_decline_reason(cur)
                if reason is not None:
                    cur._fusion_decline = reason
                    break
                run.append(cur)
                cur = cur.children[0]
            if len(run) >= 2:
                steps = [_step_of(n) for n in reversed(run)]
                members = [type(n).__name__ for n in reversed(run)]
                chain = StageChain(steps, run[-1].children[0].schema,
                                   run[0].schema)
                if chain.cache_key() is not None:
                    ws = TpuWholeStageExec(rec(run[-1].children[0]), chain,
                                           members,
                                           decisions.next_stage_id())
                    decisions.note(
                        f"stage #{ws.stage_id}: {'+'.join(members)} -> "
                        f"one fused program per batch")
                    return ws
                run[0]._fusion_decline = \
                    "unkeyable expression in chain (per-exec jit only)"
            elif run:
                run[0]._fusion_single = True
        for i, c in enumerate(node.children):
            node.children[i] = rec(c)
        return node

    return rec(root)


def fusion_annotations(root: TpuExec) -> Dict[str, List[str]]:
    """Per-node EXPLAIN ANALYZE annotations keyed by the same
    root->node class-name path the contract validator uses: fused-stage
    membership for stage nodes and folded aggregates, decline reasons for
    operators left on the per-op path."""
    out: Dict[str, List[str]] = {}

    def walk(node, path: str, idx: Optional[int] = None) -> None:
        name = type(node).__name__
        here = f"{path}/{idx}.{name}" if path else name
        lines: List[str] = []
        if isinstance(node, TpuWholeStageExec):
            lines.append(
                f"* fused stage #{node.stage_id}: "
                f"{'+'.join(node.members)} compiled into one program"
                + (" (fell back to per-op eager)" if node.broken else ""))
        stage = getattr(node, "_fusion_stage", None)
        if stage is not None:
            members = getattr(node, "_fusion_members", [])
            lines.append(
                f"* fused stage #{stage}: {'+'.join(members)} folded into "
                f"this aggregate's update program")
        reason = getattr(node, "_fusion_decline", None)
        if reason is not None:
            lines.append(f"* fusion declined: {reason}")
        if getattr(node, "_fusion_single", False):
            lines.append("* single-op stage (per-op fused program)")
        if lines:
            out[here] = lines
        for i, c in enumerate(getattr(node, "children", ())):
            walk(c, here, i)

    walk(root, "")
    return out


# ---------------------------------------------------------------------------
# Batch-size autotuning (ISSUE 11 prong c)
# ---------------------------------------------------------------------------

# per-process memo: (row_bytes bucket, ceiling) -> rows. The first
# computation reads the live HBM watermark; later queries reuse the pick so
# repeated runs see identical batch capacities (the recompile gate depends
# on shape stability, and the pow2 quantization already absorbs small
# watermark drift).
_TUNE_CACHE: Dict[tuple, int] = {}
_tune_lock = threading.Lock()

# a fused stage holds ~input + output + temporaries per resident batch;
# streaming pipelines (agg window, task pool) keep several batches in
# flight. 12 resident batches x 2x working set has held the measured
# corpus under budget while leaving headroom for the spill store.
_RESIDENT_BATCHES = 12
_BUDGET_FRACTION = 0.5


def _pow2_floor(n: int) -> int:
    p = 1
    while p * 2 <= n:
        p *= 2
    return p


def _pow2_ceil(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


def _device_budget_bytes() -> int:
    from ..exec.device import DeviceManager
    dm = DeviceManager.peek()
    if dm is not None:
        return int(dm.memory_budget_bytes)
    return 2 << 30          # DeviceManager's own CPU-fallback budget


def _row_bytes(schema: dt.Schema) -> int:
    total = 0
    for f in schema:
        total += (f.dtype.byte_width or 32) + 1
    return max(total, 1)


def tuned_batch_rows(conf: cfg.TpuConf, schema: dt.Schema) -> int:
    """Scan/coalesce target rows per batch: the largest SAFE batch for a
    fused stage over ``schema`` (docs/fusion.md §4).

    With ``spark.rapids.tpu.sql.batch.autotune`` (default on) the target
    is ``min(batchSizeBytes, available HBM share) / row_bytes`` quantized
    to a power of two — available = device budget minus the live device
    watermark (service/telemetry), shared across ~12 resident batches at
    half occupancy. An explicit ``reader.batchSizeRows`` setting stays a
    hard user cap. Autotune off reproduces the legacy bytes-derived
    target capped at reader.batchSizeRows."""
    row_bytes = _row_bytes(schema)
    reader_cap = int(conf.get(cfg.MAX_READER_BATCH_SIZE_ROWS))
    # caps apply AFTER the floor: an explicit small reader.batchSizeRows
    # must win over the 16k floor (tests pin tiny batches to force
    # multi-batch streams)
    legacy = min(max(1 << 14, int(conf.batch_size_bytes) // row_bytes),
                 reader_cap)
    if not bool(conf.get(cfg.BATCH_AUTOTUNE)):
        return legacy
    ceiling = int(conf.get(cfg.BATCH_AUTOTUNE_MAX_ROWS))
    if cfg.MAX_READER_BATCH_SIZE_ROWS.key in conf._settings:
        # the user pinned a rows cap: autotune may shrink below it under
        # memory pressure but never exceed it
        ceiling = min(ceiling, reader_cap)
    # the division uses the pow2-CEIL of the row width so the pick is a
    # pure (deterministic) function of the memo key — stable capacities
    # are what the recompile gate enforces. batchSizeBytes participates
    # in the computation, so it must participate in the key (a session
    # that lowers it must not hit another session's larger pick)
    rb = _pow2_ceil(row_bytes)
    memo_key = (rb, ceiling, int(conf.batch_size_bytes))
    with _tune_lock:
        hit = _TUNE_CACHE.get(memo_key)
    if hit is not None:
        return hit
    budget = _device_budget_bytes()
    try:
        from ..service.telemetry import watermark
        in_use = int(watermark("device").current)
    except Exception:
        in_use = 0
    avail = max(budget - in_use, budget // 4)
    share = int(avail * _BUDGET_FRACTION) // _RESIDENT_BATCHES
    per_batch_bytes = min(int(conf.batch_size_bytes), max(share, 1))
    rows = min(max(1 << 14, per_batch_bytes // rb), ceiling)
    rows = _pow2_floor(rows)
    with _tune_lock:
        _TUNE_CACHE.setdefault(memo_key, rows)
        rows = _TUNE_CACHE[memo_key]
    return rows


def reset_tuning_cache() -> None:
    with _tune_lock:
        _TUNE_CACHE.clear()
