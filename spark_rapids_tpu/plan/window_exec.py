"""TpuWindowExec: window operator (GpuWindowExec.scala analog).

Sorts the (single, RequireSingleBatch like the reference) batch by
(partition keys, order keys), computes each window expression with the
segment kernels in ops/window.py, then restores the input row order so window
columns append positionally.
"""

from __future__ import annotations

from typing import List, Tuple

import jax.numpy as jnp

from ..columnar import dtypes as dt
from ..columnar.batch import ColumnarBatch
from ..columnar.column import Column
from ..ops import expressions as ex
from ..ops import kernels as K
from ..ops import window as W
from . import logical as lp
from ..analysis.contracts import exec_contract
from .physical import (Partition, TpuExec, bind_refs, concat_batches,
                       exec_metrics)


class TpuWindowExec(TpuExec):
    CONTRACT = exec_contract(schema="defined", partitioning="preserve",
                             extras=("window_schema",))
    METRICS = exec_metrics("windowTime")

    def __init__(self, child: TpuExec, window_exprs: List[Tuple[str, W.WindowExpression]]):
        super().__init__(child)
        self.window_exprs = window_exprs
        fields = list(child.schema.fields)
        for name, w in window_exprs:
            fields.append(dt.Field(name, w.dtype, True))
        self._schema = dt.Schema(fields)
        # bind references inside function + spec against child schema
        cs = child.schema
        self._bound = []
        for name, w in window_exprs:
            fn = bind_refs(w.function, cs)
            part = [bind_refs(e, cs) for e in w.spec.partition_by]
            orders = [lp.SortOrder(bind_refs(o.child, cs), o.ascending,
                                   o.nulls_first) for o in w.spec.order_by]
            self._bound.append((name, fn, part, orders, w.spec.frame))

    @property
    def schema(self):
        return self._schema

    def children_coalesce_goal(self, i: int):
        # window partitions must be grouped within one batch
        # (GpuWindowExec RequireSingleBatch)
        return "single"

    def execute(self) -> List[Partition]:
        return [self._map(p) for p in self.children[0].execute()]

    def _map(self, part: Partition) -> Partition:
        batches = list(part)
        if not batches:
            return
        batch = concat_batches(self.children[0].schema, batches)
        cap = batch.capacity
        n = batch.num_rows
        out_cols = list(batch.columns)
        from ..exec.tracing import trace_span
        for (name, fn, part_exprs, orders, frame) in self._bound:
            with trace_span("window", self.metrics, "windowTime"):
                out_cols.append(self._compute_one(batch, fn, part_exprs,
                                                  orders, frame))
        self.metrics.inc("numOutputRows", n)
        yield ColumnarBatch(self._schema, out_cols, n)

    def _compute_one(self, batch: ColumnarBatch, fn, part_exprs, orders,
                     frame) -> Column:
        cap = batch.capacity
        n = batch.num_rows
        pkeys = [ex.materialize(e.eval(batch), batch) for e in part_exprs]
        okeys = [(ex.materialize(o.child.eval(batch), batch), o) for o in orders]
        sort_keys = [K.SortKey(c) for c in pkeys] + \
            [K.SortKey(c, o.ascending, o.nulls_first) for c, o in okeys]
        if sort_keys:
            order = K.sort_indices(sort_keys, n, cap)
        else:
            order = jnp.arange(cap, dtype=jnp.int32)
        live = jnp.arange(cap) < n
        sorted_pkeys = [K.gather_column(c, order) for c in pkeys]
        starts = K.segment_starts_from_sorted_keys(sorted_pkeys, n, cap) \
            if sorted_pkeys else (jnp.arange(cap) == 0) & live
        seg_ids = K.segment_ids(starts)

        result = self._fn_on_sorted(batch, fn, okeys, order, starts, seg_ids,
                                    live, frame, cap)
        # scatter back to input order: inv_perm
        inv = jnp.zeros(cap, dtype=jnp.int32).at[order].set(
            jnp.arange(cap, dtype=jnp.int32))
        return K.gather_column(result, inv, out_valid=live)

    def _fn_on_sorted(self, batch, fn, okeys, order, starts, seg_ids, live,
                      frame, cap) -> Column:
        if isinstance(fn, W.RowNumber):
            data = W.row_number_k(seg_ids, starts, cap)
            return Column(dt.INT32, jnp.where(live, data, 0), live)
        if isinstance(fn, (W.Rank, W.DenseRank)):
            changed = self._order_changed(okeys, order, cap)
            data = W.rank_k(seg_ids, starts, changed, cap,
                            dense=isinstance(fn, W.DenseRank))
            return Column(dt.INT32, jnp.where(live, data, 0), live)
        if isinstance(fn, W.Lead):  # Lead and Lag (subclass)
            col = ex.materialize(fn.children[0].eval(batch), batch)
            scol = K.gather_column(col, order)
            off = fn.offset if not isinstance(fn, W.Lag) else -fn.offset
            return W.shift_in_segment(scol, seg_ids, off, fn.default, cap)
        from ..ops.python_udf import PandasAggUDF
        if isinstance(fn, PandasAggUDF):
            # GpuWindowInPandasExec analog: one fn(Series...) -> scalar
            # call per window PARTITION, broadcast to its rows. Whole-
            # partition frames only (the reference's grouped-agg window
            # scope); bounded frames stay native-only.
            if frame is not None and not frame.is_whole_partition:
                raise NotImplementedError(
                    "pandas window UDFs support whole-partition frames "
                    "only")
            import numpy as np
            import pandas as pd
            seg = np.asarray(seg_ids)
            lv = np.asarray(live)
            cols = [K.gather_column(
                ex.materialize(c.eval(batch), batch), order)
                for c in fn.children]
            n_rows = int(lv.sum())
            series = [pd.Series(c.to_arrow(n_rows).to_pandas())
                      for c in cols]
            out_np = np.zeros(cap, dtype=object)
            for sid in np.unique(seg[lv]):
                rows = np.nonzero(lv & (seg == sid))[0]
                sliced = [s.iloc[rows].reset_index(drop=True)
                          for s in series]
                out_np[rows] = fn.fn(*sliced)
            # NaN results stay NaN (Spark keeps a pandas UDF's NaN as a
            # double NaN, not NULL); only dead rows become NULL
            vals = [out_np[i] if lv[i] else None for i in range(cap)]
            return Column.from_pylist(vals, fn.return_type, capacity=cap)
        if isinstance(fn, lp.AggregateExpression):
            col = None
            if fn.children:
                col = K.gather_column(
                    ex.materialize(fn.children[0].eval(batch), batch), order)
            if frame is None or frame.is_whole_partition or not okeys:
                return W.whole_partition_agg(fn.op, col, seg_ids, live, cap,
                                             fn.ignore_nulls)
            if frame.is_unbounded_to_current:
                if fn.op == "count_star":
                    return W.running_agg("count_star",
                                         Column(dt.BOOL, live, live),
                                         seg_ids, starts, live, cap)
                return W.running_agg(fn.op, col, seg_ids, starts, live, cap)
            # bounded frames: per-row [lo, hi] index bounds, then one
            # windowed aggregation (GpuWindowExpression.scala:734-800)
            if frame.is_range:
                okey_sorted = K.gather_column(okeys[0][0], order)
                lo, hi = W.frame_bounds_range(
                    okey_sorted, seg_ids, starts, live, cap,
                    frame.lower, frame.upper)
            else:
                lo, hi = W.frame_bounds_rows(seg_ids, starts, live, cap,
                                             frame.lower, frame.upper)
            return W.bounded_frame_agg(fn.op, col, lo, hi, live, cap)
        raise NotImplementedError(f"window function {type(fn).__name__}")

    def _order_changed(self, okeys, order, cap) -> jnp.ndarray:
        changed = jnp.zeros(cap, dtype=jnp.bool_)
        for c, _o in okeys:
            sc = K.gather_column(c, order)
            prev_v = jnp.concatenate([sc.validity[:1], sc.validity[:-1]])
            vdiff = sc.validity != prev_v
            if sc.dtype == dt.STRING:
                prev_d = jnp.concatenate([sc.data[:1], sc.data[:-1]])
                ddiff = jnp.any(sc.data != prev_d, axis=1) | \
                    (sc.lengths != jnp.concatenate([sc.lengths[:1],
                                                    sc.lengths[:-1]]))
            else:
                prev_d = jnp.concatenate([sc.data[:1], sc.data[:-1]])
                if sc.dtype.is_floating:
                    both_nan = jnp.isnan(sc.data) & jnp.isnan(prev_d)
                    ddiff = (sc.data != prev_d) & ~both_nan
                else:
                    ddiff = sc.data != prev_d
            changed = changed | vdiff | (ddiff & sc.validity & prev_v)
        idx = jnp.arange(cap)
        return changed & (idx > 0)
