"""Long-lived service layer: process-lifetime telemetry today, the
multi-tenant query server tomorrow (ROADMAP open item 5).

The reference plugin lives inside a long-running Spark executor whose
metrics stream continuously into the driver UI/listener bus
(GpuMetricNames -> SQLMetrics, SURVEY.md §2.7-§2.8). Standalone there is
no executor process wrapping us, so this package holds the
process-lifetime substrate instead: :mod:`.telemetry` (metrics registry,
HBM watermarks, flight recorder, scrape endpoint).
"""

from . import telemetry  # noqa: F401
