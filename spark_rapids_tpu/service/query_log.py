"""Structured query log: one JSONL record per executed query.

Opt-in via conf ``spark.rapids.tpu.sql.telemetry.queryLog.dir``
(docs/observability.md §8): every collect appends one self-contained
record — query id, plan fingerprint, serving-cache verdicts, per-stage
exchange statistics and wall seconds, stage retries, faults fired,
shuffle plane bytes, the HBM peak operator, drift flags, and the top
operators by time — to ``<dir>/query_log-<pid>.jsonl``. Distributed
workers each write their own file; the shared query id joins them
(``python -m tools.query_report`` renders the digest).

The record's field surface is DECLARED in :data:`QUERY_LOG_FIELDS` and
lint-enforced (rule ``querylog-key``, analysis/lint.py) exactly like the
exec METRICS and TELEMETRY_KEYS surfaces, so artifact consumers can grep
one tuple instead of reverse-engineering the writer.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional, Tuple

#: every top-level key a query-log record may carry (lint rule
#: ``querylog-key`` checks :func:`build_record`'s literals against this)
QUERY_LOG_FIELDS: Tuple[str, ...] = (
    "queryId", "tenant", "tS", "wallS", "planTimeS", "rows",
    "fingerprint", "planCache", "resultCache", "params",
    "stageStats", "stageWallS", "stageRetries", "fetchRetries",
    "faultsFired", "shufflePlanes", "hbmPeakBytes", "hbmPeakOperator",
    "drift", "operators", "hostSyncs", "recompiles", "aqe",
    "firstRowS", "compileS", "leakedBuffers", "peakDeviceBytes",
    "lifecycle",
)


def aqe_summary(exec_plan) -> Dict[str, Any]:
    """Adaptive-execution decisions reduced to the artifact shape:
    per-rule applied/declined counts plus the full decision records
    (plan/aqe.py; ``tools/query_report`` renders the per-query
    section)."""
    from ..plan.aqe import collect_decisions
    decisions = collect_decisions(exec_plan)
    rules: Dict[str, Dict[str, int]] = {}
    for d in decisions:
        e = rules.setdefault(d["rule"], {"applied": 0, "declined": 0})
        e["applied" if d["applied"] else "declined"] += 1
    return {"rules": rules, "decisions": decisions}


def stage_summaries(exec_plan) -> list:
    """Per-exchange stage stats with the per-partition lists dropped —
    the per-query artifact shape (this log AND the bench runner's
    ``stageStats`` entry share it; ``session.last_stage_stats()`` keeps
    the full per-partition vectors)."""
    from ..shuffle.exchange import collect_stage_stats
    out = []
    for st in collect_stage_stats(exec_plan):
        out.append({k: st[k] for k in
                    ("operator", "stageId", "plane", "partitions",
                     "totalRows", "totalBytes", "p50Bytes", "maxBytes",
                     "skew") if k in st})
    return out


def drift_summary(exec_plan, conf=None) -> Dict[str, Any]:
    """The drift report reduced to its artifact shape: node/flag counts
    plus the worst flagged misestimate (shared by this log and the
    bench runner's ``drift`` entry)."""
    from ..plan.estimates import drift_report
    drift = drift_report(exec_plan, conf=conf)
    flagged = [d for d in drift if d["flagged"]]
    out: Dict[str, Any] = {"nodes": len(drift), "flagged": len(flagged)}
    if flagged:
        worst = flagged[0]
        out["worst"] = {k: worst[k] for k in
                        ("operator", "estRows", "actualRows", "ratio")}
    return out


def _stage_walls(exec_plan) -> Dict[str, float]:
    """stage id -> write+fetch wall seconds per exchange node."""
    out: Dict[str, float] = {}

    def walk(node) -> None:
        sid = getattr(node, "stage_id", None)
        if sid is not None and getattr(node, "stage_stats", None):
            m = node.metrics
            wall = float(m.get("shuffleWriteTime", 0.0) or 0.0) + \
                float(m.get("fetchWaitTime", 0.0) or 0.0)
            out[str(sid)] = round(out.get(str(sid), 0.0) + wall, 4)
        for c in getattr(node, "children", ()):
            walk(c)

    walk(exec_plan)
    return out


def _metric_total(exec_plan, key: str) -> int:
    total = 0

    def walk(node) -> None:
        nonlocal total
        total += int(node.metrics.get(key, 0) or 0)
        for c in getattr(node, "children", ()):
            walk(c)

    walk(exec_plan)
    return total


def _metric_total_f(exec_plan, key: str) -> float:
    total = 0.0

    def walk(node) -> None:
        nonlocal total
        total += float(node.metrics.get(key, 0.0) or 0.0)
        for c in getattr(node, "children", ()):
            walk(c)

    walk(exec_plan)
    return total


def _top_operators(exec_plan, top: int = 5) -> list:
    rows = []
    for depth, name, m in exec_plan.metrics_tree():
        t = float(m.get("opTime", 0.0) or 0.0)
        if t > 0:
            rows.append({"operator": name.split(" ")[0].split("[")[0],
                         "opTimeS": round(t, 4),
                         "rows": int(m.get("numOutputRows", 0) or 0)})
    rows.sort(key=lambda r: -r["opTimeS"])
    return rows[:top]


def _plane_bytes(exec_plan) -> Dict[str, int]:
    from ..shuffle.exchange import shuffle_report
    out: Dict[str, int] = {}
    for entry in shuffle_report(exec_plan):
        plane = entry.get("plane")
        if plane:
            out[plane] = out.get(plane, 0) + int(entry.get("bytesWritten",
                                                           0) or 0)
    return out


def build_record(session, exec_plan, serving: Dict[str, Any],
                 query_id: Optional[str],
                 faults_before: int = 0,
                 tenant: Optional[str] = None) -> Dict[str, Any]:
    """Assemble one query-log record (every key declared in
    :data:`QUERY_LOG_FIELDS`). Pure read of post-execution state."""
    import hashlib
    import time
    from ..analysis import faults
    from .telemetry import watermarks
    serving = serving or {}
    fp = serving.get("fingerprint")
    sync = getattr(session, "_last_sync_report", {}) or {}
    stage_retries = _metric_total(exec_plan, "stageRetries")
    fetch_retries = _metric_total(exec_plan, "fetchFailedRetries")
    drift_entry = drift_summary(exec_plan, conf=session.conf)
    dev = watermarks().get("device")
    try:
        root_rows = int(exec_plan.metrics.get("numOutputRows", 0) or 0)
    except Exception:
        root_rows = 0
    rec: Dict[str, Any] = {
        "queryId": query_id,
        # the tenant the query ran on behalf of (service multi-tenancy;
        # None for direct caller-owned sessions) — tools/query_report
        # groups its per-tenant rollup on this
        "tenant": tenant,
        "tS": round(time.time(), 3),
        "wallS": round(getattr(session, "_last_execute_time_s", 0.0), 4),
        "planTimeS": round(getattr(session, "_last_plan_time_s", 0.0), 4),
        "rows": root_rows,
        "fingerprint": (hashlib.sha1(repr(fp).encode()).hexdigest()[:12]
                        if fp is not None else None),
        "planCache": serving.get("planCache", "off"),
        "resultCache": serving.get("resultCache", "off"),
        "params": serving.get("params", 0),
        "stageStats": stage_summaries(exec_plan),
        "stageWallS": _stage_walls(exec_plan),
        "stageRetries": stage_retries,
        "fetchRetries": fetch_retries,
        "faultsFired": max(0, faults.fired_total() - int(faults_before)),
        "shufflePlanes": _plane_bytes(exec_plan),
        "hbmPeakBytes": int(dev.peak) if dev is not None else 0,
        "hbmPeakOperator": dev.peak_operator if dev is not None else None,
        "drift": drift_entry,
        "operators": _top_operators(exec_plan),
        "hostSyncs": int(sync.get("hostSyncs", 0) or 0),
        "recompiles": _metric_total(exec_plan, "recompiles"),
        "aqe": aqe_summary(exec_plan),
        # wall seconds until the first batch reached the caller — equals
        # wallS for a materializing collect, strictly smaller when the
        # query streamed via collect_iter (docs/observability.md)
        "firstRowS": round(
            getattr(session, "_last_first_row_s", 0.0) or 0.0, 4),
        # seconds this query spent blocked on synchronous stage builds
        # (async pool builds land on pool threads and are NOT attributed
        # here — the gap between cold wallS and compileS is the async
        # win; tools/query_report renders the breakdown)
        "compileS": round(
            float(_metric_total_f(exec_plan, "compileSeconds")), 4),
    }
    # buffer-lifecycle ledger verdict for this query (analysis/ledger.py
    # end_of_query, stashed by the collect paths; zeros when the ledger
    # is off so the record shape stays stable)
    ledger = getattr(session, "_last_ledger", None) or {}
    rec["leakedBuffers"] = int(ledger.get("leakedBuffers", 0) or 0)
    rec["peakDeviceBytes"] = int(ledger.get("peakDeviceBytes", 0) or 0)
    # lifecycle transition log (exec/lifecycle.py): only non-trivial
    # histories are recorded — a query that just ran to completion
    # carries no "lifecycle" noise, a cancelled/suspended/resumed one
    # shows its full timestamped path (tools/query_report rolls the
    # per-tenant preempted/cancelled counts up from this)
    try:
        from ..exec import lifecycle as _lc
        transitions = _lc.transitions_for(query_id)
        if len(transitions) > 1:
            rec["lifecycle"] = transitions
    except Exception:
        pass
    return rec


def log_dir(session) -> Optional[str]:
    from .. import config as cfg
    try:
        d = str(session.conf.get(cfg.TELEMETRY_QUERY_LOG_DIR)).strip()
        return d or None
    except Exception:
        return None


def maybe_log(session, exec_plan, serving, query_id,
              faults_before: int = 0,
              tenant: Optional[str] = None) -> Optional[str]:
    """Append one record when the query log is enabled; returns the log
    path. Never raises — a broken log directory must not fail queries
    (callers also guard, belt and braces)."""
    d = log_dir(session)
    if not d:
        return None
    try:
        rec = build_record(session, exec_plan, serving, query_id,
                           faults_before=faults_before, tenant=tenant)
        os.makedirs(d, exist_ok=True)
        path = os.path.join(d, f"query_log-{os.getpid()}.jsonl")
        with open(path, "a") as f:
            f.write(json.dumps(rec, default=str) + "\n")
        from .telemetry import MetricsRegistry
        try:
            MetricsRegistry.get().counter(
                "tpu_query_log_records_total",
                "structured query-log records written").inc()
            n_flagged = rec["drift"].get("flagged", 0)
            if n_flagged:
                MetricsRegistry.get().counter(
                    "tpu_query_drift_flags_total",
                    "plan nodes whose estimate-vs-actual drift crossed "
                    "observability.driftThreshold").inc(n_flagged)
        except Exception:
            pass
        return path
    except Exception:
        import logging
        logging.getLogger("spark_rapids_tpu.query_log").exception(
            "query-log write failed (query unaffected)")
        return None
