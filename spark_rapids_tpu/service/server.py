"""Multi-tenant query service: admission control, budgets, scheduling.

The reference runs as an executor-resident plugin whose GpuSemaphore
bounds concurrent device tasks (SURVEY §2.7 ``GpuSemaphore.scala:27-161``)
and whose bootstrap initializes device+memory once per long-lived
executor (§2.1 ``Plugin.scala:108-154``); Spark's scheduler above it
decides WHICH tasks run. Standalone there is no scheduler — this module
is it: a long-lived in-process :class:`QueryService` that admits
concurrent queries from named tenants against ONE shared engine
(session, device, buffer catalog), layered over the existing admission
primitives:

* ``TpuSemaphore`` still bounds threads holding the device (the
  concurrentGpuTasks analog) — the service bounds QUERIES above it;
* per-tenant ``slots`` bound a tenant's concurrent queries, and a
  bounded ``max_queue_depth`` load-sheds excess submissions with a typed
  :class:`AdmissionRejected` instead of queueing unboundedly;
* the queue orders on (priority DESC, deadline, arrival) under the
  default ``service.scheduler.policy=priority`` — a low-priority flood
  cannot starve a high-priority tenant — or by weighted deficit
  round-robin under ``policy=wfq``, where each backlogged tenant's
  normalized service (admitted cost / ``TenantSpec.weight``) is
  levelled so a weight-3 tenant drains three queries for every one a
  weight-1 tenant drains; under either policy a query whose deadline
  lapses in the queue fails fast with a typed
  :class:`DeadlineExceededError` without ever occupying a slot;
* per-tenant device-byte budgets are enforced by the buffer catalog
  (``exec/spill.py``) through the ambient tenant the service installs
  around each execution (``service/tenants.tenant_scope``);
* RUNNING queries are controllable (``exec/lifecycle.py``): each
  admitted execution carries the ticket's :class:`CancelToken`, so
  :meth:`QueryService.cancel` unwinds a query at its next cooperative
  poll point, :meth:`QueryService.suspend` parks it — working set
  spilled via ``BufferCatalog.pin_working_set``, slot freed, stage
  cursor recorded — and :meth:`QueryService.resume` re-admits it
  through the scheduler (spilled buffers re-promote lazily). Under
  ``policy=wfq`` with ``service.scheduler.preemption=true`` a
  high-priority arrival that finds every worker busy preempts the
  most-overserved strictly-lower-priority running query automatically.

Every admit / reject / deadline-shed decision is flight-recorded (kind
``admission``) and counted in the tenant-labeled telemetry series
(``tpu_tenant_queue_depth`` / ``tpu_tenant_admitted_total`` /
``tpu_tenant_rejected_total`` / ``tpu_query_queue_seconds``), so a
saturated tenant is diagnosable from the same scrape surface as any
other engine pressure (docs/service.md).

Scope: one service per process-resident engine, in-process callers
(the traffic-replay bench, ``tools/serve``). Concurrent DISTRIBUTED
queries are out of scope — the lockstep shuffle-id contract serializes
multi-process queries (docs/shuffle.md).
"""

from __future__ import annotations

import itertools
import queue
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from ..analysis.lockdep import named_lock
from . import tenants as tn
from .tenants import TenantSpec, tenant_scope

_INF = float("inf")


class AdmissionRejected(RuntimeError):
    """Load shedding: the tenant's queue is at its bound (or the service
    is closed) — the submission was REFUSED, nothing was queued. Typed
    so callers can distinguish back-pressure from query failure and
    retry with their own policy."""

    def __init__(self, tenant: str, reason: str):
        super().__init__(f"tenant {tenant!r}: {reason}")
        self.tenant = tenant
        self.reason = reason


class DeadlineExceededError(RuntimeError):
    """The query's deadline lapsed before (or while) it could run; it
    never occupied an execution slot past the deadline."""

    def __init__(self, tenant: str, label: str, late_s: float):
        super().__init__(
            f"tenant {tenant!r} query {label!r} missed its deadline "
            f"by {late_s:.3f}s")
        self.tenant = tenant
        self.late_s = late_s


class ServiceClosed(RuntimeError):
    """The service shut down before this query could run."""


#: end-of-stream sentinel on a streaming ticket's batch queue
_STREAM_END = object()


class _StreamFailure:
    """A producer-side failure riding the stream queue so the consumer
    re-raises it in-order (after every batch that preceded it)."""

    __slots__ = ("exc",)

    def __init__(self, exc: BaseException):
        self.exc = exc


class QueryTicket:
    """One submitted query's handle: wait on :meth:`result`. Carries the
    admission timeline (submitted/started/finished) the replay bench's
    latency percentiles are computed from."""

    _seq = itertools.count(1)

    def __init__(self, tenant: str, label: str, priority: int,
                 deadline_at: Optional[float], thunk: Callable[[], Any]):
        self.tenant = tenant
        self.label = label
        self.priority = priority
        self.deadline_at = deadline_at      # perf_counter timestamp
        self.seq = next(QueryTicket._seq)
        self.thunk = thunk
        # admission cost in queue-depth units (plan/aqe.py observed-cost
        # weighting; 1 = unweighted)
        self.cost = 1
        self.submitted_at = time.perf_counter()
        self.started_at: Optional[float] = None
        self.finished_at: Optional[float] = None
        self.query_id: Optional[str] = None
        # exec.lifecycle.CancelToken, minted at first admission and kept
        # across suspend/resume so the ticket and every (re-)execution
        # share one lifecycle flag pair + transition log
        self.token = None
        self._done = threading.Event()
        self._result: Any = None
        self._exc: Optional[BaseException] = None
        # streaming submissions only (QueryService.submit_stream)
        self._stream_q: Optional[queue.Queue] = None
        self._stream_closed: Optional[threading.Event] = None

    @property
    def sort_key(self):
        """(priority DESC, deadline, arrival): the queue order."""
        return (-self.priority,
                self.deadline_at if self.deadline_at is not None else _INF,
                self.seq)

    def queue_wait_s(self) -> float:
        if self.started_at is None:
            return 0.0
        return self.started_at - self.submitted_at

    def latency_s(self) -> float:
        """Submit -> finished wall seconds (inf while unfinished)."""
        if self.finished_at is None:
            return _INF
        return self.finished_at - self.submitted_at

    def done(self) -> bool:
        return self._done.is_set()

    def result(self, timeout: Optional[float] = None):
        """Block for the query's result; re-raises its typed failure."""
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"query {self.label!r} (tenant {self.tenant!r}) still "
                f"pending after {timeout}s")
        if self._exc is not None:
            raise self._exc
        return self._result

    def stream(self):
        """Iterate the query's batches as partitions drain (tickets from
        :meth:`QueryService.submit_stream` only). Yields in partition
        order; a producer-side failure re-raises here after every batch
        that preceded it. Closing the iterator early tells the producer
        to stop — the underlying ``collect_iter`` generator's cleanup
        runs, so staging arenas and prefetch threads release."""
        if self._stream_q is None:
            raise TypeError(
                f"query {self.label!r} was not submitted via "
                f"submit_stream; use result()")
        q = self._stream_q
        try:
            while True:
                try:
                    item = q.get(timeout=0.1)
                except queue.Empty:
                    if self._done.is_set() and q.empty():
                        # shed / service-closed before the thunk ran (or
                        # the producer died sentinel-less): surface the
                        # ticket's typed failure instead of hanging
                        if self._exc is not None:
                            raise self._exc
                        return
                    continue
                if item is _STREAM_END:
                    return
                if isinstance(item, _StreamFailure):
                    raise item.exc
                yield item
        finally:
            if self._stream_closed is not None:
                self._stream_closed.set()
            # unblock a producer parked on a full queue
            try:
                while True:
                    q.get_nowait()
            except queue.Empty:
                pass

    def _finish(self, result=None, exc: Optional[BaseException] = None
                ) -> None:
        self.finished_at = time.perf_counter()
        self._result = result
        self._exc = exc
        self._done.set()


class _TenantState:
    """One registered tenant's live admission state (guarded by the
    service condition's lock)."""

    def __init__(self, spec: TenantSpec, slots: int, depth: int,
                 budget: int, weight: float):
        self.spec = spec
        self.name = spec.name
        self.priority = int(spec.priority)
        self.slots = max(1, int(slots))
        self.max_queue_depth = max(1, int(depth))
        self.memory_budget_bytes = max(0, int(budget))
        self.weight = max(1e-6, float(weight))
        # wfq: normalized service admitted so far (sum of cost/weight);
        # the deficit scheduler admits the backlogged tenant with the
        # LOWEST value and charges the winner here
        self.service_units = 0.0
        self.queued = 0
        self.running = 0
        self.admitted = 0
        self.rejected = 0
        self.completed = 0
        self.failed = 0
        self.deadline_expired = 0
        self.preempted = 0
        self.resumed = 0
        self.cancelled = 0
        self.queue_wait_s_total = 0.0
        self.queue_wait_s_max = 0.0


class QueryService:
    """The long-lived in-process query front door (see module doc).

    ::

        svc = QueryService(session, tenants=[
            TenantSpec("gold", priority=10, slots=2,
                       memory_budget_bytes=1 << 30),
            TenantSpec("bronze", priority=0, slots=1,
                       memory_budget_bytes=64 << 20)])
        t = svc.submit("gold", "SELECT sum(v) FROM t", deadline_s=5.0)
        batch = t.result(timeout=30)
        svc.close()

    ``submit`` accepts SQL text (parsed through the session's SQL-text
    parse cache), a DataFrame, a (PreparedStatement, params) pair, or a
    zero-argument callable returning the result."""

    def __init__(self, session, tenants=(),
                 max_workers: Optional[int] = None):
        from .. import config as cfg
        self.session = session
        conf = session.conf
        self._default_slots = int(conf.get(cfg.SERVICE_DEFAULT_SLOTS))
        self._default_depth = int(
            conf.get(cfg.SERVICE_DEFAULT_QUEUE_DEPTH))
        self._default_budget = int(
            conf.get(cfg.SERVICE_DEFAULT_MEMORY_BYTES))
        self._policy = str(conf.get(cfg.SERVICE_SCHEDULER_POLICY))
        self._preempt = bool(conf.get(cfg.SERVICE_SCHEDULER_PREEMPTION))
        self._default_weight = float(
            conf.get(cfg.SERVICE_DEFAULT_TENANT_WEIGHT))
        if max_workers is None:
            max_workers = int(conf.get(cfg.SERVICE_MAX_CONCURRENT))
        self.max_workers = max(1, int(max_workers))
        # ONE leaf lock guards the queue + tenant states; the workers
        # wait/notify on the condition built over it. No engine lock is
        # ever taken under it (execution happens outside), so it cannot
        # participate in an inversion with the catalog/device locks.
        self._mu = named_lock("service.server.QueryService._mu")
        self._cond = threading.Condition(self._mu)  # lint: raw-lock-ok condition OVER the named service lock; wait/notify not expressible through NamedLock alone
        self._queue: List[QueryTicket] = []
        self._tenants: Dict[str, _TenantState] = {}
        # admitted tickets currently executing (preemption victim scan)
        self._running: List[QueryTicket] = []
        # query_id -> parked ticket awaiting resume() (or cancel/close)
        self._suspended: Dict[str, QueryTicket] = {}
        # label -> serving fingerprint key learned from completed
        # executions: the bridge from a submission (which only has the
        # label) to AQE's observed-cost table (which keys on the plan
        # fingerprint). GIL-atomic dict ops; advisory only.
        self._label_fp: Dict[str, str] = {}
        self._closed = False
        for spec in tenants:
            self.register_tenant(spec)
        self._workers = [
            threading.Thread(target=self._worker_loop, daemon=True,
                             name=f"tpu-service-{i}")
            for i in range(self.max_workers)]
        for w in self._workers:
            w.start()

    # -- tenant registry -----------------------------------------------------
    def register_tenant(self, spec) -> _TenantState:
        """Register a tenant from a :class:`TenantSpec` (a bare name
        registers with the ``service.*`` conf defaults), or UPDATE a
        live one's bounds IN PLACE: re-registering must never reset the
        running/queued accounting of in-flight work (a fresh zeroed
        state would let the scheduler overshoot the slot bound).
        Installs the tenant's device budget into the process budget
        table the buffer catalog enforces."""
        if isinstance(spec, str):
            spec = TenantSpec(spec)
        slots = spec.slots if spec.slots is not None else \
            self._default_slots
        depth = spec.max_queue_depth if spec.max_queue_depth is not None \
            else self._default_depth
        budget = spec.memory_budget_bytes \
            if spec.memory_budget_bytes is not None else \
            self._default_budget
        weight = spec.weight if spec.weight is not None else \
            self._default_weight
        with self._cond:
            state = self._tenants.get(spec.name)
            if state is None:
                state = _TenantState(spec, slots, depth, budget, weight)
                self._tenants[spec.name] = state
            else:
                state.spec = spec
                state.priority = int(spec.priority)
                state.slots = max(1, int(slots))
                state.max_queue_depth = max(1, int(depth))
                state.memory_budget_bytes = max(0, int(budget))
                # service_units is deliberately NOT reset: re-registering
                # must not hand a tenant a fresh fairness slate
                state.weight = max(1e-6, float(weight))
            self._cond.notify_all()    # a raised slot bound unblocks
        tn.set_budget(spec.name, state.memory_budget_bytes)
        return state

    def _state(self, tenant: str) -> _TenantState:
        st = self._tenants.get(tenant)
        if st is None:
            st = self.register_tenant(tenant)
        return st

    # -- submission ----------------------------------------------------------
    def _thunk_for(self, query, params: Optional[dict]):
        from ..api.dataframe import DataFrame
        if callable(query) and not isinstance(query, DataFrame):
            return query
        if isinstance(query, str):
            text = query
            return lambda: self.session.sql(text).collect_batch()
        if isinstance(query, DataFrame):
            return query.collect_batch
        # PreparedStatement (duck-typed: anything with .execute(**kw));
        # NOTE a statement binds in place — at most one in-flight
        # execute per statement object (one per stream, docs/service.md)
        if hasattr(query, "execute"):
            kw = dict(params or {})
            return lambda: query.execute(**kw)
        raise TypeError(f"unsupported query form: {type(query).__name__}")

    def submit(self, tenant: str, query, *, params: Optional[dict] = None,
               priority: Optional[int] = None,
               deadline_s: Optional[float] = None,
               label: str = "") -> QueryTicket:
        """Queue one query for ``tenant``. Raises
        :class:`AdmissionRejected` (load shed) when the tenant's queue
        is at its bound, :class:`DeadlineExceededError` when
        ``deadline_s`` is already non-positive. ``priority`` overrides
        the tenant's default for this query only."""
        from .telemetry import flight_record
        state = self._state(tenant)
        label = label or (query if isinstance(query, str) else
                          type(query).__name__)[:80]
        if deadline_s is not None and deadline_s <= 0:
            state.deadline_expired += 1
            self._count("tpu_tenant_rejected_total", tenant)
            flight_record("admission", "deadline-expired",
                          {"tenant": tenant, "label": label})
            raise DeadlineExceededError(tenant, label, -float(deadline_s))
        ticket = QueryTicket(
            tenant, label,
            priority if priority is not None else state.priority,
            time.perf_counter() + deadline_s if deadline_s is not None
            else None,
            self._thunk_for(query, params))
        ticket.cost = self._admission_cost(label)
        with self._cond:
            if self._closed:
                raise AdmissionRejected(tenant, "service is closed")
            if state.queued + ticket.cost > state.max_queue_depth:
                state.rejected += 1
                self._count("tpu_tenant_rejected_total", tenant)
                flight_record("admission", "queue-full",
                              {"tenant": tenant, "label": label,
                               "depth": state.queued,
                               "cost": ticket.cost})
                raise AdmissionRejected(
                    tenant, f"queue depth {state.queued} + cost "
                            f"{ticket.cost} past bound "
                            f"{state.max_queue_depth} (load shed)")
            self._queue.append(ticket)
            state.queued += ticket.cost
            self._gauge("tpu_tenant_queue_depth", tenant, state.queued)
            self._cond.notify()
            victim = self._preempt_victim_locked(ticket)
        if victim is not None and victim.token is not None and \
                victim.token.request_suspend(
                    f"preempt: higher-priority arrival {label!r} "
                    f"(tenant {tenant!r})"):
            flight_record("admission", "preempt",
                          {"tenant": victim.tenant, "label": victim.label,
                           "byTenant": tenant, "byLabel": label})
        if ticket.cost > 1:
            # observed-expensive fingerprint: the extra units charged
            # against the tenant's queue bound, beyond the flat 1
            self._count("tpu_admission_cost_debits_total", tenant,
                        ticket.cost - 1)
            flight_record("admission", "cost-weighted",
                          {"tenant": tenant, "label": label,
                           "cost": ticket.cost})
        return ticket

    def submit_stream(self, tenant: str, query, *,
                      priority: Optional[int] = None,
                      deadline_s: Optional[float] = None,
                      label: str = "",
                      buffer_batches: int = 4) -> QueryTicket:
        """Queue one query whose result STREAMS: iterate the returned
        ticket's :meth:`QueryTicket.stream` to receive batches as
        partitions drain (``DataFrame.collect_iter`` under the hood), so
        first rows arrive before the query finishes. Admission, priority
        and deadline shedding are exactly :meth:`submit`'s; the producer
        runs under the ticket's deadline scope, so the async compile
        pool sees the deadline when routing cold stage builds
        (docs/service.md, docs/compile.md §5). ``buffer_batches`` bounds
        the producer->consumer queue — a slow consumer back-pressures
        the drain instead of buffering the whole result.
        ``ticket.result()`` returns the total row count after the
        stream completes."""
        from ..api.dataframe import DataFrame
        if isinstance(query, str):
            text = query
            label = label or text[:80]

            def df_for():
                return self.session.sql(text)
        elif isinstance(query, DataFrame):
            label = label or type(query).__name__

            def df_for():
                return query
        else:
            raise TypeError(
                f"submit_stream takes SQL text or a DataFrame, got "
                f"{type(query).__name__}")
        q: queue.Queue = queue.Queue(maxsize=max(1, int(buffer_batches)))
        closed = threading.Event()

        def deliver(item) -> bool:
            # bounded put that aborts when the consumer closed the
            # stream (drains on close, so this converges quickly)
            while not closed.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def run():
            rows = 0
            it = df_for().collect_iter()
            try:
                for batch in it:
                    rows += int(getattr(batch, "num_rows", 0) or 0)
                    if not deliver(batch):
                        break      # consumer closed early
            except BaseException as e:
                deliver(_StreamFailure(e))
                raise              # the ticket's result() fails too
            finally:
                it.close()         # collect_iter cleanup: arenas release
            deliver(_STREAM_END)
            return rows

        ticket = self.submit(tenant, run, priority=priority,
                             deadline_s=deadline_s, label=label)
        ticket._stream_q = q
        ticket._stream_closed = closed
        return ticket

    def _admission_cost(self, label: str) -> int:
        """Queue-depth units this submission charges: 1, or more when
        its label's last execution was OBSERVED expensive
        (``service.admission.expensiveBytes``; plan/aqe.py keeps the
        fingerprint-keyed cost table, ROADMAP item 1's closing
        clause)."""
        from .. import config as cfg
        try:
            expensive = int(self.session.conf.get(
                cfg.SERVICE_ADMISSION_EXPENSIVE_BYTES))
            if expensive <= 0:
                return 1
            from ..plan import aqe
            return aqe.admission_cost_units(self._label_fp.get(label),
                                            expensive)
        except Exception:
            return 1           # cost weighting must never block submit

    # -- scheduling ----------------------------------------------------------
    def _pop_eligible_locked(self) -> Optional[QueryTicket]:
        """The best queued ticket whose tenant has a free slot; None
        when every queued tenant is saturated. Deadline-lapsed tickets
        fail fast HERE — they are removed and finished without consuming
        a slot. Under the default ``priority`` policy "best" is
        (priority DESC, deadline, arrival); under ``wfq`` it is deficit
        round-robin (:meth:`_pop_wfq_locked`). Caller holds the
        condition's lock."""
        from .telemetry import flight_record
        now = time.perf_counter()
        expired = [t for t in self._queue
                   if t.deadline_at is not None and now >= t.deadline_at]
        for t in expired:
            self._queue.remove(t)
            state = self._tenants[t.tenant]
            state.queued -= t.cost
            state.deadline_expired += 1
            self._gauge("tpu_tenant_queue_depth", t.tenant, state.queued)
            flight_record("admission", "deadline-shed",
                          {"tenant": t.tenant, "label": t.label,
                           "lateS": round(now - t.deadline_at, 4)})
            t._finish(exc=DeadlineExceededError(
                t.tenant, t.label, now - t.deadline_at))
        if self._policy == "wfq":
            return self._pop_wfq_locked()
        best = None
        for t in self._queue:
            if self._tenants[t.tenant].running >= \
                    self._tenants[t.tenant].slots:
                continue
            if best is None or t.sort_key < best.sort_key:
                best = t
        if best is not None:
            self._queue.remove(best)
        return best

    def _pop_wfq_locked(self) -> Optional[QueryTicket]:
        """Weighted deficit round-robin: admit from the eligible tenant
        whose normalized service (sum of admitted cost / weight) is
        LOWEST, so backlogged tenants drain in proportion to their
        weights instead of strictly by priority; within a tenant the
        (priority DESC, deadline, arrival) order still picks the ticket.
        A tenant idle long enough to fall below the busy floor re-enters
        AT the floor — idleness banks no burst credit. Charges the
        winner's service counter; caller holds the condition's lock."""
        active = [st for st in self._tenants.values()
                  if st.queued > 0 or st.running > 0]
        floor = min((st.service_units for st in active), default=0.0)
        best = None
        best_key = None
        for t in self._queue:
            st = self._tenants[t.tenant]
            if st.running >= st.slots:
                continue
            key = (max(st.service_units, floor),) + t.sort_key
            if best is None or key < best_key:
                best, best_key = t, key
        if best is not None:
            self._queue.remove(best)
            st = self._tenants[best.tenant]
            st.service_units = max(st.service_units, floor) + \
                best.cost / st.weight
        return best

    def _preempt_victim_locked(self, ticket: QueryTicket) \
            -> Optional[QueryTicket]:
        """Preemption candidate for a fresh arrival, or None: under
        ``wfq`` with ``service.scheduler.preemption`` on, an arrival
        that finds EVERY worker busy may suspend a strictly-lower-
        priority running query — the one whose tenant sits furthest
        above the busy floor (largest deficit, i.e. most overserved);
        ties prefer the lower-priority, later-admitted query. Caller
        holds the condition's lock; the suspend request itself is sent
        OUTSIDE it (the token lock and telemetry must not nest under
        the service lock on the submit path)."""
        if self._policy != "wfq" or not self._preempt:
            return None
        if sum(st.running for st in self._tenants.values()) < \
                self.max_workers:
            return None            # a free worker will pick it up
        active = [st for st in self._tenants.values()
                  if st.queued > 0 or st.running > 0]
        floor = min((st.service_units for st in active), default=0.0)
        victim = None
        victim_key = None
        for rt in self._running:
            if rt.priority >= ticket.priority or rt.token is None:
                continue
            if rt.token.cancelled or rt.token.suspend_requested:
                continue           # already unwinding
            st = self._tenants[rt.tenant]
            key = (st.service_units - floor, -rt.priority, rt.seq)
            if victim is None or key > victim_key:
                victim, victim_key = rt, key
        return victim

    def _worker_loop(self) -> None:
        from .telemetry import MetricsRegistry, flight_record
        from ..exec import lifecycle as lc
        from ..exec import query_context as qc
        while True:
            with self._cond:
                ticket = None
                while not self._closed:
                    ticket = self._pop_eligible_locked()
                    if ticket is not None:
                        break
                    self._cond.wait(0.2)
                if ticket is None:          # closed and drained
                    return
                state = self._tenants[ticket.tenant]
                state.queued -= ticket.cost
                state.running += 1
                state.admitted += 1
                if ticket.token is None:
                    # minted at FIRST admission (not at submit, so a
                    # shed ticket never allocates one); a resumed ticket
                    # keeps its original token and transition log
                    ticket.token = lc.CancelToken()
                self._running.append(ticket)
                ticket.started_at = time.perf_counter()
                wait = ticket.queue_wait_s()
                state.queue_wait_s_total += wait
                state.queue_wait_s_max = max(state.queue_wait_s_max, wait)
                self._gauge("tpu_tenant_queue_depth", ticket.tenant,
                            state.queued)
            self._count("tpu_tenant_admitted_total", ticket.tenant)
            try:
                MetricsRegistry.get().histogram(
                    "tpu_query_queue_seconds",
                    "service admission-queue wait seconds",
                    tenant=ticket.tenant).observe(wait)
            except Exception:
                pass               # telemetry must never fail the query
            flight_record("admission", "admit",
                          {"tenant": ticket.tenant, "label": ticket.label,
                           "queueWaitS": round(wait, 4)})
            ok = suspended = cancelled = False
            try:
                # cleared before, read after: the id THIS thread's thunk
                # executed (a result-cache hit executes nothing -> None);
                # session._last_query_id is last-writer-wins and must
                # not be joined to a ticket
                qc.note_thread_query_id(None)
                # the deadline AND the lifecycle token ride the worker's
                # TLS into the minted QueryContext: the async compile
                # pool can route cold stage builds off the query thread
                # (exec/compile_pool.py), and cancel/suspend by query id
                # reach the execution through the ticket's token
                # (exec/lifecycle.py)
                with tenant_scope(ticket.tenant), \
                        qc.deadline_scope(ticket.deadline_at), \
                        qc.cancel_token_scope(ticket.token):
                    out = ticket.thunk()
                ticket.query_id = qc.thread_last_query_id()
                try:
                    # learn this label's plan fingerprint so the NEXT
                    # submit can charge its observed cost (plan/aqe.py)
                    from ..plan import aqe, plan_cache as pc
                    fpk = aqe.fingerprint_key(pc.thread_serving())
                    if fpk is not None:
                        with self._cond:
                            self._label_fp[ticket.label] = fpk
                except Exception:
                    pass
                ticket._finish(result=out)
                ok = True
            except lc.QuerySuspendedError:
                # NOT a failure: park the ticket without finishing it —
                # result() keeps blocking until the resumed re-execution
                # completes (or cancel/close fails it)
                suspended = True
                ticket.query_id = qc.thread_last_query_id() or \
                    ticket.query_id
                self._park_suspended(ticket)
            except BaseException as e:      # typed failure rides the ticket
                ticket.query_id = qc.thread_last_query_id() or \
                    ticket.query_id
                cancelled = isinstance(e, lc.QueryCancelledError)
                ticket._finish(exc=e)
            finally:
                with self._cond:
                    try:
                        self._running.remove(ticket)
                    except ValueError:
                        pass
                    state.running -= 1
                    if suspended:
                        pass       # neither completed nor failed yet
                    elif ok:
                        state.completed += 1
                    else:
                        state.failed += 1
                        if cancelled:
                            state.cancelled += 1
                    self._cond.notify_all()

    def _park_suspended(self, ticket: QueryTicket) -> None:
        """Suspend bookkeeping, OUTSIDE the service lock: spill the
        tenant's device working set (resume re-promotes lazily through
        the catalog's normal acquire path), mark the token suspended
        (the poll site that unwound already parked its stage cursor),
        and index the ticket by query id for :meth:`resume`."""
        from .telemetry import flight_record
        moved_n = moved_bytes = 0
        try:
            from ..exec.spill import BufferCatalog
            cat = BufferCatalog.peek()
            if cat is not None:
                moved_n, moved_bytes = cat.pin_working_set(ticket.tenant)
        except Exception:
            pass    # spill-to-park is best-effort; budgets still enforce
        tok = ticket.token
        if tok is not None:
            tok.mark_suspended()
        key = ticket.query_id or f"seq-{ticket.seq}"
        with self._cond:
            self._suspended[key] = ticket
            st = self._tenants.get(ticket.tenant)
            if st is not None:
                st.preempted += 1
        flight_record("lifecycle", "service-suspend",
                      {"tenant": ticket.tenant, "label": ticket.label,
                       "queryId": ticket.query_id,
                       "spilledBuffers": moved_n,
                       "spilledBytes": moved_bytes,
                       "cursor": tok.cursor if tok is not None else None})

    # -- query lifecycle ops -------------------------------------------------
    def cancel(self, query_id: str, reason: str = "cancel") -> bool:
        """Cancel a query this service is RUNNING or has SUSPENDED.
        Running: the cooperative flag is set and the query unwinds with
        a typed ``QueryCancelledError`` at its next poll point (never a
        thread kill). Suspended: the parked ticket fails immediately —
        nothing is executing. False when the id is unknown (finished,
        shed, or never this service's)."""
        from ..exec import lifecycle as lc
        with self._cond:
            ticket = self._suspended.pop(query_id, None)
        if ticket is not None:
            if ticket.token is not None:
                ticket.token.cancel(reason)
            ticket._finish(exc=lc.QueryCancelledError(query_id, reason))
            with self._cond:
                st = self._tenants.get(ticket.tenant)
                if st is not None:
                    st.failed += 1
                    st.cancelled += 1
                self._cond.notify_all()
            return True
        return lc.cancel_query(query_id, reason)

    def suspend(self, query_id: str, reason: str = "operator") -> bool:
        """Ask a RUNNING query to park at its next poll point; the
        worker loop then spills its working set, frees the slot and
        holds the ticket for :meth:`resume`. False when no such query
        is live."""
        from ..exec import lifecycle as lc
        return lc.request_suspend(query_id, reason)

    def resume(self, query_id: str) -> QueryTicket:
        """Re-admit a suspended query: clears its suspend flag and
        re-queues the ticket through the normal scheduler (same
        priority/deadline/token; spilled buffers re-promote lazily as
        the re-execution touches them). Raises ``KeyError`` for ids not
        parked here."""
        from .telemetry import flight_record
        with self._cond:
            if self._closed:
                raise ServiceClosed("service is closed")
            ticket = self._suspended.pop(query_id, None)
        if ticket is None:
            raise KeyError(f"no suspended query {query_id!r}")
        if ticket.token is not None:
            ticket.token.resume()
        state = self._state(ticket.tenant)
        with self._cond:
            self._queue.append(ticket)
            state.queued += ticket.cost
            state.resumed += 1
            self._gauge("tpu_tenant_queue_depth", ticket.tenant,
                        state.queued)
            self._cond.notify()
        flight_record("lifecycle", "service-resume",
                      {"tenant": ticket.tenant, "label": ticket.label,
                       "queryId": query_id})
        return ticket

    def suspended_queries(self) -> List[str]:
        """Query ids currently parked awaiting :meth:`resume`."""
        with self._cond:
            return sorted(self._suspended)

    # -- observability -------------------------------------------------------
    @staticmethod
    def _count(name: str, tenant: str, n: int = 1) -> None:
        from .telemetry import MetricsRegistry
        try:
            MetricsRegistry.get().counter(
                name, "service per-tenant admission counter",
                tenant=tenant).inc(n)
        except Exception:
            pass

    @staticmethod
    def _gauge(name: str, tenant: str, value: float) -> None:
        from .telemetry import MetricsRegistry
        try:
            MetricsRegistry.get().gauge(
                name, "service per-tenant admission gauge",
                tenant=tenant).set(value)
        except Exception:
            pass

    def stats(self) -> Dict[str, Any]:
        """Per-tenant service counters plus the catalog's per-tenant
        device residency and the device semaphore's live admission state
        — the dashboard dict (docs/service.md §6)."""
        from ..exec.device import TpuSemaphore
        from ..exec.spill import BufferCatalog
        cat = BufferCatalog.peek()
        dev = cat.tenant_device_bytes() if cat is not None else {}
        out: Dict[str, Any] = {"tenants": {}, "queued": 0, "running": 0,
                               "suspended": 0, "policy": self._policy}
        sem = TpuSemaphore.peek()
        if sem is not None:
            # the layer BELOW the service (docs/service.md §1): how many
            # admitted queries' tasks are blocked on the device right now
            out["device"] = dict(sem.stats(),
                                 permits=sem.max_concurrent)
        with self._cond:
            for name, st in sorted(self._tenants.items()):
                done = st.completed + st.failed
                out["tenants"][name] = {
                    "priority": st.priority,
                    "slots": st.slots,
                    "maxQueueDepth": st.max_queue_depth,
                    "memoryBudgetBytes": st.memory_budget_bytes,
                    "queued": st.queued,
                    "running": st.running,
                    "admitted": st.admitted,
                    "rejected": st.rejected,
                    "completed": st.completed,
                    "failed": st.failed,
                    "deadlineExpired": st.deadline_expired,
                    "weight": st.weight,
                    "serviceUnits": round(st.service_units, 4),
                    "preempted": st.preempted,
                    "resumed": st.resumed,
                    "cancelled": st.cancelled,
                    "queueWaitAvgS": round(
                        st.queue_wait_s_total / done, 4) if done else 0.0,
                    "queueWaitMaxS": round(st.queue_wait_s_max, 4),
                    "deviceBytes": dev.get(name, 0),
                }
                out["queued"] += st.queued
                out["running"] += st.running
            out["suspended"] = len(self._suspended)
        return out

    # -- lifecycle -----------------------------------------------------------
    def close(self, timeout_s: float = 10.0) -> None:
        """Stop admitting, fail queued work with :class:`ServiceClosed`,
        join workers with a bounded timeout (running queries finish)."""
        from ..exec.tasks import record_join_timeout
        with self._cond:
            if self._closed:
                return
            self._closed = True
            pending, self._queue = self._queue, []
            parked = list(self._suspended.values())
            self._suspended.clear()
            for t in pending:
                st = self._tenants.get(t.tenant)
                if st is not None:
                    st.queued -= t.cost
                    self._gauge("tpu_tenant_queue_depth", t.tenant,
                                st.queued)
                t._finish(exc=ServiceClosed(
                    f"service closed before {t.label!r} ran"))
            for t in parked:
                # a suspended ticket consumes no queue-depth units; it
                # just fails typed instead of blocking result() forever
                t._finish(exc=ServiceClosed(
                    f"service closed while {t.label!r} was suspended"))
            self._cond.notify_all()
        deadline = time.monotonic() + max(0.0, timeout_s)
        for w in self._workers:
            w.join(timeout=max(0.1, deadline - time.monotonic()))
        alive = [w.name for w in self._workers if w.is_alive()]
        if alive:
            record_join_timeout("tpu-service", alive,
                                logger="spark_rapids_tpu.service")

    def __enter__(self) -> "QueryService":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False
