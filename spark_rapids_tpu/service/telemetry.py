"""Process-lifetime telemetry: metrics registry, HBM watermarks, flight
recorder, scrape endpoint.

PR 6 gave every query a snapshot (per-operator metrics, EXPLAIN ANALYZE,
timelines); the reference plugin's observability is *continuous* —
GpuMetricNames metrics stream into the live Spark UI/listener bus for
the lifetime of the executor (SURVEY.md §2.7-§2.8), and shuffle/memory
state is inspectable while queries run. This module is that substrate,
four pillars:

* :class:`MetricsRegistry` — named counters/gauges/histograms with
  labels. Cross-cutting instruments publish in at **resolve/flush
  boundaries, never per row**: per-exec ``TpuMetrics`` bags fold their
  deltas in on ``resolve``, span durations land at span end, and
  everything pull-shaped (semaphore wait/hold, lockdep per-lock stats,
  sync/recompile totals, spill residency, shuffle transport totals,
  watermarks) is harvested by a collector only when someone actually
  reads the registry (``collect``/scrape). Exported as Prometheus text
  (:meth:`MetricsRegistry.prometheus_text`), JSONL snapshots
  (``session.metrics_snapshot()``), and an opt-in background HTTP
  scrape endpoint (conf ``spark.rapids.tpu.sql.telemetry.port``, off by
  default).
* :func:`watermark` accounting — DeviceManager budget, the buffer
  catalog's device/host residency and the native bounce arena track
  current + peak bytes; a new peak records the innermost open exec
  (``exec/metrics.exec_scope``) and charges ``peakDeviceBytes`` onto its
  bag, so "which operator drove peak HBM" is answerable per query
  (EXPLAIN ANALYZE) and per process (the registry gauge).
* :class:`FlightRecorder` — an always-on, fixed-size, lock-light ring of
  recent span begin/ends, sync/recompile/spill/lock incidents, and conf
  changes, dumped to a JSON artifact automatically when a task body or
  ``collect()`` raises (and on demand via
  ``session.dump_flight_record()``) — post-mortems on a dead multichip
  run no longer depend on having enabled tracing in advance.
* the scrape endpoint — :class:`TelemetryServer`, a daemon-thread HTTP
  server answering ``/metrics`` (Prometheus text) and ``/snapshot``
  (JSON), started by session bootstrap when the port conf is set.

Every registry metric name is a literal declared in
:data:`TELEMETRY_KEYS`; the project linter (rule ``telemetry-key``)
enforces the declaration, keeping the scrape surface greppable exactly
like the per-exec ``METRICS`` surface.
"""

from __future__ import annotations

import itertools
import json
import logging
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..analysis.lockdep import named_lock

log = logging.getLogger("spark_rapids_tpu.telemetry")

# ---------------------------------------------------------------------------
# Declared metric names (the lint-enforced scrape surface)
# ---------------------------------------------------------------------------

#: Every metric name the registry may carry. The ``telemetry-key`` lint
#: rule checks each ``registry.counter/gauge/histogram("...")`` literal
#: in the package against this tuple — an undeclared name fails tier-1,
#: so the scrape surface cannot drift silently.
TELEMETRY_KEYS: Tuple[str, ...] = (
    # pushed at resolve/flush boundaries
    "tpu_exec_metric_total",            # label key=<TpuMetrics key>
    "tpu_span_seconds",                 # histogram, label name=<span>
    "tpu_query_execute_seconds",        # histogram, per collect
    "tpu_compile_seconds",              # histogram, label kind=cold|disk
    "tpu_jit_map_relief_total",         # program-cache drops (map limit)
    "tpu_preflight_probe_seconds",
    "tpu_preflight_backend_info",       # label backend=..., value 1
    "tpu_flight_dumps_total",
    # harvested at collect/scrape time
    "tpu_semaphore_wait_seconds_total",
    "tpu_semaphore_hold_seconds_total",
    "tpu_semaphore_acquires_total",
    "tpu_semaphore_permits",
    "tpu_lock_wait_seconds_total",      # label lock=<lockdep name>
    "tpu_lock_hold_seconds_total",
    "tpu_lock_acquires_total",
    "tpu_lockdep_cycles_total",
    "tpu_host_syncs_total",
    "tpu_recompiles_total",
    "tpu_fused_calls_total",
    "tpu_spill_device_bytes",
    "tpu_spill_host_bytes",
    "tpu_spilled_device_bytes_total",
    "tpu_spilled_host_bytes_total",
    "tpu_spill_buffers",
    "tpu_shuffle_bytes_fetched_total",
    "tpu_shuffle_chunks_total",
    "tpu_shuffle_retries_total",
    "tpu_shuffle_bounce_misses_total",
    "tpu_shuffle_bytes_sent_total",
    "tpu_shuffle_chunks_sent_total",
    "tpu_shuffle_exchanges_total",      # label plane=ici|dcn
    "tpu_shuffle_plane_bytes_total",    # label plane=ici|dcn
    "tpu_shuffle_plane_seconds_total",  # label plane=ici|dcn
    "tpu_shuffle_gbps",                 # label plane=ici|dcn
    "tpu_hbm_bytes",                    # label store=device|host|...
    "tpu_hbm_peak_bytes",
    "tpu_hbm_peak_operator_info",       # labels store=..., operator=...
    "tpu_device_budget_bytes",
    "tpu_device_count",
    "tpu_backend_info",                 # label platform=..., value 1
    "tpu_flight_events_total",
    # serving front door (plan/plan_cache.py, docs/plan_cache.md)
    "tpu_plan_cache_hits_total",
    "tpu_plan_cache_misses_total",
    "tpu_plan_cache_entries",
    "tpu_result_cache_hits_total",
    "tpu_result_cache_misses_total",
    "tpu_result_cache_bytes",
    # fault tolerance (exec/recovery.py, analysis/faults.py,
    # docs/resilience.md)
    "tpu_stage_retries_total",
    "tpu_worker_lost_total",
    "tpu_worker_rejoin_total",
    "tpu_recovery_seconds",             # histogram, failure -> recovered
    "tpu_faults_injected_total",        # deterministic chaos firings
    # lockstep divergence audit (analysis/divergence.py,
    # docs/analysis.md §6)
    "tpu_divergence_checks_total",      # digest comparisons on META replies
    "tpu_desync_total",                 # divergences detected
    # query-lifecycle observability (docs/observability.md §8)
    "tpu_exchange_partition_bytes",     # histogram, label plane=ici|dcn
    "tpu_exchange_skew_factor",         # gauge, last exchange, label plane
    "tpu_exchange_p50_bytes",           # gauge, last exchange, label plane
    "tpu_exchange_max_bytes",           # gauge, last exchange, label plane
    "tpu_durable_evicted_bytes_total",  # durable-tier GC budget evictions
    "tpu_query_log_records_total",      # structured query-log lines
    "tpu_query_drift_flags_total",      # plan nodes past driftThreshold
    # multi-tenant query service (service/server.py, docs/service.md)
    "tpu_tenant_queue_depth",           # gauge, label tenant=<name>
    "tpu_tenant_admitted_total",        # counter, label tenant=<name>
    "tpu_tenant_rejected_total",        # load sheds, label tenant=<name>
    "tpu_tenant_device_bytes",          # gauge, harvested, label tenant
    "tpu_query_queue_seconds",          # histogram, label tenant=<name>
    # adaptive query execution (plan/aqe.py, docs/aqe.md)
    "tpu_aqe_decisions_total",          # counter, label rule=<AQE_RULES>
    "tpu_admission_cost_debits_total",  # extra queue slots charged, label
                                        # tenant=<name>
    # cold-path killers (exec/compile_pool.py, docs/compile.md §5)
    "tpu_compile_queue_depth",          # gauge, pending+running pool jobs
    "tpu_prewarm_compiles_total",       # programs built by prewarm jobs
    "tpu_query_first_row_seconds",      # histogram, wall to first batch
    # buffer-lifecycle ledger (analysis/ledger.py, docs/analysis.md §7)
    "tpu_buffer_leaks_total",           # end-of-query residency leaks
    "tpu_use_after_free_total",         # UAF + use-after-donate + dbl-free
    # query lifecycle control (exec/lifecycle.py, docs/service.md §4)
    "tpu_query_cancelled_total",        # counter, label tenant when ambient
    "tpu_query_preempted_total",        # suspensions parked by the service
    "tpu_query_resumed_total",          # suspended queries re-admitted
)

_DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 30.0,
                    float("inf"))


# ---------------------------------------------------------------------------
# Metric families and handles
# ---------------------------------------------------------------------------

def _label_key(labels: Dict[str, str]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class _Family:
    """One metric family: a kind, a help string, and samples per label
    set (the Prometheus data model reduced to what the engine needs)."""

    def __init__(self, kind: str, name: str, help_text: str,
                 buckets: Optional[Tuple[float, ...]] = None):
        self.kind = kind
        self.name = name
        self.help = help_text
        self.buckets = tuple(buckets or _DEFAULT_BUCKETS) \
            if kind == "histogram" else None
        # label key -> float value, or [counts per bucket, sum, count]
        self.samples: Dict[Tuple, Any] = {}

    def _blank(self):
        if self.kind == "histogram":
            return [[0] * len(self.buckets), 0.0, 0]
        return 0.0


class _Handle:
    """A (family, label set) pair: the object call sites hold."""

    def __init__(self, reg: "MetricsRegistry", family: _Family, key: Tuple):
        self._reg = reg
        self._family = family
        self._key = key

    def _sample(self):
        return self._family.samples[self._key]


class _Counter(_Handle):
    def inc(self, amount: float = 1) -> None:
        if amount < 0:
            raise ValueError(f"counter {self._family.name} cannot "
                             f"decrease (inc {amount})")
        with self._reg._values_mu:
            self._family.samples[self._key] += amount

    @property
    def value(self) -> float:
        with self._reg._values_mu:
            return self._family.samples[self._key]


class _Gauge(_Handle):
    def set(self, value: float) -> None:
        with self._reg._values_mu:
            self._family.samples[self._key] = float(value)

    def inc(self, amount: float = 1) -> None:
        with self._reg._values_mu:
            self._family.samples[self._key] += amount

    @property
    def value(self) -> float:
        with self._reg._values_mu:
            return self._family.samples[self._key]


class _Histogram(_Handle):
    def observe(self, value: float) -> None:
        value = float(value)
        buckets = self._family.buckets
        with self._reg._values_mu:
            counts, total, n = self._family.samples[self._key]
            for i, le in enumerate(buckets):
                if value <= le:
                    counts[i] += 1
                    break
            self._family.samples[self._key] = [counts, total + value, n + 1]

    @property
    def count(self) -> int:
        with self._reg._values_mu:
            return self._family.samples[self._key][2]

    @property
    def sum(self) -> float:
        with self._reg._values_mu:
            return self._family.samples[self._key][1]


# ---------------------------------------------------------------------------
# The registry
# ---------------------------------------------------------------------------

class MetricsRegistry:
    """Process-singleton metric registry (the SQLMetrics/Dropwizard layer
    of the reference executor, reduced to one process)."""

    _instance: Optional["MetricsRegistry"] = None
    _lock = named_lock("service.telemetry.MetricsRegistry._lock")

    def __init__(self):
        self._families: Dict[str, _Family] = {}
        # a RAW leaf lock on purpose (the TpuMetrics._lock rationale):
        # histogram observes land at span end on every task thread, and
        # a lockdep NamedLock would take the process-global lockdep
        # state mutex per publish under record mode. Never nests.
        self._values_mu = threading.Lock()
        self._collectors: List[Callable] = [_harvest]

    @classmethod
    def get(cls) -> "MetricsRegistry":
        # lock-free fast path: get() runs at every span close on every
        # task thread, and the NamedLock below would take the process-
        # global lockdep state mutex per event under record mode (the
        # TpuMetrics._lock rationale). The double-checked read is safe:
        # _instance only ever goes None -> instance
        inst = cls._instance
        if inst is not None:
            return inst
        with cls._lock:
            if cls._instance is None:
                cls._instance = MetricsRegistry()
            return cls._instance

    @classmethod
    def reset(cls) -> None:
        """Drop the singleton (tests)."""
        with cls._lock:
            cls._instance = None

    # -- handle creation -----------------------------------------------------
    def _handle(self, kind: str, klass, name: str, help_text: str,
                buckets, labels: Dict[str, str]):
        key = _label_key(labels)
        with self._values_mu:
            fam = self._families.get(name)
            if fam is None:
                fam = self._families[name] = _Family(kind, name, help_text,
                                                    buckets)
            elif fam.kind != kind:
                raise ValueError(
                    f"metric {name} already registered as {fam.kind}, "
                    f"requested {kind}")
            if key not in fam.samples:
                fam.samples[key] = fam._blank()
            if help_text and not fam.help:
                fam.help = help_text
        return klass(self, fam, key)

    # positional-only (/) so label names like ``name=`` cannot collide
    # with the declaration parameters
    def counter(self, name: str, help_text: str = "", /,
                **labels: str) -> _Counter:
        return self._handle("counter", _Counter, name, help_text, None,
                            labels)

    def gauge(self, name: str, help_text: str = "", /,
              **labels: str) -> _Gauge:
        return self._handle("gauge", _Gauge, name, help_text, None, labels)

    def histogram(self, name: str, help_text: str = "",
                  buckets: Optional[Tuple[float, ...]] = None, /,
                  **labels: str) -> _Histogram:
        return self._handle("histogram", _Histogram, name, help_text,
                            buckets, labels)

    def register_collector(self, fn: Callable) -> None:
        """``fn(registry)`` runs before every collect/scrape — the pull
        side of the registry (subsystems harvested only when read)."""
        if fn not in self._collectors:
            self._collectors.append(fn)

    # -- export --------------------------------------------------------------
    def collect(self) -> Dict[str, Dict]:
        """Harvest collectors, then snapshot every family:
        ``{name: {kind, help, samples: [{labels, value}...]}}``
        (histograms carry buckets/counts/sum/count)."""
        for fn in list(self._collectors):
            try:
                fn(self)
            except Exception:
                # a broken subsystem must never take the scrape down
                log.exception("telemetry collector %r failed", fn)
        out: Dict[str, Dict] = {}
        with self._values_mu:
            for name, fam in sorted(self._families.items()):
                samples = []
                for key, val in sorted(fam.samples.items()):
                    labels = dict(key)
                    if fam.kind == "histogram":
                        counts, total, n = val
                        samples.append({
                            "labels": labels,
                            "buckets": list(fam.buckets),
                            "counts": list(counts),
                            "sum": total, "count": n})
                    else:
                        samples.append({"labels": labels, "value": val})
                out[name] = {"kind": fam.kind, "help": fam.help,
                             "samples": samples}
        return out

    def prometheus_text(self) -> str:
        """The registry in Prometheus text exposition format (what the
        scrape endpoint serves at ``/metrics``)."""
        lines: List[str] = []
        for name, fam in self.collect().items():
            if fam["help"]:
                lines.append(f"# HELP {name} {fam['help']}")
            lines.append(f"# TYPE {name} {fam['kind']}")
            for s in fam["samples"]:
                if fam["kind"] == "histogram":
                    cum = 0
                    for le, c in zip(s["buckets"], s["counts"]):
                        cum += c
                        le_s = "+Inf" if le == float("inf") else repr(le)
                        lines.append(
                            f"{name}_bucket"
                            f"{_fmt_labels({**s['labels'], 'le': le_s})}"
                            f" {cum}")
                    lines.append(f"{name}_sum{_fmt_labels(s['labels'])} "
                                 f"{_fmt_value(s['sum'])}")
                    lines.append(f"{name}_count{_fmt_labels(s['labels'])} "
                                 f"{s['count']}")
                else:
                    lines.append(f"{name}{_fmt_labels(s['labels'])} "
                                 f"{_fmt_value(s['value'])}")
        return "\n".join(lines) + "\n"

    def snapshot(self) -> Dict:
        """JSON-able point-in-time snapshot (``session.metrics_snapshot``;
        one line of this is the JSONL export)."""
        return {"atS": round(time.time(), 3), "metrics": self.collect()}

    def snapshot_jsonl(self, path: str,
                       snap: Optional[Dict] = None) -> Dict:
        """Append one JSONL snapshot line to ``path`` (parent dirs
        created defensively); returns the snapshot written — pass
        ``snap`` to write an already-taken snapshot instead of
        harvesting again."""
        snap = snap if snap is not None else self.snapshot()
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        with open(path, "a") as f:
            f.write(json.dumps(snap) + "\n")
        return snap


def _fmt_labels(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{_escape(v)}"' for k, v in sorted(labels.items()))
    return "{" + inner + "}"


def _escape(v: Any) -> str:
    return str(v).replace("\\", r"\\").replace('"', r'\"').replace(
        "\n", r"\n")


def _fmt_value(v: float) -> str:
    if isinstance(v, float) and v.is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(v) if isinstance(v, float) else str(v)


def parse_prometheus_text(text: str) -> Dict[str, List[Tuple[Dict, float]]]:
    """Parse Prometheus text exposition back into
    ``{sample_name: [(labels, value)...]}`` — the round-trip half the
    tests use to prove the endpoint emits what a scraper reads."""
    import re
    out: Dict[str, List[Tuple[Dict, float]]] = {}
    sample_re = re.compile(
        r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})?\s+(\S+)$")
    label_re = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = sample_re.match(line)
        if not m:
            raise ValueError(f"unparseable sample line: {line!r}")
        name, raw_labels, raw_val = m.groups()
        # single-pass unescape: chained str.replace would corrupt values
        # containing literal backslash-n sequences (r'\\n' -> newline)
        unesc = {r"\\": "\\", r'\"': '"', r"\n": "\n"}
        labels = {k: re.sub(r'\\(?:\\|"|n)', lambda m2: unesc[m2.group(0)],
                            v)
                  for k, v in label_re.findall(raw_labels or "")}
        out.setdefault(name, []).append((labels, float(raw_val)))
    return out


# ---------------------------------------------------------------------------
# HBM / memory watermarks
# ---------------------------------------------------------------------------

class Watermark:
    """Current + peak bytes for one store, with per-operator peak
    attribution: a new peak records the innermost open exec
    (``exec/metrics.exec_scope``) and, when ``bag_key`` is set, charges
    the peak onto that exec's metrics bag — so EXPLAIN ANALYZE answers
    "which operator drove peak HBM" per query while the registry gauge
    answers it per process."""

    def __init__(self, name: str, bag_key: Optional[str] = None):
        self.name = name
        self.bag_key = bag_key
        self.current = 0
        self.peak = 0
        self.peak_operator: Optional[str] = None
        # raw leaf lock: updates run under the spill catalog's admission
        # lock on task threads (the TpuMetrics._lock rationale); the
        # critical section is two assignments and never nests
        self._mu = threading.Lock()

    def update(self, current: int) -> None:
        current = int(current)
        with self._mu:
            self.current = current
            new_peak = current > self.peak
            if new_peak:
                self.peak = current
        if new_peak:
            from ..exec import metrics as em
            bag = em.current()
            operator = getattr(bag, "owner", None) if bag is not None \
                else None
            with self._mu:
                # only if OUR peak is still the record: a concurrent
                # larger update must not have its attribution overwritten
                # by this (smaller, slower) one
                if self.peak == current and operator:
                    self.peak_operator = operator
            if bag is not None and self.bag_key:
                bag.max(self.bag_key, current)

    def reset(self) -> None:
        with self._mu:
            self.current = 0
            self.peak = 0
            self.peak_operator = None


_watermarks: Dict[str, Watermark] = {}
_watermarks_mu = named_lock("service.telemetry._watermarks_mu")


def watermark(name: str, bag_key: Optional[str] = None) -> Watermark:
    """The process watermark for ``name`` (created on first use).
    ``bag_key`` (first creation only) names the exec-bag metric the peak
    attribution charges — the device store uses ``peakDeviceBytes``."""
    with _watermarks_mu:
        wm = _watermarks.get(name)
        if wm is None:
            wm = _watermarks[name] = Watermark(name, bag_key)
        return wm


def watermarks() -> Dict[str, Watermark]:
    with _watermarks_mu:
        return dict(_watermarks)


def reset_watermarks() -> None:
    with _watermarks_mu:
        _watermarks.clear()


# ---------------------------------------------------------------------------
# Flight recorder
# ---------------------------------------------------------------------------

_flight_enabled: Optional[bool] = None
_flight_capacity = 4096
_flight_dir = "/tmp/spark_rapids_tpu_flight"
_dump_seq = itertools.count(1)


def _flight_on() -> bool:
    global _flight_enabled
    if _flight_enabled is None:
        try:
            from .. import config as cfg
            _flight_enabled = bool(
                cfg.TpuConf().get(cfg.TELEMETRY_FLIGHT_RECORDER))
        except Exception:
            _flight_enabled = True
    return _flight_enabled


class FlightRecorder:
    """Always-on fixed-size ring of recent engine events.

    Events are ``(tS, thread, kind, name, data)`` tuples; ``record`` is
    lock-light (a raw leaf lock around one index bump + slot write) so
    it can sit on the span-close path of every operator without showing
    up in the bench. The ring never grows: the newest
    ``capacity`` events win, which is exactly what a post-mortem wants."""

    _instance: Optional["FlightRecorder"] = None
    _lock = named_lock("service.telemetry.FlightRecorder._lock")

    def __init__(self, capacity: int = 4096):
        self.capacity = max(16, int(capacity))
        self._ring: List = [None] * self.capacity
        self._n = 0
        # raw leaf lock, hot path (every span close): see Watermark._mu
        self._mu = threading.Lock()

    @classmethod
    def get(cls) -> "FlightRecorder":
        # lock-free fast path (the MetricsRegistry.get rationale): the
        # flight funnel runs at every span close. Capacity changes only
        # at session bootstrap; the slow path handles them
        inst = cls._instance
        want = max(16, _flight_capacity)
        if inst is not None and inst.capacity == want:
            return inst
        with cls._lock:
            if cls._instance is None or cls._instance.capacity != want:
                cls._instance = FlightRecorder(_flight_capacity)
            return cls._instance

    @classmethod
    def reset(cls) -> None:
        with cls._lock:
            cls._instance = None

    def record(self, kind: str, name: str,
               data: Optional[Dict] = None) -> None:
        ev = (round(time.time(), 6), threading.current_thread().name,
              kind, name, data)
        with self._mu:
            self._ring[self._n % self.capacity] = ev
            self._n += 1

    def events(self) -> List[Dict]:
        """The retained events, oldest first, as JSON-able dicts."""
        with self._mu:
            n = self._n
            if n <= self.capacity:
                raw = self._ring[:n]
            else:
                cut = n % self.capacity
                raw = self._ring[cut:] + self._ring[:cut]
        return [{"tS": t, "thread": th, "kind": k, "name": nm,
                 **({"data": d} if d else {})}
                for (t, th, k, nm, d) in raw]

    def event_count(self) -> int:
        with self._mu:
            return self._n

    def dump(self, path: Optional[str] = None,
             reason: Optional[str] = None,
             query_id: Optional[str] = None) -> str:
        """Write the ring to a JSON artifact and return its path. Parent
        directories are created defensively; IO errors raise here — the
        *automatic* dump path (:func:`dump_on_error`) wraps this so a
        failed telemetry write can never mask a query exception.

        With ``query_id`` the artifact is SCOPED to that query: the
        filename carries the id, and ring entries attributed to a
        DIFFERENT query are filtered out (a concurrent session's events
        no longer interleave the post-mortem) — process-level events
        with no query attribution are kept, they are context."""
        if path is None:
            qpart = f"-{query_id}" if query_id else ""
            path = os.path.join(
                _flight_dir,
                f"flight-{os.getpid()}{qpart}-{next(_dump_seq)}.json")
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        events = self.events()
        if query_id is not None:
            events = [e for e in events
                      if e.get("data", {}).get("query", query_id)
                      == query_id]
        doc = {"dumpedAtS": round(time.time(), 3), "pid": os.getpid(),
               "reason": reason, "totalEvents": self.event_count(),
               "events": events}
        if query_id is not None:
            doc["queryId"] = query_id
        with open(path, "w") as f:
            json.dump(doc, f, indent=1, default=str)
        try:
            MetricsRegistry.get().counter(
                "tpu_flight_dumps_total",
                "flight-recorder artifacts written").inc()
        except Exception:
            pass
        return path


_flight_tls = threading.local()


def flight_record(kind: str, name: str, data: Optional[Dict] = None) -> None:
    """Record one event into the process flight ring (no-op when the
    recorder conf is off). The funnel every instrument calls. Re-entry
    on the same thread is dropped: lockdep's cycle incident can fire
    *inside* the acquisition of this module's own singleton lock, and
    recursing there would deadlock on the non-reentrant raw lock.

    When a query context is active (``exec/query_context``), its query
    id is stamped into the event's data — so EVERY instrument routing
    through this funnel (spans, syncs, spills, recompiles, faults,
    recovery, conf changes) is attributable to the query that paid for
    it, and ``dump(query_id=...)`` can filter a concurrent session's
    events out of a post-mortem."""
    if getattr(_flight_tls, "busy", False) or not _flight_on():
        return
    _flight_tls.busy = True
    try:
        try:
            from ..exec.query_context import current_query_id, \
                current_tenant
            qid = current_query_id()
            tenant = current_tenant()
        except Exception:
            qid = tenant = None
        if qid is not None or tenant is not None:
            data = dict(data) if data else {}
            if qid is not None:
                data.setdefault("query", qid)
            # the tenant rides NEXT to the query id (docs/service.md):
            # a post-mortem groups one tenant's events without joining
            # through the query log
            if tenant is not None:
                data.setdefault("tenant", tenant)
        FlightRecorder.get().record(kind, name, data)
    finally:
        _flight_tls.busy = False


def dump_on_error(exc: BaseException) -> Optional[str]:
    """Automatic post-mortem dump for a failing task body / collect.
    Never raises, never dumps the same exception twice (the task-level
    and collect-level hooks both see it); returns the artifact path."""
    if not _flight_on():
        return None
    try:
        existing = getattr(exc, "_tpu_flight_dump", None)
        if existing is not None:
            return existing
        # scope the artifact to the FAILING query: an exception that
        # names its query (DesyncError carries query_id) wins — a
        # desync post-mortem must filter to the DESYNCED query even
        # when the dump runs on a thread whose ambient context moved
        # on; otherwise the ambient context on the failing task/collect
        # thread IS the query that died
        qid = getattr(exc, "query_id", None)
        if qid is None:
            try:
                from ..exec.query_context import current_query_id
                qid = current_query_id()
            except Exception:
                qid = None
        path = FlightRecorder.get().dump(
            reason=f"{type(exc).__name__}: {exc}", query_id=qid)
        try:
            exc._tpu_flight_dump = path
        except Exception:
            pass           # exceptions with __slots__: dedup is best-effort
        log.warning("flight record dumped to %s", path)
        return path
    except Exception:
        # the original query exception is in flight — a failed telemetry
        # write must never replace it
        log.exception("flight-record dump failed (original error "
                      "propagates unmasked)")
        return None


# ---------------------------------------------------------------------------
# Conf priming (session bootstrap calls refresh, like lockdep/metrics)
# ---------------------------------------------------------------------------

def refresh(conf=None) -> None:
    """Prime the flight-recorder gate/capacity/dir from a session conf
    (eager, the lockdep pattern: lazy conf reads on hot paths recurse
    into the conf-registry lock) and start the scrape endpoint when the
    port conf is set."""
    global _flight_enabled, _flight_capacity, _flight_dir
    try:
        from .. import config as cfg
        conf = conf or cfg.TpuConf()
        _flight_enabled = bool(conf.get(cfg.TELEMETRY_FLIGHT_RECORDER))
        _flight_capacity = int(conf.get(cfg.TELEMETRY_FLIGHT_EVENTS))
        _flight_dir = str(conf.get(cfg.TELEMETRY_FLIGHT_DIR))
        port = int(conf.get(cfg.TELEMETRY_PORT))
    except Exception:
        _flight_enabled = True
        return
    if port > 0:
        try:
            start_server(port)
        except Exception:
            # a taken port must not fail session construction
            log.exception("telemetry scrape endpoint failed to start on "
                          "port %d", port)


def reset_cache() -> None:
    global _flight_enabled
    _flight_enabled = None


# ---------------------------------------------------------------------------
# Harvest: the pull side of the registry
# ---------------------------------------------------------------------------

def _harvest(reg: MetricsRegistry) -> None:
    """Read every pull-shaped subsystem into registry gauges. Runs only
    at collect/scrape time — the subsystems pay nothing until someone
    looks. Peeks never *create* singletons: an idle subsystem simply
    contributes no samples."""
    # semaphore admission (exec/device.TpuSemaphore)
    from ..exec.device import DeviceManager, TpuSemaphore
    sem = TpuSemaphore.peek()
    if sem is not None:
        st = sem.stats()
        reg.gauge("tpu_semaphore_wait_seconds_total",
                  "cumulative task wait for a device permit").set(st["waitS"])
        reg.gauge("tpu_semaphore_hold_seconds_total",
                  "cumulative device occupancy").set(st["holdS"])
        reg.gauge("tpu_semaphore_acquires_total").set(st["acquires"])
        reg.gauge("tpu_semaphore_permits").set(sem.max_concurrent)
    dm = DeviceManager.peek()
    if dm is not None:
        reg.gauge("tpu_device_budget_bytes",
                  "allocFraction * device memory").set(
            dm.memory_budget_bytes)
        reg.gauge("tpu_device_count").set(len(dm.devices))
        reg.gauge("tpu_backend_info", "constant 1, platform label",
                  platform=dm.platform).set(1)

    # lockdep per-lock wait/hold (analysis/lockdep)
    from ..analysis import lockdep, recompile
    for name, st in lockdep.stats().items():
        reg.gauge("tpu_lock_wait_seconds_total", lock=name).set(st["waitS"])
        reg.gauge("tpu_lock_hold_seconds_total", lock=name).set(st["holdS"])
        reg.gauge("tpu_lock_acquires_total", lock=name).set(st["acquires"])
    reg.gauge("tpu_lockdep_cycles_total",
              "lock-order inversion cycles observed").set(
        len(lockdep.report()["cycles"]))

    # host syncs (exec/tracing.SyncCounter process total)
    from ..exec.tracing import SyncCounter
    reg.gauge("tpu_host_syncs_total",
              "blocking device->host readbacks (counted while any query "
              "sync counter is active)").set(SyncCounter.process_total)

    # recompile audit totals
    rc = recompile.report()
    reg.gauge("tpu_recompiles_total",
              "fused-program cache-miss builds").set(
        sum(v["compiles"] for v in rc.values()))
    reg.gauge("tpu_fused_calls_total").set(
        sum(v["calls"] for v in rc.values()))

    # spill store residency + cumulative spill volume
    from ..exec.spill import BufferCatalog
    cat = BufferCatalog.peek()
    if cat is not None:
        reg.gauge("tpu_spill_device_bytes",
                  "device-tier bytes held").set(cat.device_bytes)
        reg.gauge("tpu_spill_host_bytes").set(cat.host_bytes)
        reg.gauge("tpu_spilled_device_bytes_total",
                  "cumulative device->host spill volume").set(
            cat.spilled_device_bytes)
        reg.gauge("tpu_spilled_host_bytes_total").set(cat.spilled_host_bytes)
        reg.gauge("tpu_spill_buffers").set(cat.buffer_count())
        # per-tenant device residency (service multi-tenancy): one gauge
        # sample per tenant. Previously-seen tenants whose buffers all
        # left the device are explicitly zeroed — a scrape must show the
        # watermark RETURNING to 0, not a stale last value
        tenant_dev = cat.tenant_device_bytes()
        with reg._values_mu:        # snapshot keys: a concurrent scrape
            fam = reg._families.get("tpu_tenant_device_bytes")
            known = [dict(k).get("tenant")
                     for k in fam.samples] if fam is not None else []
        for t in known:
            if t and t not in tenant_dev:
                reg.gauge("tpu_tenant_device_bytes", tenant=t).set(0)
        for t, nbytes in tenant_dev.items():
            reg.gauge("tpu_tenant_device_bytes",
                      "device bytes held per service tenant",
                      tenant=t).set(nbytes)

    # shuffle transport process totals (both wire directions)
    from ..shuffle import transport
    for key, val in transport.transport_totals().items():
        name = {"bytes_fetched": "tpu_shuffle_bytes_fetched_total",
                "chunks": "tpu_shuffle_chunks_total",
                "retries": "tpu_shuffle_retries_total",
                "bounce_misses": "tpu_shuffle_bounce_misses_total",
                "bytes_sent": "tpu_shuffle_bytes_sent_total",
                "chunks_sent": "tpu_shuffle_chunks_sent_total"}.get(key)
        if name:
            reg.gauge(name).set(val)

    # shuffle data-plane totals (shuffle/exchange.plane_totals): which
    # plane exchanges took, bytes moved, and the resulting GB/s per plane
    from ..shuffle import exchange as _exchange
    pt = _exchange.plane_totals()
    for plane in ("ici", "dcn"):
        n_ex = pt.get(f"{plane}_exchanges", 0)
        if not n_ex:
            continue
        secs = pt.get(f"{plane}_seconds", 0.0)
        moved = pt.get(f"{plane}_bytes", 0)
        reg.gauge("tpu_shuffle_exchanges_total",
                  "completed shuffle exchanges per data plane",
                  plane=plane).set(n_ex)
        reg.gauge("tpu_shuffle_plane_bytes_total",
                  "bytes entering the shuffle per data plane",
                  plane=plane).set(moved)
        reg.gauge("tpu_shuffle_plane_seconds_total",
                  "wall seconds spent in exchanges per data plane",
                  plane=plane).set(round(secs, 4))
        if secs > 0:
            reg.gauge("tpu_shuffle_gbps",
                      "cumulative shuffle throughput per data plane",
                      plane=plane).set(round(moved / secs / 1e9, 6))

    # watermarks (current + peak + peak-operator attribution)
    for wm in watermarks().values():
        reg.gauge("tpu_hbm_bytes", "current accounted bytes",
                  store=wm.name).set(wm.current)
        reg.gauge("tpu_hbm_peak_bytes", "peak accounted bytes",
                  store=wm.name).set(wm.peak)
        if wm.peak_operator:
            reg.gauge("tpu_hbm_peak_operator_info",
                      "constant 1; operator that drove the peak",
                      store=wm.name, operator=wm.peak_operator).set(1)

    # the flight ring itself
    reg.gauge("tpu_flight_events_total",
              "events recorded into the flight ring").set(
        FlightRecorder.get().event_count() if _flight_on() else 0)


def compact_snapshot() -> Dict[str, Any]:
    """A small flat snapshot for bench/multichip artifact tails: the
    handful of registry numbers a round-over-round reader actually
    diffs."""
    snap = MetricsRegistry.get().collect()

    def val(name, default=0):
        fam = snap.get(name)
        if not fam or not fam["samples"]:
            return default
        return fam["samples"][0].get("value", default)

    out = {
        "hostSyncs": val("tpu_host_syncs_total"),
        "recompiles": val("tpu_recompiles_total"),
        "semaphoreWaitS": round(val("tpu_semaphore_wait_seconds_total"), 3),
        "semaphoreHoldS": round(val("tpu_semaphore_hold_seconds_total"), 3),
        "spilledDeviceBytes": val("tpu_spilled_device_bytes_total"),
        "shuffleBytesFetched": val("tpu_shuffle_bytes_fetched_total"),
        "shuffleBytesSent": val("tpu_shuffle_bytes_sent_total"),
        "flightEvents": val("tpu_flight_events_total"),
    }
    # compile-time discipline (exec/compile_cache): seconds paid building
    # programs this process, split cold build vs persistent-cache disk
    # hit — the warm-restart story in one diffable entry
    fam = snap.get("tpu_compile_seconds")
    if fam and fam.get("samples"):
        comp = {}
        for s in fam["samples"]:
            kind = dict(s.get("labels") or {}).get("kind", "cold")
            comp[kind] = {"builds": s.get("count", 0),
                          "seconds": round(s.get("sum", 0.0), 3)}
        if comp:
            out["compile"] = comp
    # per-plane exchange counts + GB/s (shuffle/exchange plane totals):
    # the one-line answer to "did the shuffle ride ICI, and how fast"
    try:
        from ..shuffle.exchange import plane_totals
        pt = plane_totals()
        planes = {}
        for plane in ("ici", "dcn"):
            if pt.get(f"{plane}_exchanges"):
                entry = {"exchanges": int(pt[f"{plane}_exchanges"]),
                         "bytes": int(pt[f"{plane}_bytes"])}
                secs = pt.get(f"{plane}_seconds", 0.0)
                if secs > 0:
                    entry["gbps"] = round(
                        pt[f"{plane}_bytes"] / secs / 1e9, 6)
                planes[plane] = entry
        if planes:
            out["shufflePlanes"] = planes
    except Exception:
        pass
    dev = watermarks().get("device")
    if dev is not None:
        out["hbmPeakBytes"] = dev.peak
        if dev.peak_operator:
            out["hbmPeakOperator"] = dev.peak_operator
    return out


# ---------------------------------------------------------------------------
# Scrape endpoint
# ---------------------------------------------------------------------------

class TelemetryServer:
    """Background HTTP scrape endpoint: ``GET /metrics`` answers
    Prometheus text, ``GET /snapshot`` the JSON snapshot. Daemon-thread
    server (a wedged scraper must never block interpreter exit);
    ``stop()`` shuts it down cleanly with a bounded join."""

    def __init__(self, port: int, host: str = "127.0.0.1"):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(self):              # noqa: N802 (http.server API)
                try:
                    if self.path.split("?")[0] == "/metrics":
                        body = MetricsRegistry.get().prometheus_text() \
                            .encode()
                        ctype = "text/plain; version=0.0.4"
                    elif self.path.split("?")[0] == "/snapshot":
                        body = json.dumps(
                            MetricsRegistry.get().snapshot()).encode()
                        ctype = "application/json"
                    else:
                        self.send_error(404)
                        return
                except Exception as e:     # scrape must answer, not die
                    self.send_error(500, str(e)[:200])
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, fmt, *args):
                log.debug("scrape: " + fmt, *args)

        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self.host = host
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "TelemetryServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="tpu-telemetry-http")
        self._thread.start()
        return self

    def stop(self, join_timeout_s: float = 2.0) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=join_timeout_s)
        self._thread = None


_server: Optional[TelemetryServer] = None
_server_mu = named_lock("service.telemetry._server_mu")


def start_server(port: int, host: str = "127.0.0.1") -> TelemetryServer:
    """Start (or return) the process scrape endpoint. ``port=0`` binds an
    ephemeral port (tests); the conf path only calls with port > 0."""
    global _server
    with _server_mu:
        if _server is None:
            _server = TelemetryServer(port, host).start()
        return _server


def stop_server() -> None:
    global _server
    with _server_mu:
        srv, _server = _server, None
    if srv is not None:
        srv.stop()


def active_server() -> Optional[TelemetryServer]:
    with _server_mu:
        return _server
