"""Tenant identity, budgets and priorities for the multi-tenant service.

The reference plugin isolates concurrent Spark tasks only at the device
level (GpuSemaphore permits, SURVEY §2.7); a query SERVICE needs one
more axis: WHO a query runs for. A :class:`TenantSpec` names a tenant
and carries its scheduling weight (``priority``), its admission bounds
(``slots`` concurrent queries, ``max_queue_depth`` before load-shedding)
and its device-memory budget. The spec's enforcement is split across
layers:

* admission/scheduling — ``service/server.QueryService`` (slots, queue
  depth, priority/deadline ordering);
* memory — ``exec/spill.BufferCatalog`` reads the process-global budget
  table kept HERE at its reserve/register boundaries and spills an
  over-budget tenant's own buffers first (docs/service.md §3);
* attribution — ``exec/query_context.tenant_scope`` (re-exported here)
  makes the tenant ambient for a query's execution, so buffer
  registration, flight-recorder events, the shuffle protocol and the
  query log all tag the tenant with no per-callsite plumbing.

The budget table is process-global (like the watermarks) because the
buffer catalog is a process singleton: two services on one engine share
one memory truth.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional

from ..analysis.lockdep import named_lock
# re-export: the ambient tenant machinery lives with the query context
# (exec/query_context.py) so exec/ never imports service/; service code
# and tests reach it from here
from ..exec.query_context import current_tenant, tenant_scope  # noqa: F401


@dataclass(frozen=True)
class TenantSpec:
    """One tenant's contract with the service. ``priority``: HIGHER runs
    first (the queue orders on (-priority, deadline, arrival)).
    ``slots``: concurrent queries this tenant may occupy in the service
    pool (its concurrentGpuTasks analog one level up). ``max_queue_depth``:
    queued (not yet running) queries beyond this are load-shed with a
    typed ``AdmissionRejected``. ``memory_budget_bytes``: device bytes
    this tenant may hold before its own buffers become the first spill
    victims; 0 = unbudgeted. ``weight``: the tenant's share under the
    weighted-fair scheduler (``service.scheduler.policy=wfq``,
    docs/service.md §4) — a weight-3 tenant is credited three times the
    deficit of a weight-1 tenant per scheduling round; ignored under the
    strict-priority policy. ``None`` fields fall back to the
    ``service.*`` conf defaults at registration."""

    name: str
    priority: int = 0
    slots: Optional[int] = None
    max_queue_depth: Optional[int] = None
    memory_budget_bytes: Optional[int] = None
    weight: Optional[float] = None


# ---------------------------------------------------------------------------
# Process-global device-memory budget table (the spill layer's view)
# ---------------------------------------------------------------------------

_mu = named_lock("service.tenants._mu")
_budgets: Dict[str, int] = {}


def set_budget(tenant: str, nbytes: int) -> None:
    """Install/replace one tenant's device-byte budget (0 removes it —
    an unbudgeted tenant is never a preferred spill victim)."""
    with _mu:
        if nbytes and int(nbytes) > 0:
            _budgets[tenant] = int(nbytes)
        else:
            _budgets.pop(tenant, None)


def budget_for(tenant: Optional[str]) -> int:
    """The tenant's device budget in bytes, 0 when unbudgeted (or for
    untenanted buffers)."""
    if tenant is None:
        return 0
    with _mu:
        return _budgets.get(tenant, 0)


def budgets() -> Dict[str, int]:
    with _mu:
        return dict(_budgets)


def reset_budgets() -> None:
    """Drop every installed budget (test/service teardown)."""
    with _mu:
        _budgets.clear()


def over_budget(tenant: Optional[str], held_bytes: int) -> bool:
    """True when ``tenant`` holds more device bytes than its budget
    allows — the spill cascade's victim-ordering predicate (an
    unbudgeted tenant is never over)."""
    b = budget_for(tenant)
    return b > 0 and held_bytes > b
