"""Shuffle/spill compression codecs.

Reference: ``TableCompressionCodec.scala:41-107`` + ``NvcompLZ4Compression
Codec.scala:25`` + ``CopyCompressionCodec.scala`` — batched device
compression for shuffle payloads, codec chosen by
``spark.rapids.shuffle.compression.codec`` (RapidsConf.scala:729).

TPU-standalone: there is no device decompression engine, so codecs run
host-side on the staged bytes — exactly where the transfer server and the
disk spill tier already hold them. ``zlib`` ships with CPython; the codec
interface leaves room for zstd/lz4 wheels when present.
"""

from __future__ import annotations

import zlib
from typing import Dict, Optional


class Codec:
    name = "none"

    def compress(self, data: bytes) -> bytes:
        return data

    def decompress(self, data: bytes, uncompressed_size: int) -> bytes:
        return data


class CopyCodec(Codec):
    """Identity (CopyCompressionCodec.scala analog)."""


class ZlibCodec(Codec):
    name = "zlib"

    def __init__(self, level: int = 1):
        # level 1: shuffle payloads favor speed over ratio (the reference's
        # nvcomp LZ4 is likewise a speed-first codec)
        self.level = level

    def compress(self, data: bytes) -> bytes:
        return zlib.compress(data, self.level)

    def decompress(self, data: bytes, uncompressed_size: int) -> bytes:
        out = zlib.decompress(data)
        if uncompressed_size and len(out) != uncompressed_size:
            raise ValueError(
                f"decompressed {len(out)} bytes, expected "
                f"{uncompressed_size}")
        return out


_CODECS: Dict[str, Codec] = {"none": CopyCodec(), "zlib": ZlibCodec()}


def get_codec(name: Optional[str]) -> Codec:
    codec = _CODECS.get((name or "none").lower())
    if codec is None:
        raise ValueError(f"unknown compression codec {name!r} "
                         f"(available: {sorted(_CODECS)})")
    return codec
