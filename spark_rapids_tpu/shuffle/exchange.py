"""Shuffle exchange: repartition batches between stages.

Reference: ``GpuShuffleExchangeExec`` (SURVEY.md §2.6) builds a
GpuShuffleDependency with a GpuPartitioning and moves partition slices through
the shuffle manager; ``RapidsCachingWriter`` keeps slices in the spillable
device store instead of writing shuffle files
(RapidsShuffleInternalManager.scala:73-192).

This local exchange does the same single-process: map side splits each batch
with a partitioner and registers the slices as spillable buffers keyed by
(map partition, reduce partition); reduce side pulls and concatenates its
slices. The multi-host data plane (ICI all_to_all / DCN transfer server)
lives in parallel/ and shuffle/transport.py."""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..analysis.contracts import exec_contract
from ..columnar import dtypes as dt
from ..columnar.batch import ColumnarBatch
from ..exec.spill import (OUTPUT_FOR_SHUFFLE_PRIORITY, BufferCatalog,
                          SpillableColumnarBatch)
from ..ops import expressions as ex
from ..plan.physical import (Partition, TpuExec, bind_refs, concat_batches,
                             exec_metrics)
from ..exec.tracing import trace_span
from .partitioning import (HashPartitioner, RoundRobinPartitioner,
                           SinglePartitioner, TpuPartitioner)


class LocalShuffle:
    """In-process shuffle state: (reduce partition) -> list of spillable
    slices (ShuffleBufferCatalog analog, scoped to one exchange)."""

    def __init__(self, num_partitions: int, catalog: Optional[BufferCatalog] = None):
        self.num_partitions = num_partitions
        self.catalog = catalog or BufferCatalog.get()
        self.slices: Dict[int, List[SpillableColumnarBatch]] = {
            p: [] for p in range(num_partitions)}

    def write(self, partitioner: TpuPartitioner, batch: ColumnarBatch) -> None:
        for p, piece in enumerate(partitioner.split(batch)):
            if piece.num_rows > 0:
                self.slices[p].append(SpillableColumnarBatch(
                    piece, OUTPUT_FOR_SHUFFLE_PRIORITY, self.catalog))

    def read(self, p: int, schema: dt.Schema) -> Partition:
        pending = self.slices[p]
        batches = []
        for s in pending:
            batches.append(s.get_batch())
            s.close()
        if batches:
            yield concat_batches(schema, batches)

    def read_slices(self, p: int, lo: int, hi: int,
                    schema: dt.Schema) -> Partition:
        """A mapper-subset read of reduce partition ``p``: slices
        [lo, hi) only — the partial-mapper partition spec behind AQE skew
        splitting (ShuffledBatchRDD.scala:202 PartialMapperPartitionSpec)."""
        batches = []
        for s in self.slices[p][lo:hi]:
            batches.append(s.get_batch())
            s.close()
        if batches:
            yield concat_batches(schema, batches)

    def read_row_chunk(self, p: int, idx: int, chunk: int, n_chunks: int,
                       schema: dt.Schema) -> Partition:
        """Row-range read of one slice of partition ``p``: chunk
        ``chunk``/``n_chunks`` by row position — sub-mapper granularity
        for the single-giant-slice skew case (finer than the reference's
        map-block granularity; columnar row gathers make it cheap). The
        slice is SHARED by its chunks, so it is not closed here —
        ``close_pending`` releases it at exchange cleanup."""
        import jax.numpy as jnp
        from ..columnar.column import bucket
        from ..ops import kernels as K
        b = self.slices[p][idx].get_batch()
        n = b.num_rows
        lo = (n * chunk) // n_chunks
        hi = (n * (chunk + 1)) // n_chunks
        count = hi - lo
        if count <= 0:
            return
        cap = bucket(max(count, 1))
        live = jnp.arange(cap) < count
        idxs = jnp.where(live, jnp.arange(cap, dtype=jnp.int32) + lo, 0)
        cols = [K.gather_column(c, idxs, out_valid=live)
                for c in b.columns]
        yield ColumnarBatch(schema, cols, count)

    def close_pending(self) -> None:
        """Release slices never pulled (early-terminating consumers)."""
        for pending in self.slices.values():
            for s in pending:
                if not s._closed:
                    s.close()


class TpuShuffleExchangeExec(TpuExec):
    """Repartition(n) / repartition(n, cols) exchange.

    ``adaptive_ok``: the planner marks exchanges whose consumer tolerates a
    runtime-reduced partition count (aggregates: merged partitions keep key
    ownership disjoint) — those coalesce small post-shuffle partitions from
    OBSERVED map-side sizes, the AQE + GpuCustomShuffleReaderExec behavior
    (GpuOverrides.scala:1920). Join exchanges stay fixed: both sides must
    keep identical partitioning."""

    CONTRACT = exec_contract(schema="passthrough", partitioning="defined")
    METRICS = exec_metrics("dataSize", "shuffleWriteTime",
                           "shuffleFetchTime", "skewSplitPartitions",
                           "skewSplitTasks", "coalescedPartitions",
                           "fetchFailedRetries")

    def __init__(self, child: TpuExec, num_partitions: int,
                 by: Optional[List[ex.Expression]] = None,
                 adaptive_ok: bool = False,
                 adaptive_min_bytes: Optional[int] = None):
        super().__init__(child)
        self.num_partitions = max(1, num_partitions)
        self.by = [bind_refs(e, child.schema) for e in by] if by else None
        self.adaptive_ok = adaptive_ok
        # resolved at PLAN time from the session conf (exec-level TpuConf()
        # would read global defaults, not the session's settings)
        self.adaptive_min_bytes = adaptive_min_bytes
        self.coalesced_to: Optional[int] = None    # runtime observation

    @property
    def schema(self):
        return self.children[0].schema

    @property
    def output_partitions(self) -> int:
        return self.num_partitions

    def _make_partitioner(self) -> TpuPartitioner:
        if self.num_partitions == 1:
            return SinglePartitioner()
        if self.by:
            return HashPartitioner(self.num_partitions, self.by)
        return RoundRobinPartitioner(self.num_partitions)

    def _run_map_phase(self, shuffle) -> None:
        """Map side: split every upstream batch and register the slices,
        one task per upstream partition, drained concurrently (shared by
        the local, distributed, and skew-split execute forms)."""
        from ..exec.tasks import run_partition_tasks
        partitioner = self._make_partitioner()

        def map_task(pid, part):
            for batch in part:
                shuffle.write(partitioner, batch)
                self.metrics.inc("dataSize", batch.device_size_bytes())

        with trace_span("shuffle_write", self.metrics, "shuffleWriteTime"):
            run_partition_tasks(self.children[0].execute(), map_task)

    def execute(self) -> List[Partition]:
        from .manager import WorkerContext
        ctx = WorkerContext.current
        if ctx is not None:
            return self._execute_distributed(ctx)
        shuffle = self._shuffle = LocalShuffle(self.num_partitions)
        self._run_map_phase(shuffle)
        groups = self._reduce_groups(shuffle)
        return [self._read_group(shuffle, g) for g in groups]

    def execute_skew(self, threshold: int) -> List[List[Partition]]:
        """AQE skew-split form of :meth:`execute` (local mode): run the
        map phase, then return per reduce partition a LIST of
        sub-partitions — one when under ``threshold`` observed bytes,
        multiple mapper-subset reads (partial-mapper partition specs,
        ShuffledBatchRDD.scala:202) when a hot partition exceeds it. The
        caller (skewed join) keeps the other side aligned per ORIGINAL
        partition index. Unsplit partitions keep the elastic-recovery
        read path; SPLIT chunks cannot re-run the map phase safely (other
        chunks of the same partition may already be consumed against the
        old slice boundaries), so a lost buffer there aborts loudly."""
        from .manager import WorkerContext
        assert WorkerContext.current is None, \
            "skew split is a local-mode path"
        shuffle = self._shuffle = LocalShuffle(self.num_partitions)
        self._run_map_phase(shuffle)
        out: List[List[Partition]] = []
        for p in range(self.num_partitions):
            sizes = [s.size_bytes for s in shuffle.slices[p]]
            total = sum(sizes)
            if total <= threshold:
                out.append([self._read_group(shuffle, [p])])
                continue
            if len(sizes) < 2:
                # one giant map slice: split by row ranges instead
                n_chunks = min(-(-total // threshold), 64)
                chunks = [shuffle.read_row_chunk(p, 0, c, n_chunks,
                                                 self.schema)
                          for c in range(n_chunks)]
            else:
                # split on slice (mapper-output) boundaries into chunks
                # of ~threshold bytes, at least one slice each
                chunks = []
                lo = 0
                acc = 0
                for i, sz in enumerate(sizes):
                    acc += sz
                    if acc >= threshold and i + 1 > lo:
                        chunks.append(shuffle.read_slices(p, lo, i + 1,
                                                          self.schema))
                        lo, acc = i + 1, 0
                if lo < len(sizes):
                    chunks.append(shuffle.read_slices(p, lo, len(sizes),
                                                      self.schema))
            self.metrics.inc("skewSplitPartitions")
            self.metrics.inc("skewSplitTasks", len(chunks))
            out.append([self._loud_chunk(c, p) for c in chunks])
        return out

    def _loud_chunk(self, chunk: Partition, p: int) -> Partition:
        """Split-chunk reads abort with CONTEXT on lost buffers instead
        of recovering — re-running the map phase would move the slice/row
        boundaries under chunks that were already consumed."""
        from ..exec.spill import BufferLostError
        try:
            yield from chunk
        except BufferLostError as e:
            raise RuntimeError(
                f"skew-split chunk of shuffle partition {p} lost a "
                f"buffer; map-stage retry is unsafe for split chunks "
                f"(consumed siblings pin the old boundaries): {e}") from e

    def plan_fingerprint(self) -> str:
        """Structural hash of this exchange's plan subtree: exec class
        names + output schemas + the partitioning KEY EXPRESSIONS,
        recursively. Deliberately EXCLUDES data-dependent detail (row
        counts, shard paths) so every worker running the same logical
        query computes the same value, while structurally different
        exchanges — including two identical trees hash-partitioned on
        different columns, the exact silent-wrong-data signature —
        compute different ones."""
        import hashlib

        def desc(node) -> str:
            try:
                sch = ",".join(f"{f.name}:{f.dtype.name}"
                               for f in node.schema)
            except Exception:
                sch = "?"
            kids = ";".join(desc(c) for c in node.children)
            return f"{type(node).__name__}[{sch}]({kids})"
        by = ",".join(repr(e) for e in self.by) if self.by else ""
        s = f"{desc(self)}|n={self.num_partitions}|by={by}"
        return hashlib.sha1(s.encode()).hexdigest()[:16]

    def _execute_distributed(self, ctx) -> List[Partition]:
        """Multi-process mode: map slices register in the worker's
        ShuffleStore (RapidsCachingWriter), reduce partitions this worker
        OWNS read local + peer slices (RapidsCachingReader split); the
        other partitions are empty here — their owners produce them.
        Adaptive coalescing stays off: partition->worker ownership must be
        identical on every worker."""
        from .manager import DistributedShuffle
        shuffle = self._shuffle = DistributedShuffle(
            self.num_partitions, ctx, fingerprint=self.plan_fingerprint())
        self._run_map_phase(shuffle)
        shuffle.finish_writes()

        def owned(p):
            with trace_span("shuffle_fetch", self.metrics, "shuffleFetchTime"):
                yield from shuffle.read(p, self.schema)

        def empty():
            return
            yield

        return [owned(p) if ctx.owns_reduce(p) else empty()
                for p in range(self.num_partitions)]

    def _reduce_groups(self, shuffle: LocalShuffle) -> List[List[int]]:
        """Adaptive partition coalescing: group adjacent reduce partitions
        below minPartitionSize using the map side's observed slice sizes."""
        all_parts = [[p] for p in range(self.num_partitions)]
        if not self.adaptive_ok or not self.adaptive_min_bytes:
            return all_parts
        target = int(self.adaptive_min_bytes)
        sizes = [sum(s.size_bytes for s in shuffle.slices[p])
                 for p in range(self.num_partitions)]
        groups: List[List[int]] = []
        cur: List[int] = []
        cur_bytes = 0
        for p, sz in enumerate(sizes):
            cur.append(p)
            cur_bytes += sz
            if cur_bytes >= target:
                groups.append(cur)
                cur, cur_bytes = [], 0
        if cur:
            if groups:
                groups[-1].extend(cur)   # tail merges into the last group
            else:
                groups.append(cur)
        self.coalesced_to = len(groups)
        if len(groups) < self.num_partitions:
            self.metrics.inc("coalescedPartitions",
                             self.num_partitions - len(groups))
        return groups

    def _read_group(self, shuffle: LocalShuffle, group: List[int]) -> Partition:
        """Reduce-side read with ELASTIC RECOVERY: a failed fetch (lost /
        released buffers, transport give-up) triggers one re-execution of
        the upstream map phase for the lost partitions — the analog of
        RapidsShuffleFetchFailedException -> Spark FetchFailed -> map-stage
        retry (RapidsShuffleIterator.scala:28,49)."""
        from ..exec.spill import BufferLostError
        from .transport import ShuffleFetchError
        try:
            batches = self._pull_group(shuffle, group)
        except (ShuffleFetchError, BufferLostError) as e:
            if not self.children[0].subtree_deterministic():
                # re-executing an indeterminate map stage re-partitions
                # rows differently; partitions already consumed from the
                # first run would silently duplicate/drop rows (Spark
                # aborts the stage for the same reason)
                raise
            import logging
            logging.getLogger("spark_rapids_tpu.shuffle").warning(
                "shuffle fetch for partitions %s failed (%s); re-running "
                "the map stage for them", group, e)
            self.metrics.inc("fetchFailedRetries")
            self._refill(shuffle, group)
            batches = self._pull_group(shuffle, group)
        if batches:
            yield concat_batches(self.schema, batches)

    def _pull_group(self, shuffle: LocalShuffle,
                    group: List[int]) -> List[ColumnarBatch]:
        batches = []
        for p in group:
            for b in shuffle.read(p, self.schema):
                batches.append(b)
        return batches

    def _refill(self, shuffle: LocalShuffle, group: List[int]) -> None:
        """Re-run the upstream map tasks, keeping ONLY the lost reduce
        partitions' slices (Spark recomputes lost map outputs from lineage;
        other partitions' refills are discarded). Caller guarantees the
        upstream is deterministic."""
        from ..exec.tasks import run_partition_tasks
        lost = set(group)
        partitioner = self._make_partitioner()
        for p in lost:
            for s in shuffle.slices[p]:
                if not s._closed:     # release survivors before replacing
                    s.close()
            shuffle.slices[p] = []

        def map_task(pid, part):
            for batch in part:
                for pi, piece in enumerate(partitioner.split(batch)):
                    if pi in lost and piece.num_rows > 0:
                        shuffle.slices[pi].append(SpillableColumnarBatch(
                            piece, OUTPUT_FOR_SHUFFLE_PRIORITY,
                            shuffle.catalog))

        run_partition_tasks(self.children[0].execute(), map_task)

    def _cleanup(self) -> None:
        sh = getattr(self, "_shuffle", None)
        if sh is not None:
            sh.close_pending()
            self._shuffle = None


class TpuHashExchangeExec(TpuShuffleExchangeExec):
    """Hash exchange for aggregate/join key distribution (partial->final)."""

    CONTRACT = exec_contract(schema="passthrough", partitioning="defined",
                             bound={"by": 0})
    METRICS = TpuShuffleExchangeExec.METRICS   # emits only inherited keys

    def __init__(self, child: TpuExec, num_partitions: int,
                 keys: List[ex.Expression], adaptive_ok: bool = False,
                 adaptive_min_bytes: Optional[int] = None):
        super().__init__(child, num_partitions, by=keys,
                         adaptive_ok=adaptive_ok,
                         adaptive_min_bytes=adaptive_min_bytes)


class TpuRangeExchangeExec(TpuExec):
    """Range exchange for distributed sort (GpuRangePartitioning.scala +
    GpuRangePartitioner.scala:237): sample the child, compute ordered bound
    rows, route every row to the partition owning its key range. Partition i
    of the output holds keys strictly below partition i+1's, so per-partition
    sorts compose into a total order.

    Two passes over spillable handles: accumulate (bounded residency), sample
    bounds, then split — the reference samples with a driver-side reservoir;
    here the sample is a per-batch random gather (~sample_target rows total).
    """

    CONTRACT = exec_contract(schema="passthrough", partitioning="defined",
                             bound={"orders": 0})
    METRICS = exec_metrics("sampleTime", "shuffleWriteTime")

    SAMPLE_TARGET_PER_PARTITION = 100

    def __init__(self, child: TpuExec, num_partitions: int, orders):
        super().__init__(child)
        from ..plan.physical import bind_refs
        from ..plan import logical as lp
        self.num_partitions = max(1, num_partitions)
        self.orders = [lp.SortOrder(bind_refs(o.child, child.schema),
                                    o.ascending, o.nulls_first)
                       for o in orders]

    @property
    def schema(self):
        return self.children[0].schema

    @property
    def output_partitions(self) -> int:
        return self.num_partitions

    def _sample(self, batch: ColumnarBatch, k: int) -> ColumnarBatch:
        import numpy as np
        import jax.numpy as jnp
        from ..columnar.column import bucket
        from ..ops import kernels as K
        n = batch.num_rows
        take = min(n, k)
        rng = np.random.default_rng(42 + n)
        idx = jnp.asarray(np.sort(rng.choice(n, size=take, replace=False)),
                          dtype=jnp.int32)
        live = jnp.arange(len(idx)) < take
        cols = [K.gather_column(c, idx, out_valid=live)
                for c in batch.columns]
        return ColumnarBatch(batch.schema, cols, take)

    def execute(self) -> List[Partition]:
        from ..plan.physical import accumulate_spillable
        from .partitioning import RangePartitioner
        spillables = accumulate_spillable(self.children[0].execute())
        if not spillables:
            def empty():
                return
                yield
            return [empty() for _ in range(self.num_partitions)]
        target = self.SAMPLE_TARGET_PER_PARTITION * self.num_partitions
        per_batch = max(8, -(-target // len(spillables)))
        samples = []
        with trace_span("range_sample", self.metrics, "sampleTime"):
            for s in spillables:
                samples.append(self._sample(s.get_batch(), per_batch))
        partitioner = RangePartitioner(self.num_partitions, self.orders,
                                       samples)
        shuffle = self._shuffle = LocalShuffle(self.num_partitions)
        with trace_span("shuffle_write", self.metrics, "shuffleWriteTime"):
            for s in spillables:
                shuffle.write(partitioner, s.get_batch())
                s.close()
        return [shuffle.read(p, self.schema)
                for p in range(self.num_partitions)]

    def _cleanup(self) -> None:
        sh = getattr(self, "_shuffle", None)
        if sh is not None:
            sh.close_pending()
            self._shuffle = None


class TpuBroadcastExchangeExec(TpuExec):
    """Broadcast exchange: collect the child ONCE into a single spillable
    batch shared by every consumer partition
    (GpuBroadcastExchangeExec.scala:47,238-367 — async driver collect +
    lazy device materialization on executors; standalone, the 'broadcast'
    is one registered spillable buffer re-acquired per stream partition).
    """

    CONTRACT = exec_contract(schema="passthrough", partitioning="single")
    METRICS = exec_metrics("broadcastTime", "dataSize")

    def __init__(self, child: TpuExec):
        super().__init__(child)
        self._handle: Optional[SpillableColumnarBatch] = None
        self._lock = __import__("threading").Lock()

    @property
    def schema(self):
        return self.children[0].schema

    @property
    def output_partitions(self) -> int:
        return 1

    def materialize(self) -> SpillableColumnarBatch:
        """Build (once) and return the shared broadcast handle."""
        from ..plan.physical import accumulate_spillable, concat_spillable
        with self._lock:
            if self._handle is None:
                with trace_span("broadcast_build", self.metrics, "broadcastTime"):
                    batch = concat_spillable(
                        self.schema,
                        accumulate_spillable(self.children[0].execute()))
                self.metrics.inc("dataSize", batch.device_size_bytes())
                self._handle = SpillableColumnarBatch(batch)
            return self._handle

    def execute(self) -> List[Partition]:
        def gen():
            yield self.materialize().get_batch()
        return [gen()]

    def _cleanup(self) -> None:
        with self._lock:
            if self._handle is not None:
                self._handle.close()
                self._handle = None
