"""Shuffle exchange: repartition batches between stages.

Reference: ``GpuShuffleExchangeExec`` (SURVEY.md §2.6) builds a
GpuShuffleDependency with a GpuPartitioning and moves partition slices through
the shuffle manager; ``RapidsCachingWriter`` keeps slices in the spillable
device store instead of writing shuffle files
(RapidsShuffleInternalManager.scala:73-192).

The exchange is TWO-PLANE (docs/shuffle.md, conf
``spark.rapids.tpu.sql.shuffle.plane``):

* **ICI** — with an active device mesh, the whole exchange lowers to one
  fused ``all_to_all`` program (parallel/mesh.run_partition_exchange):
  partitioned rows move device->device over the interconnect, uncompressed,
  and the host reads back ONE counts array per exchange. The TPU analog of
  the reference's device store + RDMA transport (SURVEY.md §2.8, §5).
* **DCN** — the host-staged path below: map side splits each batch with a
  partitioner (slice sizing pipelined through a PipelineWindow so the map
  phase pays O(1) host syncs, not one per batch) and registers the slices
  as spillable buffers; reduce side pulls and concatenates. Multi-process,
  the TCP transfer server (shuffle/transport.py) moves the bytes with the
  shuffle/compression.py codec on the wire; this plane also carries the
  elastic-retry and AQE skew-split machinery the ICI plane does not need.
"""

from __future__ import annotations

import logging
import time
from typing import Any, Dict, List, Optional, Tuple

from ..analysis.contracts import exec_contract
from ..analysis.lockdep import named_lock
from ..columnar import dtypes as dt
from ..columnar.batch import ColumnarBatch
from ..exec.spill import (OUTPUT_FOR_SHUFFLE_PRIORITY, BufferCatalog,
                          SpillableColumnarBatch)
from ..ops import expressions as ex
from ..plan.physical import (Partition, TpuExec, bind_refs, concat_batches,
                             exec_metrics)
from ..exec.tracing import trace_span
from .partitioning import (HashPartitioner, RoundRobinPartitioner,
                           SinglePartitioner, TpuPartitioner)


# ---------------------------------------------------------------------------
# Process-lifetime plane totals (service/telemetry harvest): which plane
# exchanges actually took, how many bytes each moved, and how long — the
# numbers behind the ``tpu_shuffle_gbps{plane=...}`` gauge and the bench
# artifacts' shuffle report. Bumped once per exchange at completion
# boundaries, never per batch.
# ---------------------------------------------------------------------------

log = logging.getLogger("spark_rapids_tpu.shuffle")

_PLANE_TOTALS: Dict[str, float] = {
    "ici_exchanges": 0, "dcn_exchanges": 0,
    "ici_bytes": 0, "dcn_bytes": 0,
    "ici_seconds": 0.0, "dcn_seconds": 0.0,
}
_plane_mu = named_lock("shuffle.exchange._plane_mu")


def note_plane(plane: str, bytes_moved: int, seconds: float) -> None:
    """Record one completed exchange on ``plane`` ('ici' | 'dcn')."""
    with _plane_mu:
        _PLANE_TOTALS[f"{plane}_exchanges"] += 1
        _PLANE_TOTALS[f"{plane}_bytes"] += int(bytes_moved)
        _PLANE_TOTALS[f"{plane}_seconds"] += float(seconds)


def plane_totals() -> Dict[str, float]:
    """Cumulative per-plane exchange totals for this process."""
    with _plane_mu:
        return dict(_PLANE_TOTALS)


# ---------------------------------------------------------------------------
# Stage-boundary exchange statistics (docs/observability.md §8): what an
# exchange ACTUALLY produced, per reduce partition — the feed AQE's
# coalesce/skew decisions read (ROADMAP item 2), recorded at
# materialization on all three planes (local DCN, distributed, ICI).
# ---------------------------------------------------------------------------

#: byte-scale buckets for the per-partition size histogram (the default
#: registry buckets are second-scale)
_PARTITION_BYTE_BUCKETS = (1 << 10, 1 << 14, 1 << 17, 1 << 20, 1 << 23,
                           1 << 26, 1 << 30, float("inf"))


def compute_stage_stats(stage_id: Optional[int], plane: str,
                        rows: List[int], bytes_: List[int],
                        query_id: Optional[str] = None) -> Dict[str, Any]:
    """Derive the stage-boundary statistics of one materialized exchange
    from its per-partition row/byte observations: partition count, p50
    and max partition bytes, and the skew factor (max partition bytes
    over the MEAN partition bytes — 1.0 is perfectly balanced; the AQE
    skew splitter compares this shape against its threshold)."""
    import statistics
    n = len(bytes_)
    total_b = int(sum(bytes_))
    total_r = int(sum(rows))
    p50 = float(statistics.median(bytes_)) if bytes_ else 0.0
    mx = int(max(bytes_)) if bytes_ else 0
    mean = total_b / n if n else 0.0
    skew = round(mx / mean, 4) if mean > 0 else 1.0
    return {"stageId": stage_id, "queryId": query_id, "plane": plane,
            "partitions": n,
            "rows": [int(r) for r in rows],
            "bytes": [int(b) for b in bytes_],
            "totalRows": total_r, "totalBytes": total_b,
            "p50Bytes": p50, "maxBytes": mx, "skew": skew}


def publish_stage_stats(stats: Dict[str, Any]) -> None:
    """Surface one exchange's stage statistics into the continuous
    telemetry layer: per-partition bytes into the
    ``tpu_exchange_partition_bytes`` histogram, the derived shape into
    the last-exchange gauges, and a flight-recorder breadcrumb (kind
    ``stage``, query id auto-stamped by the funnel). Bumped once per
    exchange at materialization, never per batch."""
    from ..service.telemetry import MetricsRegistry, flight_record
    flight_record("stage", f"stage-{stats.get('stageId')}",
                  {k: stats[k] for k in ("plane", "partitions", "totalRows",
                                         "totalBytes", "maxBytes", "skew")})
    try:
        reg = MetricsRegistry.get()
        plane = stats["plane"]
        h = reg.histogram("tpu_exchange_partition_bytes",
                          "post-shuffle partition sizes at exchange "
                          "materialization", _PARTITION_BYTE_BUCKETS,
                          plane=plane)
        for b in stats["bytes"]:
            h.observe(b)
        reg.gauge("tpu_exchange_skew_factor",
                  "last exchange's max/mean partition-size ratio",
                  plane=plane).set(stats["skew"])
        reg.gauge("tpu_exchange_p50_bytes",
                  "last exchange's median partition bytes",
                  plane=plane).set(stats["p50Bytes"])
        reg.gauge("tpu_exchange_max_bytes",
                  "last exchange's largest partition bytes",
                  plane=plane).set(stats["maxBytes"])
    except Exception:
        pass               # telemetry must never fail the exchange


def assign_stage(node) -> None:
    """Draw ``node``'s query id + stage id for THIS execution from the
    ambient query context (exec/query_context.py). Exchange ``execute()``
    runs on the single driving thread during plan-tree construction, so
    stage ids are deterministic per query — lockstep workers number
    their exchanges identically."""
    from ..exec import query_context as qc
    ctx = qc.current()
    node.query_id = ctx.query_id if ctx is not None else None
    node.stage_id = ctx.next_stage_id() if ctx is not None else None
    node.stage_stats = None            # fresh per execution
    node._aqe_decisions = []           # fresh per execution (plan/aqe.py)
    if node.stage_id is not None:
        # a stage-id draw is a lockstep-relevant event: fold it into the
        # per-query divergence digest (analysis/divergence.py)
        from ..analysis import divergence
        divergence.note_event(
            f"stage-id:{node.stage_id}:{type(node).__name__}",
            query_id=node.query_id)


def record_local_shuffle_stats(node, shuffle) -> None:
    """Per-partition rows/bytes from a LocalShuffle's registered
    map-output slices (the local DCN plane's materialization boundary);
    commits + publishes the node's stage statistics. Gated by
    ``sql.metrics.enabled`` — the dataSize AQE feed stays load-bearing
    regardless."""
    from ..exec.metrics import metrics_enabled
    if not metrics_enabled():
        return
    rows: List[int] = []
    bytes_: List[int] = []
    for p in range(node.num_partitions):
        r = b = 0
        for s in shuffle.slices[p]:
            try:
                r += int(s.num_rows)
            except Exception:
                pass           # a closed/lazy slice: rows stay partial
            b += int(getattr(s, "size_bytes", 0) or 0)
        rows.append(r)
        bytes_.append(b)
    node.stage_stats = compute_stage_stats(
        node.stage_id, "dcn", rows, bytes_, query_id=node.query_id)
    publish_stage_stats(node.stage_stats)
    _note_aqe_stats(node)


def _note_aqe_stats(node) -> None:
    """Feed one committed materialization into AQE's fingerprint-keyed
    stage history (plan/aqe.py) — what lets a repeat execution of the
    same structural exchange decide from observed shape before its map
    phase runs (the ICI skew fallback). Best-effort."""
    try:
        from ..plan import aqe
        aqe.note_stage_stats(node)
    except Exception:
        pass               # the history feed must never fail the exchange


def collect_stage_stats(root) -> List[Dict[str, Any]]:
    """Every exchange's stage statistics in an executed plan tree, in
    tree order with the operator name attached —
    ``session.last_stage_stats()``'s data, shaped so the AQE feedback
    loop (ROADMAP item 2) consumes it without rework."""
    out: List[Dict[str, Any]] = []

    def walk(node) -> None:
        st = getattr(node, "stage_stats", None)
        if st:
            out.append({"operator": type(node).__name__, **st})
        for c in getattr(node, "children", ()):
            walk(c)

    walk(root)
    return out


def stage_stats_annotations(root) -> Dict[str, List[str]]:
    """Per-exchange EXPLAIN ANALYZE annotations keyed by the same
    root->node class-name path the contract validator and
    ``stage_compiler.fusion_annotations`` use."""
    out: Dict[str, List[str]] = {}

    def walk(node, path: str, idx: Optional[int] = None) -> None:
        name = type(node).__name__
        here = f"{path}/{idx}.{name}" if path else name
        st = getattr(node, "stage_stats", None)
        if st:
            out[here] = [
                f"* stage {st.get('stageId')} exchange [{st['plane']}]: "
                f"partitions={st['partitions']} rows={st['totalRows']} "
                f"p50Bytes={int(st['p50Bytes'])} "
                f"maxBytes={st['maxBytes']} skew={st['skew']}"]
        for i, c in enumerate(getattr(node, "children", ())):
            walk(c, here, i)

    walk(root, "")
    return out


def shuffle_report(root) -> List[Dict[str, Any]]:
    """Per-exchange shuffle accounting for an executed plan tree: which
    plane each exchange took, bytes written/read, write/fetch seconds and
    the resulting GB/s — the bench artifacts' per-query shuffle story."""
    out: List[Dict[str, Any]] = []

    def walk(node) -> None:
        if isinstance(node, TpuShuffleExchangeExec):
            m = node.metrics
            bw = m.get("shuffleBytesWritten", 0) or 0
            br = m.get("shuffleBytesRead", 0) or 0
            ws = m.get("shuffleWriteTime", 0.0) or 0.0
            fw = m.get("fetchWaitTime", 0.0) or 0.0
            entry: Dict[str, Any] = {
                "exec": type(node).__name__,
                "plane": getattr(node, "plane_used", None),
                "partitions": node.num_partitions,
                "bytesWritten": int(bw), "bytesRead": int(br),
                "writeTimeS": round(float(ws), 4),
                "fetchWaitS": round(float(fw), 4),
            }
            # GB/s definition matches note_plane / tpu_shuffle_gbps:
            # bytes enter the exchange ONCE (the write side) over total
            # exchange seconds — read bytes are reported but not summed
            # into the rate, or the same byte would count twice
            rate = m.gbps(("shuffleBytesWritten",),
                          ("shuffleWriteTime", "fetchWaitTime"))
            if rate is not None:
                entry["gbps"] = round(rate, 6)
            out.append(entry)
        for c in getattr(node, "children", ()):
            walk(c)

    walk(root)
    return out


class LocalShuffle:
    """In-process shuffle state: (reduce partition) -> list of spillable
    slices (ShuffleBufferCatalog analog, scoped to one exchange).

    ``durable`` (conf ``spark.rapids.tpu.sql.shuffle.durable``) keeps
    slices REGISTERED after a read instead of closing them, and pins the
    map outputs through the spill store's disk tier at map-phase end —
    so a reduce-side stage retry re-reads the durable outputs instead of
    re-running the map stage (docs/resilience.md). Slices free at
    ``close_pending`` (exchange cleanup) as before."""

    def __init__(self, num_partitions: int,
                 catalog: Optional[BufferCatalog] = None,
                 durable: bool = False):
        self.num_partitions = num_partitions
        self.catalog = catalog or BufferCatalog.get()
        self.durable = durable
        self.slices: Dict[int, List[SpillableColumnarBatch]] = {
            p: [] for p in range(num_partitions)}

    def write(self, partitioner: TpuPartitioner, batch: ColumnarBatch) -> None:
        for p, piece in enumerate(partitioner.split(batch)):
            if piece.num_rows > 0:
                self.slices[p].append(SpillableColumnarBatch(
                    piece, OUTPUT_FOR_SHUFFLE_PRIORITY, self.catalog))

    def write_deferred(self, window, partitioner: TpuPartitioner,
                       batch: ColumnarBatch) -> None:
        """Pipelined map-side write: dispatch the fused device split now,
        park the slice-sizing scalar in ``window`` (a PipelineWindow), and
        register the slices when the batched readback lands — batch k+1's
        split dispatches before batch k's sizing resolves, so a map phase
        of B batches pays O(1) packed syncs instead of B blocking ones."""
        deferred = partitioner.split_deferred(batch)
        if deferred is None:          # nothing to defer (empty / single)
            self.write(partitioner, batch)
            return
        counts, make_pieces = deferred

        def land(host_counts):
            for p, piece in enumerate(make_pieces(host_counts)):
                if piece.num_rows > 0:
                    self.slices[p].append(SpillableColumnarBatch(
                        piece, OUTPUT_FOR_SHUFFLE_PRIORITY, self.catalog))

        window.push(land, counts)

    def read(self, p: int, schema: dt.Schema) -> Partition:
        pending = self.slices[p]
        batches = []
        for s in pending:
            batches.append(s.get_batch())
            if not self.durable:
                s.close()          # durable outputs stay re-fetchable
        if batches:
            out = concat_batches(schema, batches)
            if self.durable:
                # get_batch re-promoted the pinned slices DISK->DEVICE;
                # re-pin them NOW (before yielding — an abandoned
                # consumer must not strand them device-resident) so only
                # the in-flight partition holds HBM, keeping
                # pin_outputs_to_disk's discipline across reads. Safe
                # even when ``out`` aliases a demoted buffer's arrays
                # (single-slice concat short-circuit): jax arrays are
                # immutable and acquire_batch marked the batch shared,
                # so no downstream program can donate them.
                del batches
                for s in pending:
                    s.pin_to_disk()
            yield out

    def pin_outputs_to_disk(self) -> int:
        """Durable tier: push every registered slice through to the disk
        tier of the spill store (the checkpoint write of SURVEY §5
        "Checkpoint / resume" — paid once at map-phase end, bounding the
        memory the retained outputs hold). Returns bytes pinned."""
        pinned = 0
        for pending in self.slices.values():
            for s in pending:
                if not s._closed:
                    pinned += s.pin_to_disk()
        return pinned

    def read_slices(self, p: int, lo: int, hi: int,
                    schema: dt.Schema) -> Partition:
        """A mapper-subset read of reduce partition ``p``: slices
        [lo, hi) only — the partial-mapper partition spec behind AQE skew
        splitting (ShuffledBatchRDD.scala:202 PartialMapperPartitionSpec)."""
        batches = []
        for s in self.slices[p][lo:hi]:
            batches.append(s.get_batch())
            s.close()
        if batches:
            yield concat_batches(schema, batches)

    def read_row_chunk(self, p: int, idx: int, chunk: int, n_chunks: int,
                       schema: dt.Schema) -> Partition:
        """Row-range read of one slice of partition ``p``: chunk
        ``chunk``/``n_chunks`` by row position — sub-mapper granularity
        for the single-giant-slice skew case (finer than the reference's
        map-block granularity; columnar row gathers make it cheap). The
        slice is SHARED by its chunks, so it is not closed here —
        ``close_pending`` releases it at exchange cleanup."""
        import jax.numpy as jnp
        from ..columnar.column import bucket
        from ..ops import kernels as K
        b = self.slices[p][idx].get_batch()
        n = b.num_rows
        lo = (n * chunk) // n_chunks
        hi = (n * (chunk + 1)) // n_chunks
        count = hi - lo
        if count <= 0:
            return
        cap = bucket(max(count, 1))
        live = jnp.arange(cap) < count
        idxs = jnp.where(live, jnp.arange(cap, dtype=jnp.int32) + lo, 0)
        cols = [K.gather_column(c, idxs, out_valid=live)
                for c in b.columns]
        yield ColumnarBatch(schema, cols, count)

    def close_pending(self) -> None:
        """Release slices never pulled (early-terminating consumers)."""
        for pending in self.slices.values():
            for s in pending:
                if not s._closed:
                    s.close()


class TpuShuffleExchangeExec(TpuExec):
    """Repartition(n) / repartition(n, cols) exchange.

    ``adaptive_ok``: the planner marks exchanges whose consumer tolerates a
    runtime-reduced partition count (aggregates: merged partitions keep key
    ownership disjoint) — those coalesce small post-shuffle partitions from
    OBSERVED map-side sizes, the AQE + GpuCustomShuffleReaderExec behavior
    (GpuOverrides.scala:1920). Join exchanges stay fixed: both sides must
    keep identical partitioning."""

    CONTRACT = exec_contract(schema="passthrough", partitioning="defined",
                             extras=("exchange_plane",))
    METRICS = exec_metrics("dataSize", "shuffleWriteTime", "fetchWaitTime",
                           "shuffleBytesWritten", "shuffleBytesRead",
                           "iciExchanges", "dcnExchanges",
                           "skewSplitPartitions", "skewSplitTasks",
                           "coalescedPartitions", "fetchFailedRetries",
                           "stageRetries")

    def __init__(self, child: TpuExec, num_partitions: int,
                 by: Optional[List[ex.Expression]] = None,
                 adaptive_ok: bool = False,
                 adaptive_min_bytes: Optional[int] = None,
                 plane: str = "auto", mesh=None,
                 split_depth: Optional[int] = None):
        super().__init__(child)
        self.num_partitions = max(1, num_partitions)
        self.by = [bind_refs(e, child.schema) for e in by] if by else None
        self.adaptive_ok = adaptive_ok
        # resolved at PLAN time from the session conf (exec-level TpuConf()
        # would read global defaults, not the session's settings)
        self.adaptive_min_bytes = adaptive_min_bytes
        self.coalesced_to: Optional[int] = None    # runtime observation
        # data-plane routing (spark.rapids.tpu.sql.shuffle.plane), also
        # plan-time-resolved: 'auto' rides the mesh the planner handed us
        # (None when no mesh is active or the stage is too large to stage
        # device-resident), 'ici' forces collectives, 'dcn' forces the
        # host/TCP path. plane_used records the runtime decision.
        self.plane = plane
        self.mesh = mesh
        self.split_depth = split_depth
        self.plane_used: Optional[str] = None
        # query-lifecycle identity + the exchange's stage-boundary
        # statistics (docs/observability.md §8): assigned at execute time
        # from the ambient query context, refreshed per execution (cached
        # plan trees re-execute under new query ids)
        self.query_id: Optional[str] = None
        self.stage_id: Optional[int] = None
        self.stage_stats: Optional[Dict[str, Any]] = None

    @property
    def schema(self):
        return self.children[0].schema

    @property
    def output_partitions(self) -> int:
        return self.num_partitions

    def _make_partitioner(self) -> TpuPartitioner:
        if self.num_partitions == 1:
            return SinglePartitioner()
        if self.by:
            return HashPartitioner(self.num_partitions, self.by)
        return RoundRobinPartitioner(self.num_partitions)

    def _split_window_depth(self) -> int:
        if self.split_depth is not None:
            return max(1, int(self.split_depth))
        from .. import config as cfg
        return max(1, int(cfg.TpuConf().get(cfg.SHUFFLE_PIPELINE_DEPTH)))

    def _run_map_phase(self, shuffle) -> None:
        """Map side: split every upstream batch and register the slices,
        one task per upstream partition, drained concurrently (shared by
        the local, distributed, and skew-split execute forms). Slice
        sizing is PIPELINED: each task parks its batches' packed split
        counts in a PipelineWindow, so the sizing readbacks land in O(1)
        batched resolves per task instead of one blocking readback per
        batch (the host-plane half of the device-resident shuffle)."""
        from ..analysis import faults
        from ..exec import recovery
        from ..exec.pipeline import PipelineWindow
        from ..exec.tasks import run_partition_tasks
        partitioner = self._make_partitioner()
        depth = self._split_window_depth()
        written: List[int] = []            # per-task input bytes
        t0 = time.perf_counter()

        def map_task(pid, part):
            win = PipelineWindow(depth, metrics=self.metrics)
            local_bytes = 0
            for bi, batch in enumerate(part):
                if faults.armed() and faults.fire("task.poison",
                                                  pid=pid, batch=bi):
                    raise recovery.InjectedTaskFault(
                        f"injected task poison (partition {pid}, "
                        f"batch {bi})")
                shuffle.write_deferred(win, partitioner, batch)
                local_bytes += batch.device_size_bytes()
            win.flush()
            written.append(local_bytes)    # GIL-atomic append

        with trace_span("shuffle_write", self.metrics, "shuffleWriteTime"):
            run_partition_tasks(self.children[0].execute(), map_task)
        if getattr(shuffle, "durable", False):
            shuffle.pin_outputs_to_disk()
        # metrics commit only on map-phase SUCCESS: a failed attempt's
        # partial bytes must not pollute dataSize (the AQE broadcast
        # switch reads it) or the shuffle write totals on a recovered run
        total = sum(written)
        self.metrics.inc("dataSize", total)
        self.metrics.inc("shuffleBytesWritten", total)
        self.metrics.inc("dcnExchanges")
        note_plane("dcn", total, time.perf_counter() - t0)

    def _assign_stage(self) -> None:
        assign_stage(self)

    def _finish_stage_stats(self, plane: str, rows: List[int],
                            bytes_: List[int]) -> None:
        """Commit + publish this exchange's materialization statistics
        (stats collection rides the sql.metrics.enabled gate; the
        dataSize AQE feed stays load-bearing regardless)."""
        from ..exec.metrics import metrics_enabled
        if not metrics_enabled():
            return
        self.stage_stats = compute_stage_stats(
            self.stage_id, plane, rows, bytes_, query_id=self.query_id)
        publish_stage_stats(self.stage_stats)
        _note_aqe_stats(self)

    def _record_local_stats(self, shuffle: "LocalShuffle") -> None:
        record_local_shuffle_stats(self, shuffle)

    def execute(self) -> List[Partition]:
        from .manager import WorkerContext
        self._assign_stage()
        ctx = WorkerContext.current
        plane = self._resolve_plane(ctx)
        self.plane_used = plane
        if ctx is not None:
            return self._execute_distributed(ctx)
        if plane == "ici":
            return self._execute_ici()
        shuffle = self._local_map_with_retry()
        self._record_local_stats(shuffle)
        groups = self._reduce_groups(shuffle)
        return [self._read_group(shuffle, g) for g in groups]

    def _local_map_with_retry(self) -> LocalShuffle:
        """Local map phase under the stage-retry discipline
        (exec/recovery.py): an injected task fault or a recoverable
        upstream failure discards the half-written shuffle and
        re-executes the map from its (deterministic or not — nothing
        was consumed yet) inputs. Shared by :meth:`execute` and the
        skew-split path."""
        from ..exec import recovery

        def attempt():
            # an OUTER exchange's stage retry re-executes this whole
            # subtree: a stale _shuffle from the prior execution would be
            # orphaned by the reassignment below with its slices still
            # registered in the catalog — release it first (idempotent;
            # the normal path nulls _shuffle at query cleanup)
            stale = getattr(self, "_shuffle", None)
            if stale is not None:
                stale.close_pending()
            shuffle = LocalShuffle(self.num_partitions,
                                   durable=recovery.shuffle_durable())
            self._shuffle = shuffle
            self._run_map_phase(shuffle)
            return shuffle

        def discard(exc, attempt_no):
            self.metrics.inc("stageRetries")
            sh = getattr(self, "_shuffle", None)
            if sh is not None:
                sh.close_pending()     # release the partial map outputs

        return recovery.retry_stage("shuffle-map", attempt,
                                    on_retry=discard)

    # -- plane routing -------------------------------------------------------

    def _ici_capable(self) -> bool:
        """The fused ICI exchange carries flat primitive/string columns
        (mesh._rebuild_columns' array protocol); structs and other nested
        layouts stay on the host plane."""
        for f in self.schema:
            t = f.dtype
            if dt.is_struct(t) or dt.is_map(t) or dt.is_array(t):
                return False
            if t.var_width and t != dt.STRING:
                return False
        return True

    def _resolve_plane(self, ctx) -> str:
        """'ici' or 'dcn' for THIS execution. ``auto`` takes collectives
        exactly when the planner handed us a mesh and the shape qualifies;
        a forced ``ici`` that cannot run is a loud error, never a silent
        downgrade (the mesh.enabled=true contract)."""
        plane = (self.plane or "auto").lower()
        if plane == "dcn":
            return "dcn"
        forced = plane == "ici"
        if ctx is not None:
            # multi-process workers reach each other over DCN only; their
            # chips are not one mesh
            if forced:
                raise RuntimeError(
                    "spark.rapids.tpu.sql.shuffle.plane=ici is invalid "
                    "under a multi-process WorkerContext: peer chips are "
                    "not one ICI mesh — use auto or dcn")
            return "dcn"
        if self.mesh is None or int(self.mesh.devices.size) < 2:
            if forced:
                raise RuntimeError(
                    "spark.rapids.tpu.sql.shuffle.plane=ici but no device "
                    "mesh is active (spark.rapids.tpu.sql.mesh.enabled)")
            return "dcn"
        # mesh-participant loss (real or chaos-injected): the ICI plane
        # declines GRACEFULLY to DCN under auto — dispatching a
        # collective onto a mesh missing a participant would hang, and
        # the host plane carries the exchange correctly, just slower.
        # Forced ici stays a loud error (the mesh.enabled=true contract)
        from ..analysis import faults
        from ..exec import recovery
        if faults.armed() and faults.fire("mesh.drop"):
            recovery.note_mesh_lost(faults.INJECTED_MESH_DROP_REASON)
        lost = recovery.mesh_lost()
        if lost is not None:
            if forced:
                raise RuntimeError(
                    "spark.rapids.tpu.sql.shuffle.plane=ici but the ICI "
                    f"mesh lost a participant ({lost})")
            return "dcn"
        if self.num_partitions == 1:
            return "dcn"          # single sink: nothing to exchange
        if not self._ici_capable():
            if forced:
                raise RuntimeError(
                    "spark.rapids.tpu.sql.shuffle.plane=ici but the "
                    f"exchange schema [{self.schema}] carries nested "
                    "columns the fused collective cannot move")
            return "dcn"
        return "ici"

    def would_use_ici(self) -> bool:
        """Plane this exchange WILL take if executed now (consumers like
        the AQE skew splitter ask before running the map phase: the
        device-resident plane has no per-slice observed sizes to split
        on, so skew handling stays a host-plane feature)."""
        from .manager import WorkerContext
        return self._resolve_plane(WorkerContext.current) == "ici"

    def _execute_ici(self) -> List[Partition]:
        """Device-resident exchange: shard the child across the mesh,
        route every row to its reduce partition's owning worker through
        one fused ``all_to_all`` program, and slice each worker's
        pid-sorted rows into its owned partitions. Payload bytes never
        touch the host; the one readback is the counts array."""
        from ..parallel import mesh as M
        from ..parallel.mesh_exec import shard_for_mesh
        mesh = self.mesh
        n = int(mesh.devices.size)
        t0 = time.perf_counter()
        with trace_span("shuffle_write", self.metrics, "shuffleWriteTime"):
            shards = shard_for_mesh(self.children[0], n)
            moved = 0
            for s in shards:
                moved += s.device_size_bytes()
                self.metrics.inc("dataSize", s.device_size_bytes())
            self.metrics.inc("shuffleBytesWritten", moved)
            partitioner = self._make_partitioner()
            pids = [partitioner.partition_ids(s) for s in shards]
            results = self._ici_results = M.run_partition_exchange(
                mesh, shards, pids, self.num_partitions)
        self.metrics.inc("iciExchanges")
        note_plane("ici", moved, time.perf_counter() - t0)
        # stage-boundary statistics from the ONE counts readback that
        # already came home: per-partition rows are the column sums of
        # the [n, num_partitions] counts; bytes are estimated from the
        # exchange's fixed-width row footprint (moved / total rows) —
        # the ICI plane never stages per-slice host bytes to measure
        counts = [r[1] for r in results]
        rows = [int(sum(int(c[p]) for c in counts))
                for p in range(self.num_partitions)]
        total_rows = sum(rows)
        bpr = (moved / total_rows) if total_rows else 0.0
        self._finish_stage_stats("ici", rows,
                                 [int(r * bpr) for r in rows])

        def gen(p: int) -> Partition:
            from ..columnar.column import bucket
            from ..ops import kernels as K
            cols_w, counts_w = self._ici_results[p % n]
            count = int(counts_w[p])
            if count <= 0:
                return
            offset = int(counts_w[:p].sum())
            with trace_span("shuffle_fetch", self.metrics, "fetchWaitTime"):
                pcap = bucket(count)
                cols = [K.slice_column(c, offset, pcap, count)
                        for c in cols_w]
                out = ColumnarBatch(self.schema, cols, count)
            self.metrics.inc("shuffleBytesRead", out.device_size_bytes())
            yield out

        return [gen(p) for p in range(self.num_partitions)]

    def execute_skew(self, threshold: int,
                     factor: Optional[float] = None
                     ) -> List[List[Partition]]:
        """AQE skew-split form of :meth:`execute` (local mode): run the
        map phase, then return per reduce partition a LIST of
        sub-partitions — one when under ``threshold`` observed bytes,
        multiple mapper-subset reads (partial-mapper partition specs,
        ShuffledBatchRDD.scala:202) when a hot partition exceeds it. The
        caller (skewed join) keeps the other side aligned per ORIGINAL
        partition index. Unsplit partitions keep the elastic-recovery
        read path; SPLIT chunks cannot re-run the map phase safely (other
        chunks of the same partition may already be consumed against the
        old slice boundaries), so a lost buffer there aborts loudly."""
        from .manager import WorkerContext
        assert WorkerContext.current is None, \
            "skew split is a local-mode path"
        self._assign_stage()
        self.plane_used = "dcn"       # skew split is a host-plane feature
        shuffle = self._local_map_with_retry()
        self._record_local_stats(shuffle)
        # effective cut line: at least ``threshold`` bytes, raised to
        # ``factor x median partition bytes`` when that is higher — a
        # partition must be both large AND an outlier among its siblings
        # (plan/aqe.py's skewedPartitionFactor rule)
        totals = [sum(s.size_bytes for s in shuffle.slices[p])
                  for p in range(self.num_partitions)]
        import statistics
        from ..plan import aqe
        median = float(statistics.median(totals)) if totals else 0.0
        eff = aqe.effective_skew_threshold(threshold, factor, median)
        out: List[List[Partition]] = []
        for p in range(self.num_partitions):
            sizes = [s.size_bytes for s in shuffle.slices[p]]
            total = totals[p]
            if total <= eff:
                out.append([self._read_group(shuffle, [p])])
                continue
            if len(sizes) < 2:
                # one giant map slice: split by row ranges instead
                n_chunks = min(-(-total // eff), 64)
                chunks = [shuffle.read_row_chunk(p, 0, c, n_chunks,
                                                 self.schema)
                          for c in range(n_chunks)]
            else:
                # split on slice (mapper-output) boundaries into chunks
                # of ~eff bytes, at least one slice each
                chunks = []
                lo = 0
                acc = 0
                for i, sz in enumerate(sizes):
                    acc += sz
                    if acc >= eff and i + 1 > lo:
                        chunks.append(shuffle.read_slices(p, lo, i + 1,
                                                          self.schema))
                        lo, acc = i + 1, 0
                if lo < len(sizes):
                    chunks.append(shuffle.read_slices(p, lo, len(sizes),
                                                      self.schema))
            self.metrics.inc("skewSplitPartitions")
            self.metrics.inc("skewSplitTasks", len(chunks))
            out.append([self._loud_chunk(c, p) for c in chunks])
        return out

    def _loud_chunk(self, chunk: Partition, p: int) -> Partition:
        """Split-chunk reads abort with CONTEXT on lost buffers instead
        of recovering — re-running the map phase would move the slice/row
        boundaries under chunks that were already consumed."""
        from ..exec.spill import BufferLostError
        try:
            yield from chunk
        except BufferLostError as e:  # lint: recover-ok deliberate FAIL_QUERY: consumed sibling chunks pin the old slice boundaries, re-execution is unsafe here
            raise RuntimeError(
                f"skew-split chunk of shuffle partition {p} lost a "
                f"buffer; map-stage retry is unsafe for split chunks "
                f"(consumed siblings pin the old boundaries): {e}") from e

    def plan_fingerprint(self) -> str:
        """Structural hash of this exchange's plan subtree: exec class
        names + output schemas + the partitioning KEY EXPRESSIONS,
        recursively. Deliberately EXCLUDES data-dependent detail (row
        counts, shard paths) so every worker running the same logical
        query computes the same value, while structurally different
        exchanges — including two identical trees hash-partitioned on
        different columns, the exact silent-wrong-data signature —
        compute different ones."""
        import hashlib

        def desc(node) -> str:
            try:
                sch = ",".join(f"{f.name}:{f.dtype.name}"
                               for f in node.schema)
            except Exception:
                sch = "?"
            kids = ";".join(desc(c) for c in node.children)
            return f"{type(node).__name__}[{sch}]({kids})"
        by = ",".join(repr(e) for e in self.by) if self.by else ""
        s = f"{desc(self)}|n={self.num_partitions}|by={by}"
        return hashlib.sha1(s.encode()).hexdigest()[:16]

    @staticmethod
    def _subtree_allocates_shuffle_ids(node) -> bool:
        """True when ``node``'s subtree holds an exchange that would
        allocate a lockstep shuffle id if re-executed (distributed
        mode's :class:`DistributedShuffle` constructor)."""
        if isinstance(node, TpuShuffleExchangeExec):
            return True
        return any(TpuShuffleExchangeExec._subtree_allocates_shuffle_ids(c)
                   for c in node.children)

    def _execute_distributed(self, ctx) -> List[Partition]:
        """Multi-process mode: map slices register in the worker's
        ShuffleStore (RapidsCachingWriter), reduce partitions this worker
        OWNS read local + peer slices (RapidsCachingReader split); the
        other partitions are empty here — their owners produce them.
        Adaptive coalescing stays off: partition->worker ownership must be
        identical on every worker."""
        from ..exec import recovery
        from .manager import DistributedShuffle
        # the shuffle is created ONCE (its id comes from the lockstep
        # counter — a retry must not consume another id); only the map
        # run retries, resetting this worker's partial outputs first.
        # Safe because peers cannot have fetched yet: completion is only
        # marked after the retry loop succeeds
        shuffle = self._shuffle = DistributedShuffle(
            self.num_partitions, ctx, fingerprint=self.plan_fingerprint())

        def attempt():
            self._run_map_phase(shuffle)

        def discard(exc, attempt_no):
            self.metrics.inc("stageRetries")
            shuffle.reset_outputs()

        # a retry re-executes the whole child subtree; if that subtree
        # holds ANOTHER exchange, re-running it would consume a fresh
        # lockstep shuffle id on THIS worker only, desyncing the id /
        # fingerprint streams from peers (each budget attempt would then
        # burn a full fetch timeout against a shuffle no peer completes).
        # Query-namespaced ids (shuffle/manager.py) do NOT lift this:
        # namespacing fixes id COLLISION across queries, not lockstep
        # AGREEMENT within one — the retried child exchange is a
        # distributed barrier that peers (who saw no failure) never
        # re-enter, so one worker re-running it alone can never complete
        # it under any namespace. Recovery stays declined — the fault
        # propagates unmasked instead of wedging (docs/resilience.md
        # "nested-exchange maps")
        nested = self._subtree_allocates_shuffle_ids(self.children[0])

        def gate(exc):
            if nested:
                log.warning(
                    "shuffle-map retry declined: child subtree holds "
                    "another exchange (lockstep id streams cannot "
                    "re-execute on one worker); propagating %s",
                    type(exc).__name__)
                return False
            return True

        recovery.retry_stage("shuffle-map", attempt, on_retry=discard,
                             retryable=gate)
        shuffle.finish_writes()
        self._record_distributed_stats(shuffle, ctx)

        def owned(p):
            with trace_span("shuffle_fetch", self.metrics, "fetchWaitTime"):
                for b in shuffle.read(p, self.schema):
                    self.metrics.inc("shuffleBytesRead",
                                     b.device_size_bytes())
                    yield b

        def empty():
            return
            yield

        return [owned(p) if ctx.owns_reduce(p) else empty()
                for p in range(self.num_partitions)]

    def _record_distributed_stats(self, shuffle, ctx) -> None:
        """Per-partition rows/bytes of THIS worker's map outputs, read
        from the shuffle store's registered buffer metadata (the
        distributed plane's materialization boundary). Each worker
        records its own map-side contribution; the union across workers
        is the exchange's global shape — summing here would cost a
        cross-worker round trip per exchange."""
        from ..exec.metrics import metrics_enabled
        if not metrics_enabled():
            return
        rows = [0] * self.num_partitions
        bytes_ = [0] * self.num_partitions
        try:
            metas = ctx.store.metas(shuffle.shuffle_id,
                                    list(range(self.num_partitions)))
            for m in metas:
                if 0 <= m.reduce_id < self.num_partitions:
                    rows[m.reduce_id] += int(m.num_rows)
                    bytes_[m.reduce_id] += int(m.total_bytes)
        except Exception:
            return             # stats must never fail the exchange
        self._finish_stage_stats("dcn", rows, bytes_)

    def _reduce_groups(self, shuffle: LocalShuffle) -> List[List[int]]:
        """Adaptive partition coalescing: group adjacent reduce partitions
        below minPartitionSize using the map side's observed slice sizes
        (the grouping itself is plan/aqe.py's coalesce rule; this method
        feeds it the observations and records the decision)."""
        all_parts = [[p] for p in range(self.num_partitions)]
        if not self.adaptive_ok or not self.adaptive_min_bytes:
            return all_parts
        target = int(self.adaptive_min_bytes)
        sizes = [sum(s.size_bytes for s in shuffle.slices[p])
                 for p in range(self.num_partitions)]
        from ..plan import aqe
        groups = aqe.plan_coalesce(sizes, target)
        self.coalesced_to = len(groups)
        if len(groups) < self.num_partitions:
            self.metrics.inc("coalescedPartitions",
                             self.num_partitions - len(groups))
            aqe.record_decision(
                self, "coalesce", stage_id=self.stage_id,
                before=f"{self.num_partitions} partitions",
                after=f"{len(groups)} partitions",
                reason=(f"observed {sum(sizes)}B across "
                        f"{self.num_partitions} partitions; target "
                        f"{target}B per task"))
        return groups

    def _read_group(self, shuffle: LocalShuffle, group: List[int]) -> Partition:
        """Reduce-side read with ELASTIC RECOVERY: a failed fetch (lost /
        released buffers, transport give-up) re-executes up to
        ``recovery.maxStageRetries`` times with backoff — the analog of
        RapidsShuffleFetchFailedException -> Spark FetchFailed -> map-stage
        retry (RapidsShuffleIterator.scala:28,49). With DURABLE outputs
        the retry re-reads the retained slices; only a genuinely lost
        buffer re-runs the upstream map for the lost partitions."""
        from ..exec import recovery
        from ..exec.spill import BufferLostError
        from .transport import ShuffleFetchError

        def retryable(exc):
            if self.children[0].subtree_deterministic():
                return True
            # a consumed-elsewhere indeterminate map stage would
            # re-partition rows differently on refill; the durable
            # re-read path is still safe (same slices, no re-execution)
            return shuffle.durable and not isinstance(exc, BufferLostError)

        rs = recovery.StageRetryState(f"shuffle-reduce-p{group}",
                                      retryable=retryable)
        from ..exec.lifecycle import check_cancel
        while True:
            check_cancel()       # a cancelled query must not keep retrying
            try:
                with trace_span("shuffle_fetch", self.metrics,
                                "fetchWaitTime"):
                    batches = self._count_read(
                        self._pull_group(shuffle, group))
                rs.succeeded()
                break
            except (ShuffleFetchError, BufferLostError) as e:  # lint: recover-ok the FetchFailed -> map-stage-retry boundary, driven by exec/recovery's budget
                # discard partial state BEFORE the backoff dwell: the
                # failed attempt's half-read slices must not stay pinned
                # through the sleep (the retry_stage discipline)
                rs.failed(e, sleep=False)  # re-raises when not retryable
                self.metrics.inc("fetchFailedRetries")
                self.metrics.inc("stageRetries")
                if not shuffle.durable or isinstance(e, BufferLostError):
                    # no durable tier to re-read (or it lost a buffer):
                    # re-run the upstream map for the lost partitions
                    self._refill(shuffle, group)
                rs.sleep_backoff()
        if batches:
            yield concat_batches(self.schema, batches)

    def _count_read(self, batches: List[ColumnarBatch]
                    ) -> List[ColumnarBatch]:
        """Meter shuffleBytesRead AFTER a group pull succeeds: counting
        inside the pull would leave a failed mid-group attempt's bytes in
        the counter and re-count them on the elastic retry."""
        for b in batches:
            self.metrics.inc("shuffleBytesRead", b.device_size_bytes())
        return batches

    def _pull_group(self, shuffle: LocalShuffle,
                    group: List[int]) -> List[ColumnarBatch]:
        from ..analysis import faults
        from .transport import ShuffleFetchError
        if faults.armed() and faults.fire("fetch.fail"):
            raise ShuffleFetchError(
                f"injected fetch fault (partitions {group})")
        batches = []
        for p in group:
            for b in shuffle.read(p, self.schema):
                batches.append(b)
        return batches

    def _refill(self, shuffle: LocalShuffle, group: List[int]) -> None:
        """Re-run the upstream map tasks, keeping ONLY the lost reduce
        partitions' slices (Spark recomputes lost map outputs from lineage;
        other partitions' refills are discarded). Caller guarantees the
        upstream is deterministic."""
        from ..exec.tasks import run_partition_tasks
        lost = set(group)
        partitioner = self._make_partitioner()
        for p in lost:
            for s in shuffle.slices[p]:
                if not s._closed:     # release survivors before replacing
                    s.close()
            shuffle.slices[p] = []

        def map_task(pid, part):
            for batch in part:
                for pi, piece in enumerate(partitioner.split(batch)):
                    if pi in lost and piece.num_rows > 0:
                        shuffle.slices[pi].append(SpillableColumnarBatch(
                            piece, OUTPUT_FOR_SHUFFLE_PRIORITY,
                            shuffle.catalog))

        run_partition_tasks(self.children[0].execute(), map_task)

    def _cleanup(self) -> None:
        sh = getattr(self, "_shuffle", None)
        if sh is not None:
            sh.close_pending()
            self._shuffle = None
        if getattr(self, "_ici_results", None) is not None:
            self._ici_results = None       # release the device arrays


class TpuHashExchangeExec(TpuShuffleExchangeExec):
    """Hash exchange for aggregate/join key distribution (partial->final)."""

    CONTRACT = exec_contract(schema="passthrough", partitioning="defined",
                             bound={"by": 0}, extras=("exchange_plane",))
    METRICS = TpuShuffleExchangeExec.METRICS   # emits only inherited keys

    def __init__(self, child: TpuExec, num_partitions: int,
                 keys: List[ex.Expression], adaptive_ok: bool = False,
                 adaptive_min_bytes: Optional[int] = None,
                 plane: str = "auto", mesh=None,
                 split_depth: Optional[int] = None):
        super().__init__(child, num_partitions, by=keys,
                         adaptive_ok=adaptive_ok,
                         adaptive_min_bytes=adaptive_min_bytes,
                         plane=plane, mesh=mesh, split_depth=split_depth)


class TpuRangeExchangeExec(TpuExec):
    """Range exchange for distributed sort (GpuRangePartitioning.scala +
    GpuRangePartitioner.scala:237): sample the child, compute ordered bound
    rows, route every row to the partition owning its key range. Partition i
    of the output holds keys strictly below partition i+1's, so per-partition
    sorts compose into a total order.

    Two passes over spillable handles: accumulate (bounded residency), sample
    bounds, then split — the reference samples with a driver-side reservoir;
    here the sample is a per-batch random gather (~sample_target rows total).
    """

    CONTRACT = exec_contract(schema="passthrough", partitioning="defined",
                             bound={"orders": 0})
    METRICS = exec_metrics("sampleTime", "shuffleWriteTime")

    SAMPLE_TARGET_PER_PARTITION = 100

    def __init__(self, child: TpuExec, num_partitions: int, orders):
        super().__init__(child)
        from ..plan.physical import bind_refs
        from ..plan import logical as lp
        self.num_partitions = max(1, num_partitions)
        self.orders = [lp.SortOrder(bind_refs(o.child, child.schema),
                                    o.ascending, o.nulls_first)
                       for o in orders]
        self.query_id: Optional[str] = None
        self.stage_id: Optional[int] = None
        self.stage_stats: Optional[Dict[str, Any]] = None

    @property
    def schema(self):
        return self.children[0].schema

    @property
    def output_partitions(self) -> int:
        return self.num_partitions

    def _sample(self, batch: ColumnarBatch, k: int) -> ColumnarBatch:
        import numpy as np
        import jax.numpy as jnp
        from ..columnar.column import bucket
        from ..ops import kernels as K
        n = batch.num_rows
        take = min(n, k)
        rng = np.random.default_rng(42 + n)
        idx = jnp.asarray(np.sort(rng.choice(n, size=take, replace=False)),
                          dtype=jnp.int32)
        live = jnp.arange(len(idx)) < take
        cols = [K.gather_column(c, idx, out_valid=live)
                for c in batch.columns]
        return ColumnarBatch(batch.schema, cols, take)

    def execute(self) -> List[Partition]:
        from ..plan.physical import accumulate_spillable
        from .partitioning import RangePartitioner
        assign_stage(self)
        spillables = accumulate_spillable(self.children[0].execute())
        if not spillables:
            def empty():
                return
                yield
            return [empty() for _ in range(self.num_partitions)]
        target = self.SAMPLE_TARGET_PER_PARTITION * self.num_partitions
        per_batch = max(8, -(-target // len(spillables)))
        samples = []
        with trace_span("range_sample", self.metrics, "sampleTime"):
            for s in spillables:
                samples.append(self._sample(s.get_batch(), per_batch))
        partitioner = RangePartitioner(self.num_partitions, self.orders,
                                       samples)
        stale = getattr(self, "_shuffle", None)
        if stale is not None:       # re-execution under an outer stage
            stale.close_pending()   # retry: release the orphaned slices
        shuffle = self._shuffle = LocalShuffle(self.num_partitions)
        from .. import config as cfg
        from ..exec.pipeline import PipelineWindow
        win = PipelineWindow(
            max(1, int(cfg.TpuConf().get(cfg.SHUFFLE_PIPELINE_DEPTH))),
            metrics=self.metrics)
        with trace_span("shuffle_write", self.metrics, "shuffleWriteTime"):
            for s in spillables:
                shuffle.write_deferred(win, partitioner, s.get_batch())
                s.close()
            win.flush()
        record_local_shuffle_stats(self, shuffle)
        return [shuffle.read(p, self.schema)
                for p in range(self.num_partitions)]

    def _cleanup(self) -> None:
        sh = getattr(self, "_shuffle", None)
        if sh is not None:
            sh.close_pending()
            self._shuffle = None


class TpuBroadcastExchangeExec(TpuExec):
    """Broadcast exchange: collect the child ONCE into a single spillable
    batch shared by every consumer partition
    (GpuBroadcastExchangeExec.scala:47,238-367 — async driver collect +
    lazy device materialization on executors; standalone, the 'broadcast'
    is one registered spillable buffer re-acquired per stream partition).
    """

    CONTRACT = exec_contract(schema="passthrough", partitioning="single")
    METRICS = exec_metrics("broadcastTime", "dataSize")

    def __init__(self, child: TpuExec):
        super().__init__(child)
        self._handle: Optional[SpillableColumnarBatch] = None
        self._lock = __import__("threading").Lock()

    @property
    def schema(self):
        return self.children[0].schema

    @property
    def output_partitions(self) -> int:
        return 1

    def materialize(self) -> SpillableColumnarBatch:
        """Build (once) and return the shared broadcast handle."""
        from ..plan.physical import accumulate_spillable, concat_spillable
        with self._lock:
            if self._handle is None:
                with trace_span("broadcast_build", self.metrics, "broadcastTime"):
                    batch = concat_spillable(
                        self.schema,
                        accumulate_spillable(self.children[0].execute()))
                self.metrics.inc("dataSize", batch.device_size_bytes())
                self._handle = SpillableColumnarBatch(batch)
            return self._handle

    def execute(self) -> List[Partition]:
        def gen():
            yield self.materialize().get_batch()
        return [gen()]

    def _cleanup(self) -> None:
        with self._lock:
            if self._handle is not None:
                self._handle.close()
                self._handle = None
