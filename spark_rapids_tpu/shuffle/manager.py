"""Multi-process shuffle manager: the local/remote split that lets one
planner-driven query run across worker processes.

Reference mapping (SURVEY.md §2.8, VERDICT round-3 missing #1):
- ``RapidsShuffleInternalManager.scala:200-374`` -> :class:`WorkerContext`
  — per-worker singleton wiring the shuffle store, transfer server, and
  peer addresses (the BlockManagerId topology the reference advertises in
  MapStatus).
- ``RapidsCachingWriter`` (":73-192") -> :meth:`DistributedShuffle.write`
  — map output slices register in the LOCAL store keyed by
  (shuffle_id, reduce partition); nothing is written to disk.
- ``RapidsCachingReader.scala:49-148`` -> :meth:`DistributedShuffle.read`
  — reduce tasks short-circuit local slices straight out of the local
  store and ``ShuffleClient``-fetch remote peers' slices over TCP.

Worker model: every worker runs the SAME logical query over its own local
data shard. Exchange ids are allocated from a per-context counter, so
identical query sequences allocate identical shuffle ids on every worker
(Spark's driver hands out shuffle ids; standalone, the lockstep-query
contract replaces the driver). Reduce-partition ownership is
``p % n_workers == worker_id``; each worker's collect returns the rows of
its owned partitions, and the caller (or a front tier) concatenates.

Map-completion barrier: a reduce-side fetch must not observe a peer's
half-written map output. The writer marks (shuffle_id) complete in its
store after its map phase; the fetch protocol's metadata response carries
the flag and :meth:`ShuffleClient.fetch_when_complete` polls with backoff
until the peer's map is done (the reference gets this ordering for free
from Spark's stage scheduler; the flag replaces it standalone).
"""

from __future__ import annotations

import re
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..analysis.lockdep import named_lock
from ..columnar import dtypes as dt
from ..columnar.batch import ColumnarBatch
from .transport import (ShuffleClient, ShuffleDesyncError, ShuffleFetchError,
                        ShuffleServer, ShuffleStore, ShuffleWorkerLostError,
                        _rebuild_batch)

#: shuffle-id namespace width: ids are ``(query seq << NS_SHIFT) + n``,
#: giving each query its own 2**NS_SHIFT-wide id range (docs/shuffle.md).
#: Query ids are lockstep-deterministic (exec/query_context.py), so every
#: worker derives the SAME namespace for the same query — which is what
#: lets two distributed queries be in flight CONCURRENTLY without
#: desyncing the id stream (the old single global counter interleaved
#: nondeterministically under concurrency).
NS_SHIFT = 20

_QSEQ_RE = re.compile(r"^q(\d+)")


def _query_namespace() -> int:
    """The shuffle-id namespace of the AMBIENT query: its lockstep query
    sequence number (the ``q<seq>`` prefix every worker mints identically
    for the same query), or namespace 0 when no query context is active
    (direct shuffle-layer callers, tests)."""
    from ..exec.query_context import current_query_id
    qid = current_query_id()
    if not qid:
        return 0
    m = _QSEQ_RE.match(qid)
    return int(m.group(1)) if m else 0


class WorkerContext:
    """Per-process shuffle worker state (GpuShuffleEnv + shuffle-manager
    singleton analog). ``current`` activates multi-process shuffle in every
    exchange exec planned afterwards."""

    current: Optional["WorkerContext"] = None
    # class-level: ``current`` is a CLASS attribute, so its two writers
    # (init_worker, shutdown) must share one lock — a per-instance lock
    # would let a dying context's check-then-clear race a fresh
    # init_worker and clobber the new context
    _current_mu = named_lock("shuffle.manager.WorkerContext._current_mu")

    def __init__(self, worker_id: int, n_workers: int,
                 port: int = 0, codec: str = "none",
                 fetch_timeout_s: float = 60.0,
                 durable_dir: Optional[str] = None):
        self.worker_id = worker_id
        self.n_workers = n_workers
        # durable shuffle tier (docs/resilience.md): explicit dir wins;
        # otherwise conf shuffle.durable pins map outputs under the
        # spill dir so a dead worker's rejoin re-serves them. The knobs
        # come from the recovery-primed state (session bootstrap primes
        # it) — a fresh TpuConf() here would only see env/defaults and
        # silently ignore the session's conf
        if durable_dir is None:
            from ..exec import recovery
            if recovery.shuffle_durable():
                import os
                durable_dir = os.path.join(
                    recovery.spill_dir(),
                    f"shuffle-durable-w{worker_id}")
        self.durable_dir = durable_dir
        from ..exec import recovery as _recovery
        self.store = ShuffleStore(
            durable_dir=durable_dir,
            durable_budget=_recovery.durable_max_bytes())
        self.store.release_quorum = n_workers
        if durable_dir:
            # a rejoining worker (fresh process, same durable dir)
            # re-serves the outputs its previous incarnation pinned
            self.store.reload_durable()
        self.server = ShuffleServer(self.store, port=port,
                                    codec=codec).start()
        self.port = self.server.port
        self.codec = codec
        self.peers: Dict[int, Tuple[str, int]] = {}
        self.fetch_timeout_s = fetch_timeout_s
        # per-query-NAMESPACE lockstep counters (LOCKSTEP_IDS registry,
        # analysis/determinism.py), resumed lazily on first mint: each
        # namespace's counter starts PAST any durable-reloaded ids in
        # that namespace — reusing a previous incarnation's shuffle id
        # would merge its rows into a new query and answer peers'
        # completion polls from the stale mark (an id colliding with a
        # peer's LATER exchange fails the fingerprint handshake loudly
        # instead)
        self._next_by_ns: Dict[int, int] = {}
        self._peer_complete: set = set()    # (worker_id, shuffle_id)
        self._lost: set = set()             # failed-send-detected peers
        self._mu = named_lock("shuffle.manager.WorkerContext._mu")

    def set_peers(self, peers: Dict[int, Tuple[str, int]]) -> None:
        """worker_id -> (host, port) for every OTHER worker."""
        self.peers = {int(w): (h, int(p)) for w, (h, p) in peers.items()  # lint: unguarded-ok cluster wiring: set once at startup before any query thread runs
                      if int(w) != self.worker_id}

    def next_shuffle_id(self) -> int:
        """Deterministic across workers running the same query sequence
        (the standalone replacement for driver-issued shuffle ids),
        NAMESPACED by the ambient query: ``(query seq << NS_SHIFT) + n``.
        Two concurrent distributed queries draw from disjoint counters,
        so their interleaving cannot desync the id stream — the gating
        contract for concurrent distributed serving (docs/shuffle.md)."""
        ns = _query_namespace()
        base = ns << NS_SHIFT
        with self._mu:
            nxt = self._next_by_ns.get(ns)
            if nxt is None:
                # first mint in this namespace: resume past the durable
                # tier's ids WITHIN the namespace (a rejoining worker
                # re-serving old outputs must not re-mint their ids)
                nxt = max(base, self.store.durable_max_shuffle_id_in(
                    base, base + (1 << NS_SHIFT))) + 1
            sid = nxt
            self._next_by_ns[ns] = sid + 1
        # the mint is a lockstep-relevant event: fold it into the
        # per-query divergence digest (outside the mutex — the audit
        # takes its own leaf lock and may flight-record)
        from ..analysis import divergence
        divergence.note_event(f"shuffle-id:{sid}")
        return sid

    def owns_reduce(self, p: int) -> bool:
        return p % self.n_workers == self.worker_id

    def client_for(self, worker_id: int) -> ShuffleClient:
        host, port = self.peers[worker_id]
        return ShuffleClient.for_address(host, port)

    # -- liveness / death / rejoin ------------------------------------------
    def mark_worker_lost(self, worker_id: int,
                         exc: Optional[BaseException] = None) -> None:
        """Failed-send detection: record the peer as dead (telemetry
        counter + flight record; idempotent per loss episode)."""
        with self._mu:
            fresh = worker_id not in self._lost
            self._lost.add(worker_id)
        if fresh:
            from ..exec import recovery
            recovery.note_worker_lost(worker_id, exc)

    def is_worker_lost(self, worker_id: int) -> bool:
        with self._mu:
            return worker_id in self._lost

    def lost_workers(self) -> List[int]:
        with self._mu:
            return sorted(self._lost)

    def admit_worker(self, worker_id: int,
                     address: Optional[Tuple[str, int]] = None) -> None:
        """(Re-)admit a peer: update its address when given and clear
        the lost mark — the rejoin half of death/rejoin. A worker that
        restarted with a durable store re-serves its old outputs, so
        in-flight stage retries recover without re-running map stages."""
        with self._mu:
            was_lost = worker_id in self._lost
            self._lost.discard(worker_id)
            if address is not None:
                self.peers[worker_id] = (address[0], int(address[1]))
        if was_lost:
            from ..exec import recovery
            recovery.note_worker_rejoin(worker_id)

    def probe_peer(self, worker_id: int, timeout_s: float = 1.0) -> bool:
        """Cheap liveness heartbeat: one metadata round trip against the
        peer's transfer server (shuffle 0 is never registered, so the
        reply content is irrelevant — answering at all means alive)."""
        from .wire import META_REQ, FrameReader, encode_frame
        import socket as _socket
        host, port = self.peers[worker_id]
        conn = None
        try:
            sock = _socket.create_connection((host, port),
                                             timeout=timeout_s)
            from .transport import SocketConnection
            conn = SocketConnection(sock)
            conn.send(encode_frame(META_REQ, {"shuffle_id": 0,
                                              "reduce_ids": []}))
            FrameReader(conn.read_exact).next_frame()
            return True
        except (ConnectionError, OSError):
            return False
        finally:
            if conn is not None:
                conn.close()

    def restart_server(self) -> int:
        """Restart this worker's transfer server on its ORIGINAL port
        (peers keep their address book) — the in-process rejoin after an
        injected or real server death. Returns the bound port."""
        old = self.server
        try:
            old.stop()
        except Exception:
            pass
        server = ShuffleServer(self.store, port=self.port,
                               codec=self.codec).start()
        with self._mu:
            self.server = server
            self.port = server.port
        return server.port

    def fetch_from_peer(self, worker_id: int, shuffle_id: int,
                        reduce_ids: List[int],
                        fingerprint: Optional[str] = None):
        """One peer fetch under the stage-retry discipline
        (exec/recovery.py): a desync aborts immediately; a dead worker
        is marked lost and probed on its OWN wall-clock window (one
        fetch timeout per budget attempt — liveness probes are not
        stage retries, so they neither consume the budget nor count in
        ``tpu_stage_retries_total``); a rejoined server (durable
        outputs re-served) is re-admitted and the fetch re-executes
        from those durable inputs; stragglers/released outputs retry on
        the same budget. The budget exhausted, the original loud error
        propagates (partial rows are never returned)."""
        import time as _time
        from ..exec import recovery
        rs = recovery.StageRetryState(f"fetch-peer{worker_id}")
        while True:
            try:
                out = self._fetch_attempt(worker_id, shuffle_id,
                                          reduce_ids, fingerprint)
                rs.succeeded()
                if rs.attempts:
                    # the peer answered after a loss episode: re-admit
                    self.admit_worker(worker_id)
                return out
            except ShuffleWorkerLostError as e:  # lint: recover-ok failed-send detection: marks the peer lost, then routes into the recovery retry loop
                self.mark_worker_lost(worker_id, e)
                # sleep=False: the probe loop below paces itself from
                # 50ms — prepending the stage-retry backoff would only
                # delay the millisecond-scale dead-peer probe this
                # method exists to provide
                rs.failed(e, sleep=False)  # re-raises when budget exhausted
                # probe window: a dead peer fails each probe in
                # milliseconds instead of burning a full fetch timeout;
                # the window expiring just returns to the fetch attempt,
                # which re-fails and consumes the NEXT budget unit
                deadline = _time.monotonic() + max(self.fetch_timeout_s,
                                                   0.5)
                wait = 0.05
                while not self.probe_peer(worker_id):
                    if _time.monotonic() > deadline:
                        break
                    _time.sleep(wait)
                    wait = min(wait * 2, 1.0)
                else:
                    self.admit_worker(worker_id)
            except ShuffleFetchError as e:  # lint: recover-ok straggler/released-output failures route into the recovery retry loop (desync FAIL_QUERYs inside)
                rs.failed(e)           # desync/protocol re-raise inside

    def _fetch_attempt(self, worker_id: int, shuffle_id: int,
                       reduce_ids: List[int],
                       fingerprint: Optional[str] = None):
        """One fetch attempt with per-(peer, shuffle) completion caching:
        map completion is monotonic, so only the FIRST fetch per
        peer+shuffle pays the completion-poll round trips. Failures
        surface LOUDLY and with the right label: a desync keeps its type
        (wrong-pairing detection); connection-rooted failures become
        :class:`ShuffleWorkerLostError` naming the peer; protocol/
        straggler failures (released outputs, live-but-slow map phase)
        keep their ShuffleFetchError identity with the peer id prepended
        — a slow worker is not a dead worker."""
        client = self.client_for(worker_id)
        key = (worker_id, shuffle_id)
        with self._mu:
            complete = key in self._peer_complete
        try:
            if complete:
                return client.fetch(shuffle_id, reduce_ids,
                                    fingerprint=fingerprint)
            out = client.fetch_when_complete(
                shuffle_id, reduce_ids, timeout_s=self.fetch_timeout_s,
                fingerprint=fingerprint)
        except ShuffleDesyncError as e:  # lint: recover-ok relabeling boundary: prepends the peer id, keeps the type, never retries
            raise ShuffleDesyncError(
                f"worker {worker_id}: {e}") from e
        except ShuffleFetchError as e:  # lint: recover-ok relabeling boundary: maps connection-rooted failures to worker-lost for the recovery loop above
            if isinstance(e.__cause__, (ConnectionError, OSError)):
                raise ShuffleWorkerLostError(
                    worker_id,
                    f"worker {worker_id} lost while fetching shuffle "
                    f"{shuffle_id} partitions {reduce_ids}: {e}") from e
            raise ShuffleFetchError(
                f"worker {worker_id}: {e}") from e
        with self._mu:
            self._peer_complete.add(key)
        return out

    def release_shuffle(self, shuffle_id: int) -> None:
        """This worker finished ALL reads of ``shuffle_id``: ack locally
        and notify every peer (fire-and-forget). Each store frees the
        shuffle's outputs once the full quorum has acked."""
        self.store.add_release(shuffle_id, self.worker_id)
        for wid in sorted(self.peers):
            self.client_for(wid).send_release(shuffle_id, self.worker_id)

    def allreduce_bytes(self, tag: int, value: int) -> int:
        """Sum one integer across all workers through the shuffle store
        (the control-plane allreduce behind mesh-consistent runtime
        decisions — every worker computes the SAME total, so adaptive
        branches stay lockstep). ``tag`` keys a reserved negative shuffle
        namespace so control values never collide with data shuffles."""
        ctrl_sid = -abs(int(tag))
        batch = ColumnarBatch.from_pydict({"v": [int(value)]})
        self.store.register_batch(ctrl_sid, self.worker_id,
                                  batch.fetch_to_host())
        self.store.mark_complete(ctrl_sid)
        total = int(value)
        for wid in sorted(self.peers):
            for b in self.fetch_from_peer(wid, ctrl_sid, [wid]):
                total += int(b.rows()[0][0])
        self.release_shuffle(ctrl_sid)
        return total

    def shutdown(self) -> None:
        self.server.stop()
        with WorkerContext._current_mu:
            if WorkerContext.current is self:
                WorkerContext.current = None


def init_worker(worker_id: int, n_workers: int, port: int = 0,
                codec: str = "none", fetch_timeout_s: float = 60.0,
                durable_dir: Optional[str] = None) -> WorkerContext:
    """Bootstrap this process as shuffle worker ``worker_id`` (the
    RapidsExecutorPlugin.init analog). Returns the context; call
    ``set_peers`` once every worker's port is known."""
    ctx = WorkerContext(worker_id, n_workers, port, codec,
                        fetch_timeout_s=fetch_timeout_s,
                        durable_dir=durable_dir)
    with WorkerContext._current_mu:
        WorkerContext.current = ctx
    return ctx


class DistributedShuffle:
    """LocalShuffle-compatible exchange state backed by the worker's
    ShuffleStore + peer fetches (the caching writer/reader pair).

    ``fingerprint`` is the structural hash of the exchange's plan subtree:
    registered with the local store and sent on every peer fetch, so a
    worker whose query stream diverged (the lockstep shuffle-id contract)
    gets a LOUD :class:`ShuffleDesyncError` instead of silently joining
    mismatched shuffles."""

    def __init__(self, num_partitions: int, ctx: WorkerContext,
                 fingerprint: Optional[str] = None):
        self.num_partitions = num_partitions
        self.ctx = ctx
        self.shuffle_id = ctx.next_shuffle_id()
        self.fingerprint = fingerprint
        if fingerprint:
            # bind BEFORE any write: peers polling completion already get
            # fingerprint validation on their first metadata round trip
            ctx.store.set_fingerprint(self.shuffle_id, fingerprint)
            from ..analysis import divergence
            divergence.note_event(
                f"fingerprint:{self.shuffle_id}:{fingerprint[:16]}")
        self._wrote = False

    # -- map side ------------------------------------------------------------
    def write(self, partitioner, batch: ColumnarBatch) -> None:
        for p, piece in enumerate(partitioner.split(batch)):
            if piece.num_rows > 0:
                # ONE batched device->host transfer per slice; the store
                # serves host bytes (the reference's device-store residency
                # trades off against the tunnel's per-array sync cost here)
                self.ctx.store.register_batch(self.shuffle_id, p,
                                              piece.fetch_to_host())
        self._wrote = True

    def write_deferred(self, window, partitioner,
                       batch: ColumnarBatch) -> None:
        """Pipelined map-side write (LocalShuffle.write_deferred's store
        twin): the fused device split dispatches now, the slice-sizing
        scalar parks in ``window``, and the host staging transfer runs at
        landing — so the per-batch sizing readbacks pack into O(1)
        resolves per map task while the store still serves host bytes."""
        deferred = partitioner.split_deferred(batch)
        if deferred is None:
            self.write(partitioner, batch)
            return
        counts, make_pieces = deferred

        def land(host_counts):
            for p, piece in enumerate(make_pieces(host_counts)):
                if piece.num_rows > 0:
                    self.ctx.store.register_batch(self.shuffle_id, p,
                                                  piece.fetch_to_host())
            self._wrote = True  # lint: unguarded-ok single-writer flag: each task's window lands on its own thread; True is the only value ever written

        window.push(land, counts)

    def finish_writes(self) -> None:
        self.ctx.store.mark_complete(self.shuffle_id)

    @property
    def durable(self) -> bool:
        """True when the worker's store write-throughs to the durable
        .npz tier (outputs survive a worker death for rejoin re-serve)."""
        return bool(self.ctx.store.durable_dir)

    def pin_outputs_to_disk(self) -> int:
        """No-op: the durable ShuffleStore persists each slice at
        registration (write-through), unlike the local spill-store pin."""
        return 0

    def reset_outputs(self) -> None:
        """Discard this worker's (partial) map outputs for a stage
        retry. Only legal BEFORE ``finish_writes``: peers poll the
        completion mark before fetching, so nothing was observed yet."""
        self.ctx.store.remove_shuffle(self.shuffle_id)
        if self.fingerprint:
            self.ctx.store.set_fingerprint(self.shuffle_id,
                                           self.fingerprint)
        self._wrote = False  # lint: unguarded-ok single-writer flag: reset runs on the one thread driving this exchange's map retry

    # -- reduce side ---------------------------------------------------------
    def read(self, p: int, schema: dt.Schema):
        """All slices of reduce partition ``p``: local short-circuit +
        remote fetches (RapidsCachingReader's local/remote block split)."""
        from ..plan.physical import concat_batches
        batches = list(self.ctx.store.local_batches(self.shuffle_id, p))
        for wid in sorted(self.ctx.peers):
            batches.extend(self.ctx.fetch_from_peer(
                wid, self.shuffle_id, [p], fingerprint=self.fingerprint))
        if batches:
            yield concat_batches(schema, batches)

    def read_all_partition_sources(self) -> List:
        """EVERY reduce partition's full data (local + all peers), not
        just the owned ones — the mesh-consistent runtime-broadcast path:
        when the global build size is under threshold, every worker
        materializes the complete build side from the already-shuffled
        slices. Returned as one generator per SOURCE (local store + each
        peer) so the caller's task runner drains sources concurrently
        instead of paying each peer's fetch latency serially."""
        def local():
            for p in range(self.num_partitions):
                yield from self.ctx.store.local_batches(self.shuffle_id, p)

        def from_peer(wid):
            yield from self.ctx.fetch_from_peer(
                wid, self.shuffle_id, list(range(self.num_partitions)),
                fingerprint=self.fingerprint)

        return [local()] + [from_peer(w) for w in sorted(self.ctx.peers)]

    def close_pending(self) -> None:
        """This worker is done READING this shuffle: ack the release
        quorum (local + every peer). Nothing is freed until ALL workers
        have acked, so a faster worker's cleanup can never strand slower
        peers still fetching its map outputs — but once the quorum
        completes, every store frees the outputs instead of holding them
        until ``WorkerContext.shutdown`` (the reference's driver-scoped
        active-shuffle lifecycle, ShuffleBufferCatalog.scala)."""
        self.ctx.release_shuffle(self.shuffle_id)
