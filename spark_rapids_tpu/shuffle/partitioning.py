"""Partitioning strategies: hash / range / round-robin / single.

Reference: ``GpuPartitioning.scala:45-72`` (device slice + host copy paths),
``GpuHashPartitioning.scala`` (Murmur3-compatible device hash -> contiguous
split), ``GpuRangePartitioning.scala`` + ``GpuRangePartitioner`` (reservoir
sample bounds -> upper_bound search), ``GpuRoundRobinPartitioning.scala``,
``GpuSinglePartitioning.scala``.

Spark-compatible placement matters (golden-compare across engines), so the
hash path uses the bit-compatible Murmur3 from ops/hashing.py with Spark's
``pmod(hash, n)`` partition id."""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from ..columnar import dtypes as dt
from ..columnar.batch import ColumnarBatch
from ..columnar.column import Column, bucket
from ..ops import expressions as ex
from ..ops import kernels as K
from ..ops.hashing import murmur3_batch

# fused map-side split kernels, keyed by (num_partitions, cap, array
# signature): partition-id mask -> stable sort by partition -> gather of
# every payload array -> per-partition counts, ONE compiled program per
# shape class instead of a chain of eager dispatches per batch
_SPLIT_FN_CACHE: Dict[tuple, Any] = {}

# registered with the JIT map-pressure relief valve
# (exec/compile_cache.jit_map_guard): cached split programs pin loaded
# executables
from ..exec.compile_cache import register_program_cache as _rpc  # noqa: E402
_rpc(_SPLIT_FN_CACHE.clear)
del _rpc


def _fused_split_fn(num_partitions: int, cap: int, sig: tuple):
    """One jitted program: (pids, live, *arrays) -> (*sorted_arrays,
    counts). Rows sort stably by partition id (padding rows last), so
    partition p occupies rows [offsets[p], offsets[p]+counts[p])."""
    import jax

    def fn(pids, live, *arrays):
        pids = jnp.where(live, pids, num_partitions)      # padding last
        order = jnp.argsort(pids, stable=True)
        sorted_arrays = [a[order] for a in arrays]
        counts = jnp.bincount(
            jnp.clip(pids, 0, num_partitions),
            length=num_partitions + 1)[:num_partitions]
        return tuple(sorted_arrays) + (counts.astype(jnp.int32),)
    # lint: naked-jit-ok map-side split builder: every call rides _split_kernel -> compile_cache.note_build (audited + persisted)
    return jax.jit(fn)


def _split_kernel(num_partitions: int, cap: int, arrays: List[jnp.ndarray]):
    sig = tuple((str(a.dtype), tuple(a.shape[1:])) for a in arrays)
    key = (num_partitions, cap, sig)
    fn = _SPLIT_FN_CACHE.get(key)
    if fn is None:
        if len(_SPLIT_FN_CACHE) > 256:
            _SPLIT_FN_CACHE.clear()  # lint: unguarded-ok idempotent jit cache: a racing refill rebuilds the same function
        # shuffle split compiles ride the recompile audit + persistent
        # compile cache like every _fused_fn program
        from ..exec import compile_cache as _cc
        _kind, wrap = _cc.note_build(("shuffle_split",) + key,
                                     "shuffle_split")
        fn = _SPLIT_FN_CACHE[key] = wrap(_fused_split_fn(num_partitions, cap, sig))  # lint: unguarded-ok idempotent jit cache: a racing refill rebuilds the same function
    else:
        from ..analysis import recompile as _recompile
        _recompile.note_call("shuffle_split")
    return fn


class TpuPartitioner:
    num_partitions: int

    def partition_ids(self, batch: ColumnarBatch) -> jnp.ndarray:
        """int32[cap] partition id per row (live rows)."""
        raise NotImplementedError

    def split_deferred(self, batch: ColumnarBatch
                       ) -> Optional[Tuple[jnp.ndarray, Callable]]:
        """Device half of :meth:`split`, sizing readback deferred.

        Dispatches the fused split kernel (partition-id hash -> stable
        sort by partition -> counts) and returns ``(counts_device,
        make_pieces)`` WITHOUT reading the counts back: the caller parks
        ``counts_device`` in a :class:`~..exec.pipeline.PipelineWindow`
        so batch k+1's split dispatches before batch k's sizing lands,
        and calls ``make_pieces(host_counts)`` once resolved (``None``
        host counts re-read blocking — the window's degraded-resolve
        contract). Returns ``None`` when there is nothing to defer
        (empty batch / single partition): the caller should fall back to
        the blocking :meth:`split`, which is then readback-free."""
        if batch.num_rows == 0 or self.num_partitions == 1:
            return None
        from ..columnar.column import StructColumn
        cap = batch.capacity
        pids = self.partition_ids(batch)
        live = batch.row_mask()
        if any(isinstance(c, StructColumn) for c in batch.columns):
            # struct payloads have a nested array layout the flat fused
            # kernel cannot carry: sort+count eagerly, gather through the
            # struct-aware gather (rare path; exchanges over structs)
            pids_m = jnp.where(live, pids, self.num_partitions)
            order = jnp.argsort(pids_m, stable=True)
            counts = jnp.bincount(
                jnp.clip(pids_m, 0, self.num_partitions),
                length=self.num_partitions + 1
            )[:self.num_partitions].astype(jnp.int32)
            sorted_cols = [K.gather_column(c, order) for c in batch.columns]
        else:
            arrays = [a for c in batch.columns for a in c.arrays()]
            outs = _split_kernel(self.num_partitions, cap, arrays)(
                pids, live, *arrays)
            counts = outs[-1]
            sorted_cols = []
            i = 0
            for c in batch.columns:
                n = len(c.arrays())
                sorted_cols.append(Column(
                    c.dtype, outs[i], outs[i + 1],
                    outs[i + 2] if c.dtype.var_width else None,
                    outs[i + 3] if n == 4 else None))
                i += n

        def make_pieces(host_counts) -> List[ColumnarBatch]:
            if host_counts is None:      # degraded resolve: re-read
                from ..analysis.sync_audit import allowed_host_transfer
                with allowed_host_transfer("map-side split sizing"):
                    host_counts = np.asarray(counts)  # lint: host-sync-ok map-side split sizing: degraded-resolve fallback, one readback for this batch
            host_counts = np.asarray(host_counts).reshape(-1)
            out: List[ColumnarBatch] = []
            offset = 0
            for p in range(self.num_partitions):
                n = int(host_counts[p])
                if n == 0:
                    out.append(ColumnarBatch.empty(batch.schema))
                    continue
                pcap = bucket(n)
                cols = [K.slice_column(c, offset, pcap, n)
                        for c in sorted_cols]
                out.append(ColumnarBatch(batch.schema, cols, n))
                offset += n
            return out

        return counts, make_pieces

    def split(self, batch: ColumnarBatch) -> List[ColumnarBatch]:
        """Slice a batch into per-partition batches (contiguous_split analog:
        one stable sort by partition id + counted slices). Blocking form:
        the sizing readback resolves immediately — the pipelined map path
        uses :meth:`split_deferred` instead."""
        if batch.num_rows == 0:
            return [ColumnarBatch.empty(batch.schema)
                    for _ in range(self.num_partitions)]
        deferred = self.split_deferred(batch)
        if deferred is None:
            return [batch]                       # single partition
        counts, make_pieces = deferred
        from ..analysis.sync_audit import allowed_host_transfer
        with allowed_host_transfer("map-side split sizing"):
            host_counts = np.asarray(counts)  # lint: host-sync-ok map-side split sizing: one readback sizes every slice of this batch
        return make_pieces(host_counts)


class SinglePartitioner(TpuPartitioner):
    def __init__(self):
        self.num_partitions = 1

    def partition_ids(self, batch: ColumnarBatch) -> jnp.ndarray:
        return jnp.zeros(batch.capacity, dtype=jnp.int32)

    def split(self, batch: ColumnarBatch) -> List[ColumnarBatch]:
        return [batch]


class HashPartitioner(TpuPartitioner):
    """pmod(murmur3(keys, seed=42), n) — Spark HashPartitioning compatible."""

    def __init__(self, num_partitions: int, key_exprs: Sequence[ex.Expression]):
        self.num_partitions = num_partitions
        self.key_exprs = key_exprs

    def partition_ids(self, batch: ColumnarBatch) -> jnp.ndarray:
        cols = [ex.materialize(e.eval(batch), batch) for e in self.key_exprs]
        h = murmur3_batch(cols, batch.capacity)
        n = jnp.int32(self.num_partitions)
        return jnp.mod(jnp.mod(h, n) + n, n)


#: device round-robin index per (capacity, num_partitions, start%n):
#: rebuilding arange+mod per batch re-uploads/re-dispatches an array that
#: is a pure function of the shape class (the columnar/batch.py
#: ``_UNPACK_CACHE`` pattern applied to pick indices)
_RR_IDX_CACHE: Dict[Tuple[int, int, int], jnp.ndarray] = {}


class RoundRobinPartitioner(TpuPartitioner):
    def __init__(self, num_partitions: int, start: int = 0):
        self.num_partitions = num_partitions
        self.start = start

    def partition_ids(self, batch: ColumnarBatch) -> jnp.ndarray:
        key = (batch.capacity, self.num_partitions,
               self.start % self.num_partitions)
        idx = _RR_IDX_CACHE.get(key)
        if idx is None:
            if len(_RR_IDX_CACHE) > 256:
                _RR_IDX_CACHE.clear()  # lint: unguarded-ok idempotent device-constant cache: a racing refill recomputes the same array
            idx = jnp.mod(
                jnp.arange(batch.capacity, dtype=jnp.int32) + key[2],
                self.num_partitions)
            _RR_IDX_CACHE[key] = idx  # lint: unguarded-ok idempotent device-constant cache: a racing refill recomputes the same array
        return idx


class RangePartitioner(TpuPartitioner):
    """Sample-based range partitioning (GpuRangePartitioner: reservoir sample
    -> sorted bounds -> device upper_bound). Bounds are computed host-side
    from a sample; ids via searchsorted on the encoded sort keys."""

    def __init__(self, num_partitions: int, orders: List, sample_batches):
        from ..plan.logical import SortOrder
        self.num_partitions = num_partitions
        self.orders = orders
        self._bounds: Optional[List[ColumnarBatch]] = None
        self._sample = sample_batches

    def _compute_bounds(self, batch_schema) -> ColumnarBatch:
        """Collect sample rows, sort, pick n-1 evenly spaced bound rows."""
        from ..plan.physical import concat_batches
        sample = concat_batches(batch_schema, list(self._sample))
        cap = sample.capacity
        keys = []
        for o in self.orders:
            c = ex.materialize(o.child.eval(sample), sample)
            keys.append(K.SortKey(c, o.ascending, o.nulls_first))
        order = K.sort_indices(keys, sample.num_rows, cap)
        cols = [K.gather_column(c, order) for c in sample.columns]
        n = sample.num_rows
        k = self.num_partitions
        if n == 0 or k <= 1:
            return None
        picks = [min(n - 1, max(0, (i + 1) * n // k)) for i in range(k - 1)]
        idx = jnp.asarray(picks, dtype=jnp.int32)
        bcols = [K.gather_column(c, idx,
                                 out_valid=jnp.ones(len(picks), jnp.bool_))
                 for c in cols]
        return ColumnarBatch(sample.schema, bcols, len(picks))

    def partition_ids(self, batch: ColumnarBatch) -> jnp.ndarray:
        if self._bounds is None:
            self._bounds = self._compute_bounds(batch.schema) or "empty"
        if self._bounds == "empty":
            return jnp.zeros(batch.capacity, dtype=jnp.int32)
        bounds = self._bounds
        # rank rows against bound rows with the join machinery's word compare
        from ..ops.joins import _lex_cmp
        row_words, bound_words = self._encode(batch), self._encode(bounds)
        # Spark RangePartitioning.getPartition: advance while key > bound, so
        # pid = count of bounds strictly less than the row's key
        pid = jnp.zeros(batch.capacity, dtype=jnp.int32)
        for bi in range(bounds.num_rows):
            bw = [jnp.broadcast_to(w[bi], (batch.capacity,))
                  for w in bound_words]
            blt, _beq = _lex_cmp(bw, row_words)   # bound < row
            pid = pid + blt.astype(jnp.int32)
        return jnp.clip(pid, 0, self.num_partitions - 1)

    def _encode(self, batch: ColumnarBatch):
        words: List[jnp.ndarray] = []
        for o in self.orders:
            c = ex.materialize(o.child.eval(batch), batch)
            arrs = K._key_arrays(K.SortKey(c, o.ascending, o.nulls_first))
            # floats in _key_arrays stay as floats; bitcast like joins do
            import jax
            for w in arrs:
                if w.dtype.kind == "f":
                    bits = jax.lax.bitcast_convert_type(
                        w.astype(jnp.float32), jnp.uint32)
                    sign = bits >> 31
                    w = jnp.where(sign == 1, ~bits, bits | jnp.uint32(0x80000000))
                words.append(w)
        return words
