"""Partitioning strategies: hash / range / round-robin / single.

Reference: ``GpuPartitioning.scala:45-72`` (device slice + host copy paths),
``GpuHashPartitioning.scala`` (Murmur3-compatible device hash -> contiguous
split), ``GpuRangePartitioning.scala`` + ``GpuRangePartitioner`` (reservoir
sample bounds -> upper_bound search), ``GpuRoundRobinPartitioning.scala``,
``GpuSinglePartitioning.scala``.

Spark-compatible placement matters (golden-compare across engines), so the
hash path uses the bit-compatible Murmur3 from ops/hashing.py with Spark's
``pmod(hash, n)`` partition id."""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from ..columnar import dtypes as dt
from ..columnar.batch import ColumnarBatch
from ..columnar.column import Column, bucket
from ..ops import expressions as ex
from ..ops import kernels as K
from ..ops.hashing import murmur3_batch


class TpuPartitioner:
    num_partitions: int

    def partition_ids(self, batch: ColumnarBatch) -> jnp.ndarray:
        """int32[cap] partition id per row (live rows)."""
        raise NotImplementedError

    def split(self, batch: ColumnarBatch) -> List[ColumnarBatch]:
        """Slice a batch into per-partition batches (contiguous_split analog:
        one stable sort by partition id + counted slices)."""
        if batch.num_rows == 0:
            return [ColumnarBatch.empty(batch.schema)
                    for _ in range(self.num_partitions)]
        cap = batch.capacity
        pids = self.partition_ids(batch)
        live = batch.row_mask()
        pids = jnp.where(live, pids, self.num_partitions)  # padding last
        order = jnp.argsort(pids, stable=True)
        sorted_cols = [K.gather_column(c, order) for c in batch.columns]
        from ..analysis.sync_audit import allowed_host_transfer
        with allowed_host_transfer("map-side split sizing"):
            counts = np.asarray(jnp.bincount(  # lint: host-sync-ok map-side split sizing: one readback sizes every slice of this batch
                jnp.clip(pids, 0, self.num_partitions),
                length=self.num_partitions + 1))[:self.num_partitions]
        out: List[ColumnarBatch] = []
        offset = 0
        for p in range(self.num_partitions):
            n = int(counts[p])
            if n == 0:
                out.append(ColumnarBatch.empty(batch.schema))
                offset += n
                continue
            pcap = bucket(n)
            cols = [K.slice_column(c, offset, pcap, n) for c in sorted_cols]
            out.append(ColumnarBatch(batch.schema, cols, n))
            offset += n
        return out


class SinglePartitioner(TpuPartitioner):
    def __init__(self):
        self.num_partitions = 1

    def partition_ids(self, batch: ColumnarBatch) -> jnp.ndarray:
        return jnp.zeros(batch.capacity, dtype=jnp.int32)

    def split(self, batch: ColumnarBatch) -> List[ColumnarBatch]:
        return [batch]


class HashPartitioner(TpuPartitioner):
    """pmod(murmur3(keys, seed=42), n) — Spark HashPartitioning compatible."""

    def __init__(self, num_partitions: int, key_exprs: Sequence[ex.Expression]):
        self.num_partitions = num_partitions
        self.key_exprs = key_exprs

    def partition_ids(self, batch: ColumnarBatch) -> jnp.ndarray:
        cols = [ex.materialize(e.eval(batch), batch) for e in self.key_exprs]
        h = murmur3_batch(cols, batch.capacity)
        n = jnp.int32(self.num_partitions)
        return jnp.mod(jnp.mod(h, n) + n, n)


class RoundRobinPartitioner(TpuPartitioner):
    def __init__(self, num_partitions: int, start: int = 0):
        self.num_partitions = num_partitions
        self.start = start

    def partition_ids(self, batch: ColumnarBatch) -> jnp.ndarray:
        idx = jnp.arange(batch.capacity, dtype=jnp.int32)
        return jnp.mod(idx + self.start, self.num_partitions)


class RangePartitioner(TpuPartitioner):
    """Sample-based range partitioning (GpuRangePartitioner: reservoir sample
    -> sorted bounds -> device upper_bound). Bounds are computed host-side
    from a sample; ids via searchsorted on the encoded sort keys."""

    def __init__(self, num_partitions: int, orders: List, sample_batches):
        from ..plan.logical import SortOrder
        self.num_partitions = num_partitions
        self.orders = orders
        self._bounds: Optional[List[ColumnarBatch]] = None
        self._sample = sample_batches

    def _compute_bounds(self, batch_schema) -> ColumnarBatch:
        """Collect sample rows, sort, pick n-1 evenly spaced bound rows."""
        from ..plan.physical import concat_batches
        sample = concat_batches(batch_schema, list(self._sample))
        cap = sample.capacity
        keys = []
        for o in self.orders:
            c = ex.materialize(o.child.eval(sample), sample)
            keys.append(K.SortKey(c, o.ascending, o.nulls_first))
        order = K.sort_indices(keys, sample.num_rows, cap)
        cols = [K.gather_column(c, order) for c in sample.columns]
        n = sample.num_rows
        k = self.num_partitions
        if n == 0 or k <= 1:
            return None
        picks = [min(n - 1, max(0, (i + 1) * n // k)) for i in range(k - 1)]
        idx = jnp.asarray(picks, dtype=jnp.int32)
        bcols = [K.gather_column(c, idx,
                                 out_valid=jnp.ones(len(picks), jnp.bool_))
                 for c in cols]
        return ColumnarBatch(sample.schema, bcols, len(picks))

    def partition_ids(self, batch: ColumnarBatch) -> jnp.ndarray:
        if self._bounds is None:
            self._bounds = self._compute_bounds(batch.schema) or "empty"
        if self._bounds == "empty":
            return jnp.zeros(batch.capacity, dtype=jnp.int32)
        bounds = self._bounds
        # rank rows against bound rows with the join machinery's word compare
        from ..ops.joins import _lex_cmp
        row_words, bound_words = self._encode(batch), self._encode(bounds)
        # Spark RangePartitioning.getPartition: advance while key > bound, so
        # pid = count of bounds strictly less than the row's key
        pid = jnp.zeros(batch.capacity, dtype=jnp.int32)
        for bi in range(bounds.num_rows):
            bw = [jnp.broadcast_to(w[bi], (batch.capacity,))
                  for w in bound_words]
            blt, _beq = _lex_cmp(bw, row_words)   # bound < row
            pid = pid + blt.astype(jnp.int32)
        return jnp.clip(pid, 0, self.num_partitions - 1)

    def _encode(self, batch: ColumnarBatch):
        words: List[jnp.ndarray] = []
        for o in self.orders:
            c = ex.materialize(o.child.eval(batch), batch)
            arrs = K._key_arrays(K.SortKey(c, o.ascending, o.nulls_first))
            # floats in _key_arrays stay as floats; bitcast like joins do
            import jax
            for w in arrs:
                if w.dtype.kind == "f":
                    bits = jax.lax.bitcast_convert_type(
                        w.astype(jnp.float32), jnp.uint32)
                    sign = bits >> 31
                    w = jnp.where(sign == 1, ~bits, bits | jnp.uint32(0x80000000))
                words.append(w)
        return words
