"""Multi-host shuffle transport: TCP transfer server + fetching client.

Reference mapping (SURVEY.md §2.8):
- ``RapidsShuffleServer.scala:67-671`` -> :class:`ShuffleServer` — serves
  metadata and streams table bytes through fixed-size send windows
  (``BufferSendState`` windowing -> CRC-tagged chunk frames).
- ``RapidsShuffleClient.scala:480-612`` -> :class:`ShuffleClient` — fetch
  protocol: MetadataRequest -> MetadataResponse -> TransferRequest(s) with
  inflight-byte throttling (``RapidsShuffleTransport.scala:413-435``),
  chunk reassembly, batch reconstruction.
- ``RapidsShuffleIterator.scala:49-365`` -> :meth:`ShuffleClient.fetch`'s
  retry loop — transport errors surface as :class:`ShuffleFetchError` after
  bounded retries (the reference throws RapidsShuffleFetchFailedException to
  trigger Spark's stage retry; standalone, the caller decides).

The UCX/RDMA plane of the reference maps to ICI collectives (parallel/mesh);
this TCP plane is the DCN fallback for inter-host fetches, stragglers, and
elastic retry, exactly the split SURVEY.md §5 calls for.
"""

from __future__ import annotations

import os
import socket
import struct
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from ..analysis.lockdep import named_lock
from ..columnar import dtypes as dt
from ..columnar.batch import ColumnarBatch
from ..columnar.column import Column
from . import wire
from .wire import (ERROR, META_REQ, META_RESP, RELEASE, XFER_CHUNK,
                   XFER_DONE, XFER_REQ, ArrayDesc, BufferDesc, FrameReader,
                   encode_frame)


# process-lifetime transport totals (service/telemetry harvest): client
# instances are per-peer and short-lived, so cumulative counters live at
# module level, bumped at buffer-completion / retry boundaries. The send
# side (bytes_sent/chunks_sent, bumped by the SERVER at send-window
# completion) mirrors the fetch side so the telemetry shuffle gauges are
# symmetric: a worker that serves much more than it fetches is visible.
_TOTALS: Dict[str, int] = {"retries": 0, "bytes_fetched": 0, "chunks": 0,
                           "bounce_misses": 0,
                           "bytes_sent": 0, "chunks_sent": 0}
_totals_mu = named_lock("shuffle.transport._totals_mu")


def _note_total(key: str, amount: int = 1) -> None:
    with _totals_mu:
        _TOTALS[key] += amount


def transport_totals() -> Dict[str, int]:
    """Cumulative transport counters (both directions) across every
    client/server this process created (the telemetry registry's shuffle
    gauges)."""
    with _totals_mu:
        return dict(_TOTALS)


def _current_query_id():
    """The ambient query id for protocol headers (None outside a
    collect — e.g. liveness probes); guarded so the transport never
    fails on observability."""
    try:
        from ..exec.query_context import current_query_id
        return current_query_id()
    except Exception:
        return None


def _current_tenant():
    """The ambient tenant for protocol headers (service multi-tenancy):
    rides META_REQ next to the query id so the SERVING peer — a
    different process with no ambient context for the fetching query —
    attributes the serve to the right tenant in its flight ring."""
    try:
        from ..exec.query_context import current_tenant
        return current_tenant()
    except Exception:
        return None


class ShuffleFetchError(RuntimeError):
    """Fetch failed after retries (RapidsShuffleFetchFailedException analog:
    the caller maps this to a stage retry / recompute)."""


class ShuffleDesyncError(ShuffleFetchError):
    """The peer's registered plan fingerprint for this shuffle id does not
    match ours: the lockstep shuffle-id contract broke (one worker's query
    stream diverged). NEVER retried — retrying a desync would fetch wrong
    data; the query must abort loudly (the reference cannot hit this class
    of bug because the driver issues shuffle ids; standalone, the
    fingerprint handshake detects divergence instead)."""


class ShuffleProtocolError(ShuffleFetchError):
    """The peer is ALIVE and answered, but with a protocol-level ERROR
    (unknown message/buffer, version skew). Kept distinct from connection
    failures so the caller never mislabels a live-but-confused peer as a
    dead worker."""


class ShuffleWorkerLostError(ShuffleFetchError):
    """A peer worker is unreachable/dead: its local data shard cannot be
    recomputed from any other worker's lineage, so the distributed query
    aborts loudly naming the lost peer (the standalone analog of Spark's
    executor-lost -> job abort when no replication exists)."""

    def __init__(self, worker_id: int, message: str):
        super().__init__(message)
        self.worker_id = worker_id


# ---------------------------------------------------------------------------
# Server-side store
# ---------------------------------------------------------------------------

class ShuffleStore:
    """(shuffle_id, reduce_id) -> registered host buffers with metadata
    (ShuffleBufferCatalog analog, host-tier: the transfer server serves
    bytes from host staging, never touching the device).

    ``durable_dir`` (conf ``spark.rapids.tpu.sql.shuffle.durable``, wired
    by WorkerContext) opts map outputs into a write-through .npz disk
    tier: every registered slice and completion mark also lands on disk,
    and :meth:`reload_durable` re-serves them after a worker death —
    the rejoining worker's peers re-fetch instead of aborting (the
    checkpoint/resume trade of SURVEY §5, docs/resilience.md)."""

    def __init__(self, durable_dir: Optional[str] = None,
                 durable_budget: int = 0):
        self._mu = named_lock("shuffle.transport.ShuffleStore._mu")
        self._next_id = 1
        self._buffers: Dict[int, Tuple[BufferDesc, List[np.ndarray]]] = {}
        self._by_partition: Dict[Tuple[int, int], List[int]] = {}
        self._complete: set = set()
        self._fingerprints: Dict[int, str] = {}
        self._release_acks: Dict[int, set] = {}
        self._released: set = set()
        # how many distinct worker release-acks free a shuffle's outputs
        # (set by WorkerContext to n_workers; 0 disables the protocol)
        self.release_quorum = 0
        self.durable_dir = durable_dir
        self._durable_files: Dict[int, Tuple[str, str]] = {}
        self._durable_max_sid = 0
        # durable-tier GC budget (conf shuffle.durable.maxBytes, wired
        # by WorkerContext; 0 = unbounded): total .npz bytes on disk,
        # per-shuffle byte shares, and the completion order the
        # oldest-completed eviction walks
        self.durable_budget = int(durable_budget)
        self._durable_bytes = 0
        self._durable_sid_bytes: Dict[int, int] = {}
        self._durable_complete_order: List[int] = []

    def register_batch(self, shuffle_id: int, reduce_id: int,
                       batch: ColumnarBatch) -> int:
        from ..analysis.sync_audit import allowed_host_transfer
        with allowed_host_transfer("wire serialization"):
            arrays = [np.asarray(a) for c in batch.columns for a in c.arrays()]  # lint: host-sync-ok wire serialization: the shuffle payload must cross to host
        descs = [ArrayDesc(str(a.dtype), a.shape, a.nbytes) for a in arrays]
        with self._mu:
            bid = self._next_id
            self._next_id += 1  # lint: nondeterminism-ok store-local buffer id, exchanged via metadata — never minted in lockstep
            desc = BufferDesc(
                bid, shuffle_id, reduce_id, batch.num_rows,
                [f.name for f in batch.schema],
                [f.dtype.name for f in batch.schema], descs)
            self._buffers[bid] = (desc, arrays)
            self._by_partition.setdefault((shuffle_id, reduce_id),
                                          []).append(bid)
        # durable write-through runs OUTSIDE the store lock (npz IO must
        # not serialize the transfer server); control-plane shuffles
        # (negative ids) are ephemeral and never persisted
        if self.durable_dir and shuffle_id >= 0:
            self._persist(bid, desc, arrays)
        return bid

    # -- durable tier --------------------------------------------------------
    def _persist(self, bid: int, desc: BufferDesc,
                 arrays: List[np.ndarray]) -> None:
        import json as _json
        os.makedirs(self.durable_dir, exist_ok=True)
        stem = os.path.join(self.durable_dir,
                            f"buf-{desc.shuffle_id}-{desc.reduce_id}-{bid}")
        np.savez(stem + ".npz", *arrays)
        with open(stem + ".json", "w") as f:
            _json.dump(desc.to_json(), f)
        nbytes = int(sum(a.nbytes for a in arrays))
        with self._mu:
            self._durable_files[bid] = (stem + ".npz", stem + ".json")
            self._durable_bytes += nbytes
            self._durable_sid_bytes[desc.shuffle_id] = \
                self._durable_sid_bytes.get(desc.shuffle_id, 0) + nbytes
        from ..service.telemetry import flight_record
        flight_record("spill", f"shuffle-durable-{bid}",
                      {"shuffle": desc.shuffle_id,
                       "reduce": desc.reduce_id})
        self._enforce_durable_budget()

    def _enforce_durable_budget(self) -> None:
        """Durable-tier GC (conf ``shuffle.durable.maxBytes``): while the
        .npz tier exceeds its disk budget, evict the OLDEST COMPLETED
        shuffle's durable files — the in-memory outputs keep serving this
        process unchanged; only the dead-worker rejoin re-serve for that
        old shuffle is given up. The newest completed shuffle is never
        evicted (it is the one an in-flight retry most plausibly needs),
        so a long-lived ``shuffle.durable`` session degrades to bounded
        disk instead of filling it. Evicted bytes are metered into
        ``tpu_durable_evicted_bytes_total``."""
        if not self.durable_budget or not self.durable_dir:
            return
        while True:  # lint: cancel-ok bounded by completed-shuffle count, no dwell; eviction must finish even for a cancelled query
            with self._mu:
                if self._durable_bytes <= self.durable_budget or \
                        len(self._durable_complete_order) <= 1:
                    return
                sid = self._durable_complete_order.pop(0)
                freed = self._durable_sid_bytes.pop(sid, 0)
                self._durable_bytes -= freed
                bids = [b for b, (d, _a) in self._buffers.items()
                        if d.shuffle_id == sid and b in self._durable_files]
            self._unlink_durable(bids, shuffle_id=sid)
            from ..service.telemetry import MetricsRegistry, flight_record
            flight_record("spill", f"shuffle-durable-evict-{sid}",
                          {"shuffle": sid, "bytes": freed})
            try:
                MetricsRegistry.get().counter(
                    "tpu_durable_evicted_bytes_total",
                    "durable shuffle-tier bytes evicted by the "
                    "shuffle.durable.maxBytes GC budget").inc(freed)
            except Exception:
                pass           # telemetry must never fail the eviction

    def reload_durable(self) -> int:
        """Rebuild the store from a durable directory (a rejoining
        worker re-serving the outputs its previous incarnation pinned);
        returns the number of buffers re-registered. Completion marks
        AND fingerprints reload too, so peers' completion polls resume
        immediately and the desync handshake still validates the old
        outputs. The highest reloaded shuffle id is tracked
        (:meth:`durable_max_shuffle_id`) so the rejoining worker's
        lockstep counter can advance PAST the previous incarnation's
        ids — reusing one would merge a dead run's rows into a new
        query (and its stale completion mark would answer peers'
        polls before the new map phase even ran)."""
        import glob
        import json as _json
        if not self.durable_dir or not os.path.isdir(self.durable_dir):
            return 0
        n = 0
        for meta_path in sorted(glob.glob(
                os.path.join(self.durable_dir, "buf-*.json"))):
            npz_path = meta_path[:-len(".json")] + ".npz"
            try:
                with open(meta_path) as f:
                    desc = BufferDesc.from_json(_json.load(f))
                with np.load(npz_path) as z:
                    arrays = [z[k] for k in z.files]
            except Exception:
                # a torn write from the death: np.load on a truncated
                # npz raises zipfile.BadZipFile / zlib.error, not just
                # OSError — ANY unreadable pair is skipped, never fatal
                continue
            with self._mu:
                bid = desc.buffer_id
                if bid in self._buffers:
                    continue
                self._next_id = max(self._next_id, bid + 1)
                self._buffers[bid] = (desc, arrays)
                self._by_partition.setdefault(
                    (desc.shuffle_id, desc.reduce_id), []).append(bid)
                self._durable_files[bid] = (npz_path, meta_path)
                self._durable_max_sid = max(self._durable_max_sid,
                                            desc.shuffle_id)
                nbytes = int(sum(a.nbytes for a in arrays))
                self._durable_bytes += nbytes
                self._durable_sid_bytes[desc.shuffle_id] = \
                    self._durable_sid_bytes.get(desc.shuffle_id, 0) + \
                    nbytes
            n += 1
        for marker in sorted(glob.glob(
                os.path.join(self.durable_dir, "complete-*"))):
            try:
                sid = int(os.path.basename(marker).split("-", 1)[1])
            except ValueError:
                continue
            with self._mu:
                self._complete.add(sid)
                self._durable_max_sid = max(self._durable_max_sid, sid)
                if sid not in self._durable_complete_order:
                    self._durable_complete_order.append(sid)
        # the reloaded tier obeys the budget too (sorted marker order
        # approximates completion order; ids are monotonic per worker)
        with self._mu:
            self._durable_complete_order.sort()
        self._enforce_durable_budget()
        # sorted like the buf-*/complete-* scans above: directory order
        # is filesystem-dependent, and a lockstep worker replaying the
        # reload must observe the same sequence every time
        # (nondet-scan, analysis/determinism.py)
        for fp_path in sorted(glob.glob(
                os.path.join(self.durable_dir, "fp-*"))):
            try:
                sid = int(os.path.basename(fp_path).split("-", 1)[1])
                with open(fp_path) as f:
                    fp = f.read().strip()
            except Exception:
                continue
            if fp:
                with self._mu:
                    self._fingerprints.setdefault(sid, fp)
        return n

    def durable_max_shuffle_id(self) -> int:
        """Highest shuffle id the durable reload saw (0 when none)."""
        with self._mu:
            return self._durable_max_sid

    def durable_max_shuffle_id_in(self, lo: int, hi: int) -> int:
        """Highest durable shuffle id in ``[lo, hi)``, or ``lo`` when
        none — the per-NAMESPACE counter resume (shuffle/manager.py
        mints ids namespaced by query, so a rejoining worker advances
        each namespace's counter past only ITS OWN durable ids)."""
        with self._mu:
            sids = [s for s in (set(self._durable_sid_bytes) |
                                set(self._durable_complete_order))
                    if lo <= s < hi]
        return max(sids) if sids else lo

    def _unlink_durable(self, bids: List[int],
                        shuffle_id: Optional[int] = None) -> None:
        with self._mu:
            paths = [self._durable_files.pop(b) for b in bids
                     if b in self._durable_files]
        for npz_path, meta_path in paths:
            for p in (npz_path, meta_path):
                try:
                    os.unlink(p)
                except OSError:
                    pass
        if shuffle_id is not None and self.durable_dir:
            for name in (f"complete-{shuffle_id}", f"fp-{shuffle_id}"):
                try:
                    os.unlink(os.path.join(self.durable_dir, name))
                except OSError:
                    pass

    def metas(self, shuffle_id: int, reduce_ids: List[int]
              ) -> List[BufferDesc]:
        with self._mu:
            out = []
            for rid in reduce_ids:
                for bid in self._by_partition.get((shuffle_id, rid), []):
                    out.append(self._buffers[bid][0])
            return out

    def payload(self, buffer_id: int) -> Tuple[BufferDesc, bytes]:
        with self._mu:
            desc, arrays = self._buffers[buffer_id]
        return desc, b"".join(a.tobytes() for a in arrays)

    def mark_complete(self, shuffle_id: int) -> None:
        """Map phase for this shuffle is finished: every slice is
        registered, remote fetches may proceed (the stage-scheduling
        ordering Spark provides; a flag replaces it standalone)."""
        with self._mu:
            self._complete.add(shuffle_id)
            if self.durable_dir and shuffle_id >= 0 and \
                    shuffle_id not in self._durable_complete_order:
                # completion order drives the GC budget's oldest-first
                # eviction walk
                self._durable_complete_order.append(shuffle_id)
        if self.durable_dir and shuffle_id >= 0:
            # completion survives a worker death with the slices: the
            # rejoined server answers completion polls immediately
            os.makedirs(self.durable_dir, exist_ok=True)
            with open(os.path.join(self.durable_dir,
                                   f"complete-{shuffle_id}"), "w"):
                pass
            self._enforce_durable_budget()

    def is_complete(self, shuffle_id: int) -> bool:
        with self._mu:
            return shuffle_id in self._complete

    def set_fingerprint(self, shuffle_id: int, fingerprint: str) -> None:
        """Bind the structural plan fingerprint of the exchange that owns
        ``shuffle_id``; metadata requests carrying a different fingerprint
        for the same id are rejected (lockstep-desync detection). Durable
        stores persist it so a rejoined worker's re-served outputs still
        validate the handshake."""
        with self._mu:
            self._fingerprints[shuffle_id] = fingerprint
        if self.durable_dir and shuffle_id >= 0 and fingerprint:
            os.makedirs(self.durable_dir, exist_ok=True)
            with open(os.path.join(self.durable_dir,
                                   f"fp-{shuffle_id}"), "w") as f:
                f.write(fingerprint)

    def check_fingerprint(self, shuffle_id: int,
                          fingerprint: Optional[str]) -> Optional[str]:
        """None when compatible; otherwise the locally-registered
        fingerprint that conflicts with the caller's."""
        if not fingerprint:
            return None
        with self._mu:
            local = self._fingerprints.get(shuffle_id)
        if local is not None and local != fingerprint:
            return local
        return None

    def is_released(self, shuffle_id: int) -> bool:
        with self._mu:
            return shuffle_id in self._released

    def add_release(self, shuffle_id: int, worker_id: int) -> bool:
        """Record that ``worker_id`` finished ALL its reads of this
        shuffle. Once ``release_quorum`` distinct workers have released,
        the outputs are freed — no one will fetch after releasing, so
        freeing is safe (ShuffleBufferCatalog active-shuffle lifecycle;
        Spark's driver ends the stage cluster-wide, the quorum replaces
        it standalone). Returns True when this call freed the shuffle."""
        with self._mu:
            if shuffle_id in self._released:
                return False
            acks = self._release_acks.setdefault(shuffle_id, set())
            acks.add(worker_id)
            if not self.release_quorum or len(acks) < self.release_quorum:
                return False
            self._released.add(shuffle_id)
            self._release_acks.pop(shuffle_id, None)
        self.remove_shuffle(shuffle_id)
        return True

    def local_batches(self, shuffle_id: int, reduce_id: int
                      ) -> List[ColumnarBatch]:
        """Short-circuit read of locally-registered slices (the
        RapidsCachingReader local-block path — no socket, no copy of the
        payload bytes)."""
        with self._mu:
            pairs = [self._buffers[bid]
                     for bid in self._by_partition.get(
                         (shuffle_id, reduce_id), [])]
        out = []
        for desc, arrays in pairs:
            out.append(_rebuild_from_arrays(desc, arrays))
        return out

    def remove_shuffle(self, shuffle_id: int) -> None:
        removed: List[int] = []
        with self._mu:
            gone = [k for k in self._by_partition if k[0] == shuffle_id]
            for k in gone:
                for bid in self._by_partition.pop(k):
                    self._buffers.pop(bid, None)
                    removed.append(bid)
            self._complete.discard(shuffle_id)
            self._fingerprints.pop(shuffle_id, None)
            self._durable_bytes -= self._durable_sid_bytes.pop(
                shuffle_id, 0)
            if shuffle_id in self._durable_complete_order:
                self._durable_complete_order.remove(shuffle_id)
        if self.durable_dir:
            self._unlink_durable(removed, shuffle_id=shuffle_id)

    def buffer_count(self) -> int:
        with self._mu:
            return len(self._buffers)


# ---------------------------------------------------------------------------
# Connections (socket + in-process mock share this surface)
# ---------------------------------------------------------------------------

class Connection:
    """Byte-stream connection surface (ClientConnection/ServerConnection
    analog, RapidsShuffleTransport.scala:165-370)."""

    def send(self, data: bytes) -> None:
        raise NotImplementedError

    def read_exact(self, n: int) -> bytes:
        raise NotImplementedError

    def close(self) -> None:
        pass


class SocketConnection(Connection):
    def __init__(self, sock: socket.socket):
        self.sock = sock

    def send(self, data: bytes) -> None:
        self.sock.sendall(data)

    def read_exact(self, n: int) -> bytes:
        out = b""
        while len(out) < n:  # lint: cancel-ok bounded single-frame read shared by server conn threads, which have no ambient query; the fetch-level loops above it poll
            chunk = self.sock.recv(n - len(out))
            if not chunk:
                raise ConnectionError("peer closed")
            out += chunk
        return out

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass


# ---------------------------------------------------------------------------
# Server
# ---------------------------------------------------------------------------

class ShuffleServer:
    """Serves shuffle metadata + windowed buffer streams over TCP."""

    def __init__(self, store: ShuffleStore, host: str = "127.0.0.1",
                 port: int = 0, chunk_bytes: int = wire.DEFAULT_CHUNK_BYTES,
                 codec: str = "none"):
        from .compression import get_codec
        self.store = store
        self.chunk_bytes = chunk_bytes
        self.codec = get_codec(codec)
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(16)
        self.port = self._sock.getsockname()[1]
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._threads_mu = named_lock(
            "shuffle.transport.ShuffleServer._threads_mu")
        self._conn_seq = 0
        self._accept_thread: Optional[threading.Thread] = None

    def start(self) -> "ShuffleServer":
        # named so lockdep order reports and teardown diagnostics can
        # attribute acquisitions to the transport plane; still daemonic
        # (a hung peer must never wedge interpreter exit), but stop()
        # joins them bounded so orderly shutdown is observable
        self._accept_thread = threading.Thread(  # lint: unguarded-ok set once here, before the accept thread exists
            target=self._accept_loop, daemon=True,
            name="tpu-shuffle-accept")
        self._accept_thread.start()
        return self

    def _accept_loop(self) -> None:
        while not self._stop.is_set():  # lint: cancel-ok server accept thread serves ALL queries; it stops with the server, not with any one query
            try:
                self._sock.settimeout(0.2)
                sock, _addr = self._sock.accept()
            except socket.timeout:
                continue
            except OSError as e:
                if not self._stop.is_set():
                    # an accept loop dying OUTSIDE orderly shutdown is a
                    # server death, not noise: record it instead of
                    # silently stranding every future fetch
                    from ..service.telemetry import flight_record
                    flight_record("teardown", "shuffle-accept-died",
                                  {"error": f"{type(e).__name__}: {e}"})
                return
            with self._threads_mu:
                self._conn_seq += 1  # lint: nondeterminism-ok connection-thread naming only; never crosses workers
                seq = self._conn_seq
            t = threading.Thread(target=self.handle_connection,
                                 args=(SocketConnection(sock),),
                                 daemon=True,
                                 name=f"tpu-shuffle-conn-{seq}")
            t.start()
            with self._threads_mu:
                self._threads.append(t)
                # prune finished handlers so a long-lived server's list
                # does not grow with every connection ever served
                self._threads = [x for x in self._threads if x.is_alive()]

    def handle_connection(self, conn: Connection) -> None:
        """One request/response session (the server handler loop,
        RapidsShuffleServer.scala:97-167). Public so the mock rig can drive
        it directly over an in-process connection."""
        from ..analysis import faults
        if faults.armed() and faults.fire("worker.die"):
            # deterministic worker death: drop this connection unserved;
            # on_fire callbacks (tests/bench) stop the server here, so
            # the fetching peer observes connect-refused next — exactly
            # the failed-send signature WorkerContext maps to
            # worker-lost (docs/resilience.md)
            conn.close()
            return
        reader = FrameReader(conn.read_exact)
        try:
            while True:  # lint: cancel-ok server conn thread serving a PEER's fetches; it has no ambient query and exits when the peer disconnects
                msg_type, header, _payload = reader.next_frame()
                if msg_type == META_REQ:
                    sid = header["shuffle_id"]
                    peer_q = header.get("query_id")
                    peer_tenant = header.get("tenant")
                    if peer_q and header.get("reduce_ids"):
                        # the fetching peer's query id rides the protocol
                        # header: an ACTUAL data serve lands in THIS
                        # worker's flight ring attributed to the same
                        # query id the peer's events carry — the
                        # cross-process join key post-mortems filter on.
                        # Completion polls (empty reduce_ids, up to one
                        # per 50ms-1s during straggler waits) are NOT
                        # recorded — they would churn identical
                        # breadcrumbs through the fixed-size ring,
                        # displacing the events a post-mortem needs
                        from ..service.telemetry import flight_record
                        data = {"query": peer_q}
                        if peer_tenant:
                            data["tenant"] = peer_tenant
                        flight_record("serve", f"shuffle-{sid}", data)
                    conflict = self.store.check_fingerprint(
                        sid, header.get("fingerprint"))
                    if conflict is not None:
                        conn.send(encode_frame(ERROR, {
                            "code": "desync",
                            "message": f"shuffle {sid} fingerprint mismatch:"
                                       f" peer registered {conflict}, fetch "
                                       f"expects {header['fingerprint']} — "
                                       "lockstep query streams diverged"}))
                        continue
                    if self.store.is_released(sid):
                        conn.send(encode_frame(ERROR, {
                            "code": "released",
                            "message": f"shuffle {sid} outputs were already "
                                       "released by the full worker quorum"}))
                        continue
                    metas = self.store.metas(sid, header["reduce_ids"])
                    resp = {"buffers": [m.to_json() for m in metas],
                            "complete": self.store.is_complete(sid)}
                    if peer_q:
                        # divergence audit (analysis/divergence.py):
                        # THIS worker's per-query digest snapshot rides
                        # the metadata reply, so the fetching peer
                        # compares lockstep streams on every round trip
                        # it already pays for
                        from ..analysis import divergence
                        div = divergence.snapshot(peer_q)
                        if div is not None:
                            resp["divergence"] = div
                        # cross-process cancellation rides the same
                        # round trip (exec/lifecycle.py): a query
                        # cancelled on THIS worker stamps the reply, so
                        # the peer's fetch/completion poll cancels its
                        # local token instead of waiting out a full
                        # straggler timeout against a query that will
                        # never complete here
                        from ..exec import lifecycle
                        if lifecycle.is_cancelled(peer_q):
                            resp["cancelled"] = True
                    conn.send(encode_frame(META_RESP, resp))
                elif msg_type == XFER_REQ:
                    self._send_buffers(conn, header["buffer_ids"])
                elif msg_type == RELEASE:
                    self.store.add_release(header["shuffle_id"],
                                           header["worker_id"])
                else:
                    conn.send(encode_frame(
                        ERROR, {"message": f"bad msg {msg_type}"}))
                    return
        except (ConnectionError, OSError):
            return
        finally:
            conn.close()

    def _send_buffers(self, conn: Connection, buffer_ids: List[int]) -> None:
        """Stream each buffer through fixed-size chunk windows
        (BufferSendState.next windowing). Send-side totals bump once per
        buffer at send-window completion (the flush-boundary rule the
        fetch side already follows), never per chunk."""
        sent_bytes = 0
        sent_chunks = 0
        for bid in buffer_ids:
            try:
                desc, payload = self.store.payload(bid)
            except KeyError:
                conn.send(encode_frame(ERROR,
                                       {"message": f"unknown buffer {bid}"}))
                return
            from ..analysis import faults
            ranges = wire.chunk_ranges(len(payload), self.chunk_bytes)
            for seq, (off, ln) in enumerate(ranges):
                raw = payload[off:off + ln]
                body = self.codec.compress(raw)
                conn.send(encode_frame(XFER_CHUNK, {
                    "buffer_id": bid, "seq": seq, "n_chunks": len(ranges),
                    "offset": off, "raw_len": ln,
                    "codec": self.codec.name,
                    "crc32": wire.chunk_crc(body)}, body))
                if faults.armed() and faults.fire("conn.kill",
                                                  chunk=seq + 1):
                    # torn send window: the client's reassembly sees the
                    # peer close mid-buffer and retries the fetch on a
                    # fresh connection (the mid-window transport kill)
                    raise ConnectionError(
                        "injected connection kill mid send window")
            # this buffer's send window completed
            _note_total("bytes_sent", len(payload))
            _note_total("chunks_sent", len(ranges))
            sent_bytes += len(payload)
            sent_chunks += len(ranges)
        conn.send(encode_frame(XFER_DONE, {"buffer_ids": buffer_ids,
                                           "bytes_sent": sent_bytes,
                                           "chunks_sent": sent_chunks}))

    def stop(self, join_timeout_s: float = 2.0) -> None:
        """Stop accepting and join the transport threads BOUNDED: the
        accept loop exits on its next poll tick, handler threads get
        ``join_timeout_s`` each to drain their in-flight frame. A thread
        still alive after its timeout is left daemonic (it dies with the
        process) — shutdown must never hang on a wedged peer."""
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass
        me = threading.current_thread()
        acc = self._accept_thread
        if acc is not None and acc is not me and acc.is_alive():
            acc.join(timeout=join_timeout_s)
        with self._threads_mu:
            handlers = list(self._threads)
        for t in handlers:
            # a handler may call stop() itself (the worker.die chaos
            # hook fires inside handle_connection): never self-join
            if t is not me and t.is_alive():
                t.join(timeout=join_timeout_s)
        with self._threads_mu:
            self._threads = [t for t in self._threads if t.is_alive()]
            leftovers = [t.name for t in self._threads if t is not me]
        if leftovers:
            from ..exec.tasks import record_join_timeout
            record_join_timeout("shuffle-server", leftovers,
                                logger="spark_rapids_tpu.shuffle")

    def alive_threads(self) -> List[str]:
        """Names of transport threads still running (teardown reports)."""
        with self._threads_mu:
            names = [t.name for t in self._threads if t.is_alive()]
        acc = self._accept_thread
        if acc is not None and acc.is_alive():
            names.insert(0, acc.name)
        return names


# ---------------------------------------------------------------------------
# Client
# ---------------------------------------------------------------------------

class ShuffleClient:
    """Fetches shuffle partitions from a peer transfer server.

    Inflight throttling: transfer requests are issued so at most
    ``max_inflight_bytes`` of advertised buffer bytes are outstanding at a
    time (RapidsShuffleTransport throttle, :413-435) — a pull window that
    bounds receive-side memory no matter how large the partition is.
    Retries: each fetch attempt uses a fresh connection; CRC mismatches and
    connection failures retry up to ``max_retries`` with backoff.
    """

    def __init__(self, connect: Callable[[], Connection],
                 max_inflight_bytes: int = 8 << 20,
                 max_retries: Optional[int] = None,
                 retry_backoff_s: Optional[float] = None,
                 bounce: Optional["BounceBufferManager"] = None):
        from ..exec.native_alloc import BounceBufferManager
        self._connect = connect
        self.max_inflight_bytes = max_inflight_bytes
        # retry knobs are conf-driven (shuffle.fetch.maxRetries /
        # .retryBackoff) unless the caller pins them; the recovery
        # module primes them from the active session's conf at
        # bootstrap (client construction sits below the session layer)
        if max_retries is None or retry_backoff_s is None:
            from ..exec import recovery
            if max_retries is None:
                max_retries = recovery.fetch_max_retries()
            if retry_backoff_s is None:
                retry_backoff_s = recovery.fetch_retry_backoff_s()
        self.max_retries = max_retries
        self.retry_backoff_s = retry_backoff_s
        # receive staging: chunk reassembly sub-allocates windows out of one
        # arena (BounceBufferManager.scala:35) instead of transient buffers
        self.bounce = bounce or BounceBufferManager(
            max(2 * max_inflight_bytes, 16 << 20))
        self.metrics: Dict[str, int] = {"retries": 0, "bytes_fetched": 0,
                                        "chunks": 0, "bounce_misses": 0}

    @staticmethod
    def for_address(host: str, port: int, **kw) -> "ShuffleClient":
        def connect():
            sock = socket.create_connection((host, port), timeout=10)
            return SocketConnection(sock)
        return ShuffleClient(connect, **kw)

    # -- public API ----------------------------------------------------------
    def fetch_when_complete(self, shuffle_id: int, reduce_ids: List[int],
                            timeout_s: float = 60.0,
                            poll_s: float = 0.05,
                            fingerprint: Optional[str] = None
                            ) -> List[ColumnarBatch]:
        """Fetch once the peer's map phase for ``shuffle_id`` is complete,
        polling its metadata endpoint with backoff (the standalone stand-in
        for Spark's stage-scheduling guarantee that map outputs exist
        before the reduce stage fetches them). A fingerprint-desync reply
        aborts the poll immediately — waiting cannot fix diverged query
        streams."""
        deadline = time.monotonic() + timeout_s
        delay = poll_s
        last_conn_err: Optional[Exception] = None
        from ..exec.lifecycle import check_cancel, interruptible_sleep
        while True:
            check_cancel()          # completion-poll lifecycle boundary
            conn = None
            try:
                # the connect itself is the most likely transient failure
                # (backlog full / peer restarting): poll it too
                conn = self._connect()
                conn.send(encode_frame(META_REQ, {
                    "shuffle_id": shuffle_id, "reduce_ids": [],
                    "fingerprint": fingerprint,
                    "query_id": _current_query_id(),
                    "tenant": _current_tenant()}))
                reader = FrameReader(conn.read_exact)
                msg_type, header, _ = reader.next_frame()
                if msg_type == ERROR and header.get("code") in (
                        "desync", "released"):
                    self._raise_protocol_error(shuffle_id, header)
                if msg_type == META_RESP and \
                        header.get("divergence") is not None:
                    # digest audit on the completion poll too: a desync
                    # surfaces on the FIRST round trip after divergence,
                    # not after a full straggler wait (enforce raises
                    # DesyncError here — typed RuntimeError, so the
                    # poll's transient-failure handling never eats it)
                    from ..analysis import divergence
                    divergence.check(_current_query_id(),
                                     header["divergence"],
                                     peer_label=f"peer serving shuffle "
                                                f"{shuffle_id}")
                if msg_type == META_RESP and header.get("cancelled"):
                    self._peer_cancelled(shuffle_id)
                complete = msg_type == META_RESP and header.get("complete")
                last_conn_err = None
            except (ConnectionError, OSError) as e:
                complete = False
                last_conn_err = e
            finally:
                if conn is not None:
                    conn.close()
            if complete:
                return self.fetch(shuffle_id, reduce_ids,
                                  fingerprint=fingerprint)
            if time.monotonic() > deadline:
                if last_conn_err is not None:
                    # distinguishes a DEAD peer (can't even connect) from a
                    # live straggler (reachable, map just not finished):
                    # the caller maps the former to worker-lost
                    raise ShuffleFetchError(
                        f"peer unreachable for shuffle {shuffle_id} after "
                        f"{timeout_s}s: {last_conn_err}") from last_conn_err
                raise ShuffleFetchError(
                    f"peer map phase for shuffle {shuffle_id} not complete "
                    f"after {timeout_s}s (peer alive)")
            interruptible_sleep(delay)
            delay = min(delay * 2, 1.0)

    def fetch(self, shuffle_id: int, reduce_ids: List[int],
              fingerprint: Optional[str] = None) -> List[ColumnarBatch]:
        """Fetch all batches of the given reduce partitions (doFetch,
        RapidsShuffleClient.scala:480)."""
        from ..exec.lifecycle import check_cancel, interruptible_sleep
        last_err: Optional[Exception] = None
        for attempt in range(self.max_retries + 1):
            check_cancel()          # fetch-retry lifecycle boundary
            if attempt:
                self.metrics["retries"] += 1
                _note_total("retries")
                interruptible_sleep(self.retry_backoff_s * attempt)
            try:
                return self._fetch_once(shuffle_id, reduce_ids, fingerprint)
            except ShuffleDesyncError:  # lint: recover-ok transport retry loop: a desync must escape its own retries — re-fetching diverged streams pairs wrong data
                raise                    # retrying cannot un-diverge streams
            except (ConnectionError, OSError, ValueError) as e:
                last_err = e
        raise ShuffleFetchError(
            f"shuffle {shuffle_id} partitions {reduce_ids} failed after "
            f"{self.max_retries + 1} attempts: {last_err}") from last_err

    def send_release(self, shuffle_id: int, worker_id: int) -> None:
        """Notify the peer this worker finished ALL reads of the shuffle
        (fire-and-forget: an unreachable peer frees at its own shutdown)."""
        conn = None
        try:
            conn = self._connect()
            conn.send(encode_frame(RELEASE, {"shuffle_id": shuffle_id,
                                             "worker_id": worker_id}))
        except (ConnectionError, OSError):
            pass
        finally:
            if conn is not None:
                conn.close()

    @staticmethod
    def _peer_cancelled(shuffle_id: int) -> None:
        """The peer's META reply carried ``cancelled``: the query was
        cancelled on the serving worker. Cancel the LOCAL token (so every
        other loop of this query unwinds at its next poll, symmetric with
        how divergence snapshots propagate) and raise the typed error —
        FAIL_QUERY, never absorbed by fetch retries."""
        from ..exec import lifecycle
        qid = _current_query_id()
        reason = f"peer-cancelled (shuffle {shuffle_id})"
        tok = lifecycle.token_for(qid)
        if tok is not None:
            tok.cancel(reason)
        raise lifecycle.QueryCancelledError(qid, reason)

    @staticmethod
    def _raise_protocol_error(shuffle_id: int, header: Dict) -> None:
        msg = header.get("message", "protocol error")
        code = header.get("code")
        if code == "desync":
            raise ShuffleDesyncError(msg)
        if code == "released":
            raise ShuffleFetchError(f"shuffle {shuffle_id}: {msg}")
        # any other ERROR reply: the peer is alive but confused — never
        # a ConnectionError, or the caller would report a dead worker
        raise ShuffleProtocolError(f"shuffle {shuffle_id}: {msg}")

    # -- one attempt ---------------------------------------------------------
    def _fetch_once(self, shuffle_id: int, reduce_ids: List[int],
                    fingerprint: Optional[str] = None
                    ) -> List[ColumnarBatch]:
        from ..analysis import faults
        if faults.armed() and faults.fire("fetch.fail"):
            raise ConnectionError(
                f"injected fetch fault (shuffle {shuffle_id})")
        conn = self._connect()
        try:
            conn.send(encode_frame(META_REQ, {
                "shuffle_id": shuffle_id, "reduce_ids": reduce_ids,
                "fingerprint": fingerprint,
                "query_id": _current_query_id(),
                "tenant": _current_tenant()}))
            reader = FrameReader(conn.read_exact)
            msg_type, header, _ = reader.next_frame()
            if msg_type == ERROR:
                self._raise_protocol_error(shuffle_id, header)
            assert msg_type == META_RESP, msg_type
            if header.get("divergence") is not None:
                from ..analysis import divergence
                divergence.check(_current_query_id(),
                                 header["divergence"],
                                 peer_label=f"peer serving shuffle "
                                            f"{shuffle_id}")
            if header.get("cancelled"):
                self._peer_cancelled(shuffle_id)
            metas = [BufferDesc.from_json(d) for d in header["buffers"]]

            # pending transfer queue with inflight-byte throttling
            pending = list(metas)
            inflight: Dict[int, BufferDesc] = {}
            inflight_bytes = 0
            received: Dict[int, bytearray] = {}
            seen_chunks: Dict[int, int] = {}
            done: List[ColumnarBatch] = []

            def issue():
                nonlocal inflight_bytes
                batch_ids = []
                while pending and (  # lint: cancel-ok non-blocking drain of the local pending list into the inflight window
                        not inflight or
                        inflight_bytes + pending[0].total_bytes
                        <= self.max_inflight_bytes):
                    m = pending.pop(0)
                    inflight[m.buffer_id] = m
                    inflight_bytes += m.total_bytes
                    batch_ids.append(m.buffer_id)
                if batch_ids:
                    conn.send(encode_frame(XFER_REQ,
                                           {"buffer_ids": batch_ids}))

            issue()
            from ..exec.lifecycle import check_cancel
            while inflight or pending:
                check_cancel()    # per-frame poll: a multi-chunk transfer
                # must not pin a cancelled query for its full duration
                msg_type, header, payload = reader.next_frame()
                if msg_type == ERROR:
                    # mid-transfer ERROR (e.g. a buffer freed between the
                    # metadata reply and the transfer): live peer, not a
                    # dead one
                    raise ShuffleProtocolError(
                        f"shuffle {shuffle_id}: "
                        f"{header.get('message', 'transfer error')}")
                if msg_type == XFER_DONE:
                    continue
                assert msg_type == XFER_CHUNK, msg_type
                bid = header["buffer_id"]
                if wire.chunk_crc(payload) != header["crc32"]:
                    raise ValueError(f"chunk crc mismatch for buffer {bid}")
                codec_name = header.get("codec", "none")
                if codec_name != "none":
                    from .compression import get_codec
                    payload = get_codec(codec_name).decompress(
                        payload, header.get("raw_len", 0))
                buf = received.get(bid)
                if buf is None:
                    total = inflight[bid].total_bytes
                    buf = self.bounce.acquire(total)
                    if buf is None:              # arena exhausted: fall back
                        self.metrics["bounce_misses"] += 1
                        _note_total("bounce_misses")
                        buf = bytearray(total)
                    received[bid] = buf
                buf[header["offset"]:header["offset"] + len(payload)] = \
                    payload
                self.metrics["chunks"] += 1
                seen_chunks[bid] = seen_chunks.get(bid, 0) + 1
                if seen_chunks[bid] == header["n_chunks"]:
                    m = inflight.pop(bid)
                    inflight_bytes -= m.total_bytes
                    self.metrics["bytes_fetched"] += m.total_bytes
                    # registry totals bump at BUFFER completion (a flush
                    # boundary), not per chunk
                    _note_total("bytes_fetched", m.total_bytes)
                    _note_total("chunks", seen_chunks[bid])
                    buf = received.pop(bid)
                    done.append(_rebuild_batch(m, bytes(buf)))
                    if isinstance(buf, memoryview):
                        self.bounce.release(buf)
                    issue()
            return done
        finally:
            conn.close()


def _rebuild_batch(meta: BufferDesc, payload: bytes) -> ColumnarBatch:
    """Reconstruct a ColumnarBatch from wire bytes (getBatchFromMeta,
    MetaUtils.scala:33-241)."""
    arrays: List[np.ndarray] = []
    off = 0
    for d in meta.arrays:
        a = np.frombuffer(payload, dtype=np.dtype(d.dtype),
                          count=d.nbytes // np.dtype(d.dtype).itemsize,
                          offset=off).reshape(d.shape)
        arrays.append(a)
        off += d.nbytes
    return _rebuild_from_arrays(meta, arrays)


def _rebuild_from_arrays(meta: BufferDesc,
                         arrays: List[np.ndarray]) -> ColumnarBatch:
    """Host arrays + metadata -> device batch (shared by the wire path and
    the local short-circuit read)."""
    from ..columnar.column import build_column
    fields = [dt.Field(n, dt.of(t))
              for n, t in zip(meta.field_names, meta.field_dtypes)]
    schema = dt.Schema(fields)
    import jax.numpy as jnp
    dev = [jnp.asarray(a) for a in arrays]
    cols: List[Column] = []
    i = 0
    for f in fields:
        c, i = build_column(f.dtype, dev, i)
        cols.append(c)
    return ColumnarBatch(schema, cols, meta.num_rows)
